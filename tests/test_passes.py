"""Pass pipeline (docs/PRECISION.md §Pass pipeline; ISSUE 20 acceptance).

Covers: pipeline construction/validation (unknown pass names raise
naming the registered set, duplicates rejected), the ONE-shared-
fingerprint contract (order, toggle, and config changes all split it;
AMP∘quant vs quant∘AMP are distinct programs), the bitwise-off
guarantee (a disabled pass contributes nothing to the signature OR the
traced jaxpr; ``wrap_apply`` is identity when nothing is enabled),
JSON round-trips through the checkpoint-layout shape, MX_PASSES /
MX_PALLAS_FUSED env semantics, AMP's backward-graph cast metadata
seam, fused-kernel substitution at the traced dispatch branch, the
weight-only int4 serving path (pack/dequant math, ≤0.16x weight bytes,
top-1 agreement vs the fp32 engine, fingerprint splits, env gate, AOT
restart round-trip in a second process), and training-side wiring
(``DataParallelStep`` fingerprints, ``layout()`` round-trip, the
``plan`` telemetry event's pass fingerprint).

jax.make_jaxpr caches by function identity + avals, so every bitwise
comparison here traces a FRESH closure per configuration (the ``mk()``
factories) — a shared closure would replay a stale jaxpr and mask
scope changes.
"""
import dataclasses
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.models.transformer import Transformer, label_smoothed_ce
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.parallel import (DataParallelStep, compile_step_with_plan,
                                dp_plan, local_mesh)
from mxnet_tpu.passes import (AmpPass, FusedKernelPass, PassPipeline,
                              QuantizeInt4Pass, QuantizeInt8Pass,
                              apply_env_toggles, available_passes,
                              fused_kernels_from_env, hooks,
                              pipeline_for_serving, pipeline_for_training,
                              resolve_pass_type)
from mxnet_tpu.precision import (AmpPolicy, Int4WeightAdapter,
                                 LossScaleConfig, PrecisionConfig,
                                 int4_adapter, maybe_int4_adapter)
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

PAD, BOS, EOS = 0, 1, 2
PREC = PrecisionConfig(amp=AmpPolicy(),
                       loss_scale=LossScaleConfig(init_scale=16.0,
                                                  growth_interval=4))


def _amp():
    return AmpPass(AmpPolicy())


def _q4(group=32):
    # live-enough entries ({} activates an empty quant_scope); the layer
    # signature stands in for the packed-weight digests
    return QuantizeInt4Pass({}, group, (("dense0", "aa" * 8),))


@pytest.fixture
def tele(tmp_path):
    telemetry.reset()
    telemetry.enable(str(tmp_path))
    yield telemetry
    telemetry.reset()


# ---------------------------------------------------------------------------
# registry + construction
# ---------------------------------------------------------------------------
def test_registered_pass_catalog():
    assert available_passes() == ["amp", "fused_kernels", "quant_int4",
                                  "quant_int8"]


def test_unknown_pass_name_raises_naming_registered_set():
    with pytest.raises(MXNetError) as ei:
        resolve_pass_type("quant_int5")
    msg = str(ei.value)
    assert "quant_int5" in msg
    for name in available_passes():
        assert name in msg
    # the JSON path and the env path fail the same way
    with pytest.raises(MXNetError, match="unknown graph pass"):
        PassPipeline.from_json([{"pass": "nope", "config": {}}])
    with pytest.raises(MXNetError, match="unknown graph pass"):
        apply_env_toggles(PassPipeline(), {"MX_PASSES": "-nope"})


def test_pipeline_rejects_duplicates_and_non_passes():
    with pytest.raises(MXNetError, match="duplicate pass"):
        PassPipeline([_q4(), _q4(16)])
    with pytest.raises(MXNetError, match="not a GraphPass"):
        PassPipeline([object()])
    with pytest.raises(MXNetError, match="policy"):
        AmpPass(None)


# ---------------------------------------------------------------------------
# ACCEPTANCE: ONE shared fingerprint — order, toggle, config all split it
# ---------------------------------------------------------------------------
def test_pipeline_fingerprint_splits_on_config_toggle_and_order():
    """The 4-way split (the test_precision fingerprint pattern, now at
    the pipeline layer): empty / amp / fused / amp+fused are four
    distinct fingerprints, AMP∘quant and quant∘AMP differ (order is
    identity — pass i sees the graph under passes 0..i-1), and a config
    change inside one pass (int4 group size) splits too."""
    pipes = [
        PassPipeline([]),
        PassPipeline([_amp()]),
        PassPipeline([FusedKernelPass()]),
        PassPipeline([_amp(), FusedKernelPass()]),
        PassPipeline([_amp(), _q4()]),
        PassPipeline([_q4(), _amp()]),      # order flip
        PassPipeline([_amp(), _q4(16)]),    # group-size config change
    ]
    fps = [p.fingerprint() for p in pipes]
    assert len(set(fps)) == len(fps), fps


def test_disabled_pass_is_absent_from_signature():
    amp_off = AmpPass(AmpPolicy(), enabled=False)
    assert (PassPipeline([amp_off, FusedKernelPass()]).signature()
            == PassPipeline([FusedKernelPass()]).signature())
    assert PassPipeline([amp_off]).signature() == ("passes",)
    # and toggling back on restores the full identity
    on = PassPipeline([amp_off]).set_enabled("amp", True)
    assert on.signature() == PassPipeline([_amp()]).signature()
    with pytest.raises(MXNetError, match="no pass named"):
        on.set_enabled("quant_int4", False)


def test_wrap_apply_identity_when_nothing_enabled():
    def f(params, key, x):
        return x, None

    assert PassPipeline([]).wrap_apply(f) is f
    assert PassPipeline(
        [AmpPass(AmpPolicy(), enabled=False)]).wrap_apply(f) is f


# ---------------------------------------------------------------------------
# bitwise-off at the dispatch point (fresh closures per trace!)
# ---------------------------------------------------------------------------
def _mk_ln(pipeline):
    """Fresh traced fn per call: residual-add+LayerNorm through the op
    dispatch point, under ``pipeline``'s scope."""
    gamma = nd.array(np.linspace(0.5, 1.5, 8).astype(np.float32))
    beta = nd.array(np.linspace(-0.1, 0.1, 8).astype(np.float32))

    def f(x, r):
        with pipeline.scope():
            out = nd.contrib.add_layer_norm(
                NDArray(x, ctx=mx.cpu()), NDArray(r, ctx=mx.cpu()),
                gamma, beta)
        return out._data

    return f


def test_fused_pass_substitutes_in_trace_and_is_bitwise_off():
    """ACCEPTANCE (fused kernels): under the pass the traced program is
    a different jaxpr (the Pallas kernel) that agrees numerically with
    the stock op; with the pass DISABLED the jaxpr is byte-identical to
    the no-pipeline trace — bitwise absent, not merely close."""
    import jax

    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    r = rng.randn(4, 8).astype(np.float32)

    bare = str(jax.make_jaxpr(_mk_ln(PassPipeline([])))(x, r))
    off = str(jax.make_jaxpr(
        _mk_ln(PassPipeline([FusedKernelPass(enabled=False)])))(x, r))
    assert off == bare
    fused = str(jax.make_jaxpr(
        _mk_ln(PassPipeline([FusedKernelPass()])))(x, r))
    assert fused != bare

    want = jax.jit(_mk_ln(PassPipeline([])))(x, r)
    got = jax.jit(_mk_ln(PassPipeline([FusedKernelPass()])))(x, r)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    # and the dispatch hook state restored (no leak out of the scope)
    assert not hooks.active()


def test_amp_pass_parity_and_bitwise_off():
    """The amp pass traces the EXACT program the PR 15 module-global
    path (``apply_amp``) traces — absorbing it as a pass changed its
    identity, not its lowering.  Disabled, the wrapped apply is the
    bare-f32 program."""
    import jax

    from mxnet_tpu.precision.amp_pass import apply_amp

    rng = np.random.RandomState(1)
    w = rng.randn(4, 8).astype(np.float32)
    b = np.zeros(4, np.float32)
    x = rng.randn(3, 8).astype(np.float32)

    def mk():
        def apply(params, key, inp):
            out = nd.FullyConnected(
                NDArray(inp, ctx=mx.cpu()),
                NDArray(params["w"], ctx=mx.cpu()),
                NDArray(params["b"], ctx=mx.cpu()), num_hidden=4)
            return out._data, None

        return apply

    params = {"w": w, "b": b}

    def trace(fn):
        return str(jax.make_jaxpr(lambda p, v: fn(p, None, v))(params, x))

    policy = AmpPolicy()
    via_pass = trace(pipeline_for_training(
        PrecisionConfig(amp=policy), environ={}).wrap_apply(mk()))
    via_global = trace(apply_amp(mk(), policy))
    assert via_pass == via_global
    assert "bf16" in via_pass  # the cast actually happened

    bare = trace(mk())
    off = trace(PassPipeline(
        [AmpPass(policy, enabled=False)]).wrap_apply(mk()))
    assert off == bare
    assert "bf16" not in bare


# ---------------------------------------------------------------------------
# serialization: the checkpoint-layout JSON shape
# ---------------------------------------------------------------------------
def test_pipeline_json_roundtrip_preserves_identity():
    pipe = PassPipeline([_amp(), _q4(16), FusedKernelPass(enabled=False)])
    recs = json.loads(json.dumps(pipe.to_json()))
    back = PassPipeline.from_json(recs)
    assert back.signature() == pipe.signature()
    assert back.fingerprint() == pipe.fingerprint()
    assert back.names() == pipe.names()
    assert back.get("fused_kernels").enabled is False
    # a quant pass rebuilt from JSON is a DESCRIPTOR: same fingerprint,
    # but its twins' device buffers are gone — activating must raise,
    # not silently serve the fp32 graph under an int4 fingerprint
    with pytest.raises(MXNetError, match="descriptor"):
        with back.get("quant_int4").scope():
            pass


def test_metadata_never_enters_the_fingerprint():
    """Satellite: AMP publishes its backward-graph cast decisions as
    pass metadata (the future quantized-grads seam) — declarative facts
    only, no trace or fingerprint effect."""
    p = _amp()
    meta = p.metadata()["backward"]
    assert meta["grad_dtype"] == "bfloat16"
    assert "FullyConnected" in meta["low"]
    assert meta["widen"] and "cotangent" in meta["note"]
    pipe = PassPipeline([p])
    assert pipe.metadata()["amp"]["backward"] == meta
    # mutating what a consumer reads cannot move the fingerprint
    before = pipe.fingerprint()
    meta["low"].append("FakeOp")
    assert pipe.fingerprint() == before


# ---------------------------------------------------------------------------
# env surface
# ---------------------------------------------------------------------------
def test_mx_passes_toggles():
    pipe = PassPipeline([_amp(), FusedKernelPass()])
    apply_env_toggles(pipe, {"MX_PASSES": "-fused_kernels"})
    assert pipe.get("fused_kernels").enabled is False
    assert pipe.get("amp").enabled is True
    # a bare registered name is validated but (today) a no-op
    apply_env_toggles(pipe, {"MX_PASSES": "amp, -quant_int4"})
    assert pipe.get("amp").enabled is True
    assert pipe.signature() == PassPipeline([_amp()]).signature()


def test_mx_pallas_fused_env_semantics():
    assert fused_kernels_from_env({"MX_PALLAS_FUSED": "0"}) is None
    forced = fused_kernels_from_env({"MX_PALLAS_FUSED": "1"})
    assert isinstance(forced, FusedKernelPass)
    assert "_contrib_add_layer_norm" in forced._ops
    with pytest.raises(MXNetError, match="MX_PALLAS_FUSED"):
        fused_kernels_from_env({"MX_PALLAS_FUSED": "sometimes"})
    # auto on this CPU box: interpret-only kernels stay out of real runs
    assert fused_kernels_from_env({}) is None


def test_op_hook_nesting_restores():
    class H(hooks.OpHook):
        pass

    a, b = H(), H()
    assert not hooks.active()
    with hooks.op_hook(a):
        with hooks.op_hook(b):
            assert hooks._OP_HOOKS == (a, b)
        assert hooks._OP_HOOKS == (a,)
    assert not hooks.active()


# ---------------------------------------------------------------------------
# training wiring: DataParallelStep + plan telemetry
# ---------------------------------------------------------------------------
def _make_step(precision=None):
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8),
            nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    from mxnet_tpu.gluon import loss as gloss

    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    return DataParallelStep(
        net, lambda o, l: loss_fn(o, l), mesh=local_mesh(),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1},
        precision=precision)


def test_training_pipeline_splits_step_fingerprint(monkeypatch):
    """The pipeline signature joins the step's AOT fingerprint: amp
    on/off × fused on/off are four distinct executables."""
    sig = ((((16, 8), "float32"),), ((16,), "float32"))
    monkeypatch.delenv("MX_PALLAS_FUSED", raising=False)
    monkeypatch.delenv("MX_PASSES", raising=False)
    parts = [_make_step(None)._fingerprint_parts((), sig),
             _make_step(PREC)._fingerprint_parts((), sig)]
    monkeypatch.setenv("MX_PALLAS_FUSED", "1")
    parts += [_make_step(None)._fingerprint_parts((), sig),
              _make_step(PREC)._fingerprint_parts((), sig)]
    fps = [memwatch.fingerprint(p) for p in parts]
    assert len(set(fps)) == 4, fps


def test_step_layout_roundtrips_pipeline(monkeypatch):
    """Satellite: the pipeline rides the checkpoint layout — the JSON
    the step writes rebuilds a pipeline with the identical fingerprint
    (what a restore-side consistency check compares)."""
    monkeypatch.setenv("MX_PALLAS_FUSED", "1")
    monkeypatch.delenv("MX_PASSES", raising=False)
    step = _make_step(PREC)
    assert step._pipeline.names() == ["amp", "fused_kernels"]
    recs = json.loads(json.dumps(step.layout()["passes"]))
    assert (PassPipeline.from_json(recs).fingerprint()
            == step._pipeline.fingerprint())


def test_plan_event_carries_pass_fingerprint(tele, tmp_path):
    """Satellite: the ``plan`` telemetry event names the pass set and
    the shared fingerprint keying the step's AOT executables."""
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    from mxnet_tpu.gluon import loss as gloss

    loss_fn = gloss.SoftmaxCrossEntropyLoss()
    plan = dataclasses.replace(dp_plan(), precision=PREC)
    step = compile_step_with_plan(net, lambda o, l: loss_fn(o, l), plan)
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    plans = [e for e in events if e["kind"] == "plan"]
    assert plans, [e["kind"] for e in events]
    assert plans[-1]["passes"] == ["amp"]
    assert plans[-1]["pass_fingerprint"] == step._pipeline.fingerprint()


# ---------------------------------------------------------------------------
# int4 math: pack -> in-trace dequantize
# ---------------------------------------------------------------------------
def test_int4_pack_dequantize_roundtrip():
    """Packing is exact over the nibble lattice: dequantize_int4
    reproduces q*scale bitwise, reconstruction error is bounded by half
    a quantization step per group, and a non-multiple input dim pads
    with exact zeros that the ``cols`` slice removes."""
    from mxnet_tpu.contrib.quantization import _quantize_weight_int4_np

    rng = np.random.RandomState(0)
    w = (rng.randn(8, 64) * 2).astype(np.float32)
    packed, scales, cols = _quantize_weight_int4_np(w, 32)
    assert packed.shape == (8, 32) and packed.dtype == np.uint8
    assert scales.shape == (8, 2) and scales.dtype == np.float16
    assert cols == 64

    back = nd.contrib.dequantize_int4(
        nd.array(packed, dtype=np.uint8),
        nd.array(scales, dtype=np.float16),
        group_size=32, cols=64).asnumpy()
    # manual nibble unpack (low nibble = even column, two's complement)
    lo = (packed & 0x0F).astype(np.int32)
    hi = (packed >> 4).astype(np.int32)
    lo = np.where(lo > 7, lo - 16, lo)
    hi = np.where(hi > 7, hi - 16, hi)
    q = np.stack([lo, hi], axis=-1).reshape(8, -1)
    assert np.abs(q).max() <= 7
    ref = (q.reshape(8, -1, 32).astype(np.float32)
           * scales.astype(np.float32)[..., None]).reshape(8, -1)
    np.testing.assert_array_equal(back, ref)
    # half-step error bound, per group
    step = scales.astype(np.float32)[..., None]
    err = np.abs((back - w).reshape(8, -1, 32))
    assert (err <= step * 0.5 + 1e-6).all()

    w2 = (rng.randn(4, 70)).astype(np.float32)
    p2, s2, c2 = _quantize_weight_int4_np(w2, 32)
    assert c2 == 70 and p2.shape == (4, 48)  # padded to 96 cols
    back2 = nd.contrib.dequantize_int4(
        nd.array(p2, dtype=np.uint8), nd.array(s2, dtype=np.float16),
        group_size=32, cols=70).asnumpy()
    assert back2.shape == (4, 70)


def test_int4_pack_validation():
    from mxnet_tpu.contrib.quantization import _quantize_weight_int4_np

    w = np.ones((4, 8), np.float32)
    with pytest.raises(MXNetError, match="even"):
        _quantize_weight_int4_np(w, 7)
    with pytest.raises(MXNetError, match="2-D"):
        _quantize_weight_int4_np(np.ones(8, np.float32), 4)


def test_int4_dense_twin_matches_manual_dequant_fc():
    """The Int4Dense lowering is exactly dequantize -> stock
    FullyConnected (+ activation) — one composition, eager-checked."""
    from mxnet_tpu.contrib.quantization import Int4Dense

    mx.random.seed(3)
    dense = nn.Dense(16, activation="relu", in_units=32)
    dense.initialize(mx.init.Xavier())
    imp = Int4Dense(dense, group_size=32)
    assert imp.nbytes < 0.16 * imp.orig_nbytes
    rng = np.random.RandomState(5)
    x = nd.array(rng.randn(3, 32).astype(np.float32))
    got = imp(x).asnumpy()
    w = nd.contrib.dequantize_int4(imp._packed, imp._scales,
                                   group_size=32, cols=imp._cols)
    want = nd.Activation(
        nd.FullyConnected(x, w, dense.bias.data(), num_hidden=16),
        act_type="relu").asnumpy()
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# ACCEPTANCE: weight-only int4 serving
# ---------------------------------------------------------------------------
def _reverse_batch(rng, B, L=6, vocab=16):
    src = np.zeros((B, L + 1), np.int32)
    tgt_in = np.zeros((B, L + 2), np.int32)
    tgt_out = np.zeros((B, L + 2), np.int32)
    for b in range(B):
        toks = rng.randint(3, vocab, L)
        src[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    return src, tgt_in, tgt_out


@pytest.fixture(scope="module")
def trained():
    """Reverse-task transformer (the test_serving recipe): sharp logits
    so greedy decode is decision-stable across the fp32 and int4
    executables.  units=32 and hidden=64 are multiples of the default
    group (32): no padding dilutes the weight-bytes ratio."""
    mx.random.seed(0)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=20, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(2)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)
    step = DataParallelStep(
        net, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))
    for _ in range(48):
        step.step((sb, tb), lb)
    step.sync_to_block()
    return net, src


def _serve(engine, src, n=6):
    reqs = [Request(src[i], max_new_tokens=9, bos_id=BOS, eos_id=EOS)
            for i in range(n)]
    out = engine.serve(reqs, arrival_steps=[0, 0, 0, 2, 5, 9][:n])
    return reqs, out


def test_int4_engine_weight_bytes_and_top1_agreement(trained):
    """ACCEPTANCE: the int4 rewrite holds ≤0.16x the fp32 bytes for the
    rewritten layers' weights (0.5625 bytes/weight at group 32) and the
    int4 engine's greedy decode agrees ≥0.99 top-1 with the fp32
    engine on the memorized reverse task."""
    net, src = trained
    eng32 = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=3,
                          page_size=4, max_len=12, stream_every=4)
    reqs32, out32 = _serve(eng32, src)

    qad = int4_adapter(TransformerAdapter(net, src_max_len=7))
    assert qad.precision == "int4"
    ratio = qad.quantized_weight_bytes() / qad.fp32_weight_bytes()
    assert ratio <= 0.16, ratio
    # whole-model accounting still counts f32 embeddings/norms
    assert qad.quantized_param_bytes() < qad.fp32_param_bytes()
    engq = ServingEngine(qad, slots=3, page_size=4, max_len=12,
                         stream_every=4)
    assert engq._pipeline.names() == ["quant_int4"]
    reqsq, outq = _serve(engq, src)

    agree, total = 0, 0
    for a, b in zip(reqs32, reqsq):
        ta, tb = list(out32[a.id]), list(outq[b.id])
        n = min(len(ta), len(tb))
        agree += sum(1 for i in range(n) if ta[i] == tb[i])
        total += max(len(ta), len(tb))
    assert total > 0
    assert agree / total >= 0.99, (agree, total)
    # solved, not just agreed upon
    for i, r in enumerate(reqsq[:3]):
        assert list(outq[r.id][:6]) == list(src[i, :6][::-1])
    # packed nibbles + scales are census-attributed device residency
    cats = memwatch.census()["categories"]
    assert "quantized" in cats, sorted(cats)
    assert cats["quantized"]["count"] >= len(qad._entries)


def test_int4_config_splits_engine_fingerprint(trained):
    """ACCEPTANCE: fp32 vs int4 vs a different MX_QUANT_GROUP are three
    distinct AOT fingerprints, while re-packing the same weights at the
    same group reproduces the SAME fingerprint (the restart-stability
    half of the contract — a same-config restart must hit)."""
    net, src = trained
    mk = lambda ad: ServingEngine(ad, slots=2, page_size=4, max_len=8,
                                  stream_every=2)
    engines = [mk(TransformerAdapter(net, src_max_len=7)),
               mk(int4_adapter(TransformerAdapter(net, src_max_len=7))),
               mk(int4_adapter(TransformerAdapter(net, src_max_len=7),
                               group_size=16))]
    parts = [e._fingerprint_parts(("decode", 4, 2), []) for e in engines]
    fps = [memwatch.fingerprint(p) for p in parts]
    assert len(set(fps)) == len(fps), fps

    again = mk(int4_adapter(TransformerAdapter(net, src_max_len=7)))
    assert memwatch.fingerprint(
        again._fingerprint_parts(("decode", 4, 2), [])) == fps[1]


def test_maybe_int4_env_gate(monkeypatch, trained):
    net, src = trained
    adapter = TransformerAdapter(net, src_max_len=7)
    monkeypatch.delenv("MX_SERVE_INT4", raising=False)
    monkeypatch.delenv("MX_QUANTIZE", raising=False)
    assert maybe_int4_adapter(adapter) is adapter
    monkeypatch.setenv("MX_SERVE_INT4", "1")
    q = maybe_int4_adapter(adapter)
    assert isinstance(q, Int4WeightAdapter)
    assert q._group_size == 32
    monkeypatch.setenv("MX_QUANT_GROUP", "16")
    assert maybe_int4_adapter(adapter)._group_size == 16
    monkeypatch.setenv("MX_QUANT_GROUP", "lots")
    with pytest.raises(MXNetError, match="MX_QUANT_GROUP"):
        maybe_int4_adapter(adapter)
    monkeypatch.setenv("MX_QUANT_GROUP", "7")
    with pytest.raises(MXNetError, match="even"):
        maybe_int4_adapter(adapter)
    monkeypatch.delenv("MX_QUANT_GROUP", raising=False)
    monkeypatch.setenv("MX_QUANTIZE", "int8")
    with pytest.raises(MXNetError, match="pick one"):
        maybe_int4_adapter(adapter)
    monkeypatch.delenv("MX_QUANTIZE", raising=False)
    monkeypatch.setenv("MX_SERVE_INT4", "sometimes")
    with pytest.raises(MXNetError, match="MX_SERVE_INT4"):
        maybe_int4_adapter(adapter)


# ---------------------------------------------------------------------------
# fused kernels inside the serving engine (interpret mode on CPU)
# ---------------------------------------------------------------------------
def test_fused_pass_in_serving_engine(monkeypatch, trained):
    """MX_PALLAS_FUSED=1 swaps the registered kernels into the engine's
    compiled decode/prefill (interpret mode here), splits the AOT
    fingerprint, agrees top-1 with the stock engine, and MX_PASSES can
    veto the pass back out of the signature."""
    net, src = trained
    monkeypatch.delenv("MX_PALLAS_FUSED", raising=False)
    monkeypatch.delenv("MX_PASSES", raising=False)
    base = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                         page_size=4, max_len=12, stream_every=4)
    assert base._pipeline.names() == []
    reqs0, out0 = _serve(base, src, n=3)

    monkeypatch.setenv("MX_PALLAS_FUSED", "1")
    engf = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                         page_size=4, max_len=12, stream_every=4)
    assert engf._pipeline.names() == ["fused_kernels"]
    fp = lambda e: memwatch.fingerprint(
        e._fingerprint_parts(("decode", 4, 2), []))
    assert fp(engf) != fp(base)
    reqsf, outf = _serve(engf, src, n=3)
    for a, b in zip(reqs0, reqsf):
        assert list(out0[a.id]) == list(outf[b.id])

    monkeypatch.setenv("MX_PASSES", "-fused_kernels")
    vetoed = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                           page_size=4, max_len=12, stream_every=4)
    assert vetoed._pipeline.get("fused_kernels").enabled is False
    assert fp(vetoed) == fp(base)


# ---------------------------------------------------------------------------
# ACCEPTANCE: int4 AOT round-trip in a second process (the restart story)
# ---------------------------------------------------------------------------
_AOT4_CHILD = r"""
import json, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.models.transformer import Transformer
from mxnet_tpu.precision import Int4WeightAdapter, maybe_int4_adapter
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

mx.random.seed(0)
net = Transformer(16, units=32, hidden_size=64, num_heads=4, num_layers=2,
                  max_length=48, dropout=0.0)
net.initialize(mx.init.Xavier())
rng = np.random.RandomState(4)
prompts = [rng.randint(3, 16, 4) for _ in range(3)]

# int4 packing reads the weights directly (no calibration forward), so
# materialize the deferred-init parameters first
net.translate(nd.array(prompts[0].reshape(1, -1), dtype="int32"), bos_id=1,
              eos_id=2, max_len=3, beam_size=1)

qad = maybe_int4_adapter(TransformerAdapter(net, src_max_len=6))
assert isinstance(qad, Int4WeightAdapter)
eng = ServingEngine(qad, slots=2, page_size=4, max_len=8, stream_every=2)
out = eng.serve([Request(prompts[0], max_new_tokens=5, bos_id=1, eos_id=2)])
evs = [e for e in telemetry.flight_tail(256) if e["kind"] == "compile"
       and e.get("executor") == "ServingEngine"]
print("I4AOT " + json.dumps({"compiles": evs,
                             "tokens": [int(t) for t in
                                        list(out.values())[0]]}))
"""


def test_int4_aot_cache_roundtrip(tmp_path):
    """ACCEPTANCE: a second process under the SAME int4 config hits the
    AOT cache on both compile events and decodes identical tokens; a
    different MX_QUANT_GROUP misses (the fingerprint carries the int4
    config).  Fresh private jax compile cache per phase (the
    test_serving recipe)."""
    import subprocess
    import sys

    def run_phase(tele_dir, group):
        env = dict(os.environ,
                   MX_SERVE_INT4="1", MX_QUANT_GROUP=group,
                   MX_EXECUTABLE_CACHE_DIR=str(tmp_path / "aot"),
                   MX_TELEMETRY_DIR=str(tmp_path / tele_dir),
                   JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
                   JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", _AOT4_CHILD], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("I4AOT ")][-1]
        return json.loads(line[len("I4AOT "):])

    first = run_phase("tele1", "32")
    assert len(first["compiles"]) == 2
    assert all(not e.get("cache_hit") for e in first["compiles"])

    second = run_phase("tele2", "32")
    assert len(second["compiles"]) == 2, second
    for e in second["compiles"]:
        assert e.get("cache_hit") is True, e
        assert e.get("deserialize_ms", 0) > 0
    assert second["tokens"] == first["tokens"]

    other = run_phase("tele3", "16")
    assert all(not e.get("cache_hit") for e in other["compiles"]), other
