"""End-to-end example-script smoke (subprocess, CPU-pinned).

The examples are the BASELINE acceptance drivers; running one of them
through the REAL input pipeline catches integration bugs unit tests miss
(r4: the pick/(B,1)-label crash only surfaced driving train_imagenet
--rec).  Reference analog: tests/nightly tutorial/example execution.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_rec(tmp_path, n=64, size=48):
    from mxnet_tpu import recordio

    rec = str(tmp_path / "train.rec")
    idx = str(tmp_path / "train.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), np.uint8)
        h = recordio.IRHeader(0, float(i % 5), i, 0)
        w.write_idx(i, recordio.pack_img(h, arr, quality=80))
    w.close()
    return rec


def test_train_imagenet_rec_e2e(tmp_path):
    rec = _make_rec(tmp_path)
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "train_imagenet.py"),
         "--device", "cpu", "--rec", rec, "--model", "resnet18_v1",
         "--batch-size", "8", "--image-shape", "3,32,32",
         "--num-classes", "5", "--steps", "3"],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "final loss" in res.stdout, res.stdout[-500:]


def test_train_wmt_e2e(tmp_path):
    """Seq2seq example through the fused multi-input step, incl. the
    file-backed corpus path."""
    src_f, tgt_f = tmp_path / "s.txt", tmp_path / "t.txt"
    src_f.write_text("4 5 6 7\n8 9 10\n")
    tgt_f.write_text("7 6 5 4\n10 9 8\n")
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "train_wmt.py"),
         "--device", "cpu", "--model", "tiny", "--vocab-size", "16",
         "--batch-size", "2", "--steps", "3",
         "--src", str(src_f), "--tgt", str(tgt_f)],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "final loss" in res.stdout, res.stdout[-500:]


def test_train_mnist_e2e():
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "train_mnist.py"),
         "--device", "cpu", "--epochs", "2", "--batch-size", "256"],
        # converges (train-acc 1.0) by epoch 2; bs256 vectorizes the
        # 1-core CPU run 2.5x better than the example's default 64
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "MNIST example OK" in res.stdout


def test_train_detection_e2e():
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples",
                                      "train_detection.py"),
         "--device", "cpu", "--model", "faster_rcnn", "--steps", "4",
         "--image-size", "64", "--batch-size", "2"],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "faster_rcnn: loss" in res.stdout, res.stdout[-500:]


def test_train_detection_recordio_e2e():
    """BASELINE config-5 acceptance shape: detection RecordIO ->
    ImageDetIter (bbox-aware augmentation) -> SSD train step."""
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples",
                                      "train_detection.py"),
         "--device", "cpu", "--model", "ssd", "--make-rec", "16",
         "--steps", "4", "--image-size", "64", "--batch-size", "2"],
        cwd=_REPO, capture_output=True, text=True, timeout=420)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "synthesized 16-image det RecordIO" in res.stdout
    assert "ssd: loss" in res.stdout, res.stdout[-500:]


def test_bert_pretrain_3d_e2e():
    """3D-parallel (dp2 x pp2 x tp2) BERT pretrain example on the virtual
    mesh (slow tier)."""
    env = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", "bert_pretrain.py"),
         "--dp", "2", "--pp", "2", "--tp", "2", "--model", "small",
         "--steps", "3", "--batch-size", "8"],
        cwd=_REPO, capture_output=True, text=True, timeout=420, env=env)
    assert res.returncode == 0, (res.stdout[-1500:], res.stderr[-1500:])
    assert "dp2xpp2xtp2" in res.stdout, res.stdout[-500:]
