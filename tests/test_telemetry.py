"""Runtime telemetry (docs/OBSERVABILITY.md): recorder no-op guarantee,
JSONL sink + flight recorder, retrace detection, step/checkpoint events,
heartbeats, the launch.py supervisor's stale-rank diagnosis, and the
[rank N] log prefixes."""
import json
import logging
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tele():
    """Fresh recorder state per test; leaves the recorder disabled after."""
    telemetry.reset()
    yield telemetry
    telemetry.reset()


# ---------------------------------------------------------------------------
# recorder core
# ---------------------------------------------------------------------------
def test_recorder_noops_without_sink(tele):
    assert not tele.enabled()
    tele.record("step", executor="x", step=1)  # must not raise or buffer
    tele.record_step("x", step=1, wall_s=0.1, samples=8)
    tele.heartbeat(1)
    s = tele.summary()
    assert s["enabled"] is False
    assert s["events"] == {}
    assert tele.flight_tail() == []


def test_jsonl_sink_ring_and_summary(tele, tmp_path):
    tele.enable(str(tmp_path))
    assert tele.enabled()
    tele.record_step("ExecA", step=1, wall_s=0.5, samples=0, traced=True)
    tele.record_step("ExecA", step=2, wall_s=0.1, samples=16)
    tele.record_collective("device_allreduce", nbytes=1024, wall_s=0.002)
    tele.record_checkpoint("save", step=2, wall_s=0.05, nbytes=4096)
    tele.flush()
    path = tele.event_path(str(tmp_path), tele.rank())
    events = [json.loads(line) for line in open(path)]
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "start"
    assert kinds.count("step") == 2 and "collective" in kinds
    assert "checkpoint_save" in kinds
    for e in events:  # schema: every event carries t/kind/rank
        assert {"t", "kind", "rank"} <= set(e)
    s = tele.summary()
    assert s["steps"]["ExecA"]["count"] == 2
    assert s["steps"]["ExecA"]["compile_count"] == 1
    assert s["steps"]["ExecA"]["compile_ms"] == pytest.approx(500, rel=0.01)
    assert s["steps"]["ExecA"]["samples_per_sec"] == pytest.approx(160, rel=0.01)
    assert s["collectives"] == {"count": 1, "bytes": 1024,
                                "total_ms": pytest.approx(2, rel=0.01),
                                "compile_ms": 0.0}
    assert s["checkpoints"]["saves"] == 1
    # flight recorder: newest last, bounded
    tail = tele.flight_tail(3)
    assert [e["kind"] for e in tail] == ["step", "collective",
                                        "checkpoint_save"]
    json.dumps(s)  # summary must stay JSON-serializable (bench.py embeds it)


def test_heartbeat_atomic_and_rate_limited(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_HEARTBEAT_SEC", "9999")  # rate limit ~forever
    tele.enable(str(tmp_path))
    tele.heartbeat(5)
    path = tele.heartbeat_path(str(tmp_path), tele.rank())
    first = json.load(open(path))
    assert first["step"] == 5 and first["pid"] == os.getpid()
    tele.heartbeat(6)  # rate-limited: no write
    assert json.load(open(path))["step"] == 5
    tele.heartbeat(7, force=True)
    assert json.load(open(path))["step"] == 7
    # no torn tmp files left behind
    assert not [f for f in os.listdir(tmp_path) if ".tmp-" in f]


# ---------------------------------------------------------------------------
# retrace detection
# ---------------------------------------------------------------------------
def test_retrace_warning_fires_and_rate_limits(tele, monkeypatch, caplog):
    monkeypatch.setenv("MX_TELEMETRY_RETRACE_LIMIT", "3")
    caplog.set_level(logging.WARNING, logger="mxnet_tpu.telemetry")
    for i in range(4):
        assert tele.note_signature("ExecB", ("shape", i)) is True
    warns = [r for r in caplog.records if "ExecB" in r.getMessage()]
    assert len(warns) == 1, [r.getMessage() for r in warns]
    assert "4 distinct signatures" in warns[0].getMessage()
    assert "('shape', 3)" in warns[0].getMessage()  # names the offender
    # rate-limited: the next warning only once the count doubles
    for i in range(4, 8):
        tele.note_signature("ExecB", ("shape", i))
    warns = [r for r in caplog.records if "ExecB" in r.getMessage()]
    assert len(warns) == 2, [r.getMessage() for r in warns]
    assert tele.summary()["retraces"]["ExecB"]["traces"] == 8


def test_collective_compile_split(tele, tmp_path):
    tele.enable(str(tmp_path))
    tele.record_collective("device_allreduce", nbytes=64, wall_s=0.5,
                           traced=True)   # first use: jit trace + compile
    tele.record_collective("device_allreduce", nbytes=64, wall_s=0.001)
    c = tele.summary()["collectives"]
    assert c["count"] == 2
    assert c["compile_ms"] == pytest.approx(500, rel=0.01)
    assert c["total_ms"] == pytest.approx(1, rel=0.01)


def test_retrace_limit_zero_disables_detection(tele, monkeypatch, caplog):
    monkeypatch.setenv("MX_TELEMETRY_RETRACE_LIMIT", "0")
    caplog.set_level(logging.WARNING, logger="mxnet_tpu.telemetry")
    assert not tele.retrace_enabled()
    for i in range(20):
        assert tele.note_signature("ExecZ", ("shape", i)) is False
    assert not caplog.records
    assert "ExecZ" not in tele.summary()["retraces"]


def test_stable_signatures_never_warn(tele, monkeypatch, caplog):
    monkeypatch.setenv("MX_TELEMETRY_RETRACE_LIMIT", "3")
    caplog.set_level(logging.WARNING, logger="mxnet_tpu.telemetry")
    assert tele.note_signature("ExecC", ("stable",)) is True
    for _ in range(50):
        assert tele.note_signature("ExecC", ("stable",)) is False
    assert not [r for r in caplog.records if "ExecC" in r.getMessage()]


def test_cached_op_shape_churn_warns(tele, monkeypatch, caplog):
    """The integration path: a hybridized block fed a new batch shape every
    call recompiles every call — the warning must fire; a stable-shape loop
    must stay silent."""
    monkeypatch.setenv("MX_TELEMETRY_RETRACE_LIMIT", "4")
    caplog.set_level(logging.WARNING, logger="mxnet_tpu.telemetry")
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    for b in range(1, 7):  # 6 distinct batch shapes > limit of 4
        net(nd.array(np.random.rand(b, 3).astype(np.float32)))
    warns = [r for r in caplog.records if "CachedOp:Dense" in r.getMessage()]
    assert warns, "shape churn through a CachedOp did not warn"
    assert "recompile" in warns[0].getMessage()

    caplog.clear()
    stable = gluon.nn.Dense(2)
    stable.initialize(mx.init.Xavier())
    stable.hybridize()
    x = nd.array(np.random.rand(4, 3).astype(np.float32))
    for _ in range(20):
        stable(x)
    assert not [r for r in caplog.records
                if "CachedOp:Dense" in r.getMessage()]


def test_many_same_class_blocks_do_not_false_storm(tele, monkeypatch, caplog):
    """Retrace tracking is per CachedOp instance: a model holding many
    same-class blocks of different widths (one stable signature each) must
    not pool into a phantom retrace storm."""
    monkeypatch.setenv("MX_TELEMETRY_RETRACE_LIMIT", "3")
    caplog.set_level(logging.WARNING, logger="mxnet_tpu.telemetry")
    x = nd.array(np.random.rand(2, 3).astype(np.float32))
    for width in range(1, 7):  # 6 instances > limit of 3
        b = gluon.nn.Dense(width)
        b.initialize(mx.init.Xavier())
        b.hybridize()
        b(x)
    assert not [r for r in caplog.records if "CachedOp" in r.getMessage()]


# ---------------------------------------------------------------------------
# executor step events
# ---------------------------------------------------------------------------
def test_data_parallel_step_events_and_heartbeat(tele, tmp_path, monkeypatch):
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    monkeypatch.setenv("MX_HEARTBEAT_SEC", "0")
    tele.enable(str(tmp_path))
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                            optimizer="sgd")
    x = nd.array(np.random.rand(8, 4).astype(np.float32))
    y = nd.array(np.random.rand(8, 4).astype(np.float32))
    for _ in range(3):
        step.step(x, y)
    tele.flush()
    events = [json.loads(line)
              for line in open(tele.event_path(str(tmp_path), 0))]
    steps = [e for e in events if e["kind"] == "step"]
    assert [e["step"] for e in steps] == [1, 2, 3]
    assert steps[0]["traced"] is True  # first call = trace + compile
    assert steps[1]["traced"] is False and steps[2]["traced"] is False
    assert all(e["samples"] == 8 for e in steps)
    assert all(e["transfer_bytes"] > 0 for e in steps)
    step_keys = [k for k in tele.summary()["steps"]
                 if k.startswith("DataParallelStep:Dense#")]
    assert len(step_keys) == 1, tele.summary()["steps"]
    ex = tele.summary()["steps"][step_keys[0]]
    assert ex["compile_count"] == 1 and ex["count"] == 3
    # compile (trace+build XLA program) dominates a steady-state tiny step
    assert ex["compile_ms"] > ex["mean_exec_ms"]
    hb = json.load(open(tele.heartbeat_path(str(tmp_path), 0)))
    assert hb["step"] == 3


def test_checkpoint_events(tele, tmp_path, monkeypatch):
    from mxnet_tpu import checkpoint

    monkeypatch.setenv("MX_HEARTBEAT_SEC", "0")
    tele.enable(str(tmp_path / "t"))
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    net(nd.array(np.random.rand(2, 3).astype(np.float32)))
    ckdir = str(tmp_path / "ck")
    ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=2, keep=2)
    for _ in range(4):
        ckpt.step(net)
    ckpt.close()
    assert checkpoint.restore(ckdir, net) == 4
    tele.flush()
    events = [json.loads(line)
              for line in open(tele.event_path(str(tmp_path / "t"), 0))]
    saves = [e for e in events if e["kind"] == "checkpoint_save"]
    assert [e["step"] for e in saves] == [2, 4]
    assert all(e["nbytes"] > 0 and e["wall_ms"] > 0 for e in saves)
    loads = [e for e in events if e["kind"] == "checkpoint_load"]
    assert loads and loads[-1]["step"] == 4
    s = tele.summary()["checkpoints"]
    assert s["saves"] == 2 and s["loads"] == 1
    # heartbeats advanced with the step counter
    hb = json.load(open(tele.heartbeat_path(str(tmp_path / "t"), 0)))
    assert hb["step"] == 4


# ---------------------------------------------------------------------------
# satellites: Speedometer clock, profiler segments
# ---------------------------------------------------------------------------
def test_speedometer_survives_wallclock_jump(monkeypatch, caplog):
    """Speed math must use the monotonic perf counter: a backwards
    wall-clock step (NTP) used to yield negative samples/sec."""
    from mxnet_tpu import callback

    walltimes = [1000.0, 500.0, 100.0]  # time.time() jumping BACKWARDS
    monkeypatch.setattr(callback.time, "time",
                        lambda: walltimes.pop(0) if walltimes else 100.0)
    caplog.set_level(logging.INFO)
    sm = callback.Speedometer(batch_size=4, frequent=1)

    class Param:
        epoch, eval_metric = 0, None

    p = Param()
    p.nbatch = 0
    sm(p)
    time.sleep(0.01)
    p.nbatch = 1
    sm(p)
    msgs = [r.getMessage() for r in caplog.records
            if "samples/sec" in r.getMessage()]
    assert msgs, caplog.records
    speed = float(re.search(r"Speed: (-?[\d.]+)", msgs[-1]).group(1))
    assert speed > 0, msgs[-1]


def test_profiler_resume_writes_fresh_segments(tmp_path, monkeypatch):
    """resume() must not clobber the prior trace: every start()/resume()
    opens a fresh numbered segment dir, and dump() lists them all.  The
    jax profiler itself is stubbed (real capture costs ~7s per segment and
    test_profiler.py already exercises it through the same start/stop
    path); this pins OUR segment bookkeeping."""
    import jax

    from mxnet_tpu import profiler

    started = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d: (started.append(d), os.makedirs(d)))
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    profiler.set_config(filename=str(tmp_path / "prof.json"))
    before = len(profiler.dump())
    profiler.start()
    profiler.pause()
    profiler.resume()
    profiler.stop()
    segments = profiler.dump()
    new = segments[before:]
    assert len(new) == 2, segments
    assert new[0] != new[1] and started == new
    assert [os.path.basename(s) for s in new] == \
        [f"segment-{before:03d}", f"segment-{before + 1:03d}"]
    for seg in new:
        assert os.path.isdir(seg), f"trace segment {seg} not created"
    assert all(s.startswith(str(tmp_path)) for s in new)


def test_dumps_includes_telemetry_rollup(tele):
    from mxnet_tpu import profiler

    tele.note_signature("ExecD", ("a",))
    out = profiler.dumps()
    assert "Telemetry rollup:" in out
    assert "ExecD" in out


# ---------------------------------------------------------------------------
# launch.py supervisor (no-jax workers: fast)
# ---------------------------------------------------------------------------
def _launch(n, worker, env=None, timeout=90, args=()):
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), *args, "--", sys.executable, str(worker)]
    return subprocess.run(cmd, timeout=timeout, capture_output=True,
                          text=True, env=env)


def test_supervisor_stale_heartbeat_diagnosis_and_flight_tail(tmp_path):
    """One supervised gang covers three supervisor features: worker
    stdout/stderr lines arrive `[rank N]`-prefixed; a rank whose heartbeat
    stops advancing is called out while the gang is still alive; and after
    the gang dies the supervisor echoes each rank's flight-recorder tail.
    Workers write the telemetry files directly (same schema as
    mxnet_tpu.telemetry) so this covers the supervisor's reader without
    paying jax imports."""
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    worker = tmp_path / "w.py"
    worker.write_text(
        "import json, os, sys, time\n"
        "rank = os.environ['MX_PROC_ID']\n"
        "td = os.environ['MX_TELEMETRY_DIR']\n"
        "print('hello from worker')\n"
        "print('oops line', file=sys.stderr)\n"
        "with open(os.path.join(td, f'heartbeat-{rank}.json'), 'w') as f:\n"
        "    json.dump({'rank': int(rank), 'step': 130 + int(rank),\n"
        "               'time': time.time(), 'pid': os.getpid()}, f)\n"
        "with open(os.path.join(td, f'rank-{rank}.jsonl'), 'a') as f:\n"
        "    for i in range(3):\n"
        "        f.write(json.dumps({'t': time.time(), 'kind': 'step',\n"
        "                            'rank': int(rank), 'step': i}) + '\\n')\n"
        "if rank == '0':\n"
        "    time.sleep(5)\n"
        "    sys.exit(9)\n"
        "time.sleep(60)\n")
    env = dict(os.environ, MX_TELEMETRY_DIR=str(tdir),
               MX_HEARTBEAT_SEC="0.2")  # stale threshold = 2s floor
    res = _launch(2, worker, env=env, timeout=60)
    assert res.returncode == 9, (res.stdout, res.stderr)
    # interleaved gang output stays attributable
    for r in (0, 1):
        assert f"[rank {r}] hello from worker" in res.stdout, res.stdout
        assert f"[rank {r}] oops line" in res.stderr, res.stderr
    # diagnosed BEFORE the gang died (rank 1 never advanced its heartbeat)
    stale = re.search(r"rank 1 last heartbeat ([\d.]+)s ago at step 131 — "
                      "suspect hung/slow rank", res.stderr)
    assert stale, res.stderr
    assert float(stale.group(1)) >= 2.0
    # post-mortem: per-rank flight-recorder tail with parseable events
    for r in (0, 1):
        assert f"flight recorder tail (rank {r}" in res.stderr, res.stderr
    tail_events = [json.loads(line.strip()) for line in res.stderr.splitlines()
                   if line.strip().startswith('{"t"')]
    assert len(tail_events) >= 6  # 3 events x 2 ranks echoed
    assert {e["kind"] for e in tail_events} == {"step"}


# ---------------------------------------------------------------------------
# the full acceptance shape: 2-rank gang with real training telemetry
# ---------------------------------------------------------------------------
@pytest.mark.dist
def test_two_rank_gang_emits_jsonl_and_advancing_heartbeats(tmp_path):
    """2-rank launch_local with MX_TELEMETRY_DIR: one parseable JSONL
    stream per rank containing step, collective, and checkpoint events,
    plus heartbeat files that ADVANCED during the run (the worker verifies
    advancement in-process; we verify the final files)."""
    tdir = tmp_path / "telemetry"
    env = dict(os.environ, MX_TELEMETRY_DIR=str(tdir),
               MX_HEARTBEAT_SEC="0.05", MX_TELEMETRY_FLUSH_SEC="0.2")
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", "2", "--force-cpu", "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "telemetry_worker.py")]
    res = subprocess.run(cmd, cwd=_REPO, timeout=240, capture_output=True,
                         text=True, env=env)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("telemetry OK") == 2, res.stdout
    assert res.stdout.count("heartbeat advanced") == 2, res.stdout
    for rank in (0, 1):
        path = tdir / f"rank-{rank}.jsonl"
        events = [json.loads(line) for line in open(path)]
        kinds = {e["kind"] for e in events}
        assert {"start", "step", "collective",
                "checkpoint_save"} <= kinds, (rank, kinds)
        assert all(e["rank"] == rank for e in events)
        trainer_steps = [e["step"] for e in events
                         if e["kind"] == "step" and e["executor"] == "Trainer"]
        assert trainer_steps == sorted(trainer_steps) and \
            len(trainer_steps) == 30
        colls = [e for e in events if e["kind"] == "collective"]
        assert all(e["nbytes"] > 0 and e["wall_ms"] >= 0 for e in colls)
        hb = json.load(open(tdir / f"heartbeat-{rank}.json"))
        assert hb["rank"] == rank and hb["step"] >= 26
