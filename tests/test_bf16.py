"""End-to-end bfloat16 coverage — the flagship dtype path (BASELINE config 2
is bf16 ResNet; reference AMP lists in python/mxnet/contrib/amp/lists/
symbol_fp16.py drive the same layers through fp16).

These tests exist because round 2 shipped "130 passed" while the bf16 fused
step was broken in two places (Pooling iinfo crash; conv transpose dtype
mismatch): no test cast a network.  Every case here casts to bfloat16 and
drives the same code path bench.py does.
"""
import ml_dtypes
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn

BF16 = ml_dtypes.bfloat16


def _tiny_convnet():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1), nn.BatchNorm(), nn.Activation("relu"),
            nn.MaxPool2D(2), nn.Conv2D(8, 3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"), nn.GlobalAvgPool2D(), nn.Dense(10))
    return net


def test_pooling_bf16_forward():
    # BENCH_r02 crash: Pooling picked the max identity via dtype.kind, which
    # is 'V' for ml_dtypes bfloat16.
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(BF16), dtype=BF16)
    for pool_type in ("max", "avg", "sum", "lp"):
        y = nd.Pooling(x, kernel=(2, 2), stride=(2, 2), pool_type=pool_type)
        assert y.dtype == BF16
        assert np.isfinite(y.asnumpy().astype(np.float32)).all()


def test_conv_bf16_grad():
    # conv transpose rule must see matching dtypes (the second r2 bf16 bug).
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(BF16), dtype=BF16)
    w = nd.array(np.random.rand(4, 3, 3, 3).astype(BF16), dtype=BF16)
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = nd.Convolution(x, w, kernel=(3, 3), num_filter=4, no_bias=True)
        loss = y.sum()
    loss.backward()
    assert x.grad.dtype == BF16
    assert w.grad.dtype == BF16
    assert np.isfinite(w.grad.asnumpy().astype(np.float32)).all()


def test_fused_step_bf16_convnet():
    """cast('bfloat16') conv+BN+pool net through the fused DataParallelStep:
    finite loss, weights stay bf16, loss decreases over a few steps."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    mx.random.seed(0)
    ctx = mx.current_context()
    net = _tiny_convnet()
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = DataParallelStep(
        net, loss_fn, mesh=local_mesh(devices=[ctx.jax_device]),
        optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})
    x = np.random.rand(8, 3, 16, 16).astype(BF16)
    y = np.random.randint(0, 10, 8).astype("float32")
    xb, yb = nd.array(x, ctx=ctx, dtype=BF16), nd.array(y, ctx=ctx)
    losses = [float(np.asarray(step.step(xb, yb))) for _ in range(8)]
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    step.sync_to_block()
    for name, p in net.collect_params().items():
        assert p.data().dtype == BF16, (name, p.data().dtype)


def test_fused_step_bf16_dp_sharded():
    """Same fused bf16 step over the full virtual 8-device DP mesh."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    mx.random.seed(0)
    net = _tiny_convnet()
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = DataParallelStep(net, loss_fn, mesh=local_mesh(), optimizer="sgd",
                            optimizer_params={"learning_rate": 0.05})
    x = np.random.rand(16, 3, 16, 16).astype(BF16)
    y = np.random.randint(0, 10, 16).astype("float32")
    loss = step.step(nd.array(x, dtype=BF16), nd.array(y))
    assert np.isfinite(float(np.asarray(loss)))


def test_eager_bf16_forward_backward():
    """Eager (non-fused) training step in bf16: the reference Trainer path."""
    mx.random.seed(0)
    net = _tiny_convnet()
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = nd.array(np.random.rand(4, 3, 16, 16).astype(BF16), dtype=BF16)
    y = nd.array(np.random.randint(0, 10, 4).astype("float32"))
    with autograd.record():
        out = net(x)
        loss = loss_fn(out, y)
    loss.backward()
    trainer.step(4)
    val = float(loss.mean().asnumpy().astype(np.float32))
    assert np.isfinite(val)


def test_softmax_output_bf16_label_grad():
    # the nn.py SoftmaxOutput backward must treat bf16 labels (numpy kind
    # 'V') as float labels, not fall into the integer/float0 branch.
    x = nd.array(np.random.rand(4, 10).astype(BF16), dtype=BF16)
    lab = nd.array(np.random.randint(0, 10, 4).astype(BF16), dtype=BF16)
    x.attach_grad()
    with autograd.record():
        y = nd.SoftmaxOutput(x, lab)
        s = y.sum()
    s.backward()
    assert np.isfinite(x.grad.asnumpy().astype(np.float32)).all()


def test_fp16_safe_accumulation():
    # MXNET_SAFE_ACCUMULATION: naive fp16 accumulation of 4096 ones stalls
    # at 2048 (fp16 integers are exact only to 2048; beyond, +1 rounds
    # away), while f32 accumulation gives exactly 4096 — which still fits
    # fp16.
    x = nd.array(np.ones((2, 4096), np.float16), dtype=np.float16)
    w = nd.array(np.ones((3, 4096), np.float16), dtype=np.float16)
    y = nd.FullyConnected(x, w, num_hidden=3, no_bias=True)
    v = y.asnumpy().astype(np.float64)
    np.testing.assert_allclose(v, np.full((2, 3), 4096.0), rtol=1e-3)


def test_hybridized_bf16_matches_eager():
    mx.random.seed(0)
    net = _tiny_convnet()
    net.initialize(mx.init.Xavier())
    net.cast("bfloat16")
    x = nd.array(np.random.rand(2, 3, 16, 16).astype(BF16), dtype=BF16)
    eager = net(x).asnumpy().astype(np.float32)
    net.hybridize()
    hybrid = net(x).asnumpy().astype(np.float32)
    np.testing.assert_allclose(eager, hybrid, rtol=2e-2, atol=2e-2)
