"""tools/_runner.py: the shared on-chip task runner's success/persist
contract (used by tools/relay_watch.py and tools/on_chip_suite.py).

A CPU-fallback measurement must never be recorded as an on-chip artifact
(r4 weak #1: the only BENCH artifact captured that round was a silent CPU
fallback), and a skipped consistency sweep must not count as done."""
import json
import os
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_REPO, "tools"))

import _runner  # noqa: E402


def _emit(payload):
    return [sys.executable, "-c",
            f"import json; print(json.dumps({payload!r}))"]


def _art(name):
    return os.path.join(_runner.ART, f"{name}.json")


def test_cpu_metric_not_persisted():
    ok, rec = _runner.run_task(
        "rt_cpu", _emit({"metric": "m", "value": 1, "platform": "cpu"}),
        {}, 60)
    assert ok is False and rec["rc"] == 0
    assert not os.path.exists(_art("rt_cpu"))


def test_tpu_metric_persisted():
    ok, _ = _runner.run_task(
        "rt_tpu", _emit({"metric": "m", "value": 2, "platform": "tpu"}),
        {}, 60)
    try:
        assert ok is True
        with open(_art("rt_tpu")) as f:
            assert json.load(f)["value"] == 2
    finally:
        if os.path.exists(_art("rt_tpu")):
            os.unlink(_art("rt_tpu"))


def test_device_key_guard():
    # bench_step.py tags "device" instead of "platform"
    ok, _ = _runner.run_task(
        "rt_dev", _emit({"metric": "m", "value": 3, "device": "cpu"}), {}, 60)
    assert ok is False
    assert not os.path.exists(_art("rt_dev"))


def test_skipped_sweep_fails():
    ok, _ = _runner.run_task("rt_skip", _emit({"skipped": True}), {}, 60)
    assert ok is False


def test_compared_sweep_passes():
    ok, _ = _runner.run_task(
        "rt_sweep", _emit({"skipped": False, "cases_compared": 10}), {}, 60)
    assert ok is True


def test_nonzero_rc_fails():
    ok, rec = _runner.run_task(
        "rt_rc", [sys.executable, "-c", "import sys; sys.exit(3)"], {}, 60)
    assert ok is False and rec["rc"] == 3


def test_validator_gates_success():
    ok, _ = _runner.run_task(
        "rt_val", [sys.executable, "-c", "print('no json')"], {}, 60,
        validator=lambda: False)
    assert ok is False
