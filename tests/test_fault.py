"""Chaos-path coverage for the fault-tolerance layer (ISSUE 1): the
MX_FAULT_SPEC harness, checkpoint integrity digests, fallback-to-older-step
restore, preemption handling, and the writer-thread lifecycle.

CPU-only and tier-1 fast: the two subprocess tests spawn ONE python each
(no gang); everything else runs in-process with the harness driven through
monkeypatched env.  Gang-level supervision lives in test_dist_launch.py.
"""
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, fault, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import AsyncCheckpointer

pytestmark = pytest.mark.chaos

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# spec grammar
# ---------------------------------------------------------------------------
def test_spec_grammar():
    faults = fault.parse_spec(
        "crash:step=30:rank=1:if-restart=0; slow-write:ms=500;"
        "torn-write:step=20:file=meta")
    assert [f.kind for f in faults] == ["crash", "slow-write", "torn-write"]
    assert faults[0].step == 30 and faults[0].rank == 1
    assert faults[0].if_restart == 0
    assert faults[1].ms == 500
    assert faults[2].file == "meta"
    assert fault.parse_spec("") == []


@pytest.mark.parametrize("bad", [
    "explode:step=1",          # unknown kind
    "crash:at=3",              # unknown key
    "crash:step=soon",         # non-integer
    "crash",                   # crash requires step=
    "slow-write:step=3",       # slow-write requires ms=
    "torn-write:step=3:file=rng",  # bad file target
    "oom",                     # oom requires step=
    "oom:ms=5",                # oom requires step=
])
def test_spec_rejects_bad_grammar(bad):
    with pytest.raises(MXNetError, match="MX_FAULT_SPEC"):
        fault.parse_spec(bad)


def test_oom_spec_grammar_and_qualifiers(monkeypatch):
    faults = fault.parse_spec("oom:step=3:rank=1")
    assert faults[0].kind == "oom" and faults[0].step == 3
    assert faults[0].rank == 1
    monkeypatch.setenv("MX_PROC_ID", "0")
    assert not faults[0].applies_here()


def test_on_dispatch_raises_resource_exhausted_at_step(monkeypatch):
    """The synthetic OOM spells RESOURCE_EXHAUSTED like PjRt's
    XlaRuntimeError, fires only at the named step, and only on the
    qualified rank."""
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=4")
    fault.on_dispatch(3)  # not yet
    with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
        fault.on_dispatch(4)
    fault.on_dispatch(5)  # one-shot trigger step, not a threshold
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=4:rank=1")
    monkeypatch.setenv("MX_PROC_ID", "0")
    fault.on_dispatch(4)  # gated off this rank: no-op


def test_injected_oom_routes_through_memwatch_match():
    """memwatch classifies the injected error exactly like a real OOM."""
    from mxnet_tpu import memwatch

    try:
        fault.parse_spec("oom:step=1")
    except MXNetError:
        pytest.fail("oom grammar must parse")
    exc = MXNetError("RESOURCE_EXHAUSTED: injected device OOM at step 1")
    assert memwatch.is_resource_exhausted(exc)
    assert not memwatch.is_resource_exhausted(ValueError("boom"))


def test_qualifiers_gate_by_rank_and_incarnation(monkeypatch):
    monkeypatch.setenv("MX_PROC_ID", "0")
    monkeypatch.setenv("MX_RESTART_COUNT", "1")
    assert not fault.parse_spec("crash:step=1:rank=1")[0].applies_here()
    assert fault.parse_spec("crash:step=1:rank=0")[0].applies_here()
    assert not fault.parse_spec("crash:step=1:if-restart=0")[0].applies_here()
    assert fault.parse_spec("crash:step=1:if-restart=1")[0].applies_here()
    # a crash gated off this rank/incarnation must be a no-op
    monkeypatch.setenv("MX_FAULT_SPEC", "crash:step=1:rank=1")
    fault.on_train_step(1)  # would os._exit(57) if it fired


# ---------------------------------------------------------------------------
# in-process training helpers
# ---------------------------------------------------------------------------
def _train_setup(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    X = np.random.randn(8, 4).astype(np.float32)
    Y = np.random.randn(8, 1).astype(np.float32)
    return net, trainer, X, Y


def _run_steps(net, trainer, X, Y, n, ckpt):
    loss_fn = gluon.loss.L2Loss()
    for _ in range(n):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(Y))
        loss.backward()
        trainer.step(8)
        ckpt.step(net, trainer=trainer)


def _truncate(path, frac=0.5):
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(int(size * frac))


# ---------------------------------------------------------------------------
# checkpoint integrity + fallback
# ---------------------------------------------------------------------------
def test_digests_recorded_in_meta(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 5, ckpt)
    ckpt.close()
    with open(tmp_path / "step-5" / "meta.json") as f:
        meta = json.load(f)
    assert sorted(meta["digests"]) == ["params.nd", "trainer.states"]
    assert all(len(d) == 64 for d in meta["digests"].values())


def test_torn_meta_falls_back_to_previous_step(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    _truncate(tmp_path / "step-10" / "meta.json")
    assert checkpoint.latest_valid_step(str(tmp_path)) == 5
    state = checkpoint.load_checkpoint_state(str(tmp_path))
    assert state["step"] == 5
    # restore() walks the same fallback — no crash on the torn dir
    net2, tr2, _, _ = _train_setup(seed=9)
    assert checkpoint.restore(str(tmp_path), net2, tr2) == 5


def test_truncated_params_digest_mismatch_falls_back(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    _truncate(tmp_path / "step-10" / "params.nd")
    # meta.json parses fine — only the digest check can catch this
    assert checkpoint.load_checkpoint_state(str(tmp_path))["step"] == 5


def test_all_checkpoints_corrupt_returns_none(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    _truncate(tmp_path / "step-5" / "meta.json")
    _truncate(tmp_path / "step-10" / "params.nd")
    assert checkpoint.load_checkpoint_state(str(tmp_path)) is None
    net2, tr2, _, _ = _train_setup(seed=9)
    assert checkpoint.restore(str(tmp_path), net2, tr2) == 0  # fresh start


def test_torn_latest_pointer_is_survivable(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    (tmp_path / "latest").write_text("1")  # torn: half of "10"
    assert checkpoint.load_checkpoint_state(str(tmp_path))["step"] == 10
    # step numbering must continue from the dirs, not reset via bad latest
    ck2 = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    assert ck2._step == 10
    ck2.close()


def test_explicit_step_demand_raises_on_corrupt(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    _truncate(tmp_path / "step-10" / "meta.json")
    assert checkpoint.load_checkpoint_state(str(tmp_path), step=5)["step"] == 5
    with pytest.raises(MXNetError, match="missing or corrupt"):
        checkpoint.load_checkpoint_state(str(tmp_path), step=10)


def test_save_now_never_evicts_scheduled_steps(tmp_path):
    """Off-cycle save_now (preemption) checkpoints must not count against
    `keep`: rotating a scheduled step away on one rank would make the
    gang's agreed restore(step=...) raise after a second preemption.  An
    off-cycle step is itself retained only until the next scheduled write
    supersedes it, and repeated save_now calls keep only the newest."""
    def dirs():
        return sorted((d for d in os.listdir(tmp_path)
                       if d.startswith("step-")),
                      key=lambda d: int(d.split("-")[1]))

    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=2)
    _run_steps(net, trainer, X, Y, 13, ckpt)  # scheduled: step-5, step-10
    ckpt.wait()
    assert ckpt.save_now(net, trainer=trainer) == 13
    ckpt.close()
    assert dirs() == ["step-5", "step-10", "step-13"]

    ck2 = AsyncCheckpointer(str(tmp_path), save_every=5, keep=2)
    _run_steps(net, trainer, X, Y, 1, ck2)  # second preemption at step 14
    assert ck2.save_now(net, trainer=trainer) == 14
    # the older off-cycle step-13 is gone, both scheduled steps survive
    assert dirs() == ["step-5", "step-10", "step-14"]
    _run_steps(net, trainer, X, Y, 1, ck2)  # step-15: scheduled write
    ck2.close()
    # the new scheduled step rotates 5 out and supersedes off-cycle 14
    assert dirs() == ["step-10", "step-15"]


def test_latest_valid_step_scheduled_only(tmp_path):
    """Gang resume agrees over SCHEDULED steps only: an off-cycle save_now
    step exists on one rank alone and must not become the agreed step."""
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=2)
    _run_steps(net, trainer, X, Y, 12, ckpt)  # scheduled 5, 10
    ckpt.wait()
    assert ckpt.save_now(net, trainer=trainer) == 12  # off-cycle
    ckpt.close()
    assert checkpoint.latest_valid_step(str(tmp_path)) == 12
    assert checkpoint.latest_valid_step(str(tmp_path), multiple_of=5) == 10


def test_explicit_resume_prunes_abandoned_timeline(tmp_path):
    """Resuming below an off-cycle preemption checkpoint abandons that
    timeline: the newer dir must be pruned, or rotation would delete the
    NEXT preemption save in its favor and a later crash would restore
    state this run never reached."""
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=2)
    _run_steps(net, trainer, X, Y, 12, ckpt)
    ckpt.wait()
    ckpt.save_now(net, trainer=trainer)  # preemption checkpoint step-12
    ckpt.close()
    # gang agreed on scheduled step 10; step-12 is an abandoned timeline
    ck2 = AsyncCheckpointer(str(tmp_path), save_every=5, keep=2,
                            initial_step=10)
    assert not (tmp_path / "step-12").exists()
    assert checkpoint.latest_valid_step(str(tmp_path)) == 10
    # second preemption at step 11: its save_now must survive as newest
    _run_steps(net, trainer, X, Y, 1, ck2)
    assert ck2.save_now(net, trainer=trainer) == 11
    ck2.close()
    assert checkpoint.latest_valid_step(str(tmp_path)) == 11


def test_agree_resume_step_single_process():
    assert checkpoint.agree_resume_step(17) == 17
    assert checkpoint.agree_resume_step(17, kv=None) == 17


# ---------------------------------------------------------------------------
# harness-driven corruption (MX_FAULT_SPEC)
# ---------------------------------------------------------------------------
def test_fault_spec_torn_write_then_fallback(tmp_path, monkeypatch):
    """The acceptance-criteria path: a checkpoint corrupted via
    MX_FAULT_SPEC=torn-write is skipped in favor of the previous valid
    step, with no crash in restore()."""
    monkeypatch.setenv("MX_FAULT_SPEC", "torn-write:step=10")
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=5, keep=3)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    monkeypatch.delenv("MX_FAULT_SPEC")
    # the harness published step-10 and THEN tore it in place
    assert (tmp_path / "step-10").is_dir()
    assert (tmp_path / "latest").read_text() == "10"
    assert checkpoint.latest_valid_step(str(tmp_path)) == 5
    net2, tr2, _, _ = _train_setup(seed=9)
    assert checkpoint.restore(str(tmp_path), net2, tr2) == 5


def test_fault_spec_slow_write(tmp_path, monkeypatch):
    monkeypatch.setenv("MX_FAULT_SPEC", "slow-write:ms=300")
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=1, keep=2)
    t0 = time.monotonic()
    _run_steps(net, trainer, X, Y, 1, ckpt)
    ckpt.close()
    assert time.monotonic() - t0 >= 0.3
    assert checkpoint.load_checkpoint_state(str(tmp_path))["step"] == 1


_SUBPROC_WORKER = """
import os, sys, time
sys.path.insert(0, {repo!r})
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, fault, gluon, nd

ckdir, mode = sys.argv[1], sys.argv[2]
mx.random.seed(0); np.random.seed(0)
net = gluon.nn.Dense(1); net.initialize(mx.init.Normal(0.5))
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {{"learning_rate": 0.05, "momentum": 0.9}})
loss_fn = gluon.loss.L2Loss()
X = np.random.randn(8, 4).astype(np.float32)
Y = np.random.randn(8, 1).astype(np.float32)
ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=3, keep=3)
if mode == "preempt":
    fault.install_preemption_handler(ckpt, net, trainer=trainer)
for i in range(12):
    with autograd.record():
        loss = loss_fn(net(nd.array(X)), nd.array(Y))
    loss.backward(); trainer.step(8)
    ckpt.step(net, trainer=trainer)
    if mode == "preempt" and i == 9:
        ckpt.wait()
        open(os.path.join(ckdir, "ready"), "w").close()
        while True:
            time.sleep(0.05)
ckpt.close()
print("done", flush=True)
"""


def _spawn_worker(tmp_path, mode, extra_env=None):
    script = tmp_path / "worker.py"
    script.write_text(_SUBPROC_WORKER.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    return subprocess.Popen(
        [sys.executable, str(script), str(tmp_path / "ck"), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_crash_mid_write_leaves_tmp_and_recovers(tmp_path):
    """crash-write:step=6 dies between the payload write and meta.json:
    the staging .tmp-6 dir survives, step-6 is never published, loads fall
    back to step-3, and the next checkpointer garbage-collects the tmp."""
    proc = _spawn_worker(tmp_path, "train",
                         {"MX_FAULT_SPEC": "crash-write:step=6"})
    out, err = proc.communicate(timeout=240)
    assert proc.returncode == fault.EXIT_INJECTED_CRASH, (out, err[-2000:])
    assert "injected crash mid-write of step 6" in out
    ckdir = str(tmp_path / "ck")
    leftovers = [d for d in os.listdir(ckdir) if d.startswith(".tmp-6")]
    assert leftovers, os.listdir(ckdir)
    assert not os.path.exists(os.path.join(ckdir, "step-6"))
    assert checkpoint.load_checkpoint_state(ckdir)["step"] == 3
    ck = AsyncCheckpointer(ckdir, save_every=3)  # GCs the leftover
    ck.close()
    assert not [d for d in os.listdir(ckdir) if d.startswith(".tmp-")]


def test_preemption_handler_final_checkpoint(tmp_path):
    """SIGTERM mid-run => one final synchronous checkpoint at the CURRENT
    step (not just the last save_every multiple) and exit EXIT_PREEMPTED."""
    proc = _spawn_worker(tmp_path, "preempt")
    ready = tmp_path / "ck" / "ready"
    deadline = time.monotonic() + 240
    while not ready.exists():
        assert proc.poll() is None, proc.communicate()
        assert time.monotonic() < deadline, "worker never became ready"
        time.sleep(0.1)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=60)
    assert proc.returncode == fault.EXIT_PREEMPTED, (out, err[-2000:])
    assert "final checkpoint at step 10" in out
    # step 10 is NOT a multiple of save_every=3 — only save_now wrote it
    state = checkpoint.load_checkpoint_state(str(tmp_path / "ck"))
    assert state["step"] == 10
    assert state["trainer"] is not None


# ---------------------------------------------------------------------------
# writer-thread lifecycle (satellite: close() after a writer error)
# ---------------------------------------------------------------------------
def test_close_shuts_writer_down_then_reraises(tmp_path):
    import shutil

    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path / "sub"), save_every=1)
    # break the directory out from under the writer
    shutil.rmtree(str(tmp_path / "sub"))
    (tmp_path / "sub").write_text("not a dir")
    _run_steps(net, trainer, X, Y, 1, ckpt)
    with pytest.raises(MXNetError, match="checkpoint writer failed"):
        ckpt.close()
    # the thread was still joined and the sentinel consumed
    assert not ckpt._writer.is_alive()
    # idempotent: a second close re-raises without hanging
    with pytest.raises(MXNetError, match="checkpoint writer failed"):
        ckpt.close()


def test_close_idempotent_on_success(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=2)
    _run_steps(net, trainer, X, Y, 2, ckpt)
    ckpt.close()
    ckpt.close()
    assert not ckpt._writer.is_alive()
    assert checkpoint.load_checkpoint_state(str(tmp_path))["step"] == 2
