"""Module system tests (reference behavioral spec:
tests/python/unittest/test_module.py; convergence pattern from
tests/python/train/)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd, io


def _toy_problem(n=256, seed=0):
    """Linearly separable 2-class problem."""
    rs = np.random.RandomState(seed)
    x = rs.randn(n, 8).astype(np.float32)
    w = rs.randn(8).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    return x, y


def _mlp_sym():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=2, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_module_fit_converges():
    x, y = _toy_problem()
    train = io.NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    # NB: SoftmaxOutput grads are per-row (summed over batch through the
    # weights), reference semantics — so lr is scaled for batch_size=32
    mod.fit(train, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.init.Xavier())
    train.reset()
    score = mod.score(train, "acc")
    assert dict(score)["accuracy"] > 0.9


def test_module_forward_predict_shapes():
    x, y = _toy_problem(64)
    it = io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
             for_training=False)
    mod.init_params(initializer=mx.init.Xavier())
    out = mod.predict(it)
    assert out.shape == (64, 2)
    probs = out.asnumpy()
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(64), rtol=1e-4)


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_problem(64)
    it = io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    prefix = str(tmp_path / "toy")
    mod.save_checkpoint(prefix, 3)

    mod2 = mx.mod.Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label,
              for_training=False)
    mod2.init_params()
    it.reset()
    batch = next(it)
    mod.forward(batch, is_train=False)
    it.reset()
    batch = next(it)
    mod2.forward(batch, is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy(),
                               mod2.get_outputs()[0].asnumpy(), rtol=1e-5)


def test_module_get_set_params():
    x, y = _toy_problem(32)
    it = io.NDArrayIter(x, y, batch_size=16)
    mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    arg, aux = mod.get_params()
    assert set(arg) == {"fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"}
    # perturb then restore
    orig = arg["fc1_weight"].asnumpy().copy()
    mod._exec.arg_dict["fc1_weight"]._set_data(
        nd.zeros(orig.shape)._data)
    mod.set_params(arg, aux)
    np.testing.assert_allclose(
        mod._exec.arg_dict["fc1_weight"].asnumpy(), orig)


def test_bucketing_module():
    """Shape-bucketed modules share parameters (reference:
    test_module.py test_bucket_module semantics)."""
    def sym_gen(seq_len):
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="fc_shared")
        out = sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})

    for key, n in ((8, 8), (8, 8), (8, 8)):
        batch = io.DataBatch(
            data=[nd.array(np.random.rand(4, n).astype(np.float32))],
            label=[nd.array(np.zeros(4, np.float32))],
            bucket_key=key,
            provide_data=[("data", (4, n))],
            provide_label=[("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    # switching to the same-key bucket reuses the module
    assert len(mod._buckets) == 1

    # a second bucket shares the fc weights
    batch = io.DataBatch(
        data=[nd.array(np.random.rand(4, 8).astype(np.float32))],
        label=[nd.array(np.zeros(4, np.float32))],
        bucket_key=8)
    mod.forward(batch, is_train=False)
    out = mod.get_outputs()[0]
    assert out.shape == (4, 4)


def test_module_input_grads():
    data = sym.Variable("data")
    out = sym.LinearRegressionOutput(sym.FullyConnected(
        data, num_hidden=1, name="fc"), name="lro")
    mod = mx.mod.Module(out, label_names=("lro_label",), context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 3))],
             label_shapes=[("lro_label", (2, 1))])
    mod.init_params(initializer=mx.init.One())
    mod.init_optimizer()
    batch = io.DataBatch(data=[nd.ones((2, 3))],
                         label=[nd.zeros((2, 1))])
    mod.forward_backward(batch)
    g = mod._exec.grad_dict["fc_weight"].asnumpy()
    assert g.shape == (1, 3)
    assert np.abs(g).sum() > 0


def test_monitor_collects_stats(caplog):
    import logging

    import mxnet_tpu as mx
    from mxnet_tpu import nd

    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    out = mx.sym.SoftmaxOutput(fc, label, name="softmax")
    mod = mx.mod.Module(out)
    rng = np.random.RandomState(0)
    X = rng.randn(32, 6).astype(np.float32)
    Y = rng.randint(0, 4, 32).astype(np.float32)
    it = mx.io.NDArrayIter(X, Y, batch_size=8)

    mon = mx.Monitor(interval=2, pattern=".*fc.*")
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.monitor"):
        mod.fit(it, num_epoch=1, monitor=mon,
                optimizer_params={"learning_rate": 0.1})
    msgs = [r.message for r in caplog.records
            if r.name == "mxnet_tpu.monitor"]
    assert any("fc_weight" in m for m in msgs), msgs
    assert any("fc_weight_grad" in m for m in msgs), msgs
    # pattern filtering: nothing outside fc*
    assert not any("softmax" in m for m in msgs)
    # manual tic/toc returns triples
    mon2 = mx.Monitor(interval=1)
    mod.install_monitor(mon2)
    mon2.tic()
    it.reset()
    batch = next(iter(it))
    mod.forward(batch, is_train=False)
    stats = mon2.toc()
    assert stats and all(len(t) == 3 for t in stats)


def test_monitor_with_bucketing_module(caplog):
    import logging

    import mxnet_tpu as mx

    sents = [[1, 2, 3, 1], [2, 3, 1, 2], [1, 2], [3, 1]] * 4
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=[2, 4],
                                   invalid_label=0)

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        emb = mx.sym.Embedding(data, input_dim=4, output_dim=4, name="emb")
        pred = mx.sym.FullyConnected(
            mx.sym.Reshape(emb, shape=(-1, 4)), num_hidden=4, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label, name="softmax"), \
            ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=4)
    mon = mx.Monitor(interval=1, pattern=".*pred.*")
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.monitor"):
        mod.fit(it, num_epoch=1, monitor=mon,
                eval_metric=mx.metric.Perplexity(ignore_label=None),
                optimizer_params={"learning_rate": 0.1})
    msgs = [r.message for r in caplog.records if r.name == "mxnet_tpu.monitor"]
    assert any("pred_weight" in m for m in msgs), msgs
    # idempotent install: one stat line per watched name per batch
    names = [m.split()[-2] for m in msgs]
    from collections import Counter

    per_batch = Counter(m.split()[1] + ":" + m.split()[-2] for m in msgs)
    assert max(per_batch.values()) <= 2  # at most once per bucket module
