"""NDArray semantics tests (reference model: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    x = nd.zeros((2, 3))
    assert x.shape == (2, 3)
    assert x.dtype == np.float32
    assert x.context == mx.cpu()
    np.testing.assert_array_equal(x.asnumpy(), np.zeros((2, 3), np.float32))

    y = nd.ones((4,), dtype="int32")
    assert y.dtype == np.int32
    assert y.sum().asscalar() == 4

    z = nd.full((2, 2), 7.5)
    assert z.asnumpy().flat[0] == 7.5

    a = nd.arange(0, 10, 2)
    np.testing.assert_array_equal(a.asnumpy(), np.arange(0, 10, 2, dtype=np.float32))


def test_array_roundtrip():
    src = np.random.randn(3, 4).astype(np.float32)
    x = nd.array(src)
    np.testing.assert_allclose(x.asnumpy(), src)
    # float64 downcasts to float32 like MXNet
    x64 = nd.array(np.random.randn(2).astype(np.float64))
    assert x64.dtype == np.float32


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((1 / a).asnumpy(), 1 / a.asnumpy())
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]])
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())
    np.testing.assert_allclose((a / b).asnumpy(), a.asnumpy() / b.asnumpy())


def test_inplace_mutation_versioning():
    a = nd.ones((2, 2))
    v0 = a.version
    a += 1
    assert a.version > v0
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))
    a[:] = 0
    np.testing.assert_allclose(a.asnumpy(), np.zeros((2, 2)))


def test_indexing():
    x = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_array_equal(x[1].asnumpy(), np.arange(24).reshape(2, 3, 4)[1])
    np.testing.assert_array_equal(x[:, 1].asnumpy(),
                                  np.arange(24).reshape(2, 3, 4)[:, 1])
    x[0, 0, 0] = 99
    assert x.asnumpy()[0, 0, 0] == 99


def test_iteration_protocol():
    """Plain-int indexing bounds-checks (jax clamps OOB gathers, which
    would make Python's legacy iteration spin forever), iteration yields
    first-dim rows, negative indices still work (reference: NDArray
    __getitem__ raises IndexError out of range)."""
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    rows = [r.asnumpy() for r in x]
    assert len(rows) == 2
    np.testing.assert_array_equal(rows[1], [3, 4, 5])
    with pytest.raises(IndexError):
        x[2]
    with pytest.raises(IndexError):
        x[-3]
    with pytest.raises(IndexError):
        x[5, 0]  # int inside a tuple key, any axis
    with pytest.raises(IndexError):
        x[0, 7]
    np.testing.assert_array_equal(x[-1].asnumpy(), [3, 4, 5])
    np.testing.assert_array_equal(x[1, 2].asnumpy(), 5)


def test_comparison():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_array_equal((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_array_equal((a <= b).asnumpy(), [1, 1, 0])


def test_reshape_transpose():
    x = nd.array(np.arange(12).reshape(3, 4))
    assert x.reshape(4, 3).shape == (4, 3)
    assert x.reshape((2, 6)).shape == (2, 6)
    assert x.reshape(-1, 2).shape == (6, 2)
    assert x.reshape(0, -1).shape == (3, 4)
    assert x.T.shape == (4, 3)
    assert x.transpose().shape == (4, 3)
    assert nd.transpose(x, axes=(1, 0)).shape == (4, 3)


def test_reduce_ops():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert x.sum().asscalar() == 66
    np.testing.assert_allclose(x.sum(axis=0).asnumpy(), x.asnumpy().sum(axis=0))
    np.testing.assert_allclose(nd.mean(x, axis=1).asnumpy(), x.asnumpy().mean(axis=1))
    np.testing.assert_allclose(nd.max(x).asnumpy(), 11)
    assert x.argmax().asscalar() == 11.0
    assert nd.argmax(x, axis=1).asnumpy().tolist() == [3, 3, 3]


def test_dot():
    a = nd.array(np.random.randn(3, 4).astype(np.float32))
    b = nd.array(np.random.randn(4, 5).astype(np.float32))
    np.testing.assert_allclose(nd.dot(a, b).asnumpy(),
                               a.asnumpy() @ b.asnumpy(), rtol=1e-5)
    c = nd.array(np.random.randn(2, 3, 4).astype(np.float32))
    d = nd.array(np.random.randn(2, 4, 5).astype(np.float32))
    np.testing.assert_allclose(nd.batch_dot(c, d).asnumpy(),
                               c.asnumpy() @ d.asnumpy(), rtol=1e-5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.concat(a, b, dim=1)
    assert c.shape == (2, 6)
    s = nd.split(c, num_outputs=2, axis=1)
    assert isinstance(s, list) and len(s) == 2
    np.testing.assert_allclose(s[0].asnumpy(), a.asnumpy())
    st = nd.stack(a, b, axis=0)
    assert st.shape == (2, 2, 3)


def test_elemwise_math():
    x = nd.array([0.5, 1.0, 2.0])
    np.testing.assert_allclose(nd.exp(x).asnumpy(), np.exp(x.asnumpy()), rtol=1e-6)
    np.testing.assert_allclose(nd.log(x).asnumpy(), np.log(x.asnumpy()), rtol=1e-6)
    np.testing.assert_allclose(nd.sqrt(x).asnumpy(), np.sqrt(x.asnumpy()), rtol=1e-6)
    np.testing.assert_allclose(nd.relu(nd.array([-1.0, 1.0])).asnumpy(), [0, 1])
    np.testing.assert_allclose(nd.sigmoid(nd.array([0.0])).asnumpy(), [0.5])
    np.testing.assert_allclose(nd.clip(x, 0.6, 1.5).asnumpy(), [0.6, 1.0, 1.5])


def test_take_onehot_where():
    w = nd.array(np.arange(10, dtype=np.float32).reshape(5, 2))
    idx = nd.array([0, 3], dtype="int32")
    np.testing.assert_allclose(nd.take(w, idx).asnumpy(), w.asnumpy()[[0, 3]])
    oh = nd.one_hot(nd.array([1, 2], dtype="int32"), 4)
    np.testing.assert_allclose(oh.asnumpy(), [[0, 1, 0, 0], [0, 0, 1, 0]])
    cond = nd.array([1.0, 0.0])
    out = nd.where(cond, nd.array([1.0, 1.0]), nd.array([2.0, 2.0]))
    np.testing.assert_allclose(out.asnumpy(), [1.0, 2.0])


def test_astype_copy_context():
    x = nd.ones((2, 2))
    y = x.astype("int32")
    assert y.dtype == np.int32
    z = x.copyto(mx.cpu())
    np.testing.assert_allclose(z.asnumpy(), x.asnumpy())
    w = x.as_in_context(mx.cpu())
    assert w.context == mx.cpu()


def test_bfloat16():
    x = nd.ones((4, 4), dtype="bfloat16")
    y = (x * 3).sum()
    assert y.asnumpy().astype(np.float32) == 48.0


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs.bin")
    a = nd.array(np.random.randn(3, 3).astype(np.float32))
    b = nd.ones((2,), dtype="int32")
    nd.save(f, {"a": a, "b": b})
    loaded = nd.load(f)
    np.testing.assert_allclose(loaded["a"].asnumpy(), a.asnumpy())
    np.testing.assert_array_equal(loaded["b"].asnumpy(), b.asnumpy())
    nd.save(f, [a, b])
    lst = nd.load(f)
    assert isinstance(lst, list) and len(lst) == 2


def test_random_ops():
    mx.random.seed(0)
    u = nd.random.uniform(0, 1, shape=(100,))
    assert 0 <= float(u.min().asscalar()) and float(u.max().asscalar()) <= 1
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(n.mean().asscalar())) < 0.2
    mx.random.seed(7)
    a = nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(7)
    b = nd.random.uniform(shape=(5,)).asnumpy()
    np.testing.assert_array_equal(a, b)
    r = nd.random.randint(0, 10, shape=(20,))
    assert r.dtype == np.int32


def test_waitall():
    x = nd.ones((10, 10))
    y = x * 2
    mx.nd.waitall()
    np.testing.assert_allclose(y.asnumpy(), 2 * np.ones((10, 10)))


def test_op_methods_via_getattr():
    x = nd.array([[1.0, -2.0], [3.0, -4.0]])
    np.testing.assert_allclose(x.relu().asnumpy(), [[1, 0], [3, 0]])
    np.testing.assert_allclose(x.square().asnumpy(), x.asnumpy() ** 2)
