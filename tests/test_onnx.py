"""ONNX interop: wire-format codec + export/import round trips.

Reference test strategy: tests/python-pytest/onnx/test_onnxruntime*.py and
test_models — full-model export→import→numerical-parity loops.  No onnx
wheel exists in this image, so parity is proven by round-tripping through
our own codec (mxnet_tpu/contrib/onnx/proto.py), which speaks the real
ModelProto wire format."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import onnx as onnx_mxnet
from mxnet_tpu.contrib.onnx import proto as P


# --------------------------------------------------------------------------
# codec
# --------------------------------------------------------------------------


def test_proto_attribute_roundtrip():
    cases = [("axis", -1), ("alpha", 0.25), ("mode", "constant"),
             ("pads", [0, 1, 2, 3]), ("scales", [1.0, 0.5]),
             ("names", ["a", "b"])]
    for name, val in cases:
        got_name, got = P.parse_attribute(P.make_attribute(name, val))
        assert got_name == name
        if isinstance(val, float):
            assert abs(got - val) < 1e-6
        elif isinstance(val, list) and isinstance(val[0], float):
            assert np.allclose(got, val)
        else:
            assert got == val


@pytest.mark.parametrize("dtype", ["float32", "float64", "int32", "int64",
                                   "uint8", "bool", "float16"])
def test_proto_tensor_roundtrip(dtype):
    rng = np.random.RandomState(0)
    arr = (rng.rand(3, 4) * 10).astype(dtype)
    parsed = P.parse_tensor(P.make_tensor("t", arr))
    assert parsed["name"] == "t"
    np.testing.assert_array_equal(parsed["array"], arr)


def test_proto_tensor_bfloat16():
    import ml_dtypes

    arr = np.arange(6, dtype=ml_dtypes.bfloat16).reshape(2, 3)
    parsed = P.parse_tensor(P.make_tensor("t", arr))
    assert parsed["data_type"] == P.BFLOAT16
    np.testing.assert_array_equal(
        parsed["array"].astype(np.float32), arr.astype(np.float32))


# --------------------------------------------------------------------------
# helpers
# --------------------------------------------------------------------------


def _fill_params(s, input_shapes, seed=0):
    rng = np.random.RandomState(seed)
    shapes, _, aux_shapes = s.infer_shape(**input_shapes)
    params = {}
    for name, shp in zip(s.list_arguments(), shapes):
        if name in input_shapes:
            continue
        params[name] = nd.array(rng.randn(*shp).astype("float32") * 0.1)
    for name, shp in zip(s.list_auxiliary_states(), aux_shapes):
        base = np.abs(rng.randn(*shp).astype("float32")) * 0.1
        params[name] = nd.array(base + (1.0 if "var" in name else 0.0))
    return params


def _forward(s, params, feeds):
    shapes = {k: v.shape for k, v in feeds.items()}
    ex = s.simple_bind(ctx=mx.cpu(), **shapes)
    for k, v in params.items():
        (ex.aux_dict if k in ex.aux_dict else ex.arg_dict)[k][:] = v
    for k, v in feeds.items():
        ex.arg_dict[k][:] = nd.array(v)
    return [o.asnumpy() for o in ex.forward(is_train=False)]


def _roundtrip(s, params, feeds, atol=1e-5):
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        onnx_mxnet.export_model(
            s, params, [feeds[k].shape for k in _data_names(s, params)],
            np.float32, path)
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
        y1 = _forward(s, params, feeds)
        y2 = _forward(sym2, {**arg2, **aux2}, feeds)
    assert len(y1) == len(y2)
    for a, b in zip(y1, y2):
        np.testing.assert_allclose(a, b, atol=atol, rtol=1e-5)


def _data_names(s, params):
    return [n for n in s.list_arguments() if n not in params]


# --------------------------------------------------------------------------
# export/import round trips
# --------------------------------------------------------------------------


@pytest.mark.smoke
def test_conv_bn_pool_fc_roundtrip():
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                        name="conv1")
    b = sym.BatchNorm(c, name="bn1")
    a = sym.Activation(b, act_type="relu", name="relu1")
    p = sym.Pooling(a, kernel=(2, 2), stride=(2, 2), pool_type="max",
                    name="pool1")
    f = sym.FullyConnected(p, num_hidden=10, name="fc1")
    s = sym.softmax(f, name="sm")
    feeds = {"data": np.random.RandomState(1).rand(2, 3, 8, 8)
             .astype("float32")}
    _roundtrip(s, _fill_params(s, {"data": (2, 3, 8, 8)}), feeds)


def test_elemwise_concat_clip_roundtrip():
    x = sym.Variable("x")
    a = sym.clip(x * 2.0 + 1.0, a_min=-1.0, a_max=1.0, name="cl")
    b = sym.LeakyReLU(x - 0.5, act_type="leaky", slope=0.1, name="lr")
    s = sym.Concat(a, b, dim=1, name="cat")
    feeds = {"x": np.random.RandomState(2).randn(2, 4).astype("float32")}
    _roundtrip(s, {}, feeds)


def test_reshape_transpose_reduce_roundtrip():
    x = sym.Variable("x")
    r = sym.Reshape(x, shape=(0, -1), name="rs")
    t = sym.transpose(r, axes=(1, 0), name="tr")
    s = sym.sum(t, axis=0, keepdims=False, name="sm")
    feeds = {"x": np.random.RandomState(3).rand(2, 3, 4).astype("float32")}
    _roundtrip(s, {}, feeds)


def test_global_pool_dropout_flatten_roundtrip():
    data = sym.Variable("data")
    c = sym.Convolution(data, kernel=(1, 1), num_filter=4, name="c")
    g = sym.Pooling(c, pool_type="avg", global_pool=True, name="gap")
    fl = sym.Flatten(g, name="fl")
    dp = sym.Dropout(fl, p=0.5, name="dp")  # identity at inference
    s = sym.FullyConnected(dp, num_hidden=3, name="fc")
    feeds = {"data": np.random.RandomState(4).rand(2, 2, 5, 5)
             .astype("float32")}
    _roundtrip(s, _fill_params(s, {"data": (2, 2, 5, 5)}), feeds)


def test_split_multi_output_roundtrip():
    x = sym.Variable("x")
    parts = sym.SliceChannel(x, num_outputs=2, axis=1, name="sp")
    s = sym.Group([parts[0] * 2.0, parts[1] + 1.0])
    feeds = {"x": np.random.RandomState(5).rand(2, 4).astype("float32")}
    _roundtrip(s, {}, feeds)


def test_fix_gamma_exported_as_ones():
    """fix_gamma=True (op default) must export scale=1 regardless of the
    stored gamma array — the kernel ignores it, so the file must too."""
    data = sym.Variable("data")
    s = sym.BatchNorm(sym.Convolution(data, kernel=(1, 1), num_filter=2,
                                      no_bias=True, name="c"),
                      fix_gamma=True, name="bn")
    params = _fill_params(s, {"data": (1, 2, 3, 3)})
    params["bn_gamma"][:] = nd.array(np.full((2,), 7.0, np.float32))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        onnx_mxnet.export_model(s, params, [(1, 2, 3, 3)], np.float32, path)
        with open(path, "rb") as f:
            graph = P.parse_model(f.read())["graph"]
        gamma = [t for t in graph["initializer"] if t["name"] == "bn_gamma"]
        np.testing.assert_array_equal(gamma[0]["array"],
                                      np.ones((2,), np.float32))


def test_unsupported_op_raises_with_name():
    x = sym.Variable("x")
    s = sym.Correlation(x, x, name="corr")
    with pytest.raises(MXNetError, match="Correlation"):
        onnx_mxnet.export_model(s, _fill_params(s, {"x": (1, 2, 6, 6)}),
                                [(1, 2, 6, 6)], np.float32,
                                os.path.join(tempfile.mkdtemp(), "m.onnx"))


def test_deconvolution_roundtrip():
    data = sym.Variable("data")
    dc = sym.Deconvolution(data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           adj=(1, 1), num_filter=4, no_bias=False,
                           name="dc")
    s = sym.Activation(dc, act_type="relu", name="r")
    feeds = {"data": np.random.RandomState(9).rand(2, 3, 5, 5)
             .astype("float32")}
    _roundtrip(s, _fill_params(s, {"data": (2, 3, 5, 5)}), feeds)


def test_get_model_metadata():
    x = sym.Variable("x")
    s = sym.FullyConnected(x, num_hidden=3, name="fc")
    params = _fill_params(s, {"x": (2, 5)})
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        onnx_mxnet.export_model(s, params, [(2, 5)], np.float32, path)
        meta = onnx_mxnet.get_model_metadata(path)
    assert meta["input_tensor_data"] == [("x", (2, 5))]
    assert meta["output_tensor_data"][0][0] == "fc"
    assert tuple(meta["output_tensor_data"][0][1]) == (2, 3)


def test_import_to_gluon():
    data = sym.Variable("data")
    f = sym.FullyConnected(data, num_hidden=4, name="fc1")
    s = sym.Activation(f, act_type="tanh", name="t1")
    params = _fill_params(s, {"data": (2, 3)})
    feeds = {"data": np.random.RandomState(6).rand(2, 3).astype("float32")}
    y_ref = _forward(s, params, feeds)[0]
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        onnx_mxnet.export_model(s, params, [(2, 3)], np.float32, path)
        net = onnx_mxnet.import_to_gluon(path)
    y = net(nd.array(feeds["data"])).asnumpy()
    np.testing.assert_allclose(y, y_ref, atol=1e-6)


def test_mini_transformer_roundtrip():
    """Transformer-family ops through real ONNX: Embedding->Gather (int32
    graph input, params keep float32), LayerNorm decomposition, per-
    position FC (MatMul path), batch_dot with transpose_b, scaled softmax,
    slice_axis, reduction."""
    V, D, T, B = 16, 8, 6, 2
    tokens = sym.Variable("tokens", dtype="int32")
    emb = sym.Embedding(tokens, input_dim=V, output_dim=D, name="emb")
    ln = sym.LayerNorm(emb, name="ln")
    q = sym.FullyConnected(ln, num_hidden=D, flatten=False, name="q")
    k = sym.FullyConnected(ln, num_hidden=D, flatten=False, name="k")
    v = sym.FullyConnected(ln, num_hidden=D, flatten=False, name="v")
    scores = sym.batch_dot(q, k, transpose_b=True, name="scores")
    att = sym.softmax(scores * (1.0 / np.sqrt(D)), axis=-1, name="att")
    ctxv = sym.batch_dot(att, v, name="ctx")
    first = sym.slice_axis(ctxv, axis=1, begin=0, end=3, name="sl")
    s = sym.sum(first, axis=-1, keepdims=False, name="out")

    rng = np.random.RandomState(0)
    shapes, _, _ = s.infer_shape(tokens=(B, T))
    params = {}
    for name, shp in zip(s.list_arguments(), shapes):
        if name == "tokens":
            continue
        params[name] = nd.array(rng.randn(*shp).astype("float32") * 0.3)
    tok = rng.randint(0, V, (B, T)).astype("int32")

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        onnx_mxnet.export_model(s, params, [(B, T)], np.int32, path)
        meta = onnx_mxnet.get_model_metadata(path)
        assert meta["input_tensor_data"] == [("tokens", (B, T))]
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)

    def fwd(S, pr):
        ex = S.simple_bind(ctx=mx.cpu(), tokens=(B, T))
        for kk, vv in pr.items():
            (ex.aux_dict if kk in ex.aux_dict else ex.arg_dict)[kk][:] = vv
        ex.arg_dict["tokens"][:] = nd.array(tok, dtype="int32")
        return ex.forward(is_train=False)[0].asnumpy()

    y1, y2 = fwd(s, params), fwd(sym2, {**arg2, **aux2})
    np.testing.assert_allclose(y1, y2, atol=1e-5, rtol=1e-5)


def test_where_broadcast_axis_expand_dims_roundtrip():
    x = sym.Variable("x")
    m = sym.expand_dims(x, axis=1, name="ed")          # (B,1,C)
    bcast = sym.broadcast_axis(m, axis=1, size=3, name="ba")  # (B,3,C)
    cond = sym._greater_scalar(bcast, scalar=0.0)
    s = sym.where(cond, bcast, bcast * 0.1, name="out")
    feeds = {"x": np.random.RandomState(8).randn(2, 4).astype("float32")}
    _roundtrip(s, {}, feeds)


def test_bert_small_roundtrip():
    """Full BERT (our flagship family) through real ONNX: the traced
    graph contains Embedding, slice_like (position table), LayerNorm,
    per-position FCs, split-heads Reshapes with -4 codes, Pallas
    _contrib_flash_attention (exported as its dense decomposition), and
    gelu (Erf decomposition) — all at static export shapes."""
    import mxnet_tpu as mx2
    from mxnet_tpu.models import bert_small

    net = bert_small()
    net.initialize(mx2.init.Normal(0.02))
    tok = np.random.RandomState(0).randint(0, 512, (2, 12)).astype("int32")
    y_ref = net(nd.array(tok, dtype="int32")).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "bert"))
        path = onnx_mxnet.export_model(
            os.path.join(d, "bert-symbol.json"),
            os.path.join(d, "bert-0000.params"),
            [(2, 12)], np.int32, os.path.join(d, "bert.onnx"))
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    ex = sym2.simple_bind(ctx=mx.cpu(), data=(2, 12))
    for kk, vv in {**arg2, **aux2}.items():
        (ex.aux_dict if kk in ex.aux_dict else ex.arg_dict)[kk][:] = vv
    ex.arg_dict["data"][:] = nd.array(tok, dtype="int32")
    y2 = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(y_ref, y2, atol=2e-5, rtol=1e-4)


def test_seq2seq_transformer_roundtrip():
    """Encoder-decoder Transformer through real ONNX: multi-input export
    (dict shapes), padding masks via not_equal/broadcast_like, the ops-
    built causal tril (ones_like/makediag/cumsum/where), shared
    embeddings, and the dense flash-attention decomposition where the
    encoder takes the unmasked path."""
    import mxnet_tpu as mx2
    from mxnet_tpu.models.transformer import Transformer

    net = Transformer(vocab_size=32, units=16, hidden_size=32,
                      num_layers=2, num_heads=2, max_length=24,
                      tie_embeddings=False)
    net.initialize(mx2.init.Xavier())
    rng = np.random.RandomState(0)
    src = rng.randint(3, 32, (2, 7)).astype("int32")
    tgt = rng.randint(3, 32, (2, 5)).astype("int32")
    y_ref = net(nd.array(src, dtype="int32"),
                nd.array(tgt, dtype="int32")).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "tf"), input_names=("src", "tgt"))
        path = onnx_mxnet.export_model(
            os.path.join(d, "tf-symbol.json"),
            os.path.join(d, "tf-0000.params"),
            {"src": (2, 7), "tgt": (2, 5)}, np.int32,
            os.path.join(d, "tf.onnx"))
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
    ex = sym2.simple_bind(ctx=mx.cpu(), src=(2, 7), tgt=(2, 5))
    for kk, vv in {**arg2, **aux2}.items():
        (ex.aux_dict if kk in ex.aux_dict else ex.arg_dict)[kk][:] = vv
    ex.arg_dict["src"][:] = nd.array(src, dtype="int32")
    ex.arg_dict["tgt"][:] = nd.array(tgt, dtype="int32")
    y2 = ex.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(y_ref, y2, atol=1e-5, rtol=1e-4)


def test_bert_import_to_gluon():
    """ONNX BERT -> SymbolBlock via import_to_gluon: parameter binding by
    initializer name at model scale, int32 token inputs."""
    import mxnet_tpu as mx2
    from mxnet_tpu.models import bert_small

    net = bert_small(num_layers=1)
    net.initialize(mx2.init.Normal(0.02))
    tok = np.random.RandomState(3).randint(0, 512, (2, 8)).astype("int32")
    y_ref = net(nd.array(tok, dtype="int32")).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "b"))
        path = onnx_mxnet.export_model(
            os.path.join(d, "b-symbol.json"),
            os.path.join(d, "b-0000.params"),
            [(2, 8)], np.int32, os.path.join(d, "b.onnx"))
        g = onnx_mxnet.import_to_gluon(path)
    y2 = g(nd.array(tok, dtype="int32"))
    y2 = (y2[0] if isinstance(y2, (list, tuple)) else y2).asnumpy()
    np.testing.assert_allclose(y_ref, y2, atol=2e-5, rtol=1e-4)


@pytest.mark.slow
def test_resnet18_roundtrip():
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=47)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).rand(1, 3, 64, 64)
                 .astype("float32"))
    y_ref = net(x).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "r18"))
        path = onnx_mxnet.export_model(
            os.path.join(d, "r18-symbol.json"),
            os.path.join(d, "r18-0000.params"),
            [(1, 3, 64, 64)], np.float32, os.path.join(d, "r18.onnx"))
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
        y2 = _forward(sym2, {**arg2, **aux2},
                      {"data": x.asnumpy()})[0]
    np.testing.assert_allclose(y_ref, y2, atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_mobilenet_v2_roundtrip():
    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.mobilenet_v2_0_25(classes=12)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(1).rand(1, 3, 64, 64)
                 .astype("float32"))
    y_ref = net(x).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        net.export(os.path.join(d, "mb2"))
        path = onnx_mxnet.export_model(
            os.path.join(d, "mb2-symbol.json"),
            os.path.join(d, "mb2-0000.params"),
            [(1, 3, 64, 64)], np.float32, os.path.join(d, "mb2.onnx"))
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
        y2 = _forward(sym2, {**arg2, **aux2}, {"data": x.asnumpy()})[0]
    np.testing.assert_allclose(y_ref, y2, atol=1e-4, rtol=1e-4)


# --------------------------------------------------------------------------
# importer diagnostics (ADVICE r5): malformed/unsupported nodes must raise
# descriptive MXNetError, not import silently-wrong graphs or bare KeyError
# --------------------------------------------------------------------------


def _import_raw(nodes, inputs, outputs, initializers=()):
    from mxnet_tpu.contrib.onnx.onnx2mx import import_onnx_model

    graph = P.make_graph(nodes, "g", inputs, outputs,
                         initializers=initializers)
    return import_onnx_model(P.make_model(graph))


def test_split_uneven_sizes_raises():
    node = P.make_node("Split", ["x"], ["a", "b"], name="sp",
                       axis=1, split=[1, 3])
    with pytest.raises(MXNetError, match=r"uneven split sizes \[1, 3\]"):
        _import_raw(
            [node],
            [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                      (2, 4))],
            [P.make_tensor_value_info("a", P.np_to_onnx_dtype(np.float32),
                                      None),
             P.make_tensor_value_info("b", P.np_to_onnx_dtype(np.float32),
                                      None)])


def test_split_even_sizes_imports():
    node = P.make_node("Split", ["x"], ["a", "b"], name="sp",
                       axis=1, split=[2, 2])
    sym2, arg2, _aux = _import_raw(
        [node],
        [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                  (2, 4))],
        [P.make_tensor_value_info("a", P.np_to_onnx_dtype(np.float32), None),
         P.make_tensor_value_info("b", P.np_to_onnx_dtype(np.float32), None)])
    x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    outs = _forward(sym2, arg2, {"x": x})
    np.testing.assert_allclose(outs[0], x[:, :2])
    np.testing.assert_allclose(outs[1], x[:, 2:])


def test_split_opset13_uneven_input_sizes_raises():
    # opset 13: split sizes arrive as a second INPUT, not an attribute —
    # the uneven-split guard must catch that form too
    node = P.make_node("Split", ["x", "sp_sizes"], ["a", "b"], name="sp",
                       axis=1)
    with pytest.raises(MXNetError, match=r"uneven split sizes \[1, 3\]"):
        _import_raw(
            [node],
            [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                      (2, 4))],
            [P.make_tensor_value_info("a", P.np_to_onnx_dtype(np.float32),
                                      None),
             P.make_tensor_value_info("b", P.np_to_onnx_dtype(np.float32),
                                      None)],
            initializers=[P.make_tensor(
                "sp_sizes", np.array([1, 3], dtype=np.int64))])


def test_split_opset13_even_input_sizes_imports():
    node = P.make_node("Split", ["x", "sp_sizes"], ["a", "b"], name="sp",
                       axis=1)
    sym2, arg2, _aux = _import_raw(
        [node],
        [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                  (2, 4))],
        [P.make_tensor_value_info("a", P.np_to_onnx_dtype(np.float32), None),
         P.make_tensor_value_info("b", P.np_to_onnx_dtype(np.float32), None)],
        initializers=[P.make_tensor(
            "sp_sizes", np.array([2, 2], dtype=np.int64))])
    x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    outs = _forward(sym2, arg2, {"x": x})
    np.testing.assert_allclose(outs[0], x[:, :2])
    np.testing.assert_allclose(outs[1], x[:, 2:])


def test_split_opset13_runtime_input_sizes_still_imports():
    # split sizes fed by a graph input (not statically known) can't be
    # validated — the legacy even-split import must keep working
    node = P.make_node("Split", ["x", "sp_sizes"], ["a", "b"], name="sp",
                       axis=1)
    sym2, arg2, _aux = _import_raw(
        [node],
        [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                  (2, 4)),
         P.make_tensor_value_info("sp_sizes", P.np_to_onnx_dtype(np.int64),
                                  (2,))],
        [P.make_tensor_value_info("a", P.np_to_onnx_dtype(np.float32), None),
         P.make_tensor_value_info("b", P.np_to_onnx_dtype(np.float32), None)])
    x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
    outs = _forward(sym2, arg2, {"x": x})
    np.testing.assert_allclose(outs[0], x[:, :2])
    np.testing.assert_allclose(outs[1], x[:, 2:])


def test_constant_nontensor_value_raises():
    node = P.make_node("Constant", [], ["c"], name="k", value_float=1.5)
    add = P.make_node("Add", ["x", "c"], ["y"], name="add")
    with pytest.raises(MXNetError, match=r"Constant node 'c'.*value_float"):
        _import_raw(
            [node, add],
            [P.make_tensor_value_info("x", P.np_to_onnx_dtype(np.float32),
                                      (2,))],
            [P.make_tensor_value_info("y", P.np_to_onnx_dtype(np.float32),
                                      None)])


# ---------------------------------------------------------------------------
# quantized-graph export (docs/PRECISION.md §ONNX; ISSUE 15 satellite)
# ---------------------------------------------------------------------------
def _quantized_mlp():
    from mxnet_tpu import gluon
    from mxnet_tpu.contrib.quantization import quantize_net

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
            gluon.nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    x = np.random.RandomState(0).rand(8, 8).astype(np.float32)
    qnet = quantize_net(net, calib_data=[nd.array(x)], calib_mode="naive")
    return qnet, x


def _run_qdq_graph(graph, x):
    """Numpy interpretation of the exported QDQ node set — the oracle
    the file's bytes are checked against."""
    vals = {"data": x}
    for t in graph["initializer"]:
        vals[t["name"]] = t["array"]
    for n in graph["node"]:
        i = [vals[k] for k in n["input"]]
        op = n["op_type"]
        if op == "QuantizeLinear":
            vals[n["output"][0]] = np.clip(
                np.round(i[0] / i[1]), -128, 127).astype(np.int8)
        elif op == "DequantizeLinear":
            vals[n["output"][0]] = i[0].astype(np.float32) * i[1]
        elif op == "Gemm":
            w = i[1].T if n["attrs"].get("transB") else i[1]
            vals[n["output"][0]] = i[0] @ w + i[2]
        elif op == "MatMul":
            vals[n["output"][0]] = i[0] @ i[1]
        elif op == "Add":
            vals[n["output"][0]] = i[0] + i[1]
        elif op == "Relu":
            vals[n["output"][0]] = np.maximum(i[0], 0)
        elif op == "Flatten":
            vals[n["output"][0]] = i[0].reshape(i[0].shape[0], -1)
        else:
            raise AssertionError(f"unexpected op {op}")
    return vals[graph["output"][0]["name"]]


def test_export_quantized_qdq_structure_and_numerics(tmp_path):
    """ACCEPTANCE satellite: the QDQ export carries QuantizeLinear /
    DequantizeLinear + int8 weight initializers, and a numpy replay of
    the file's graph matches the int8 net within one scale step."""
    qnet, x = _quantized_mlp()
    qref = qnet(nd.array(x)).asnumpy()
    p = onnx_mxnet.export_quantized_net(qnet, (8, 8),
                                        str(tmp_path / "q.onnx"))
    model = P.parse_model(open(p, "rb").read())
    g = model["graph"]
    ops = [n["op_type"] for n in g["node"]]
    assert ops.count("QuantizeLinear") == 2       # one per quantized layer
    assert ops.count("DequantizeLinear") == 4     # activation + weight
    assert ops.count("Gemm") == 2 and "Relu" in ops
    int8_inits = [t for t in g["initializer"]
                  if t["array"].dtype == np.int8 and t["array"].ndim == 2]
    assert len(int8_inits) == 2, "weights must persist as int8"
    out = _run_qdq_graph(g, x)
    # QDQ adds bias in f32 where our kernel folds it in int32 units:
    # agreement to ~1 accumulator ulp, not bitwise
    np.testing.assert_allclose(out, qref, atol=1e-2)


def test_export_quantized_dequant_fallback_roundtrips(tmp_path):
    """The documented dequantize-fallback is plain opset-11 and
    round-trips through this package's own importer: the re-imported
    gluon net tracks the int8 net within activation-quantization
    error."""
    qnet, x = _quantized_mlp()
    qref = qnet(nd.array(x)).asnumpy()
    p = onnx_mxnet.export_quantized_net(qnet, (8, 8),
                                        str(tmp_path / "qd.onnx"),
                                        mode="dequant")
    model = P.parse_model(open(p, "rb").read())
    ops = [n["op_type"] for n in model["graph"]["node"]]
    assert "QuantizeLinear" not in ops  # pure f32 surface
    gnet = onnx_mxnet.import_to_gluon(p)
    out = gnet(nd.array(x)).asnumpy()
    np.testing.assert_allclose(out, qref, atol=5e-2)


def test_export_quantized_qdq_requires_calibrated_scales(tmp_path):
    from mxnet_tpu import gluon
    from mxnet_tpu.contrib.quantization import quantize_net

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    qnet = quantize_net(net, calib_mode="none")
    with pytest.raises(MXNetError, match="calib_mode='none'"):
        onnx_mxnet.export_quantized_net(qnet, (2, 8),
                                        str(tmp_path / "x.onnx"))
    # the dequantize-fallback has no activation scales to bake: fine
    p = onnx_mxnet.export_quantized_net(qnet, (2, 8),
                                        str(tmp_path / "x.onnx"),
                                        mode="dequant")
    assert os.path.exists(p)
    with pytest.raises(MXNetError, match="mode"):
        onnx_mxnet.export_quantized_net(qnet, (2, 8),
                                        str(tmp_path / "y.onnx"),
                                        mode="qlinear")
