"""Multi-replica serving router (ISSUE 17; docs/SERVING.md §Front
door).

Covers: portfile discovery (torn files skipped), session affinity,
least-outstanding dispatch, replica-death failover (connection error →
mark dead, retry elsewhere, session re-pins), graceful drain/undrain
through the router, HTTP error passthrough, and one end-to-end
ReplicaServer round-trip over a REAL engine (sampling defaults applied
at the HTTP layer, /statusz, backpressure 503).

The fleet tests run against fake no-jax workers — plain
``http.server`` loops that echo tokens and record what they saw — so
failover/affinity logic is exercised without ever compiling a model.
"""
import json
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from mxnet_tpu.serving import (Router, discover_replicas,
                               serve_portfile_path)

PAD, BOS, EOS = 0, 1, 2


# ---------------------------------------------------------------------------
# fake no-jax worker
# ---------------------------------------------------------------------------
class _FakeWorker:
    """A replica-shaped HTTP server with no engine behind it: /generate
    echoes ``[rank, *prompt]``, /healthz follows the draining flag, and
    every request body lands in ``self.seen``."""

    def __init__(self, directory, rank):
        self.rank = rank
        self.seen = []
        self.draining = False
        worker = self

        class H(BaseHTTPRequestHandler):
            def _send(self, code, payload):
                raw = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):  # noqa: N802
                if self.path.startswith("/healthz"):
                    ok = not worker.draining
                    self._send(200 if ok else 503,
                               {"ok": ok, "draining": worker.draining,
                                "rank": worker.rank})
                else:
                    self._send(200, {"rank": worker.rank})

            def do_POST(self):  # noqa: N802
                if self.path.startswith("/admin/"):
                    worker.draining = self.path.endswith("/drain")
                    self._send(200, {"draining": worker.draining,
                                     "rank": worker.rank})
                    return
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                worker.seen.append(body)
                if body.get("boom"):
                    self._send(400, {"error": "synthetic validation",
                                     "rank": worker.rank})
                    return
                self._send(200, {
                    "request_id": body.get("request_id", "r"),
                    "tokens": [worker.rank] + list(body["prompt"]),
                    "finish_reason": "length",
                    "replica": worker.rank,
                    "session": body.get("session")})

            def log_message(self, fmt, *args):
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()
        self.portfile = serve_portfile_path(directory, rank)
        tmp = self.portfile + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rank": rank, "host": "127.0.0.1",
                       "port": self.port, "pid": os.getpid(),
                       "time": 0.0}, f)
        os.replace(tmp, self.portfile)

    def kill(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def fleet(tmp_path):
    d = str(tmp_path)
    workers = [_FakeWorker(d, r) for r in range(2)]
    # long health period: tests drive refresh()/dispatch() directly so
    # probe timing never races the assertions
    router = Router(d, port=0, health_sec=60.0)
    yield d, workers, router
    router.stop()
    for w in workers:
        try:
            w.kill()
        except Exception:
            pass


def _post(port, body, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.load(r)


# ---------------------------------------------------------------------------
# discovery
# ---------------------------------------------------------------------------
def test_portfile_discovery_skips_torn_files(tmp_path):
    d = str(tmp_path)
    _FakeWorker(d, 0)
    _FakeWorker(d, 3)
    with open(os.path.join(d, "serve-port-9.json"), "w") as f:
        f.write('{"rank": 9, "po')  # torn mid-write
    with open(os.path.join(d, "metrics-port-0.json"), "w") as f:
        f.write("{}")  # wrong family, ignored
    got = discover_replicas(d)
    assert sorted(r["rank"] for r in got) == [0, 3]
    assert all(r["host"] == "127.0.0.1" and r["port"] > 0 for r in got)


# ---------------------------------------------------------------------------
# affinity + balancing
# ---------------------------------------------------------------------------
def test_session_affinity_pins_conversation(fleet):
    """ACCEPTANCE: every request of a session lands on ONE replica (its
    prefix-cache pages stay hot there); session-free requests spread by
    least-outstanding."""
    _, workers, router = fleet
    router.start()
    outs = [_post(router.port, {"prompt": [5, 6], "session": "conv-a"})
            for _ in range(4)]
    homes = {o["routed_to"] for o in outs}
    assert len(homes) == 1
    home = homes.pop()
    assert all(o["replica"] == home for o in outs)
    assert len(workers[home].seen) == 4
    # a different session may pin elsewhere, but is itself sticky
    outs_b = [_post(router.port, {"prompt": [7], "session": "conv-b"})
              for _ in range(3)]
    assert len({o["routed_to"] for o in outs_b}) == 1


def test_sessionless_requests_balance_by_outstanding(fleet):
    _, workers, router = fleet
    # drive dispatch() directly and fake an in-flight imbalance
    with router._lock:
        router._replicas[0]["outstanding"] = 5
    code, payload = router.dispatch({"prompt": [3]})
    assert code == 200 and payload["routed_to"] == 1
    with router._lock:
        router._replicas[1]["outstanding"] = 9
    code, payload = router.dispatch({"prompt": [3]})
    assert code == 200 and payload["routed_to"] == 0


# ---------------------------------------------------------------------------
# failover + drain
# ---------------------------------------------------------------------------
def test_replica_death_fails_over_and_repins_session(fleet):
    """ACCEPTANCE: a replica dropping mid-conversation is marked dead on
    the connection error; the request retries on the survivor and the
    session re-pins there — the client only sees tokens from its new
    home."""
    d, workers, router = fleet
    router.start()
    first = _post(router.port, {"prompt": [4], "session": "s"})
    home = first["routed_to"]
    workers[home].kill()
    out = _post(router.port, {"prompt": [4, 4], "session": "s"})
    other = 1 - home
    assert out["routed_to"] == other
    assert out["tokens"] == [other, 4, 4]
    assert router.failovers == 1
    snap = router.statusz()
    dead = [r for r in snap["replicas"] if r["rank"] == home][0]
    assert dead["healthy"] is False
    # the re-pinned session keeps landing on the survivor
    again = _post(router.port, {"prompt": [4], "session": "s"})
    assert again["routed_to"] == other
    # both replicas down: an honest 503, not a hang
    workers[other].kill()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(router.port, {"prompt": [4]})
    assert ei.value.code == 503
    assert "no healthy replica" in json.load(ei.value)["error"]


def test_vanished_portfile_drops_replica_on_refresh(fleet):
    _, workers, router = fleet
    assert sorted(r["rank"] for r in router.replicas()) == [0, 1]
    workers[1].kill()
    os.unlink(workers[1].portfile)
    router.refresh()
    assert [r["rank"] for r in router.replicas()] == [0]
    code, payload = router.dispatch({"prompt": [8]})
    assert code == 200 and payload["routed_to"] == 0


def test_drain_undrain_through_router(fleet):
    """Graceful drain: the drained replica 503s /healthz and leaves
    rotation (health probe respects the flag); undrain brings it
    straight back — the rescale/hot-swap maintenance loop."""
    _, workers, router = fleet
    router.start()
    assert router.set_drain(0, True)
    assert workers[0].draining is True
    router._probe({"rank": 0, "url": f"http://127.0.0.1:{workers[0].port}"})
    for _ in range(4):
        out = _post(router.port, {"prompt": [2]})
        assert out["routed_to"] == 1
    assert router.set_drain(0, False)
    router._probe({"rank": 0, "url": f"http://127.0.0.1:{workers[0].port}"})
    live = {r["rank"]: r for r in router.replicas()}
    assert live[0]["healthy"] and not live[0]["draining"]
    assert not router.set_drain(7, True), "unknown rank refused"


def test_http_errors_pass_through_without_failover(fleet):
    """A replica's 4xx verdict is the CLIENT's problem: no failover, no
    dead-marking, the code and body relay verbatim."""
    _, workers, router = fleet
    code, payload = router.dispatch({"prompt": [1], "boom": True})
    assert code == 400
    assert payload["error"] == "synthetic validation"
    assert router.failovers == 0
    assert all(r["healthy"] for r in router.replicas())


# ---------------------------------------------------------------------------
# end-to-end over a real engine
# ---------------------------------------------------------------------------
def test_replica_server_end_to_end(tmp_path):
    """One ReplicaServer over a real (untrained, tiny) engine: HTTP
    /generate matches an in-process serve() bitwise, MX_SERVE_TEMPERATURE
    fleet defaults apply at the HTTP layer only, /statusz surfaces the
    engine snapshot, and a full queue answers 503."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.transformer import Transformer
    from mxnet_tpu.serving import (ReplicaServer, Request, ServingEngine,
                                   TransformerAdapter)

    mx.random.seed(0)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=48, dropout=0.0)
    net.initialize(mx.init.Xavier())

    def eng():
        return ServingEngine(TransformerAdapter(net, src_max_len=6),
                             slots=2, page_size=4, max_len=12,
                             stream_every=4, sampling=True)

    prompt = [5, 6, 7]
    want = eng().serve([Request(prompt, max_new_tokens=6, bos_id=BOS,
                                eos_id=EOS, request_id="w")])["w"]
    rep = ReplicaServer(eng(), bos_id=BOS, eos_id=EOS, port=0,
                        directory=str(tmp_path)).start()
    try:
        out = _post(rep.port, {"prompt": prompt, "max_new_tokens": 6})
        assert out["tokens"] == [int(t) for t in want]
        assert out["finish_reason"] == "length"
        assert out["generation"] == 0 and out["ttft_ms"] > 0
        # the portfile advertises this exact server
        got = discover_replicas(str(tmp_path))
        assert [(r["rank"], r["port"]) for r in got] == [(rep.rank,
                                                          rep.port)]
        # fleet-wide sampling default applied at the HTTP layer: same
        # request decodes DIFFERENTLY (and the body never said so)
        os.environ["MX_SERVE_TEMPERATURE"] = "0.9"
        try:
            hot = _post(rep.port, {"prompt": prompt, "max_new_tokens": 6,
                                   "seed": 3})
            assert hot["tokens"] != out["tokens"]
        finally:
            del os.environ["MX_SERVE_TEMPERATURE"]
        snap = _post_get(rep.port, "/statusz")
        assert snap["rank"] == rep.rank
        assert snap["engine"]["slots"] == 2
        assert snap["engine"]["sampling"] is True
    finally:
        rep.stop()
    assert not os.path.exists(serve_portfile_path(str(tmp_path),
                                                  rep.rank))


def _post_get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30.0) as r:
        return json.load(r)
