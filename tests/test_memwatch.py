"""Memory & compile observability (ISSUE 8, docs/OBSERVABILITY.md
§Memory): the memwatch sampler (on/off/no-op, category attribution,
sliding-window leak detector), per-executable compile events at every
jit construction site with restart-stable fingerprints, the
RESOURCE_EXHAUSTED post-mortem path (in-process + the launch.py
supervisor echo, no-jax and real-gang shapes), the tools/mem_report.py
CLI contract, and the observe-don't-perturb parity guarantee."""
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.context import normalize_memory_stats

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MEM_REPORT = os.path.join(_REPO, "tools", "mem_report.py")


@pytest.fixture
def tele():
    telemetry.reset()
    memwatch.reset()
    yield telemetry
    telemetry.reset()
    memwatch.reset()


def _events(tmp_path, rank=0):
    telemetry.flush()
    return [json.loads(line)
            for line in open(telemetry.event_path(str(tmp_path), rank))]


def _toy_step(lr=0.05):
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    return DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                            optimizer="sgd",
                            optimizer_params={"learning_rate": lr})


def _run_steps(step, n, seed=0, dim=4):
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(n):
        x = nd.array(rng.rand(8, dim).astype(np.float32))
        y = nd.array(rng.rand(8, dim).astype(np.float32))
        losses.append(float(step.step(x, y)))
    step.drain()
    return losses


# ---------------------------------------------------------------------------
# sampler: on / off / no-op
# ---------------------------------------------------------------------------
def test_disabled_without_recorder(tele):
    assert not memwatch.enabled()
    assert memwatch.sample("test") is None
    memwatch.on_step(1)  # must not raise or record
    assert memwatch.summary()["samples"] == 0


def test_kill_switch(tele, tmp_path, monkeypatch):
    """MX_MEMWATCH=0 kills the WHOLE subsystem: no mem samples, no
    compile events (and no analysis retrace behind them), no OOM census
    — with the telemetry recorder itself still on."""
    monkeypatch.setenv("MX_MEMWATCH", "0")
    tele.enable(str(tmp_path))
    assert not memwatch.enabled()
    step = _toy_step()
    _run_steps(step, 2)
    assert memwatch.note_compile("X", ("parts",), 0.1) is None
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=3")
    with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
        _run_steps(step, 1)
    kinds = {e["kind"] for e in _events(tmp_path)}
    assert not kinds & {"mem", "compile", "oom_report"}, kinds
    assert kinds & {"step"}  # the recorder itself kept running
    assert memwatch.summary()["samples"] == 0


def test_sampler_emits_categorized_mem_events(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_MEMWATCH_EVERY", "1")
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 3)
    mems = [e for e in _events(tmp_path) if e["kind"] == "mem"]
    assert len(mems) == 3
    last = mems[-1]
    assert last["site"] == "step"
    cats = last["categories"]
    # the registered providers attributed the step's buffers
    assert cats["params"]["nbytes"] > 0
    assert cats["optimizer"]["nbytes"] > 0
    assert last["live_bytes"] >= cats["params"]["nbytes"]
    assert last["watermark_bytes"] >= last["live_bytes"] or \
        last["watermark_bytes"] >= mems[0]["live_bytes"]
    s = memwatch.summary()
    assert s["samples"] == 3 and s["watermark_bytes"] > 0


def test_category_attribution_exact(tele, tmp_path, monkeypatch):
    """Registered param arrays land in 'params', byte-exact; unclaimed
    arrays fall into 'other'."""
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 1)
    c = memwatch.census()
    want = sum(int(a.nbytes) for a in step.params.values())
    assert c["categories"]["params"]["nbytes"] == want
    assert c["categories"]["params"]["count"] == len(step.params)
    assert "other" in c["categories"]  # RNG key etc. are unclaimed


def test_sampling_cadence(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_MEMWATCH_EVERY", "3")
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 6)
    mems = [e for e in _events(tmp_path) if e["kind"] == "mem"]
    # DataParallelStep.step + AsyncCheckpointer-free loop: exactly one
    # on_step observation per step -> samples at steps 3 and 6
    assert len(mems) == 2


def test_checkpoint_boundary_always_samples(tele, tmp_path, monkeypatch):
    from mxnet_tpu.checkpoint import AsyncCheckpointer

    monkeypatch.setenv("MX_MEMWATCH_EVERY", "1000")  # step cadence: never
    tele.enable(str(tmp_path / "t"))
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    net(nd.array(np.ones((2, 4), np.float32)))  # resolve deferred init
    ckpt = AsyncCheckpointer(str(tmp_path / "ckpt"), save_every=2)
    ckpt.step(net)
    ckpt.step(net)  # enqueues a save
    ckpt.close()
    mems = [e for e in _events(tmp_path / "t") if e["kind"] == "mem"]
    assert any(e["site"] == "checkpoint_save" for e in mems)


# ---------------------------------------------------------------------------
# leak detector
# ---------------------------------------------------------------------------
class _Bucket:
    def __init__(self):
        self.arrs = []


def test_leak_detector_fires_and_names_category(tele, tmp_path,
                                                monkeypatch, caplog):
    import gc

    import jax.numpy as jnp

    gc.collect()  # stale arrays from earlier tests must not free mid-run
    monkeypatch.setenv("MX_MEMWATCH_LEAK_WINDOW", "4")
    tele.enable(str(tmp_path))
    bucket = _Bucket()
    memwatch.register("inflight", bucket, lambda b: b.arrs)
    for _i in range(6):
        bucket.arrs.append(jnp.ones((64 * 1024,), jnp.float32))  # 256KB
        with caplog.at_level("WARNING", logger="mxnet_tpu.memwatch"):
            memwatch.sample("test")
    leaks = [e for e in _events(tmp_path) if e["kind"] == "mem_leak"]
    assert len(leaks) == 1  # rate-limited: one warning while growing
    assert leaks[0]["category"] == "inflight"
    assert leaks[0]["growth_bytes"] > 3 * 256 * 1024 - 1
    assert any("top-growing category: inflight" in r.message
               for r in caplog.records)
    s = memwatch.summary()
    assert s["leak"]["active"] and s["leak"]["category"] == "inflight"
    # growth stops -> detector re-arms (active flag drops)
    for _i in range(4):
        memwatch.sample("test")
    assert not memwatch.summary()["leak"]["active"]


def test_leak_detector_silent_on_steady_state(tele, tmp_path, monkeypatch):
    import gc

    import jax.numpy as jnp

    gc.collect()
    monkeypatch.setenv("MX_MEMWATCH_LEAK_WINDOW", "4")
    tele.enable(str(tmp_path))
    bucket = _Bucket()
    bucket.arrs.append(jnp.ones((64 * 1024,), jnp.float32))
    memwatch.register("inflight", bucket, lambda b: b.arrs)
    for _i in range(8):  # steady: same arrays every sample
        memwatch.sample("test")
    assert not [e for e in _events(tmp_path) if e["kind"] == "mem_leak"]
    assert not memwatch.summary()["leak"]["active"]


# ---------------------------------------------------------------------------
# compile events: one per cache entry at every jit site
# ---------------------------------------------------------------------------
def _compiles(tmp_path, site=None):
    evs = [e for e in _events(tmp_path) if e["kind"] == "compile"]
    return [e for e in evs if site is None or e["site"] == site]


def test_data_parallel_compile_event_once(tele, tmp_path):
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 3)
    comps = _compiles(tmp_path, "data_parallel")
    assert len(comps) == 1, comps
    ev = comps[0]
    assert ev["executor"] == step._tele_name
    assert len(ev["fingerprint"]) == 16
    int(ev["fingerprint"], 16)  # hex
    assert ev["wall_ms"] > 0
    # cost analysis captured on this jax (soft: presence asserted because
    # this environment exposes it; fields are best-effort by contract)
    assert ev.get("arg_bytes", 0) > 0
    _run_steps(step, 2)  # steady state: NO re-emission
    assert len(_compiles(tmp_path, "data_parallel")) == 1


def test_fused_updater_compile_event_once(tele, tmp_path):
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.optimizer.fused import FusedUpdater

    tele.enable(str(tmp_path))
    opt = opt_mod.SGD(learning_rate=0.1, momentum=0.9)
    upd = FusedUpdater(opt)
    w = nd.array(np.ones((8,), np.float32))
    g = nd.array(np.ones((8,), np.float32))
    upd.apply([(0, g, w)])
    upd.apply([(0, g, w)])
    comps = _compiles(tmp_path, "fused")
    assert len(comps) == 1, comps
    assert comps[0]["executor"] == "FusedUpdater:SGD"
    assert comps[0]["n_params"] == 1


def test_kvstore_psum_compile_event_once(tele, tmp_path):
    from mxnet_tpu import kvstore

    tele.enable(str(tmp_path))
    kv = kvstore.create("device")
    kv.init(3, nd.zeros((16,)))
    for _ in range(2):
        vals = [nd.array(np.ones((16,), np.float32), ctx=mx.cpu(i))
                for i in range(2)]
        kv.push(3, vals)
    comps = _compiles(tmp_path, "kvstore")
    assert len(comps) == 1, comps
    assert comps[0]["executor"] == "KVStore.device_allreduce"
    assert comps[0]["ndev"] == 2


def test_cached_op_compile_event_per_signature(tele, tmp_path):
    tele.enable(str(tmp_path))
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    net(nd.array(np.ones((2, 8), np.float32)))
    net(nd.array(np.ones((2, 8), np.float32)))  # cached: no re-emission
    assert len(_compiles(tmp_path, "cached_op")) == 1
    # a new input signature is a new executable -> second compile event
    net(nd.array(np.ones((5, 8), np.float32)))
    comps = _compiles(tmp_path, "cached_op")
    assert len(comps) == 2
    assert comps[0]["fingerprint"] != comps[1]["fingerprint"]


_FP_SCRIPT = r"""
import json, os, tempfile
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, local_mesh
d = tempfile.mkdtemp()
telemetry.enable(d)
mx.random.seed(0)
net = gluon.nn.Dense(4)
net.initialize(mx.init.Xavier())
step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                        optimizer="sgd",
                        optimizer_params={"learning_rate": 0.05})
x = nd.array(np.ones((8, 4), np.float32))
y = nd.array(np.ones((8, 4), np.float32))
float(step.step(x, y))
step.drain(); telemetry.flush()
evs = [json.loads(l) for l in open(telemetry.event_path(d, 0))]
print([e["fingerprint"] for e in evs if e["kind"] == "compile"][0])
"""


def test_fingerprint_stable_across_process_restart():
    """Acceptance: the same program in two separate processes maps to the
    SAME fingerprint (the AOT-cache key contract) — structural identity
    only, no object ids.  The two restarts run concurrently: the test
    pays one jax-import wall, not two (tier-1 budget)."""
    env = dict(os.environ)
    env.pop("MX_TELEMETRY_DIR", None)
    procs = [subprocess.Popen([sys.executable, "-c", _FP_SCRIPT],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True,
                              env=env, cwd=_REPO) for _ in range(2)]
    fps = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, (out, err)
        fps.append(out.strip().splitlines()[-1])
    assert fps[0] == fps[1] and len(fps[0]) == 16


# ---------------------------------------------------------------------------
# OOM post-mortem
# ---------------------------------------------------------------------------
def test_oom_injection_emits_report_and_reraises(tele, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=2")
    tele.enable(str(tmp_path))
    step = _toy_step()
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(8, 4).astype(np.float32))
    y = nd.array(rng.rand(8, 4).astype(np.float32))
    float(step.step(x, y))  # step 1: clean
    with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
        step.step(x, y)  # step 2: injected OOM at dispatch
    evs = _events(tmp_path)
    ooms = [e for e in evs if e["kind"] == "oom_report"]
    assert len(ooms) == 1
    ev = ooms[0]
    assert ev["step"] == 2
    assert ev["executor"] == step._tele_name
    assert ev["largest_category"] in ev["categories"]
    assert ev["inflight_depth"] >= 0
    assert ev["watermark_bytes"] > 0
    # top-executables ranking drawn from the compile registry
    assert any(t["executor"] == step._tele_name
               for t in ev["top_executables"])


def test_oom_report_emitted_once(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=1; oom:step=2")
    tele.enable(str(tmp_path))
    step = _toy_step()
    x = nd.array(np.ones((8, 4), np.float32))
    y = nd.array(np.ones((8, 4), np.float32))
    for _ in range(2):
        with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
            step.step(x, y)
    assert len([e for e in _events(tmp_path)
                if e["kind"] == "oom_report"]) == 1


def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "launch_for_memwatch_test", os.path.join(_REPO, "tools",
                                                 "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_supervisor_echoes_oom_post_mortem_no_jax(tmp_path, capsys):
    """The launch.py death diagnosis echoes a rank's oom_report (largest
    category, watermark, inflight depth) next to the flight tail —
    covered here with a synthetic stream so the supervisor's reader needs
    no jax."""
    launch = _load_launch()
    lines = [
        {"t": 1.0, "kind": "step", "rank": 0, "step": 3, "wall_ms": 5.0},
        {"t": 1.1, "kind": "oom_report", "rank": 0, "executor": "X",
         "step": 3, "watermark_bytes": 512 * 1024 * 1024,
         "live_bytes": 200 * 1024 * 1024,
         "categories": {"params": 120 * 1024 * 1024,
                        "other": 80 * 1024 * 1024},
         "largest_category": "params", "inflight_depth": 2,
         "top_executables": [{"executor": "DataParallelStep:Dense#1",
                              "fingerprint": "ab12cd34ef56ab12",
                              "temp_bytes": 64 * 1024 * 1024}]},
    ]
    with open(tmp_path / "rank-0.jsonl", "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    monitor = launch._HeartbeatMonitor(
        1, {"MX_TELEMETRY_DIR": str(tmp_path)})
    monitor.diagnose()
    err = capsys.readouterr().err
    assert "rank 0 OOM post-mortem (step 3)" in err
    assert "largest live-array category params" in err
    assert "watermark 536.9MB" in err
    assert "inflight depth 2" in err
    assert "DataParallelStep:Dense#1[ab12cd34ef56ab12]" in err


@pytest.mark.dist
@pytest.mark.slow
@pytest.mark.chaos
def test_gang_oom_post_mortem_in_supervisor_diagnosis(tmp_path):
    """Acceptance: injected oom:step=N in a 2-rank gang yields an
    oom_report in the supervisor's death diagnosis naming the largest
    live-array category."""
    tdir = tmp_path / "telemetry"
    env = dict(os.environ, MX_TELEMETRY_DIR=str(tdir),
               MX_TELEMETRY_FLUSH_SEC="0.2", MX_HEARTBEAT_SEC="0.5",
               MX_MEMWATCH_EVERY="1",
               MX_FAULT_SPEC="oom:step=3:rank=1")
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", "2", "--force-cpu", "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "oom_worker.py")]
    res = subprocess.run(cmd, cwd=_REPO, timeout=240, capture_output=True,
                         text=True, env=env)
    assert res.returncode != 0  # the injected rank died
    # the worker's own traceback names the synthetic OOM
    assert "RESOURCE_EXHAUSTED" in res.stderr
    # supervisor echo: the post-mortem with the largest category named
    assert "rank 1 OOM post-mortem (step 3)" in res.stderr, \
        res.stderr[-3000:]
    assert "largest live-array category" in res.stderr
    # and the stream itself carries the machine-readable report
    events = [json.loads(line) for line in open(tdir / "rank-1.jsonl")]
    ooms = [e for e in events if e["kind"] == "oom_report"]
    assert len(ooms) == 1 and ooms[0]["step"] == 3
    assert ooms[0]["largest_category"] in ooms[0]["categories"]
    # the healthy rank recorded mem samples (watchdog at every-step)
    mems = [json.loads(line) for line in open(tdir / "rank-0.jsonl")
            if '"mem"' in line]
    assert any(e.get("kind") == "mem" for e in mems)
    # mem_report flags the OOM from the same streams
    rep = subprocess.run(
        [sys.executable, _MEM_REPORT, str(tdir), "--json"],
        capture_output=True, text=True, timeout=60)
    assert rep.returncode == 3
    obj = json.loads(rep.stdout)
    assert any(a.startswith("oom: rank 1") for a in obj["anomalies"])


# ---------------------------------------------------------------------------
# tools/mem_report.py CLI
# ---------------------------------------------------------------------------
def _write_mem_stream(directory, rank, totals, leak_events=0,
                      compile_events=(), oom=False):
    lines = []
    t = 1000.0
    for i, total in enumerate(totals):
        lines.append({
            "t": t + i, "kind": "mem", "rank": rank, "site": "step",
            "step": i + 1, "live_bytes": total, "live_count": 4,
            "watermark_bytes": max(totals[:i + 1]),
            "categories": {"params": {"count": 2, "nbytes": total // 2},
                           "other": {"count": 2,
                                     "nbytes": total - total // 2}}})
    for _ in range(leak_events):
        lines.append({"t": t + 99, "kind": "mem_leak", "rank": rank,
                      "category": "other", "growth_bytes": 1 << 20,
                      "window": 4, "total_bytes": totals[-1]})
    for c in compile_events:
        lines.append(dict({"t": t, "kind": "compile", "rank": rank}, **c))
    if oom:
        lines.append({"t": t + 100, "kind": "oom_report", "rank": rank,
                      "step": 7, "largest_category": "params",
                      "categories": {"params": 100}, "watermark_bytes": 200,
                      "live_bytes": 150, "inflight_depth": 1})
    with open(os.path.join(str(directory), f"rank-{rank}.jsonl"), "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


def _report(directory, *args):
    return subprocess.run(
        [sys.executable, _MEM_REPORT, str(directory), *args],
        capture_output=True, text=True, timeout=60)


def test_mem_report_clean_run_exits_zero(tmp_path):
    _write_mem_stream(tmp_path, 0, [1000] * 8, compile_events=[
        {"executor": "DataParallelStep:Dense#1",
         "fingerprint": "ab12cd34ef56ab12", "site": "data_parallel",
         "wall_ms": 900.0, "flops": 924.0, "arg_bytes": 428,
         "out_bytes": 164}])
    _write_mem_stream(tmp_path, 1, [990] * 8)
    res = _report(tmp_path, "--window", "4")
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "no anomalies detected" in res.stdout
    assert "executable cost table" in res.stdout
    assert "ab12cd34ef56ab12" in res.stdout


def test_mem_report_exits_three_on_seeded_leak(tmp_path):
    # strictly monotonic growth above the 64KB floor across the window
    _write_mem_stream(tmp_path, 0,
                      [1 << 20, 2 << 20, 3 << 20, 4 << 20, 5 << 20])
    res = _report(tmp_path, "--window", "4", "--json")
    assert res.returncode == 3, (res.stdout, res.stderr)
    rep = json.loads(res.stdout)
    assert rep["per_rank"]["0"]["leak"]["verdict"] == "leak"
    assert rep["per_rank"]["0"]["leak"]["category"] in ("params", "other")
    assert any(a.startswith("leak: rank 0") for a in rep["anomalies"])
    # human rendering names the verdict too
    txt = _report(tmp_path, "--window", "4")
    assert txt.returncode == 3
    assert "ANOMALIES" in txt.stdout and "leak" in txt.stdout


def test_mem_report_recorded_leak_event_counts(tmp_path):
    # flat trailing window, but the run recorded a mem_leak live (the
    # leak crashed/flattened before the end): still a leak verdict
    _write_mem_stream(tmp_path, 0, [1000] * 6, leak_events=1)
    res = _report(tmp_path, "--window", "4", "--json")
    assert res.returncode == 3
    rep = json.loads(res.stdout)
    assert rep["per_rank"]["0"]["leak"]["verdict"] == "leak"
    assert rep["per_rank"]["0"]["recorded_leak_events"] == 1


def test_mem_report_json_schema_and_watermarks(tmp_path):
    _write_mem_stream(tmp_path, 0, [500, 900, 700], oom=True)
    res = _report(tmp_path, "--json")
    rep = json.loads(res.stdout)
    assert rep["num_ranks"] == 1
    r0 = rep["per_rank"]["0"]
    assert r0["samples"] == 3
    assert r0["watermark_bytes"] == 900
    assert r0["categories_last"]["params"] == 350
    assert r0["peak_category_bytes"]["params"] == 450
    assert rep["ooms"][0]["largest_category"] == "params"
    assert res.returncode == 3  # the OOM is an anomaly


def test_mem_report_empty_dir_exits_two(tmp_path):
    res = _report(tmp_path)
    assert res.returncode == 2


# ---------------------------------------------------------------------------
# satellites: normalized memory_stats + profiler plumb
# ---------------------------------------------------------------------------
def test_context_memory_stats_normalized_cpu_fallback():
    stats = mx.cpu(0).memory_stats()
    assert set(stats) == {"bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit", "available"}
    assert stats["available"] is False  # XLA:CPU: no allocator stats
    assert normalize_memory_stats(None)["available"] is False
    norm = normalize_memory_stats({"bytes_in_use": 5, "bytes_limit": 10})
    assert norm == {"bytes_in_use": 5, "peak_bytes_in_use": 5,
                    "bytes_limit": 10, "available": True}
    # util.get_gpu_memory keeps working on the normalized schema
    free, limit = mx.util.get_gpu_memory()
    assert free == 0 and limit == 0


def test_profiler_memory_plumb(tele):
    """Satellite: record_op's memory field is no longer dead —
    profile_memory plumbs memwatch.peak_bytes() through timed_call and
    dumps() surfaces it."""
    from mxnet_tpu import profiler

    import jax.numpy as jnp

    profiler.reset_stats()
    profiler.set_config(profile_memory=True)
    try:
        keep = profiler.timed_call("AllocOp",
                                   lambda: jnp.ones((1024,), jnp.float32))
        rows = json.loads(profiler.dumps(format="json"))
        assert rows[0]["name"] == "AllocOp"
        assert rows[0]["peak_mem_bytes"] > 0
        table = profiler.dumps()
        assert "Peak(MB)" in table
        del keep
    finally:
        profiler.set_config(profile_memory=False)
        profiler.reset_stats()
    # without the flag the column stays absent (back-compat)
    profiler.record_op("X", 0.001)
    assert "Peak(MB)" not in profiler.dumps()
    assert "peak_mem_bytes" not in json.loads(
        profiler.dumps(format="json"))[0]
    profiler.reset_stats()


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
def test_prometheus_gains_mem_gauges(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_MEMWATCH_EVERY", "1")
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 2)
    path = telemetry.export_prometheus(str(tmp_path / "m.prom"))
    text = open(path).read()
    assert "mx_mem_samples_total" in text
    assert "mx_mem_watermark_bytes" in text
    assert 'mx_mem_category_bytes{rank="0",category="params"}' in text
    assert "mx_mem_compile_total" in text
    assert text.rstrip().endswith("# EOF")


def test_chrome_trace_gains_memory_counter_track(tele, tmp_path,
                                                 monkeypatch):
    monkeypatch.setenv("MX_MEMWATCH_EVERY", "1")
    tele.enable(str(tmp_path))
    step = _toy_step()
    _run_steps(step, 2)
    out = telemetry.export_chrome_trace(str(tmp_path))
    evs = json.load(open(out))["traceEvents"]
    counters = [e for e in evs if e["ph"] == "C" and e["name"] == "memory"]
    assert counters, "mem events must render as ph-C counter tracks"
    assert "params" in counters[-1]["args"]


# ---------------------------------------------------------------------------
# observe, don't perturb
# ---------------------------------------------------------------------------
def _train_losses_and_weights(tmp_path, tag):
    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path / tag))
    step = _toy_step()
    losses = _run_steps(step, 5)
    step.sync_to_block()
    weights = [p.data().asnumpy().copy()
               for p in step.block.collect_params().values()]
    return losses, weights


def test_memwatch_does_not_perturb_training(tele, tmp_path, monkeypatch):
    """Acceptance: losses/weights bitwise unchanged with memwatch
    sampling every step vs MX_MEMWATCH=0."""
    monkeypatch.setenv("MX_MEMWATCH", "1")
    monkeypatch.setenv("MX_MEMWATCH_EVERY", "1")
    on_losses, on_weights = _train_losses_and_weights(tmp_path, "on")
    assert memwatch.summary()["samples"] >= 5
    monkeypatch.setenv("MX_MEMWATCH", "0")
    off_losses, off_weights = _train_losses_and_weights(tmp_path, "off")
    assert memwatch.summary()["samples"] == 0
    assert on_losses == off_losses
    for a, b in zip(on_weights, off_weights):
        assert np.array_equal(a, b)
