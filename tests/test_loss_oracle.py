"""Gluon loss blocks vs the torch oracle (reference: gluon/loss.py).

Same rationale as tests/test_nn_oracle.py: losses are formula contracts
(reduction conventions, logit vs prob inputs, margin definitions) that
loss-descent tests can't distinguish — pin them externally.  MXNet
losses reduce with MEAN over non-batch axes per sample (no batch mean),
so torch references use reduction='none' + matching manual reductions."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

from mxnet_tpu import gluon, nd  # noqa: E402

RS = np.random.RandomState


def _np(t):
    return t.numpy()


def test_l2_l1_match_torch():
    rng = RS(0)
    p = rng.randn(4, 7).astype(np.float32)
    y = rng.randn(4, 7).astype(np.float32)
    tp, ty = torch.tensor(p), torch.tensor(y)
    # MXNet L2 = 0.5 * mean((p-y)^2 over sample dims)
    ref_l2 = 0.5 * _np(TF.mse_loss(tp, ty, reduction="none")).mean(axis=1)
    got_l2 = gluon.loss.L2Loss()(nd.array(p), nd.array(y)).asnumpy()
    np.testing.assert_allclose(ref_l2, got_l2, atol=1e-6, rtol=1e-6)

    ref_l1 = _np(TF.l1_loss(tp, ty, reduction="none")).mean(axis=1)
    got_l1 = gluon.loss.L1Loss()(nd.array(p), nd.array(y)).asnumpy()
    np.testing.assert_allclose(ref_l1, got_l1, atol=1e-6, rtol=1e-6)


def test_softmax_ce_matches_torch():
    rng = RS(1)
    logits = rng.randn(6, 10).astype(np.float32)
    labels = rng.randint(0, 10, 6).astype(np.float32)
    ref = _np(TF.cross_entropy(torch.tensor(logits),
                               torch.tensor(labels.astype(np.int64)),
                               reduction="none"))
    got = gluon.loss.SoftmaxCrossEntropyLoss()(
        nd.array(logits), nd.array(labels)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-5, rtol=1e-5)


def test_sigmoid_bce_matches_torch():
    rng = RS(2)
    logits = rng.randn(5, 8).astype(np.float32)
    labels = (rng.rand(5, 8) > 0.5).astype(np.float32)
    ref = _np(TF.binary_cross_entropy_with_logits(
        torch.tensor(logits), torch.tensor(labels),
        reduction="none")).mean(axis=1)
    got = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        nd.array(logits), nd.array(labels)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-6, rtol=1e-5)


def test_huber_matches_torch():
    rng = RS(3)
    p = rng.randn(4, 9).astype(np.float32) * 3
    y = rng.randn(4, 9).astype(np.float32)
    rho = 1.0
    # torch smooth_l1(beta=rho) == MXNet HuberLoss(rho) elementwise
    ref = _np(TF.smooth_l1_loss(torch.tensor(p), torch.tensor(y),
                                reduction="none", beta=rho)).mean(axis=1)
    got = gluon.loss.HuberLoss(rho=rho)(nd.array(p),
                                        nd.array(y)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-6, rtol=1e-5)


def test_kldiv_matches_torch():
    rng = RS(4)
    logq = np.log(np.clip(rng.dirichlet(np.ones(6), 4), 1e-6, 1)
                  ).astype(np.float32)
    p = rng.dirichlet(np.ones(6), 4).astype(np.float32)
    # MXNet KLDivLoss(from_logits=True) takes log-probs pred, prob target
    ref = _np(TF.kl_div(torch.tensor(logq), torch.tensor(p),
                        reduction="none")).mean(axis=1)
    got = gluon.loss.KLDivLoss(from_logits=True)(
        nd.array(logq), nd.array(p)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-6, rtol=1e-5)


def test_triplet_matches_torch():
    rng = RS(5)
    a = rng.randn(4, 8).astype(np.float32)
    pos = rng.randn(4, 8).astype(np.float32)
    neg = rng.randn(4, 8).astype(np.float32)
    # MXNet TripletLoss uses SQUARED L2 distances summed over features —
    # torch's margin loss with a squared-L2 distance_function is the
    # external oracle for that convention
    crit = torch.nn.TripletMarginWithDistanceLoss(
        distance_function=lambda x, y: ((x - y) ** 2).sum(-1),
        margin=1.0, reduction="none")
    ref = _np(crit(torch.tensor(a), torch.tensor(pos), torch.tensor(neg)))
    got = gluon.loss.TripletLoss(margin=1.0)(
        nd.array(a), nd.array(pos), nd.array(neg)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-5, rtol=1e-5)


def test_cosine_embedding_matches_torch():
    rng = RS(6)
    x1 = rng.randn(6, 8).astype(np.float32)
    x2 = rng.randn(6, 8).astype(np.float32)
    lab = np.where(rng.rand(6) > 0.5, 1.0, -1.0).astype(np.float32)
    ref = _np(TF.cosine_embedding_loss(
        torch.tensor(x1), torch.tensor(x2),
        torch.tensor(lab), margin=0.3, reduction="none"))
    got = gluon.loss.CosineEmbeddingLoss(margin=0.3)(
        nd.array(x1), nd.array(x2), nd.array(lab)).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-5, rtol=1e-5)
