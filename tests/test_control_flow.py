"""Control-flow op + CustomOp tests (reference spec:
tests/python/unittest/test_contrib_control_flow.py, test_operator.py
CustomOp tests)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_foreach_cumsum():
    data = nd.array(np.arange(5, dtype=np.float32))
    init = nd.zeros((1,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = nd.contrib.foreach(body, data, init)
    np.testing.assert_allclose(outs.asnumpy().ravel(),
                               [0, 1, 3, 6, 10])
    np.testing.assert_allclose(final.asnumpy(), [10])


def test_foreach_multi_state_and_grad():
    data = nd.array(np.ones((4, 2), np.float32))
    w = nd.array(np.array([2.0, 3.0], np.float32))
    w.attach_grad()

    def body(x, states):
        (s,) = states
        return x * w, [s + (x * w).sum()]

    with autograd.record():
        outs, states = nd.contrib.foreach(body, data, [nd.zeros((1,))])
        loss = states[0].sum()
    loss.backward()
    # d loss / dw = 4 iterations x 1.0 each
    np.testing.assert_allclose(w.grad.asnumpy(), [4.0, 4.0])


def test_while_loop():
    def cond(i, s):
        return i < 5

    def func(i, s):
        return i * 2, (i + 1, s + i)

    outs, (i, s) = nd.contrib.while_loop(
        cond, func, (nd.array([0.0]), nd.array([0.0])), max_iterations=8)
    assert outs.shape[0] == 8
    np.testing.assert_allclose(outs.asnumpy()[:5].ravel(), [0, 2, 4, 6, 8])
    np.testing.assert_allclose(outs.asnumpy()[5:].ravel(), [0, 0, 0])
    np.testing.assert_allclose(i.asnumpy(), [5.0])
    np.testing.assert_allclose(s.asnumpy(), [10.0])


def test_cond():
    x = nd.array([3.0])
    y = nd.array([4.0])
    out = nd.contrib.cond(lambda a, b: (a < b).sum(),
                          lambda a, b: a + b,
                          lambda a, b: a - b, [x, y])
    np.testing.assert_allclose(out.asnumpy(), [7.0])
    out2 = nd.contrib.cond(lambda a, b: (a > b).sum(),
                           lambda a, b: a + b,
                           lambda a, b: a - b, [x, y])
    np.testing.assert_allclose(out2.asnumpy(), [-1.0])


# ---------------------------------------------------------------------------
# CustomOp
# ---------------------------------------------------------------------------
@mx.operator.register("sq_sum")
class SqSumProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [[1]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        class SqSum(mx.operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                x = in_data[0]
                self.assign(out_data[0], req[0], (x * x).sum().reshape((1,)))

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                x = in_data[0]
                g = out_grad[0]
                self.assign(in_grad[0], req[0], 2.0 * x * g)

        return SqSum()


def test_custom_op_forward_backward():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="sq_sum")
    np.testing.assert_allclose(y.asnumpy(), [14.0])
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 4.0, 6.0])


def test_custom_op_registry():
    assert "sq_sum" in mx.operator.get_all_registered_operators()
