"""Autograd tape tests (reference model: tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_rule():
    x = nd.array([0.5, 1.0])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x)
        z = (y * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.exp(2 * x.asnumpy()),
                               rtol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0, 6.0])


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(out_grad=nd.array([10.0, 100.0]))
    np.testing.assert_allclose(x.grad.asnumpy(), [30.0, 300.0])


def test_pause():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        with autograd.pause():
            z = y * 5  # not recorded
        w = y + 1
    w.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_detach():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x  # grad flows only through the second x
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [9.0])


def test_multi_output_backward():
    x = nd.array([1.0, 4.0])
    x.attach_grad()
    with autograd.record():
        loss = nd.sqrt(x).sum() + (x * x).sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               0.5 / np.sqrt(x.asnumpy()) + 2 * x.asnumpy(),
                               rtol=1e-5)


def test_autograd_grad_api():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x ** 3
    (g,) = autograd.grad([y], [x])
    np.testing.assert_allclose(g.asnumpy(), [12.0], rtol=1e-5)


def test_grad_through_indexing():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = x[0].sum()
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [[1, 1], [0, 0]])


def test_custom_function():
    class MulConst(autograd.Function):
        def forward(self, x):
            return x * 7

        def backward(self, dy):
            return dy * 7

    x = nd.array([1.0, 2.0])
    x.attach_grad()
    f = MulConst()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [7.0, 7.0])


def test_mark_variables():
    x = nd.array([5.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = x * 4
    y.backward()
    np.testing.assert_allclose(g.asnumpy(), [4.0])


def test_dropout_respects_mode():
    x = nd.ones((100,))
    with autograd.record(train_mode=False):
        y = nd.Dropout(x, p=0.5)
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy())
    with autograd.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    arr = y.asnumpy()
    assert (arr == 0).sum() > 10  # some were dropped
    assert abs(arr.mean() - 1.0) < 0.3  # scaled to keep expectation


def test_higher_order_grad_polynomial():
    # y = x^3: dy/dx = 3x^2, d2y/dx2 = 6x, d3y/dx3 = 6
    x = nd.array([2.0, -1.5])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        np.testing.assert_allclose(g1.asnumpy(), 3 * np.array([2.0, -1.5]) ** 2,
                                   rtol=1e-5)
        g2 = autograd.grad(g1, [x], create_graph=True)[0]
        np.testing.assert_allclose(g2.asnumpy(), 6 * np.array([2.0, -1.5]),
                                   rtol=1e-5)
        g3 = autograd.grad(g2, [x], create_graph=False)[0]
    np.testing.assert_allclose(g3.asnumpy(), [6.0, 6.0], rtol=1e-5)


def test_higher_order_grad_sin_backward():
    # second derivative via grad() then backward(): d2/dx2 sin(x) = -sin(x)
    v = np.array([0.3, 1.1, -0.7], np.float32)
    x = nd.array(v)
    x.attach_grad()
    with autograd.record():
        y = nd.sin(x)
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        s = g1.sum()
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), -np.sin(v), rtol=1e-5)


def test_higher_order_through_composition():
    # f(x) = exp(2x); f'' = 4 exp(2x); mixes registered ops on the tape
    v = np.array([0.1, -0.4], np.float32)
    x = nd.array(v)
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x * 2)
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        g2 = autograd.grad(g1, [x])[0]
    np.testing.assert_allclose(g2.asnumpy(), 4 * np.exp(2 * v), rtol=1e-5)


def test_second_order_scalar_pow_negative_base():
    # x**4 with python-scalar exponent must not open a d/d(exponent) path
    # (x^b log x is NaN for x<0 and would poison second-order backward)
    x = nd.array(np.array([-0.78, 1.3], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        g1 = autograd.grad(y, [x], create_graph=True)[0]
        s = g1.sum()
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               12 * np.array([-0.78, 1.3]) ** 2, rtol=1e-5)


def test_int_pow_keeps_dtype():
    x = nd.array(np.array([2, 3], np.int32), dtype="int32")
    out = x ** 2
    assert np.dtype(out.dtype) == np.int32
    np.testing.assert_array_equal(out.asnumpy(), [4, 9])


def test_create_graph_through_hybridized_block():
    # WGAN-GP style: gradient penalty through a hybridized net
    from mxnet_tpu import gluon

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="tanh"), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32))
    x.attach_grad()
    with autograd.record():
        y = net(x).sum()
        gx = autograd.grad(y, [x], create_graph=True)[0]
        penalty = ((gx ** 2).sum(axis=1) ** 0.5 - 1.0) ** 2
        loss = penalty.sum()
    loss.backward()
    g = x.grad.asnumpy()
    assert g.shape == x.shape and np.isfinite(g).all()
    assert np.abs(g).sum() > 0
