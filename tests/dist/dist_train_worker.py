"""2-process data-parallel Gluon training over dist_sync kvstore
(reference: example/distributed_training pattern; gradients cross the
process boundary through the compiled allreduce).

Each worker trains the same tiny regression net on its own half of a fixed
dataset; dist_sync aggregation must keep all workers' weights bit-identical
and the loss must fall.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def main():
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    np.random.seed(0)  # SAME dataset on all workers; each takes a slice
    X = np.random.randn(32, 4).astype(np.float32)
    W = np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    Y = X @ W
    lo, hi = rank * (32 // n), (rank + 1) * (32 // n)

    # DIFFERENT random init per worker: the kvstore init broadcast (rank
    # 0's value wins) is what must align the replicas.
    mx.random.seed(rank)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    for epoch in range(150):
        with autograd.record():
            loss = loss_fn(net(nd.array(X[lo:hi])), nd.array(Y[lo:hi]))
        loss.backward()
        trainer.step(hi - lo)
    final = float(loss.mean().asnumpy())
    assert final < 0.01, f"worker {rank}: did not converge, loss={final}"

    # weights must be identical across workers after sync training
    w = net.weight.data().asnumpy()
    summed = kv._global_sum(net.weight.data())
    np.testing.assert_allclose(summed.asnumpy(), w * n, rtol=1e-5,
                               err_msg="weights diverged across workers")
    print(f"worker {rank}/{n}: dist train OK loss={final:.4f}", flush=True)


if __name__ == "__main__":
    main()
