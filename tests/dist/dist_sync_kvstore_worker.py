"""Worker body for the 2-process dist_sync kvstore test (reference:
tests/nightly/dist_sync_kvstore.py, launched by tools/launch.py local mode).

Each worker pushes known tensors; the pulled value must equal the analytic
expectation.  Run via:

    python tools/launch.py -n 2 --force-cpu python tests/dist/dist_sync_kvstore_worker.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def main():
    kv = mx.kv.create("dist_sync")
    n = kv.num_workers
    rank = kv.rank
    assert n == int(os.environ["MX_NUM_PROCS"]), (n, os.environ["MX_NUM_PROCS"])
    shape = (4, 3)

    # --- plain aggregation: store ends at the global sum of pushes -------
    kv.init("a", nd.zeros(shape))
    kv.push("a", nd.ones(shape) * (rank + 1))
    out = nd.zeros(shape)
    kv.pull("a", out=out)
    expect = sum(r + 1 for r in range(n))  # 3 for n=2
    np.testing.assert_allclose(out.asnumpy(), np.full(shape, expect),
                               rtol=1e-6)

    # --- init broadcast: only rank 0's init value reaches the store ------
    kv.init("b", nd.ones(shape) * (rank + 7))
    outb = nd.zeros(shape)
    kv.pull("b", out=outb)
    np.testing.assert_allclose(outb.asnumpy(), np.full(shape, 7.0),
                               rtol=1e-6,
                               err_msg="init must broadcast rank 0's value")

    # --- server-side optimizer semantics (update_on_kvstore) -------------
    kv2 = mx.kv.create("dist_sync")
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv2.init(3, nd.ones(shape))
    for step in range(4):
        kv2.push(3, nd.ones(shape) * (rank + 1))
    w = nd.zeros(shape)
    kv2.pull(3, out=w)
    # each push applies w -= lr * global_grad_sum; grad_sum = 3 per step
    expect_w = 1.0 - 0.1 * expect * 4
    np.testing.assert_allclose(w.asnumpy(), np.full(shape, expect_w),
                               rtol=1e-5)

    kv.barrier()
    print(f"worker {rank}/{n}: dist_sync kvstore OK", flush=True)


if __name__ == "__main__":
    main()
