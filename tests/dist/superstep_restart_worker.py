"""Superstep + AOT-cache restart worker (docs/PERFORMANCE.md §Superstep
& AOT executable cache): a supervised kill-and-restart must resume
bitwise-identical with a WARM executable cache — the restarted
incarnation deserializes its step/scan programs instead of recompiling.

Phase baseline (MX_SSR_PHASE=baseline): uninterrupted 40-step run in
transparent superstep mode (MX_SUPERSTEP=4, forced on for this CPU box);
each rank writes its final weights as its own baseline.

Phase supervised (MX_SSR_PHASE=supervised): same training under
``tools/launch.py --max-restarts 1`` with a shared
MX_EXECUTABLE_CACHE_DIR.  Rank 1 self-kills at step 24 on incarnation 0
(past the step-20 checkpoint); the supervisor re-spawns the gang, each
rank resumes from its latest valid checkpoint, and asserts:

  * incarnation 1 booked AOT cache HITS for its DataParallelStep
    executables (zero fresh scan/step compiles — the restart-SLO win);
  * final weights are BITWISE identical to the uninterrupted baseline
    (superstep group boundaries re-align because the checkpoint cadence
    is a multiple of K, and the scan executable family is bitwise
    self-consistent across lengths).

Ranks train independent replicas on LOCAL single-device meshes (the
oom_worker pattern — each rank pins one virtual CPU device before jax
init), so the supervisor machinery, not cross-rank collectives, is what
this worker exercises.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# one virtual CPU device BEFORE jax init: the pytest parent exports
# XLA_FLAGS=8 which would leave 8 devices in every rank
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, memwatch, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, local_mesh

TOTAL_STEPS = 40
SAVE_EVERY = 20  # multiple of MX_SUPERSTEP=4: group boundaries re-align
KILL_STEP = 24


def build():
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(16, activation="relu"),
                gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def main():
    import jax

    phase = os.environ["MX_SSR_PHASE"]
    base = os.environ["MX_SSR_DIR"]
    rank = int(os.environ.get("MX_PROC_ID", "0"))
    restart = int(os.environ.get("MX_RESTART_COUNT", "0"))
    ckdir = os.path.join(base, phase, f"rank{rank}")
    telemetry.enable(os.path.join(base, phase, "tele"))

    rng = np.random.RandomState(rank)
    batches = [(rng.rand(8, 16).astype(np.float32),
                rng.rand(8, 4).astype(np.float32)) for _ in range(8)]

    net = build()
    start = checkpoint.restore(ckdir, net)
    if phase == "supervised" and restart == 1:
        # rank 1 died at step 24, past its step-20 checkpoint; rank 0
        # runs independently and may have finished (checkpoint 40)
        # before the gang teardown reached it
        expect = (SAVE_EVERY,) if rank == 1 else (SAVE_EVERY, TOTAL_STEPS)
        assert start in expect, f"rank {rank}: resume at {start}"
        print(f"rank {rank}: incarnation 1 resuming at step {start}",
              flush=True)

    # momentum=0: the SGD update is stateless, so params alone make the
    # checkpoint complete and the resumed trajectory bitwise-exact.
    # local_devices: under the gang rendezvous jax.devices() is GLOBAL —
    # rank 1 must mesh over its own device, not rank 0's
    step = DataParallelStep(
        net, gluon.loss.L2Loss(),
        mesh=local_mesh(devices=[jax.local_devices()[0]]), optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.0})

    ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=SAVE_EVERY,
                                        keep=2, initial_step=start)
    for i in range(start, TOTAL_STEPS):
        x, y = batches[i % len(batches)]
        step.step(nd.array(x), nd.array(y))
        step_no = i + 1
        if step_no % SAVE_EVERY == 0:
            # land the group + write params back into the gluon block so
            # the checkpoint snapshots step_no's true state
            step.sync_to_block()
        ckpt.step(net)
        if (phase == "supervised" and restart == 0 and rank == 1
                and step_no == KILL_STEP):
            step.drain()
            ckpt.wait()
            print(f"rank {rank}: self-kill at step {step_no}", flush=True)
            telemetry.flush()
            os._exit(43)
    ckpt.close()
    if step.params is not None:
        step.sync_to_block()

    comps = memwatch.summary()["compiles"]
    print(f"rank {rank}: incarnation {restart} compiles={comps['count']} "
          f"cache_hits={comps['cache_hits']}", flush=True)
    if (phase == "supervised" and restart == 1
            and start < TOTAL_STEPS):
        # the warm-cache contract: a restarted incarnation that actually
        # trained deserialized its scan executable instead of recompiling
        # (a rank that already finished before the gang died resumes at
        # TOTAL_STEPS and never dispatches)
        assert comps["cache_hits"] >= 1, comps
        print(f"rank {rank}: warm-cache restart OK", flush=True)

    w = np.concatenate([p.data().asnumpy().ravel()
                        for _n, p in sorted(net.collect_params().items())])
    wpath = os.path.join(base, f"final-rank{rank}.npy")
    if phase == "baseline":
        np.save(wpath, w)
        print(f"rank {rank}: baseline OK", flush=True)
    else:
        baseline = np.load(wpath)
        assert np.array_equal(baseline, w), (
            f"rank {rank}: resumed weights differ from baseline "
            f"(max abs {np.max(np.abs(baseline - w))})")
        print(f"rank {rank}: matches uninterrupted baseline", flush=True)
    telemetry.flush()


if __name__ == "__main__":
    main()
