"""Elastic gang resize worker (docs/FAULT_TOLERANCE.md §Elastic resize).

Every rank drives ONE global dp mesh (one CPU device per process) through
a ``DataParallelStep``; the checkpoint directory is SHARED — rank 0 is
the writer, peers are non-writing members — and every checkpoint carries
the sharded params, the optimizer slots, the save-time sharding layout,
and the iterator position.  On (re)start each rank restores the
gang-agreed scheduled step, **resharding** the snapshot onto the CURRENT
world size, and rebuilds its ``NDArrayIter`` at the saved global sample
cursor — training continues with no sample skipped or consumed twice,
even though the global batch size changed with the world size.

The parent test runs this same script as the elastic run (under
``tools/launch.py --elastic``, shrunk by the chaos harness or grown by
``--regrow-after``) AND as the fixed-size baseline (plain launch +
``MX_RESUME_STEP``): final weights must match bitwise — a resize is
trajectory-invisible past the resume point.

env:
  MX_ELASTIC_DIR         base dir: shared checkpoints under <dir>/ckpt,
                         final weights at <dir>/final_<tag>.npz
  MX_ELASTIC_TAG         name of this run's final-weights file
  MX_RESUME_STEP         (baseline runs) demand exactly this resume step
  MX_ELASTIC_STEP_SLEEP  per-step host sleep (stretches wall time so the
                         supervisor's --regrow-after lands mid-run)
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# one CPU device per process (a dp<world> global mesh) BEFORE jax
# initializes: the pytest parent's XLA_FLAGS asks for 8 virtual devices
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import checkpoint, fault, gluon
from mxnet_tpu.io.io import NDArrayIter
from mxnet_tpu.parallel import DataParallelStep, make_mesh

TOTAL = 60
SAVE_EVERY = 5
PER_RANK_BATCH = 4


def main():
    import jax

    base = os.environ["MX_ELASTIC_DIR"]
    tag = os.environ.get("MX_ELASTIC_TAG", "elastic")
    sleep_s = float(os.environ.get("MX_ELASTIC_STEP_SLEEP", "0") or 0)
    ckdir = os.path.join(base, "ckpt")
    kv = mx.kv.create("dist_sync")
    rank, world = kv.rank, kv.num_workers
    mesh = make_mesh(devices=jax.devices())
    assert mesh.shape["dp"] == world, (mesh.shape, world)

    rng = np.random.RandomState(0)
    X = rng.randn(96, 4).astype(np.float32)
    Y = (X @ np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)).astype(
        np.float32)

    mx.random.seed(0)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    step = DataParallelStep(
        net, gluon.loss.L2Loss(), mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9})
    # every rank feeds the same host-GLOBAL batch (the pjit pod-input
    # pattern); the global batch scales with the world size so the
    # per-device share stays fixed across resizes, and the cursor counts
    # global samples so the position survives the stride change
    it = NDArrayIter(X, Y, batch_size=PER_RANK_BATCH * world,
                     shuffle=True, seed=7)

    demand = os.environ.get("MX_RESUME_STEP")
    local = checkpoint.latest_valid_step(ckdir, multiple_of=SAVE_EVERY)
    start = checkpoint.agree_resume_step(local, kv)
    if demand:
        start = int(demand)
    if start:
        state = checkpoint.load_checkpoint_state(ckdir, step=start)
        host = {
            "params": {k: v.asnumpy() for k, v in state["params"].items()},
            "opt_state": {k: v.asnumpy()
                          for k, v in (state["opt_state"] or {}).items()},
        }
        info = step.load_state_dict(host, saved_layout=state.get("layout"))
        it.set_state(state["extra"]["iter"])
        print(f"elastic: rank {rank} resuming at step {start} world {world} "
              f"resharded={info['resharded']} old_world={info['old_world']}",
              flush=True)
    ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=SAVE_EVERY,
                                        keep=100, initial_step=start,
                                        writer=(rank == 0))
    fault.install_preemption_handler(ckpt, step)

    loss = None
    for _i in range(start, TOTAL):
        try:
            batch = it.next()
        except StopIteration:
            it.reset()
            batch = it.next()
        loss = step.step(batch.data[0], batch.label[0])
        # force per step: crash/preemption points stay deterministic
        loss = float(loss)
        ckpt.step(step, extra={"iter": it.get_state()})
        if sleep_s:
            time.sleep(sleep_s)
    step.drain()
    ckpt.close()
    weights = step.state_dict()["params"]
    if rank == 0:
        np.savez(os.path.join(base, f"final_{tag}.npz"), **weights)
    kv.barrier()
    print(f"elastic: rank {rank}/{world} done loss={loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
