"""Async-pipeline-under-gang worker (docs/PERFORMANCE.md §Async pipeline):
2 ranks drive a dp2 global mesh through DataParallelStep with
MX_ASYNC_INFLIGHT=2 and DEFERRED readback — every loss is forced only
after the whole epoch dispatched, so the readbacks cross the real Gloo
mesh long after dispatch.  The worker then re-runs the identical schedule
synchronously (MX_ASYNC_INFLIGHT=0) and asserts the per-step losses are
bitwise identical: asynchrony changes when the host observes results,
never what is computed — even multi-controller."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# one CPU device per process (a dp2 global mesh) BEFORE jax initializes:
# the pytest parent's XLA_FLAGS asks for 8 virtual devices per host,
# which a batch of 8 over 2 processes cannot shard
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import gluon, nd
from mxnet_tpu.parallel import DataParallelStep, make_mesh


def _run(inflight, steps=4):
    os.environ["MX_ASYNC_INFLIGHT"] = str(inflight)
    import jax

    mesh = make_mesh(devices=jax.devices())
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Normal(0.5))
    loss_fn = gluon.loss.L2Loss()
    step = DataParallelStep(net, loss_fn, mesh=mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(0)
    handles = []
    for _ in range(steps):
        x = nd.array(rng.rand(8, 4).astype(np.float32))
        y = nd.array(rng.rand(8, 4).astype(np.float32))
        handles.append(step.step(x, y))
    if inflight:
        assert not handles[-1].forced, "async handle forced at dispatch"
        assert 0 < step.inflight_depth <= inflight, step.inflight_depth
    step.drain()  # every deferred readback crosses the Gloo mesh here
    assert step.inflight_depth == 0
    return [float(h) for h in handles]


def main():
    import jax

    assert jax.process_count() == 2, jax.process_count()
    deferred = _run(2)
    sync = _run(0)
    assert all(np.isfinite(deferred)), deferred
    assert deferred == sync, (deferred, sync)
    print(f"worker {jax.process_index()}: async dist OK "
          f"losses={','.join(f'{l:.6f}' for l in deferred)}", flush=True)


if __name__ == "__main__":
    main()
