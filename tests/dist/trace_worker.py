"""Trace-analysis-under-gang worker (docs/OBSERVABILITY.md §Tracing &
analysis acceptance shape): 2 ranks drive a dp2 global mesh through
DataParallelStep in synchronous mode (MX_ASYNC_INFLIGHT=0, every step
forced inline so host waits land in recorded ``loss_wait`` spans) with a
per-step explicit loss allreduce (collective events for the bandwidth
table).  When ``TRACE_STRAGGLER_RANK`` names this rank it sleeps
``TRACE_STRAGGLER_SLEEP`` seconds of UNINSTRUMENTED host time per step —
the injected straggler.  In lock-step sync training that sleep shows up
on the peers as recorded waiting and on the straggler as unaccounted
wall, which is exactly the idle-gap signature tools/trace_report.py
flags."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# one CPU device per process (a dp2 global mesh) BEFORE jax initializes:
# the pytest parent's XLA_FLAGS asks for 8 virtual devices per host,
# which a batch of 8 over 2 processes cannot shard
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")
os.environ["MX_ASYNC_INFLIGHT"] = "0"  # sync: waits land in loss_wait

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import gluon, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, make_mesh
from mxnet_tpu.parallel import dist


def main():
    import jax

    assert telemetry.enabled(), "MX_TELEMETRY_DIR must be set"
    n = jax.process_count()
    rank = jax.process_index()
    assert n == 2, n
    straggler = int(os.environ.get("TRACE_STRAGGLER_RANK", "-1"))
    sleep_s = float(os.environ.get("TRACE_STRAGGLER_SLEEP", "0.05"))
    steps = int(os.environ.get("TRACE_STEPS", "25"))

    mesh = make_mesh(devices=jax.devices())
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Normal(0.5))
    step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=mesh,
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(0)  # same global batch on every rank (SPMD)
    val = float("nan")
    for _i in range(steps):
        x = nd.array(rng.rand(8, 4).astype(np.float32))
        y = nd.array(rng.rand(8, 4).astype(np.float32))
        loss = float(step.step(x, y))  # forced inline (sync mode)
        # explicit gang loss averaging: one recorded collective per step
        with telemetry.span("loss_allreduce", paired=True):
            summed = dist.allreduce_sum(np.float32(loss))
            val = float(np.asarray(summed)) / n
        if rank == straggler:
            time.sleep(sleep_s)  # uninstrumented host time: the straggler
    telemetry.flush()
    print(f"worker {rank}/{n}: trace OK mean_loss={val:.5f}", flush=True)


if __name__ == "__main__":
    main()
