"""2-process x 2-device-per-process combo worker: the v5p pod shape in
miniature (r4 verdict #6).

Each process owns TWO virtual CPU devices; the GLOBAL mesh is
dp2 (across the process boundary, gradients ride the DCN/Gloo path) x
tp2 (inside each process, Megatron sharding rules) and the whole BERT
train step is ONE pjit program per process — the multi-controller SPMD
pattern a real v5p pod uses, where tools/launch.py stands in for the pod
launcher.  Prints the per-step losses for the parent test to compare
against a single-process dp2xtp2 run of the same config.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# each process must see 2 virtual CPU devices BEFORE jax initializes;
# the launcher's MX_FORCE_CPU pins the platform at rendezvous time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import gluon, nd
from mxnet_tpu.models import bert_small
from mxnet_tpu.models.bert import bert_sharding_rules
from mxnet_tpu.parallel import DataParallelStep, make_mesh


def main():
    import jax

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 4, devs
    # dp rows == processes: make_mesh fills (dp, pp, sp, tp, ep) row-major
    # from the device list, and jax.devices() orders by process
    mesh = make_mesh(tp=2, devices=devs)
    assert mesh.shape["dp"] == 2 and mesh.shape["tp"] == 2

    mx.context.Context._default_ctx.value = mx.cpu()
    mx.random.seed(0)
    net = bert_small()
    net.initialize(mx.init.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    step = DataParallelStep(net, mlm_loss, mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            rules=bert_sharding_rules())
    rng = np.random.RandomState(0)
    B, T, V = 8, 16, 512
    tokens = rng.randint(0, V, (B, T)).astype(np.int32)
    labels = tokens.astype(np.float32)
    losses = []
    for _ in range(3):
        loss = step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
        losses.append(float(np.asarray(loss)))
    assert all(np.isfinite(losses)), losses
    qkv = [n for n in step.params if n.endswith("qkv_weight")]
    assert qkv and "tp" in str(step.params[qkv[0]].sharding.spec)
    print(f"worker {jax.process_index()}: dist tp OK "
          f"losses={','.join(f'{l:.6f}' for l in losses)}", flush=True)


if __name__ == "__main__":
    main()
