"""Worker body for the 2-process bucketed-allreduce parity test
(docs/PERFORMANCE.md): with a deliberately tiny MX_ALLREDUCE_BUCKET_MB the
gradient pushes must coalesce into MULTIPLE flat buckets that cross the
process boundary as whole-bucket collectives, while every pulled value
still equals the analytic per-key global sum.  Run via:

    python tools/launch.py -n 2 --force-cpu python tests/dist/dist_bucketed_worker.py
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# 80-byte cap: both the 4 analytic keys below and the toy net's 4 params
# (64+16+16+4 bytes) must split into >=2 buckets
os.environ["MX_ALLREDUCE_BUCKET_MB"] = str(80 / (1 << 20))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def main():
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    keys = [0, 1, 2, 3]
    shapes = [(4, 3), (7,), (2, 2, 2), (5, 2)]
    rng = np.random.RandomState(0)  # SAME base values on all ranks
    base = {k: rng.randn(*s).astype(np.float32) for k, s in zip(keys, shapes)}

    # --- bucketed aggregation parity: pull == sum over ranks -------------
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    n_buckets = kv.push_bucketed(
        keys, [nd.array(base[k] * (rank + 1)) for k in keys])
    assert n_buckets >= 2, f"tiny cap must split buckets, got {n_buckets}"
    scale = sum(r + 1 for r in range(n))  # 3 for n=2
    for k, s in zip(keys, shapes):
        out = nd.zeros(s)
        kv.pull(k, out)
        np.testing.assert_allclose(out.asnumpy(), base[k] * scale, rtol=1e-5)

    # --- end to end: bucketed + fused trainer keeps replicas identical ---
    np.random.seed(0)
    X = np.random.randn(32, 4).astype(np.float32)
    Y = X @ np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    lo, hi = rank * (32 // n), (rank + 1) * (32 // n)
    mx.random.seed(rank)  # init broadcast must align the replicas
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(4, activation="relu"), gluon.nn.Dense(1))
    net.initialize(mx.init.Normal(0.5))
    kv2 = mx.kv.create("dist_sync")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=kv2)
    loss_fn = gluon.loss.L2Loss()
    first = None
    for _epoch in range(60):
        with autograd.record():
            loss = loss_fn(net(nd.array(X[lo:hi])), nd.array(Y[lo:hi]))
        loss.backward()
        trainer.step(hi - lo)
        if first is None:
            first = float(loss.mean().asnumpy())
    assert trainer._last_n_buckets >= 2, trainer._last_n_buckets
    final = float(loss.mean().asnumpy())
    assert final < first * 0.1, f"rank {rank}: loss {first} -> {final}"
    for p in net.collect_params().values():
        w = p.data().asnumpy()
        summed = kv2._global_sum(p.data())
        np.testing.assert_allclose(
            summed.asnumpy(), w * n, rtol=1e-5,
            err_msg=f"param {p.name} diverged across workers")
    print(f"worker {rank}/{n}: bucketed allreduce OK buckets={n_buckets} "
          f"loss={final:.5f}", flush=True)


if __name__ == "__main__":
    main()
