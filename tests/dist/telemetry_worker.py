"""Telemetry-under-gang worker (docs/OBSERVABILITY.md acceptance shape):
2 ranks train a tiny regression net over dist_sync with step-granular
checkpoints while MX_TELEMETRY_DIR is set; each rank must leave behind a
parseable rank-<R>.jsonl containing step, collective, and checkpoint
events, plus a heartbeat file that ADVANCED during the run (verified here
by re-reading our own heartbeat at two different steps)."""
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, gluon, nd, telemetry


def _read_own_heartbeat():
    path = telemetry.heartbeat_path(os.environ["MX_TELEMETRY_DIR"],
                                    telemetry.rank())
    with open(path) as f:
        return json.load(f)


def main():
    assert telemetry.enabled(), "MX_TELEMETRY_DIR must be set for this worker"
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    np.random.seed(0)
    X = np.random.randn(32, 4).astype(np.float32)
    Y = X @ np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    lo, hi = rank * (32 // n), (rank + 1) * (32 // n)

    mx.random.seed(0)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    ckdir = os.path.join(os.environ["MX_TELEMETRY_DIR"], f"ckpt-rank{rank}")
    ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=10, keep=2)

    hb_steps = set()
    for step_i in range(30):
        with autograd.record():
            loss = loss_fn(net(nd.array(X[lo:hi])), nd.array(Y[lo:hi]))
        loss.backward()
        trainer.step(hi - lo)
        ckpt.step(net, trainer=trainer)
        if step_i in (5, 25):
            # the heartbeat file must ADVANCE while the run is alive
            time.sleep(0.1)  # outlast a tiny MX_HEARTBEAT_SEC rate limit
            telemetry.heartbeat(step_i + 1, force=True)
            hb_steps.add(_read_own_heartbeat()["step"])
    ckpt.close()
    telemetry.flush()
    assert len(hb_steps) >= 2, f"heartbeat never advanced: {hb_steps}"
    print(f"worker {rank}/{n}: heartbeat advanced {sorted(hb_steps)}",
          flush=True)
    print(f"worker {rank}/{n}: telemetry OK loss="
          f"{float(loss.mean().asnumpy()):.4f}", flush=True)


if __name__ == "__main__":
    main()
