"""OOM-post-mortem-under-gang worker (docs/OBSERVABILITY.md §Memory
acceptance shape): 2 ranks train independently (local per-rank mesh — no
collective coupling, so the surviving rank is alive for the supervisor
to tear down) with the memory watchdog sampling every step.  The test
env injects ``MX_FAULT_SPEC=oom:step=N:rank=R``: rank R's dispatch
raises a synthetic RESOURCE_EXHAUSTED at step N, memwatch records +
flushes an ``oom_report`` event, and the launch.py supervisor's death
diagnosis must echo the post-mortem (largest live-array category,
watermark, in-flight depth) next to the flight tail."""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# one CPU device per process BEFORE jax initializes (the pytest parent's
# XLA_FLAGS asks for 8 virtual devices, unshardable for a batch of 8)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=1")
os.environ.setdefault("MX_ASYNC_INFLIGHT", "2")

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import gluon, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, local_mesh


def main():
    import jax

    assert telemetry.enabled(), "MX_TELEMETRY_DIR must be set"
    rank = jax.process_index()
    steps = int(os.environ.get("OOM_STEPS", "8"))

    mesh = local_mesh(devices=jax.local_devices())
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Normal(0.5))
    step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=mesh,
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(rank)
    for _i in range(steps):
        x = nd.array(rng.rand(8, 4).astype(np.float32))
        y = nd.array(rng.rand(8, 4).astype(np.float32))
        float(step.step(x, y))  # forces readback: deferred errors surface
        # slow cadence: the surviving rank must still be mid-run when the
        # injected rank dies, so the supervisor exercises full teardown
        time.sleep(0.3)
    step.drain()
    telemetry.flush()
    print(f"worker {rank}: oom worker finished clean", flush=True)


if __name__ == "__main__":
    main()
