"""Shard-granular checkpoint chaos worker (ISSUE 16 acceptance): a
2-process gang whose tp=4 mesh spans BOTH processes, so every param is
cross-process-sharded — the state a gathered snapshot could only
capture with a collective allgather (and the case PR 11's save_now had
to refuse).  Here every rank persists exactly its own shards with ZERO
collectives, and the telemetry checkpoint_save events record per-rank
payload bytes as proof.

Phase 0 (MX_SHARD_PHASE=0): uninterrupted 15-step run; rank 0 writes
the final gathered state as the bitwise baseline.

Phase 1 (MX_SHARD_PHASE=1): the supervised chaos run, launched under
``tools/launch.py --max-restarts 1`` with
``MX_FAULT_SPEC=crash:step=12:rank=1:if-restart=0``:

  * sharded scheduled saves every 5 steps into ONE shared dir (rank 0
    leads/publishes, rank 1 commits only its shard marker);
  * at step 8 both ranks take an explicit off-cycle ``save_now`` — the
    rank-local preemption snapshot on cross-process-sharded state that
    used to be impossible — and step-8 must publish COMPLETE;
  * the chaos harness kills rank 1 at step 12; the survivor's SIGTERM
    handler best-effort-snapshots (its lone marker can only produce an
    incomplete step that validation rejects);
  * the restarted gang agrees on scheduled step 10
    (latest_valid_step(multiple_of=5) + agree_resume_step), restores
    the sharded checkpoint onto the fresh mesh, finishes training, and
    the final weights must match the phase-0 baseline BITWISE.

Run via tools/launch.py local mode (the test drives both phases).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

# each process must see 2 virtual CPU devices BEFORE jax initializes;
# the launcher's MX_FORCE_CPU pins the platform at rendezvous time
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2")

import numpy as np

import mxnet_tpu as mx  # noqa: E402  (rendezvous runs at import)
from mxnet_tpu import checkpoint, fault, gluon, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, make_mesh
from mxnet_tpu.parallel.sharding import ShardingRules

TOTAL_STEPS = 15
SAVE_EVERY = 5


def build_step():
    import jax

    assert jax.process_count() == 2, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 4, devs
    # tp spans the process boundary: 4-way tensor parallel over
    # 2 procs x 2 devices — no rank can address a full param
    mesh = make_mesh(tp=4, devices=devs)
    assert mesh.shape["tp"] == 4, dict(mesh.shape)

    mx.context.Context._default_ctx.value = mx.cpu()
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Normal(0.5))
    rules = ShardingRules([(r".*weight$", ("tp", None)),
                           (r".*bias$", ("tp",))])
    return DataParallelStep(net, gluon.loss.L2Loss(), mesh=mesh,
                            optimizer="adam",
                            optimizer_params={"learning_rate": 1e-2},
                            rules=rules)


def batch(step_i):
    rng = np.random.RandomState(1000 + step_i)
    return (nd.array(rng.randn(8, 6).astype(np.float32)),
            nd.array(rng.randn(8, 4).astype(np.float32)))


def main():
    phase = int(os.environ["MX_SHARD_PHASE"])
    base = os.environ["MX_SHARD_DIR"]
    telemetry.enable()  # MX_TELEMETRY_DIR: the per-rank save audit trail
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    step = build_step()

    if phase == 0:
        for step_i in range(TOTAL_STEPS):
            X, Y = batch(step_i)
            step.step(X, Y)
        step.drain()
        # the whole point of the sharded format: this state CANNOT be
        # snapshotted rank-locally in gathered form (params place at
        # the first step, so the probe runs after training)
        assert step.snapshot_requires_collective(), \
            "tp must span processes"
        sd = step.state_dict()  # collective allgather: every rank calls
        if rank == 0:
            np.savez(os.path.join(base, "baseline.npz"), **sd["params"])
        kv.barrier()
        print(f"worker {rank}/{n}: shard baseline OK", flush=True)
        return

    # ------------------------------------------------------------------
    # phase 1: supervised chaos (crash rank 1 @ step 12, restart once)
    # ------------------------------------------------------------------
    ckdir = os.path.join(base, "ckpt")  # ONE shared dir, all ranks
    os.makedirs(ckdir, exist_ok=True)
    restart = int(os.environ.get("MX_RESTART_COUNT", "0"))
    local = checkpoint.latest_valid_step(ckdir, multiple_of=SAVE_EVERY)
    start = checkpoint.agree_resume_step(local, kv)
    if start:
        restored = checkpoint.restore(ckdir, step, step=start)
        assert restored == start, (restored, start)
    if restart == 1:
        # step-12 (lone survivor's SIGTERM snapshot) and any step-8
        # off-cycle save must NOT win: the gang resumes at the newest
        # complete SCHEDULED step
        assert start == 10, f"expected agreed resume at 10, got {start}"
    print(f"worker {rank}: incarnation {restart} resuming at step {start}",
          flush=True)
    ck = checkpoint.AsyncCheckpointer(ckdir, save_every=SAVE_EVERY, keep=3,
                                      initial_step=start, sharded=True,
                                      writer=(rank == 0))
    fault.install_preemption_handler(ck, step)

    for step_i in range(start, TOTAL_STEPS):
        X, Y = batch(step_i)
        step.step(X, Y)
        if step_i == start:
            step.drain()
            assert step.snapshot_requires_collective(), \
                "tp must span processes"
        ck.step(step)  # chaos crash:step=12 fires in here on rank 1
        if restart == 0 and (step_i + 1) % SAVE_EVERY == 0:
            # deterministic chaos: both ranks' async shard writes for
            # this scheduled step must be committed before the injected
            # crash at step 12 can strike — otherwise the test races on
            # whether step-10 published complete
            ck.wait()
            kv.barrier()
        if step_i + 1 == 8 and restart == 0:
            # explicit preemption-style snapshot on EVERY rank, in
            # lockstep: rank-local shard writes compose a complete
            # off-cycle step-8 with zero collectives
            step.drain()
            assert ck.save_now(step) == 8
            kv.barrier()
            assert checkpoint.latest_valid_step(ckdir) == 8, \
                "lockstep save_now must publish a COMPLETE step"
    ck.close()

    final = step.state_dict()
    if rank == 0:
        ref = np.load(os.path.join(base, "baseline.npz"))
        for name in ref.files:
            np.testing.assert_array_equal(
                ref[name], final["params"][name],
                err_msg=f"param {name} diverged from baseline")
    kv.barrier()
    telemetry.flush()
    print(f"worker {rank}/{n}: sharded resume OK (bitwise baseline match)",
          flush=True)


if __name__ == "__main__":
    main()
