"""Preemption-recovery worker (SURVEY §5.3: first-class checkpoint/restart
for pod preemption; reference posture is epoch-level save_checkpoint with
no mid-run recovery).

Phase 0 (MX_RESUME_PHASE=0): uninterrupted 120-step run; rank 0 writes its
final weights as the baseline.

Phase 1 (MX_RESUME_PHASE=1): same training with step-granular
AsyncCheckpointer; the process deliberately dies ("preemption") after 30
steps, past the step-20 checkpoint.

Phase 2 (MX_RESUME_PHASE=2): a FRESH set of processes restores the
checkpoint (params + trainer momentum + RNG), verifies it resumed at step
20, finishes training, checks cross-worker consistency AND that the final
weights match the uninterrupted baseline — preemption is
trajectory-invisible.

Phase 3 (MX_RESUME_PHASE=3): the SUPERVISED, hands-off version of phases
1+2 in one launch.  Run under ``tools/launch.py --max-restarts 1`` with
``MX_FAULT_SPEC=crash:step=30:rank=1:if-restart=0``: the chaos harness
kills rank 1 at step 30 on the first incarnation, the survivor takes a
SIGTERM-triggered final checkpoint (fault.install_preemption_handler), the
supervisor re-spawns the gang, and the restarted ranks agree on the
minimum valid checkpoint step (checkpoint.agree_resume_step — the
preemption checkpoint lands wherever SIGTERM caught rank 0, so ranks WILL
disagree) before resuming.  Final weights must still match the phase-0
baseline.

Run via tools/launch.py local mode (the test drives all phases).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, checkpoint, fault, gluon, nd


def build():
    mx.random.seed(0)
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    return net


def main():
    phase = int(os.environ["MX_RESUME_PHASE"])
    base = os.environ["MX_RESUME_DIR"]
    sub = {0: "baseline", 3: "supervised"}.get(phase, "resume")
    ckdir = os.path.join(base, sub,
                         f"rank{os.environ.get('MX_PROC_ID', '0')}")
    kv = mx.kv.create("dist_sync")
    rank, n = kv.rank, kv.num_workers
    np.random.seed(0)
    X = np.random.randn(32, 4).astype(np.float32)
    Y = X @ np.array([[1.0], [-2.0], [3.0], [0.5]], np.float32)
    lo, hi = rank * (32 // n), (rank + 1) * (32 // n)

    net = build()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=kv)
    loss_fn = gluon.loss.L2Loss()
    if phase == 3:
        # supervised resume: ranks may hold checkpoints at different steps
        # (a preemption checkpoint lands wherever SIGTERM caught each
        # rank), so the gang agrees on the minimum valid SCHEDULED step —
        # the only inventory every rank shares — and restores exactly that
        local = checkpoint.latest_valid_step(ckdir, multiple_of=20)
        start = checkpoint.agree_resume_step(local, kv)
        if start:
            restored = checkpoint.restore(ckdir, net, trainer, step=start)
            assert restored == start, (restored, start)
        restart = int(os.environ.get("MX_RESTART_COUNT", "0"))
        if restart == 1:
            assert start == 20, f"expected agreed resume at 20, got {start}"
        print(f"worker {rank}: incarnation {restart} resuming at step "
              f"{start}", flush=True)
    else:
        start = checkpoint.restore(ckdir, net, trainer)
        if phase == 2:
            assert start == 20, f"expected resume at step 20, got {start}"
    ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=20, keep=2,
                                        initial_step=start)
    if phase == 3:
        fault.install_preemption_handler(ckpt, net, trainer=trainer)

    total_steps = 120
    for step_i in range(start, total_steps):
        with autograd.record():
            loss = loss_fn(net(nd.array(X[lo:hi])), nd.array(Y[lo:hi]))
        loss.backward()
        trainer.step(hi - lo)
        ckpt.step(net, trainer=trainer)
        if phase == 1 and step_i == 29:
            ckpt.wait()
            kv.barrier()  # both ranks checkpointed before the "preemption"
            print(f"worker {rank}: preempting at step {step_i + 1}",
                  flush=True)
            os._exit(43)
    ckpt.close()

    final = float(loss.mean().asnumpy())
    assert final < 0.01, f"worker {rank}: loss {final} after resume"
    w = net.weight.data()
    summed = kv._global_sum(w)
    np.testing.assert_allclose(summed.asnumpy(), w.asnumpy() * n, rtol=1e-5,
                               err_msg="weights diverged across workers")
    baseline_path = os.path.join(base, "final_weights.npy")
    if phase == 0:
        if rank == 0:
            np.save(baseline_path, w.asnumpy())
        kv.barrier()
        print(f"worker {rank}/{n}: baseline train OK loss={final:.5f}",
              flush=True)
        return
    # preemption must be trajectory-invisible: momentum + RNG restored,
    # so the resumed run lands on the SAME weights
    np.testing.assert_allclose(w.asnumpy(), np.load(baseline_path),
                               rtol=1e-6, atol=1e-7,
                               err_msg="resumed weights diverge from the "
                                       "uninterrupted run")
    kv.barrier()
    print(f"worker {rank}/{n}: resume train OK loss={final:.5f} "
          "matches uninterrupted baseline", flush=True)


if __name__ == "__main__":
    main()
