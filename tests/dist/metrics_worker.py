"""Gang worker for the live-metrics acceptance test (docs/OBSERVABILITY.md
§Live metrics): trains a tiny net while serving /metrics and /healthz
live (MX_METRICS_PORT=0 exported by the launch.py --metrics-port
supervisor -> ephemeral port + portfile), then idles until the test
drops MX_STOP_FILE.  SIGTERM exits 0 immediately: the test "kills" rank
1 this way so the supervisor keeps the gang (and its merged /metrics)
alive while the test asserts the dead rank's ``up`` gauge flipped."""
import os
import signal
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, metrics_server, nd, telemetry


def main():
    assert telemetry.enabled(), "MX_TELEMETRY_DIR must be set"
    assert metrics_server.enabled(), \
        "MX_METRICS_PORT must have started the endpoint at import"
    rank = telemetry.rank()
    signal.signal(signal.SIGTERM, lambda *_: os._exit(0))

    mx.random.seed(rank)
    rng = np.random.RandomState(rank)
    X = rng.rand(8, 4).astype(np.float32)
    Y = (X @ rng.rand(4, 1).astype(np.float32))
    net = gluon.nn.Dense(1)
    net.initialize(mx.init.Normal(0.5))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    for i in range(20):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(Y))
        loss.backward()
        trainer.step(8)
        telemetry.heartbeat(i + 1, force=True)
    telemetry.flush()
    print(f"worker {rank}: training done, port {metrics_server.port()}",
          flush=True)

    stop = os.environ["MX_STOP_FILE"]
    deadline = time.time() + 180
    while not os.path.exists(stop) and time.time() < deadline:
        telemetry.heartbeat(20, force=True)  # stay healthy while idling
        time.sleep(0.1)
    # os._exit: a SIGTERM-killed peer skipped jax.distributed.shutdown,
    # so running OUR atexit shutdown would block on its barrier until a
    # timeout error turns this clean exit dirty; telemetry is already
    # flushed above and the supervisor only needs the exit code
    os._exit(0)


if __name__ == "__main__":
    main()
