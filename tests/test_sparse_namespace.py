"""mx.nd.sparse functional namespace + new image augmenters."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def _rs(dense):
    return nd.array(dense).tostype("row_sparse")


def test_sparse_elemwise_add_stays_sparse():
    a = np.zeros((6, 3), np.float32)
    a[1] = 1
    a[4] = 2
    b = np.zeros((6, 3), np.float32)
    b[1] = 10
    b[2] = 5
    out = sparse.add(_rs(a), _rs(b))
    assert out.stype == "row_sparse"
    np.testing.assert_array_equal(out.asnumpy(), a + b)
    out = sparse.subtract(_rs(a), _rs(b))
    assert out.stype == "row_sparse"
    np.testing.assert_array_equal(out.asnumpy(), a - b)
    # mul falls back dense
    out = sparse.multiply(_rs(a), _rs(b))
    np.testing.assert_array_equal(out.asnumpy(), a * b)


def test_sparse_dot_csr():
    a = np.zeros((4, 5), np.float32)
    a[0, 1] = 2
    a[3, 4] = 7
    b = np.random.RandomState(0).rand(5, 2).astype(np.float32)
    csr = nd.array(a).tostype("csr")
    out = sparse.dot(csr, nd.array(b)).asnumpy()
    np.testing.assert_allclose(out, a @ b, rtol=1e-5)


def test_sparse_retain_and_zeros_like():
    a = np.zeros((5, 2), np.float32)
    a[1] = 3
    a[3] = 4
    rs = _rs(a)
    kept = sparse.retain(rs, nd.array(np.array([1, 2], np.float32)))
    expect = np.zeros_like(a)
    expect[1] = 3
    np.testing.assert_array_equal(kept.asnumpy(), expect)
    z = sparse.zeros_like(rs)
    assert z.stype == "row_sparse" and z.shape == (5, 2)
    assert z.asnumpy().sum() == 0


def test_random_sized_crop_aug():
    from mxnet_tpu import image

    src = np.random.RandomState(0).randint(
        0, 255, (64, 80, 3)).astype(np.uint8)
    aug = image.RandomSizedCropAug((32, 32), (0.5, 1.0), (0.75, 1.333))
    out = aug(nd.array(src.astype(np.float32)))
    assert out.shape == (32, 32, 3)
    crop, region = image.random_size_crop(
        nd.array(src.astype(np.float32)), (24, 24), (0.3, 1.0),
        (0.8, 1.25))
    assert crop.shape == (24, 24, 3)
    x0, y0, w, h = region
    assert 0 <= x0 <= 80 - w and 0 <= y0 <= 64 - h


def test_random_order_aug_and_create_augmenter_rand_resize():
    from mxnet_tpu import image

    calls = []

    class Tag(image.Augmenter):
        def __init__(self, tag):
            super().__init__()
            self.tag = tag

        def __call__(self, src):
            calls.append(self.tag)
            return src

    aug = image.RandomOrderAug([Tag(1), Tag(2), Tag(3)])
    aug(nd.zeros((4, 4, 3)))
    assert sorted(calls) == [1, 2, 3]
    augs = image.CreateAugmenter((3, 32, 32), rand_resize=True,
                                 rand_mirror=True)
    assert any(isinstance(a, image.RandomSizedCropAug) for a in augs)
    src = nd.array(np.random.RandomState(1).rand(50, 60, 3)
                         .astype(np.float32) * 255)
    out = src
    for a in augs:
        out = a(out)
    assert out.shape == (32, 32, 3)


def test_sparse_dot_transpose_b_and_sparse_rhs():
    a = np.zeros((4, 5), np.float32)
    a[0, 1] = 2
    a[3, 4] = 7
    b = np.random.RandomState(0).rand(3, 5).astype(np.float32)
    csr = nd.array(a).tostype("csr")
    out = sparse.dot(csr, nd.array(b), transpose_b=True).asnumpy()
    np.testing.assert_allclose(out, a @ b.T, rtol=1e-5)
    # sparse rhs densifies, not garbage
    rs = _rs(np.eye(5, 2, dtype=np.float32))
    out = sparse.dot(csr, rs).asnumpy()
    np.testing.assert_allclose(out, a @ np.eye(5, 2), rtol=1e-5)


def test_sparse_zeros_like_csr_keeps_stype():
    a = np.zeros((3, 4), np.float32)
    a[1, 2] = 5
    csr = nd.array(a).tostype("csr")
    z = sparse.zeros_like(csr)
    assert z.stype == "csr" and z.shape == (3, 4)
    assert z.asnumpy().sum() == 0


def test_random_order_aug_dumps_children():
    import json

    from mxnet_tpu import image

    aug = image.RandomOrderAug([image.CastAug(), image.HorizontalFlipAug(0.5)])
    payload = json.loads(aug.dumps())
    assert payload[0] == "RandomOrderAug"
    assert [c[0] for c in payload[1]] == ["CastAug", "HorizontalFlipAug"]


def test_checkpoint_fresh_run_same_dir_not_pruned(tmp_path):
    import glob

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.contrib.estimator import CheckpointHandler, Estimator

    def run():
        net = gluon.nn.Dense(2)
        net.initialize(mx.init.Xavier())
        est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                        metrics=mx.metric.Accuracy())
        rng = np.random.RandomState(0)
        data = [(nd.array(rng.randn(8, 4).astype(np.float32)),
                 nd.array((rng.rand(8) > 0.5).astype(np.float32)))]
        est.fit(iter(data), epochs=2,
                event_handlers=[CheckpointHandler(str(tmp_path),
                                                  max_checkpoints=5)])

    run()
    run()  # fresh run in the same dir must not delete its own saves
    saved = glob.glob(str(tmp_path / "model-epoch*.params"))
    assert len(saved) == 2, saved  # epoch1, epoch2 overwritten in place
