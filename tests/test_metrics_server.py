"""Live metrics plane (docs/OBSERVABILITY.md §Live metrics; ISSUE 13):
the shared OpenMetrics render core and its edge cases, the per-rank
HTTP endpoint (/metrics /healthz /statusz + portfile), the launch.py
gang merge with up/staleness gauges, per-request serving traces + SLO
counters, and bitwise training parity with the endpoint on vs off."""
import importlib.util
import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, metrics_server, nd, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_LAUNCH = os.path.join(_REPO, "tools", "launch.py")

_spec = importlib.util.spec_from_file_location("launch_mod", _LAUNCH)
launch_mod = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(launch_mod)

# one exposition line: comment or name{labels} value
_SAMPLE_RE = re.compile(r'^[a-z_][a-z0-9_]*\{[^{}]*\} -?[0-9.eE+-]+$')


def _assert_wellformed(body):
    lines = body.rstrip("\n").splitlines()
    assert lines[-1] == "# EOF", lines[-3:]
    assert body.count("# EOF") == 1
    for line in lines[:-1]:
        assert line.startswith("# TYPE ") or _SAMPLE_RE.match(line), line


@pytest.fixture
def tele():
    telemetry.reset()
    yield telemetry
    metrics_server.stop()
    telemetry.reset()


def _get(url, timeout=5.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _serve(tele, tmp_path=None):
    if tmp_path is not None:
        tele.enable(str(tmp_path))
    assert metrics_server.start(0)
    return f"http://127.0.0.1:{metrics_server.port()}"


# ---------------------------------------------------------------------------
# the shared render core (satellite: formatter edge cases)
# ---------------------------------------------------------------------------
def test_render_empty_summary_is_wellformed(tele):
    # recorder fully disabled, nothing recorded: the exposition must
    # still parse, end in # EOF, and carry the provenance stamps
    body = telemetry.render_prometheus(mode="live")
    _assert_wellformed(body)
    assert "mx_export_timestamp_seconds" in body
    assert 'mx_export_mode{rank="0",mode="live"} 1' in body


def test_export_mode_distinguishes_atexit_from_live(tele, tmp_path):
    tele.enable(str(tmp_path))
    tele.record_step("E", step=1, wall_s=0.01)
    live = telemetry.render_prometheus(mode="live")
    path = telemetry.export_prometheus(str(tmp_path / "m.prom"))
    snap = open(path).read()
    assert 'mode="live"' in live and 'mode="atexit"' not in live
    assert 'mode="atexit"' in snap and 'mode="live"' not in snap
    _assert_wellformed(snap)
    # the staleness stamp a dashboard ages a dead rank's snapshot by
    ts = float(re.search(
        r'mx_export_timestamp_seconds\{rank="0"\} ([0-9.]+)', snap).group(1))
    assert abs(time.time() - ts) < 60


def test_label_escaping_roundtrip(tele, tmp_path):
    tele.enable(str(tmp_path))
    nasty = 'Exec"quoted"\\back\\slash'
    tele.record_step(nasty, step=1, wall_s=0.01)
    body = telemetry.render_prometheus()
    _assert_wellformed(body)
    m = re.search(r'mx_step_total\{rank="0",executor="((?:[^"\\]|\\.)*)"\} 1',
                  body)
    assert m, body
    unescaped = m.group(1).replace(r"\"", '"').replace(r"\\", "\\")
    assert unescaped == nasty


def test_concurrent_scrape_during_flush_no_torn_exposition(tele, tmp_path):
    """Scrapes racing the recorder (records + flushes + heartbeats on
    other threads) must every time yield one complete, parseable
    exposition ending in # EOF — the render reads the locked rollups,
    so a torn body would mean the formatter itself is racy."""
    base = _serve(tele, tmp_path)
    stop = threading.Event()
    errs = []

    def churn():
        i = 0
        while not stop.is_set():
            i += 1
            telemetry.record_step('E"x\\y', step=i, wall_s=0.001, samples=4)
            telemetry.record_serve_request(decode_ms=1.0, tokens=2,
                                           ttft_ms=0.5, request_id=f"r{i}")
            telemetry.heartbeat(i, force=True)
            telemetry.flush()

    def scrape():
        try:
            for _ in range(25):
                status, body = _get(f"{base}/metrics")
                assert status == 200
                _assert_wellformed(body)
        except Exception as e:  # surfaces in the main thread's assert
            errs.append(e)

    churners = [threading.Thread(target=churn, daemon=True)
                for _ in range(2)]
    scrapers = [threading.Thread(target=scrape) for _ in range(3)]
    for t in churners + scrapers:
        t.start()
    for t in scrapers:
        t.join(timeout=60)
    stop.set()
    for t in churners:
        t.join(timeout=10)
    assert not errs, errs[0]


# ---------------------------------------------------------------------------
# endpoint routes
# ---------------------------------------------------------------------------
def test_metrics_route_serves_live_rollups(tele, tmp_path):
    base = _serve(tele, tmp_path)
    tele.record_step("ExecA", step=1, wall_s=0.01, samples=8,
                     inflight_depth=2)
    status, body = _get(f"{base}/metrics")
    assert status == 200
    _assert_wellformed(body)
    assert 'mx_step_total{rank="0",executor="ExecA"} 1' in body
    assert 'mode="live"' in body
    # and the root alias serves the same exposition
    status2, body2 = _get(f"{base}/")
    assert status2 == 200 and "mx_export_timestamp_seconds" in body2


def test_healthz_ok_then_stale_503(tele, tmp_path):
    base = _serve(tele, tmp_path)
    tele.heartbeat(7, force=True)
    status, body = _get(f"{base}/healthz")
    snap = json.loads(body)
    assert status == 200 and snap["healthy"], snap
    assert snap["last_step"] == 7 and snap["rank"] == 0
    # age the heartbeat far past the supervisor's staleness rule
    with telemetry._state.lock:
        telemetry._state.hb_wall = time.time() - 3600
    status, body = _get(f"{base}/healthz")
    snap = json.loads(body)
    assert status == 503 and not snap["healthy"]
    assert any("heartbeat" in r for r in snap["reasons"]), snap


def test_healthz_without_heartbeat_stays_healthy(tele, tmp_path):
    # a process that never heartbeat (startup, telemetry off) is not
    # thereby DEAD — only flowing-then-stopped heartbeats flip 503
    base = _serve(tele, tmp_path)
    status, body = _get(f"{base}/healthz")
    snap = json.loads(body)
    assert status == 200 and snap["healthy"]
    assert snap["heartbeat_age_s"] is None


def test_statusz_carries_summary_flight_and_health(tele, tmp_path):
    base = _serve(tele, tmp_path)
    tele.record_step("ExecA", step=1, wall_s=0.01)
    tele.record("custom_marker", note="x")
    status, body = _get(f"{base}/statusz")
    assert status == 200
    snap = json.loads(body)
    assert snap["export_mode"] == "live"
    assert snap["summary"]["steps"]["ExecA"]["count"] == 1
    assert any(e["kind"] == "custom_marker" for e in snap["flight"])
    assert snap["health"]["healthy"] is True
    assert "memwatch" in snap


def test_unknown_route_404(tele, tmp_path):
    base = _serve(tele, tmp_path)
    status, body = _get(f"{base}/nope")
    assert status == 404 and "/statusz" in body


def test_portfile_written_and_removed(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY_DIR", str(tmp_path))
    assert metrics_server.start(0)
    pf = metrics_server.portfile_path(str(tmp_path), 0)
    rec = json.load(open(pf))
    assert rec["port"] == metrics_server.port() > 0
    assert rec["pid"] == os.getpid()
    metrics_server.stop()
    assert not os.path.exists(pf)
    assert not metrics_server.enabled() and metrics_server.port() == 0


def test_config_port_semantics(monkeypatch):
    for raw, want in [("", None), ("off", None), ("garbage", None),
                      ("-1", None), ("0", 0), ("auto", 0), ("9100", 9100)]:
        monkeypatch.setenv("MX_METRICS_PORT", raw)
        assert metrics_server._config_port() == want, raw
    monkeypatch.delenv("MX_METRICS_PORT")
    assert metrics_server._config_port() is None
    assert metrics_server.maybe_start() is False  # default: off


# ---------------------------------------------------------------------------
# gang merge (launch.py side, unit level)
# ---------------------------------------------------------------------------
def test_merge_expositions_up_staleness_and_single_eof():
    now = time.time()
    # rank 0 carries a heartbeat-age gauge: a wedged training loop stops
    # heartbeating while its HTTP thread keeps rendering fresh export
    # timestamps — staleness must prefer the DATA age (120s), not the
    # render age (2s).  rank 1 has no heartbeat: falls back to the
    # export-timestamp age.
    body0 = ("# TYPE mx_export_timestamp_seconds gauge\n"
             f'mx_export_timestamp_seconds{{rank="0"}} {now - 2:.3f}\n'
             "# TYPE mx_heartbeat_age_seconds gauge\n"
             'mx_heartbeat_age_seconds{rank="0"} 120.0\n'
             "# TYPE mx_step_total counter\n"
             'mx_step_total{rank="0",executor="E"} 5\n'
             "# EOF\n")
    body1 = ("# TYPE mx_export_timestamp_seconds gauge\n"
             f'mx_export_timestamp_seconds{{rank="1"}} {now - 40:.3f}\n'
             "# TYPE mx_step_total counter\n"
             'mx_step_total{rank="1",executor="E"} 7\n'
             "# EOF\n")
    merged = launch_mod._merge_expositions({0: body0, 1: body1, 2: None})
    _assert_wellformed(merged)
    assert 'up{rank="0"} 1' in merged
    assert 'up{rank="1"} 1' in merged
    assert 'up{rank="2"} 0' in merged  # dead endpoint
    assert 'mx_step_total{rank="0",executor="E"} 5' in merged
    assert 'mx_step_total{rank="1",executor="E"} 7' in merged
    # duplicate TYPE lines collapse to one declaration per metric
    assert merged.count("# TYPE mx_step_total counter") == 1
    st = {m.group(1): float(m.group(2)) for m in re.finditer(
        r'mx_scrape_staleness_seconds\{rank="(\d)"\} ([0-9.]+)', merged)}
    assert st["0"] == 120.0, st          # heartbeat age wins
    assert 35.0 < st["1"] < 60.0, st     # export-timestamp fallback
    # families stay uninterrupted blocks (the OpenMetrics grouping rule)
    seen, last = set(), None
    for line in merged.rstrip().splitlines():
        if line.startswith("# EOF"):
            continue
        name = line.split()[2] if line.startswith("# TYPE ") \
            else line.split("{", 1)[0]
        if name != last:
            assert name not in seen, f"family {name} interleaved"
            seen.add(name)
            last = name


# ---------------------------------------------------------------------------
# bitwise parity: scraping must not perturb training
# ---------------------------------------------------------------------------
def _train_weights(tele, tmp_path, endpoint):
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    telemetry.reset()
    telemetry.enable(str(tmp_path))
    stop = th = None
    if endpoint:
        base = _serve(tele)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                _get(f"{base}/metrics")
                _get(f"{base}/healthz")
                stop.wait(0.01)

        th = threading.Thread(target=hammer, daemon=True)
        th.start()
    mx.random.seed(0)
    rng = np.random.RandomState(0)
    x = nd.array(rng.rand(16, 8).astype(np.float32))
    y = nd.array(rng.rand(16, 4).astype(np.float32))
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    step = DataParallelStep(
        net, gluon.loss.L2Loss(),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="sgd", optimizer_params={"learning_rate": 0.05})
    losses = []
    for _ in range(6):
        losses.append(step.step(x, y))
    step.drain()
    losses = [float(l) for l in losses]
    step.sync_to_block()
    # keyed by param order, not name: gluon's global name counter differs
    # between the two runs in one process (dense0 vs dense1)
    weights = [p.data().asnumpy().tobytes()
               for _k, p in sorted(net.collect_params().items())]
    if endpoint:
        stop.set()
        th.join(timeout=10)
        metrics_server.stop()
    return losses, weights


def test_losses_and_weights_bitwise_identical_endpoint_on_off(tele,
                                                              tmp_path):
    on_losses, on_w = _train_weights(tele, tmp_path / "on", endpoint=True)
    off_losses, off_w = _train_weights(tele, tmp_path / "off",
                                       endpoint=False)
    assert on_losses == off_losses
    assert on_w == off_w, "weights diverged with the endpoint scraped"


# ---------------------------------------------------------------------------
# serving request-trace e2e (acceptance): queue->prefill->decode spans
# per request id in the Perfetto export, TTFT p50/p99 + SLO violations
# in trace_report --json and in the prometheus exposition
# ---------------------------------------------------------------------------
def test_serving_request_trace_e2e(tele, tmp_path, monkeypatch):
    from mxnet_tpu.models.transformer import Transformer
    from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

    monkeypatch.setenv("MX_SERVE_SLO_TTFT_MS", "0.001")  # everything trips
    telemetry.enable(str(tmp_path))
    mx.random.seed(0)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=20, dropout=0.0)
    net.initialize(mx.init.Xavier())
    adapter = TransformerAdapter(net, src_max_len=8)
    eng = ServingEngine(adapter, slots=2, page_size=4, max_len=10,
                        stream_every=2)
    rng = np.random.RandomState(0)
    reqs = [Request(rng.randint(3, 16, n).astype(np.int32),
                    max_new_tokens=m, bos_id=1, eos_id=2,
                    request_id=f"q{i}")
            for i, (n, m) in enumerate([(3, 4), (6, 6), (4, 3), (2, 5)])]
    out = eng.serve(reqs, arrival_steps=[0, 0, 2, 4])  # mixed + mid-flight
    assert set(out) == {f"q{i}" for i in range(4)}
    telemetry.flush()

    # Perfetto export: every request id owns queue/prefill/decode slices
    trace_path = telemetry.export_chrome_trace(str(tmp_path))
    trace = json.load(open(trace_path))["traceEvents"]
    by_req = {}
    for ev in trace:
        rid = (ev.get("args") or {}).get("request_id")
        if rid is not None and ev.get("ph") == "X":
            by_req.setdefault(rid, set()).add(ev["name"])
    for i in range(4):
        assert {"serve_queue", "serve_prefill",
                "serve_decode"} <= by_req.get(f"q{i}", set()), by_req

    # trace_report --json: the serving section
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=60)
    rep = json.loads(res.stdout)
    srv = rep["serving"]
    assert srv["requests"] == 4
    assert srv["ttft_p50_ms"] > 0 and srv["ttft_p99_ms"] >= \
        srv["ttft_p50_ms"]
    assert srv["slo_violations"]["ttft"] == 4  # the injected violations
    ids = {r["id"] for r in srv["per_request"]}
    assert ids == {f"q{i}" for i in range(4)}
    for row in srv["per_request"]:
        assert row["decode_ms"] >= 0 and row["tokens"] > 0
    occ = srv["slot_occupancy"]
    assert occ["samples"] > 0 and 1 <= occ["max_active_slots"] <= 2
    # human rendering has the section too
    res_txt = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert "serving" in res_txt.stdout and "SLO violations: ttft=4" in \
        res_txt.stdout, res_txt.stdout

    # ...and the live exposition counts them
    body = telemetry.render_prometheus()
    assert 'mx_serve_slo_violations_total{rank="0",stage="ttft"} 4' in body
    assert 'mx_serve_slo_violations_total{rank="0",stage="tpot"} 0' in body
    assert "mx_serve_ttft_p50_ms" in body


# ---------------------------------------------------------------------------
# 2-rank gang e2e (acceptance): live per-rank endpoints during training,
# merged gang /metrics with both ranks' counters + up gauges, and a
# killed rank flipping up/healthz within one scrape
# ---------------------------------------------------------------------------
def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _poll(fn, deadline, why, sleep=0.2):
    while time.time() < deadline:
        out = fn()
        if out is not None:
            return out
        time.sleep(sleep)
    raise AssertionError(f"timed out waiting for {why}")


@pytest.mark.dist
def test_two_rank_gang_live_metrics_and_up_flip(tmp_path):
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    stop_file = tmp_path / "stop"
    gang_port = _free_port()
    env = dict(os.environ, MX_TELEMETRY_DIR=str(tdir),
               MX_HEARTBEAT_SEC="0.2", MX_TELEMETRY_FLUSH_SEC="0.2",
               MX_STOP_FILE=str(stop_file))
    env.pop("MX_METRICS_PORT", None)  # the supervisor exports it
    cmd = [sys.executable, _LAUNCH, "-n", "2", "--force-cpu",
           "--metrics-port", str(gang_port), "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "metrics_worker.py")]
    proc = subprocess.Popen(cmd, cwd=_REPO, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)
    try:
        deadline = time.time() + 210

        def ports():
            out = {}
            for r in (0, 1):
                pf = tdir / f"metrics-port-{r}.json"
                if pf.exists():
                    out[r] = json.load(open(pf))
            return out if len(out) == 2 else None

        ends = _poll(ports, deadline, "both rank portfiles")

        # each rank serves live /metrics + /healthz while running
        for r, rec in ends.items():
            base = f"http://127.0.0.1:{rec['port']}"

            def rank_training(base=base, r=r):
                status, body = _get(f"{base}/metrics")
                return body if status == 200 and \
                    f'mx_step_total{{rank="{r}"' in body else None

            body = _poll(rank_training, deadline, f"rank {r} step counters")
            _assert_wellformed(body)
            assert 'mode="live"' in body
            status, hz = _get(f"{base}/healthz")
            assert status == 200 and json.loads(hz)["healthy"], hz

        # the supervisor's merged gang exposition
        def merged_ready():
            status, body = _get(
                f"http://127.0.0.1:{gang_port}/metrics")
            ok = (status == 200 and 'up{rank="0"} 1' in body
                  and 'up{rank="1"} 1' in body
                  and 'mx_step_total{rank="0"' in body
                  and 'mx_step_total{rank="1"' in body)
            return body if ok else None

        merged = _poll(merged_ready, deadline, "merged gang metrics")
        _assert_wellformed(merged)
        assert "mx_scrape_staleness_seconds" in merged

        # kill rank 1: its up gauge and healthz flip on the next scrape
        os.kill(ends[1]["pid"], signal.SIGTERM)

        def rank1_down():
            status, body = _get(
                f"http://127.0.0.1:{gang_port}/metrics")
            return body if status == 200 and 'up{rank="1"} 0' in body \
                else None

        merged = _poll(rank1_down, time.time() + 30, "up flip for rank 1")
        assert 'up{rank="0"} 1' in merged  # the survivor is still live
        _assert_wellformed(merged)
        status, hz = _get(f"http://127.0.0.1:{gang_port}/healthz")
        snap = json.loads(hz)
        assert status == 503 and not snap["healthy"], snap
        assert not snap["ranks"]["1"]["healthy"]
        assert snap["ranks"]["0"]["healthy"]

        stop_file.write_text("go")
        out, err = proc.communicate(timeout=120)
        assert proc.returncode == 0, (out[-2000:], err[-2000:])
        assert "gang /metrics on" in err
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
