"""SyncBatchNorm semantics under the fused step (reference:
gluon/contrib/nn SyncBatchNorm ~L100 — cross-device BN via an engine-level
NCCL reduce).

The TPU-native realization (documented in gluon/contrib/nn/__init__.py):
under a pjit-compiled DataParallelStep the batch axis is GLOBAL, so batch
statistics are computed over the whole (sharded) batch with XLA inserting
the ICI all-reduce — ordinary BatchNorm IS sync-BN there.  This test pins
that claim: a dp8 run must match a single-device full-batch run exactly,
which can only happen if the normalization statistics are global (per-
device stats would see 8 different shard distributions and diverge)."""
import jax
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib.nn import SyncBatchNorm
from mxnet_tpu.parallel import DataParallelStep, local_mesh


def _make_net(seed):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1))
        net.add(SyncBatchNorm(num_devices=8))
        net.add(nn.Activation("relu"))
        net.add(nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(5))
    net.initialize(mx.init.Xavier())
    return net


import pytest


@pytest.mark.skipif(len(jax.devices()) < 8,
                    reason="needs the 8-device mesh (conftest provides it)")
def test_syncbn_fused_dp8_matches_single_device_full_batch():
    rng = np.random.RandomState(0)
    # deliberately non-iid across the batch so per-device statistics
    # would differ strongly shard to shard
    X = np.concatenate([rng.randn(2, 3, 8, 8) * (i + 1) + i
                        for i in range(8)]).astype(np.float32)
    Y = rng.randint(0, 5, 16).astype(np.float32)

    losses = {}
    for tag, devices in (("dp8", jax.devices()),
                         ("single", [jax.devices()[0]])):
        net = _make_net(7)
        step = DataParallelStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            mesh=local_mesh(devices=devices), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
        losses[tag] = [float(np.asarray(step.step(nd.array(X), nd.array(Y))))
                       for _ in range(4)]

    # identical trajectories <=> global batch statistics on the dp8 mesh
    np.testing.assert_allclose(losses["dp8"], losses["single"],
                               rtol=2e-4, atol=2e-5)
    # and training moved (the comparison isn't vacuous)
    assert losses["dp8"][-1] < losses["dp8"][0]
