"""Async step pipeline (docs/PERFORMANCE.md §Async pipeline): lazy
AsyncLoss handles, the bounded MX_ASYNC_INFLIGHT window, the device
prefetcher/step handshake, epoch/preemption drains, and deferred-error
delivery naming the dispatching step."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.parallel import AsyncLoss, DataParallelStep, local_mesh
from mxnet_tpu.parallel import async_loss as al
from mxnet_tpu.parallel import data_parallel as dp_mod

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tele(tmp_path):
    from mxnet_tpu import telemetry

    telemetry.reset()
    telemetry.enable(str(tmp_path / "tele"))
    yield telemetry
    telemetry.flush()
    telemetry.reset()


def _build(optimizer="sgd"):
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    return DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                            optimizer=optimizer)


def _batches(n, b=8, d=4):
    rng = np.random.RandomState(0)
    return [(nd.array(rng.rand(b, d).astype(np.float32)),
             nd.array(rng.rand(b, 4).astype(np.float32)))
            for _ in range(n)]


# ---------------------------------------------------------------------------
# parity: async changes WHEN the host observes results, never what is
# computed
# ---------------------------------------------------------------------------
def test_losses_and_weights_bitwise_identical_across_window_sizes(
        monkeypatch):
    batches = _batches(6)

    def run(limit):
        import jax

        monkeypatch.setenv("MX_ASYNC_INFLIGHT", str(limit))
        step = _build()
        handles = [step.step(x, y) for x, y in batches]
        step.drain()
        losses = [h.asnumpy() for h in handles]
        # gluon's global name counter gives each _build() a fresh block
        # prefix (dense0_, dense1_, ...) — strip it so runs compare
        weights = {n.split("_", 1)[-1]: np.asarray(jax.device_get(a))
                   for n, a in step.params.items()}
        return losses, weights

    sync_l, sync_w = run(0)
    for limit in (1, 2, 4):
        async_l, async_w = run(limit)
        for a, b in zip(sync_l, async_l):
            assert np.array_equal(a, b), (limit, sync_l, async_l)
        assert sync_w.keys() == async_w.keys()
        for name in sync_w:
            assert np.array_equal(sync_w[name], async_w[name]), (limit, name)


def test_step_returns_lazy_handle_and_sync_mode_forces(monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    (x, y), = _batches(1)
    h = step.step(x, y)
    assert isinstance(h, AsyncLoss)
    assert not h.forced and step.inflight_depth == 1
    v = float(h)  # __float__ forces
    assert h.forced and np.isfinite(v)
    assert step.inflight_depth == 0  # forcing removed it from the ring
    # np.asarray / asnumpy / asscalar / item agree after forcing
    assert float(np.asarray(h)) == v == h.asscalar() == h.item()
    # MX_ASYNC_INFLIGHT=0: today's synchronous behavior, forced at dispatch
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "0")
    h2 = step.step(x, y)
    assert isinstance(h2, AsyncLoss) and h2.forced
    assert step.inflight_depth == 0


def test_window_never_exceeds_limit(tele, monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    for x, y in _batches(8):
        step.step(x, y)  # never forced by the caller
        assert step.inflight_depth <= 2
    depths = [e["inflight_depth"] for e in tele.flight_tail(50)
              if e["kind"] == "step"]
    assert len(depths) == 8
    assert max(depths) == 2 and all(d <= 2 for d in depths), depths
    step.drain()
    assert step.inflight_depth == 0
    # the ring-full dispatches blocked on the oldest step: the rollup saw it
    row = [v for k, v in tele.summary()["steps"].items()
           if k.startswith("DataParallelStep")][0]
    assert row["block_wait_ms"] >= 0.0


def test_drain_on_epoch_end_via_device_prefetcher(monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "4")
    step = _build()
    rng = np.random.RandomState(0)
    it = mx.io.NDArrayIter(rng.rand(32, 4).astype(np.float32),
                           rng.rand(32, 4).astype(np.float32), batch_size=8)
    dit = mx.io.DevicePrefetchIter(it, step)
    n = 0
    for b in dit:
        step.step(b.data[0], b.label[0])
        n += 1
        assert step.inflight_depth <= 4
    assert n == 4
    # StopIteration drained the ring: every dispatched step has landed
    assert step.inflight_depth == 0
    # and the iterator resets cleanly for another epoch
    dit.reset()
    assert sum(1 for _ in dit) == 4 and step.inflight_depth == 0


def test_prefetcher_step_handshake_no_double_transfer(tele, monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    (x, y), = _batches(1)
    float(step.step(x, y))  # init params/state so puts below are inputs only
    calls = {"n": 0}
    orig = dp_mod._global_put

    def counting(arr, sharding):
        calls["n"] += 1
        return orig(arr, sharding)

    monkeypatch.setattr(dp_mod, "_global_put", counting)
    staged_d, staged_l = step.stage((x,), y)
    assert calls["n"] == 2  # one put per input, in the staging thread's stead
    step.step(staged_d[0], staged_l)
    assert calls["n"] == 2, "step re-transferred a pre-placed input"
    step.drain()
    ev = [e for e in tele.flight_tail(20) if e["kind"] == "step"][-1]
    assert ev["h2d_overlapped"] == ev["transfer_bytes"] > 0
    # an un-staged batch reports zero overlap
    float(step.step(x, y))
    ev = [e for e in tele.flight_tail(20) if e["kind"] == "step"][-1]
    assert "h2d_overlapped" not in ev and ev["transfer_bytes"] > 0
    row = tele.summary()["steps"][ev["executor"]]
    assert 0 < row["h2d_overlapped_bytes"] < row["transfer_bytes"]


def test_dataloader_prefetch_to_hook(monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    rng = np.random.RandomState(0)
    ds = gluon.data.ArrayDataset(rng.rand(32, 4).astype(np.float32),
                                 rng.rand(32, 4).astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=8, prefetch_to=step)
    n = 0
    for data, label in loader:
        h = step.step(data, label)
        n += 1
    assert n == 4
    assert step.inflight_depth == 0  # loader exhaustion drained the ring
    assert np.isfinite(float(h))


def test_stage_batches_abandoned_consumer_retires_worker(monkeypatch):
    import threading
    import time as _time

    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    rng = np.random.RandomState(0)
    ds = gluon.data.ArrayDataset(rng.rand(64, 4).astype(np.float32),
                                 rng.rand(64, 4).astype(np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=8, prefetch_to=step)
    before = threading.active_count()
    # the common fixed-steps loop: abandons the generator mid-epoch
    for _i, (data, label) in zip(range(2), loader):
        step.step(data, label)
    # generator close must retire the staging worker (no leaked thread
    # parked forever in q.put) and drain the in-flight ring
    deadline = _time.monotonic() + 5.0
    while threading.active_count() > before and _time.monotonic() < deadline:
        _time.sleep(0.05)
    assert threading.active_count() <= before
    assert step.inflight_depth == 0


# ---------------------------------------------------------------------------
# deferred failures
# ---------------------------------------------------------------------------
def test_deferred_error_names_dispatching_step():
    def boom(_value):
        raise RuntimeError("kaboom")

    ring = al.InflightRing("X")
    h = AsyncLoss(object(), step=7, executor="DataParallelStep:Net#9",
                  ring=ring, host_fn=boom)
    ring.admit(h)
    with pytest.raises(mx.base.MXNetError) as ei:
        h.wait()
    msg = str(ei.value)
    assert "step 7" in msg and "DataParallelStep:Net#9" in msg
    assert "kaboom" in msg
    # exactly the same (wrapped) error again on re-force; the ring is clean
    with pytest.raises(mx.base.MXNetError):
        float(h)
    assert ring.depth == 0

    # a poisoned handle inside the window surfaces when dispatch makes
    # room, and the ring never wedges
    ring2 = al.InflightRing("Y")
    bad = AsyncLoss(object(), step=1, executor="Y", ring=ring2, host_fn=boom)
    ring2.admit(bad)
    with pytest.raises(mx.base.MXNetError):
        ring2.make_room(1)
    assert ring2.depth == 0 and ring2.make_room(1) == 0.0


def test_drain_all_preemption_path(monkeypatch):
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "4")
    step = _build()
    for x, y in _batches(3):
        step.step(x, y)
    assert step.inflight_depth > 0
    assert al.drain_all() == []  # what the SIGTERM handler runs pre-snapshot
    assert step.inflight_depth == 0

    # best-effort contract: failures are returned, not raised
    ring = al.InflightRing("Z")
    ring.admit(AsyncLoss(object(), step=3, executor="Z", ring=ring,
                         host_fn=lambda v: (_ for _ in ()).throw(
                             RuntimeError("dead"))))
    errs = al.drain_all()
    assert len(errs) == 1 and "step 3" in str(errs[0])
    assert ring.depth == 0


# ---------------------------------------------------------------------------
# Trainer / Module ride the same window
# ---------------------------------------------------------------------------
def test_trainer_window_bounded_and_drains(tele, monkeypatch):
    from mxnet_tpu import autograd

    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    mx.random.seed(0)
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.L2Loss()
    x = nd.array(np.random.rand(4, 3).astype(np.float32))
    y = nd.array(np.random.rand(4, 2).astype(np.float32))
    for _ in range(5):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(4)
    depths = [e["inflight_depth"] for e in tele.flight_tail(50)
              if e["kind"] == "step" and e["executor"] == "Trainer"]
    assert len(depths) == 5 and all(0 < d <= 2 for d in depths), depths
    trainer.drain()
    assert trainer._inflight.depth == 0


def test_trainer_sync_mode_adds_no_fences(monkeypatch):
    from mxnet_tpu import autograd

    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "0")
    mx.random.seed(0)
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    with autograd.record():
        loss = gluon.loss.L2Loss()(
            net(nd.array(np.random.rand(4, 3).astype(np.float32))),
            nd.array(np.random.rand(4, 2).astype(np.float32)))
    loss.backward()
    trainer.step(4)
    assert trainer._inflight is None
    trainer.drain()  # no-op, must not raise


# ---------------------------------------------------------------------------
# 2-rank gang: deferred readback across a real Gloo mesh (slow tier per
# the tier-1 wall budget; the in-process tests above cover the default
# tier)
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.dist
def test_two_rank_gang_deferred_readback_parity():
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", "2", "--force-cpu", "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "async_step_worker.py")]
    res = subprocess.run(cmd, cwd=_REPO, timeout=240, capture_output=True,
                         text=True, env=dict(os.environ))
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("async dist OK") == 2, res.stdout
