"""INT8 quantization (reference: src/operator/quantization/ + contrib
quantize_net/calibrate.cc) and the subgraph partitioning API (reference:
src/operator/subgraph/ build_subgraph.cc).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym
from mxnet_tpu.contrib import quantization as qz


# ---------------------------------------------------------------------------
# quantization ops
# ---------------------------------------------------------------------------
def test_quantize_dequantize_roundtrip():
    x = np.random.RandomState(0).uniform(-3, 5, (4, 6)).astype(np.float32)
    q, mn, mx_ = nd.contrib.quantize_v2(nd.array(x))
    assert q.dtype == np.int8
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    # int8 resolution: |err| <= thresh/127
    np.testing.assert_allclose(back, x, atol=float(mx_.asnumpy()) / 127 + 1e-6)


def test_quantize_with_calibrated_range_clips():
    x = nd.array(np.array([0.5, 10.0, -0.25], np.float32))
    q, mn, mx_ = nd.contrib.quantize_v2(x, min_calib_range=-1.0,
                                        max_calib_range=1.0)
    v = q.asnumpy()
    assert v[1] == 127  # outlier saturates
    np.testing.assert_allclose(float(mx_.asnumpy()), 1.0)


def test_quantized_fc_matches_f32():
    rng = np.random.RandomState(1)
    x = rng.uniform(-1, 1, (3, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, (4, 8)).astype(np.float32)
    b = rng.uniform(-1, 1, 4).astype(np.float32)
    qx, mnx, mxx = nd.contrib.quantize_v2(nd.array(x))
    qw, mnw, mxw = nd.contrib.quantize_v2(nd.array(w))
    acc, amn, amx = nd.contrib.quantized_fully_connected(
        qx, qw, nd.array(b), mnx, mxx, mnw, mxw, num_hidden=4)
    out = nd.contrib.dequantize(acc, amn, amx).asnumpy()
    ref = x @ w.T + b
    np.testing.assert_allclose(out, ref, rtol=0.05, atol=0.05)


def test_quantized_conv_matches_f32():
    rng = np.random.RandomState(2)
    x = rng.uniform(-1, 1, (2, 3, 8, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, (4, 3, 3, 3)).astype(np.float32)
    qx, mnx, mxx = nd.contrib.quantize_v2(nd.array(x))
    qw, mnw, mxw = nd.contrib.quantize_v2(nd.array(w))
    acc, amn, amx = nd.contrib.quantized_conv(
        qx, qw, nd.zeros((4,)), mnx, mxx, mnw, mxw, kernel=(3, 3),
        num_filter=4, pad=(1, 1), no_bias=True)
    out = nd.contrib.dequantize(acc, amn, amx).asnumpy()
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=4, pad=(1, 1), no_bias=True).asnumpy()
    scale = np.abs(ref).max()
    np.testing.assert_allclose(out, ref, atol=0.05 * scale)


def test_requantize_int32_to_int8():
    rng = np.random.RandomState(3)
    x = rng.uniform(-1, 1, (3, 8)).astype(np.float32)
    w = rng.uniform(-1, 1, (4, 8)).astype(np.float32)
    qx, mnx, mxx = nd.contrib.quantize_v2(nd.array(x))
    qw, mnw, mxw = nd.contrib.quantize_v2(nd.array(w))
    acc, amn, amx = nd.contrib.quantized_fully_connected(
        qx, qw, nd.zeros((4,)), mnx, mxx, mnw, mxw, num_hidden=4,
        no_bias=True)
    q8, rmn, rmx = nd.contrib.requantize(acc, amn, amx)
    assert q8.dtype == np.int8
    back = nd.contrib.dequantize(q8, rmn, rmx).asnumpy()
    np.testing.assert_allclose(back, x @ w.T, rtol=0.1, atol=0.08)


# ---------------------------------------------------------------------------
# calibration + quantize_net
# ---------------------------------------------------------------------------
def test_entropy_threshold_ignores_outlier():
    rng = np.random.RandomState(4)
    arr = np.concatenate([rng.uniform(-1, 1, 100000), [100.0]])
    t = qz.calib_entropy_threshold(arr.astype(np.float32))
    # candidate thresholds start at bin num_quantized_bins/num_bins of the
    # range (reference calibrate.cc granularity): ~12.5 here vs naive 100
    assert t < 15.0, t


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


@pytest.mark.parametrize("mode", ["none", "naive", "entropy"])
def test_quantize_net_close_to_f32(mode):
    mx.random.seed(5)
    net = _mlp()
    x = nd.array(np.random.RandomState(5).uniform(-1, 1, (16, 10))
                 .astype(np.float32))
    ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_data=[x] if mode != "none" else None,
                           calib_mode=mode)
    out = qnet(x).asnumpy()
    scale = max(1.0, np.abs(ref).max())
    np.testing.assert_allclose(out, ref, atol=0.1 * scale,
                               err_msg=f"mode={mode}")
    # classification decisions should essentially agree
    agree = (out.argmax(1) == ref.argmax(1)).mean()
    assert agree >= 0.9, agree


def test_quantize_net_hybridized_calibration():
    """Regression (review): forward pre-hooks don't fire through the
    CachedOp path; calibration must de-hybridize temporarily."""
    mx.random.seed(12)
    net = _mlp()
    net.hybridize()
    x = nd.array(np.random.RandomState(12).uniform(-1, 1, (8, 10))
                 .astype(np.float32))
    net(x)  # compile the cached op
    ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = qnet(x).asnumpy()
    scale = max(1.0, np.abs(ref).max())
    np.testing.assert_allclose(out, ref, atol=0.1 * scale)
    # hybridization restored afterwards
    assert net._children["0"]._active or net._active


def test_quantize_net_conv(tmp_path):
    mx.random.seed(6)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.Conv2D(4, 3, padding=1))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(6).uniform(-1, 1, (2, 3, 8, 8))
                 .astype(np.float32))
    ref = net(x).asnumpy()
    qnet = qz.quantize_net(net, calib_data=[x], calib_mode="naive")
    out = qnet(x).asnumpy()
    scale = max(1.0, np.abs(ref).max())
    np.testing.assert_allclose(out, ref, atol=0.12 * scale)


def test_quantize_model_symbol_api_raises():
    # returning the symbol unchanged would be a silent f32 no-op; the
    # symbolic rewrite is unimplemented and must say so
    from mxnet_tpu.base import MXNetError

    data = sym.Variable("data")
    fc = sym.FullyConnected(data, name="fc1", num_hidden=4)
    w = nd.array(np.random.rand(4, 8).astype(np.float32))
    with pytest.raises(MXNetError, match="quantize_net"):
        qz.quantize_model(fc, {"fc1_weight": w}, {})


def test_quantize_net_rejects_custom_forward_root():
    from mxnet_tpu.base import MXNetError

    class Residual(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.fc1 = gluon.nn.Dense(8)
                self.fc2 = gluon.nn.Dense(8)

        def hybrid_forward(self, F, x):
            return x + self.fc2(F.relu(self.fc1(x)))

    net = Residual()
    net.initialize(mx.init.Xavier())
    net(nd.array(np.random.rand(2, 8).astype(np.float32)))
    with pytest.raises(MXNetError, match="Sequential"):
        qz.quantize_net(net, calib_mode="none")


# ---------------------------------------------------------------------------
# subgraph partitioning
# ---------------------------------------------------------------------------
def test_partition_claims_compute_chain():
    from mxnet_tpu import subgraph as sg

    data = sym.Variable("data")
    h = sym.FullyConnected(data, name="fc1", num_hidden=8)
    h = sym.Activation(h, act_type="relu")
    h = sym.FullyConnected(h, name="fc2", num_hidden=4)
    out = sym.softmax(h)
    part = sg.partition(out, "default")
    import json

    js = json.loads(part.tojson())
    sub_nodes = [n for n in js["nodes"] if n["op"] == "_subgraph"]
    assert len(sub_nodes) == 1
    # all four compute ops claimed into one region
    assert int(sub_nodes[0]["attrs"]["num_nodes"]) == 4


def test_partition_extends_past_merge():
    """Regression (review): a multi-input join that merges two groups must
    not poison the merged group — the downstream op still fuses in."""
    from mxnet_tpu import subgraph as sg

    a = sym.Variable("a")
    n1 = sym.relu(a)
    n2 = sym.sigmoid(a)
    out = sym.relu(n1 + n2)
    part = sg.partition(out, "default")
    import json

    js = json.loads(part.tojson())
    subs = [n for n in js["nodes"] if n["op"] == "_subgraph"]
    assert len(subs) == 1
    assert int(subs[0]["attrs"]["num_nodes"]) == 4  # relu,sigmoid,add,relu
    x = nd.array(np.random.RandomState(12).randn(2, 3).astype(np.float32))
    got = part.bind(args={"a": x}, grad_req="null").forward()[0].asnumpy()
    ref = out.bind(args={"a": x}, grad_req="null").forward()[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_partition_executes_same_results():
    from mxnet_tpu import subgraph as sg

    data = sym.Variable("data")
    h = sym.FullyConnected(data, name="fcp1", num_hidden=8)
    h = sym.Activation(h, act_type="relu")
    out = sym.FullyConnected(h, name="fcp2", num_hidden=3)

    x = np.random.RandomState(7).rand(4, 6).astype(np.float32)
    args = {"data": nd.array(x),
            "fcp1_weight": nd.array(np.random.RandomState(8).rand(8, 6)
                                    .astype(np.float32)),
            "fcp1_bias": nd.zeros((8,)),
            "fcp2_weight": nd.array(np.random.RandomState(9).rand(3, 8)
                                    .astype(np.float32)),
            "fcp2_bias": nd.zeros((3,))}
    ref = out.bind(args=dict(args), grad_req="null").forward()[0].asnumpy()
    part = sg.partition(out, "default")
    got = part.bind(args=dict(args), grad_req="null").forward()[0].asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_partition_respects_unsupported_node():
    """BatchNorm (stateful, unclaimed) splits the chain; the partitioner
    must not fuse across it (cycle-safety poison rule)."""
    from mxnet_tpu import subgraph as sg

    data = sym.Variable("data")
    h = sym.FullyConnected(data, name="fcs1", num_hidden=8)
    h = sym.Activation(h, act_type="relu")
    h = sym.BatchNorm(h, name="bns1")
    h = sym.FullyConnected(h, name="fcs2", num_hidden=4)
    out = sym.Activation(h, act_type="relu")
    part = sg.partition(out, "default")
    import json

    js = json.loads(part.tojson())
    ops = [n["op"] for n in js["nodes"]]
    assert ops.count("_subgraph") == 2
    assert "BatchNorm" in ops


def test_partition_merge_then_poison_regression():
    """Regression (review): after two groups merge, poison sets recorded
    under the OLD group id must still protect the merged group — this
    graph used to recurse infinitely."""
    from mxnet_tpu import subgraph as sg

    a = sym.Variable("a")
    b = sym.Variable("b")
    n1 = sym.relu(a)
    n2 = sym.relu(b)
    n3 = sym.BatchNorm(n2, name="bn_poison")
    n4 = n1 + n2  # merges n1/n2's groups
    n5 = n3 + n4  # must NOT join the merged group (path through bn)
    out = sym.Group([n1, n5])
    part = sg.partition(out, "default")
    import json

    js = json.loads(part.tojson())
    ops = [n["op"] for n in js["nodes"]]
    assert "BatchNorm" in ops
    # executes correctly end-to-end
    args = {"a": nd.array(np.random.rand(2, 3).astype(np.float32)),
            "b": nd.array(np.random.rand(2, 3).astype(np.float32)),
            "bn_poison_gamma": nd.ones((3,)),
            "bn_poison_beta": nd.zeros((3,))}
    aux = {"bn_poison_moving_mean": nd.zeros((3,)),
           "bn_poison_moving_var": nd.ones((3,))}
    outs = part.bind(args=args, aux_states=aux, grad_req="null").forward()
    for o in outs:
        assert np.isfinite(o.asnumpy()).all()


def test_env_backend_hook(monkeypatch):
    from mxnet_tpu import subgraph as sg

    monkeypatch.setenv("MXNET_SUBGRAPH_BACKEND", "default")
    assert sg.env_backend() == "default"
    data = sym.Variable("data")
    out = sym.Activation(sym.FullyConnected(data, name="fce", num_hidden=4),
                         act_type="relu")
    x = np.random.RandomState(10).rand(2, 6).astype(np.float32)
    args = {"data": nd.array(x),
            "fce_weight": nd.array(np.random.RandomState(11).rand(4, 6)
                                   .astype(np.float32)),
            "fce_bias": nd.zeros((4,))}
    # bind applies the env partition transparently and still computes right
    got = out.bind(args=args, grad_req="null").forward()[0].asnumpy()
    assert np.isfinite(got).all()
