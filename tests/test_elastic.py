"""Elastic gang resize (docs/FAULT_TOLERANCE.md §Elastic resize): resharding
checkpoint restore onto a different mesh/world size, the checkpointable
iterator cursor that survives a resize with no sample skipped or consumed
twice, the shared-dir writer contract, the --elastic supervisor (shrink on
exhausted restarts, regrow after stable running), and the resize-aware
report tools.

Fast tier: everything except the two gang e2e runs at the bottom (slow):
a 3-rank gang that permanently loses rank 2 (`if-world=3` chaos spec),
shrinks to 2, and finishes bitwise-identical to a fixed 2-rank baseline
resumed from the same checkpoint — and the 2->3 grow mirror.
"""
import json
import os
import re
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io.io import NDArrayIter
from mxnet_tpu.parallel import DataParallelStep, make_mesh
from mxnet_tpu.parallel.sharding import ShardingRules

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# checkpointable iterator position (tentpole (c) + seeded-shuffle satellite)
# ---------------------------------------------------------------------------
def _data(n=48, d=1):
    X = np.arange(n * d, dtype=np.float32).reshape(n, d)
    Y = np.arange(n, dtype=np.float32)
    return X, Y


def test_seeded_shuffle_reproducible_and_per_iterator():
    """Same seed => same epoch order, independent of global np.random and
    of any other iterator's draws (the io.py:130 global-shuffle fix)."""
    X, Y = _data()
    np.random.seed(1)
    a = NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=7)
    np.random.seed(999)  # global state must be irrelevant
    b = NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=7)
    # interleave a third iterator's construction + draws: no perturbation
    c = NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=8)
    c.next()
    ia = [int(v) for _ in range(3) for v in (a.next(), a.getindex())[1]]
    ib = [int(v) for _ in range(3) for v in (b.next(), b.getindex())[1]]
    assert ia == ib
    # different epochs shuffle differently, reproducibly
    a.reset(), b.reset()
    ia2 = [int(v) for _ in range(3) for v in (a.next(), a.getindex())[1]]
    ib2 = [int(v) for _ in range(3) for v in (b.next(), b.getindex())[1]]
    assert ia2 == ib2 and ia2 != ia


def test_unseeded_iterator_state_still_restores_exactly():
    """seed=None draws a seed but records it in get_state: a restore
    reproduces the order without the caller ever choosing a seed."""
    X, Y = _data()
    it = NDArrayIter(X, Y, batch_size=4, shuffle=True)
    it.next()
    state = it.get_state()
    rest = [int(v) for _ in range(2) for v in (it.next(), it.getindex())[1]]
    it2 = NDArrayIter(X, Y, batch_size=4, shuffle=True)
    it2.set_state(state)
    rest2 = [int(v) for _ in range(2)
             for v in (it2.next(), it2.getindex())[1]]
    assert rest == rest2


def test_gang_sharding_rejects_unsafe_configs():
    """Divergent-per-rank hazards fail at construction: shuffle without
    an agreed seed would shard DIFFERENT permutations, and roll_over
    would hand higher-index parts ragged final batches."""
    X, Y = _data()
    with pytest.raises(MXNetError, match="explicit.*seed|seed.*explicit"):
        NDArrayIter(X, Y, batch_size=4, shuffle=True, num_parts=2,
                    part_index=0)
    with pytest.raises(MXNetError, match="roll_over"):
        NDArrayIter(X, Y, batch_size=4, seed=1, num_parts=2, part_index=1,
                    last_batch_handle="roll_over")
    # single-part legacy behaviors keep working
    NDArrayIter(X, Y, batch_size=4, shuffle=True)
    NDArrayIter(X, Y, batch_size=4, last_batch_handle="roll_over")


def test_state_rejects_different_dataset():
    X, Y = _data()
    it = NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=3)
    state = it.get_state()
    other = NDArrayIter(X[:40], Y[:40], batch_size=4, shuffle=True, seed=3)
    with pytest.raises(MXNetError, match="same dataset"):
        other.set_state(state)


def test_num_parts_shards_one_global_order():
    """Ranks of one (seed, epoch) permutation tile the global batch:
    part p takes batch_size samples at offset p, cursor strides by
    batch_size * num_parts."""
    X, Y = _data(24)
    parts = [NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=5,
                         num_parts=2, part_index=p) for p in range(2)]
    whole = NDArrayIter(X, Y, batch_size=8, shuffle=True, seed=5)
    for _ in range(3):
        whole.next()
        got = []
        for it in parts:
            it.next()
            got.extend(int(v) for v in it.getindex())
        assert got == [int(v) for v in whole.getindex()]


def test_iterator_census_across_resize_no_skip_no_dup():
    """ACCEPTANCE: a mid-epoch world-size change (3 ranks -> 2 ranks,
    different per-rank batch split) via get_state/set_state consumes
    every sample of the epoch EXACTLY once — the sample-id census."""
    X, Y = _data(48)
    old = [NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=7,
                       num_parts=3, part_index=p) for p in range(3)]
    seen = []
    for _ in range(2):  # 2 global batches x 12 samples at world 3
        for it in old:
            it.next()
            seen.extend(int(v) for v in it.getindex())
    state = old[0].get_state()
    assert state["sample_cursor"] == 24
    # "resize": 2 ranks, batch 6 (stride 12 -> 12; also try uneven stride)
    new = [NDArrayIter(X, Y, batch_size=6, shuffle=True, seed=0,
                       num_parts=2, part_index=p) for p in range(2)]
    for it in new:
        it.set_state(state)
    while True:
        try:
            for it in new:
                it.next()
                seen.extend(int(v) for v in it.getindex())
        except StopIteration:
            break
    assert sorted(seen) == list(range(48)), "census: skipped/duplicated"


def test_iterator_census_grow_with_stride_change():
    """Grow mirror with a stride that does NOT divide the old cursor:
    2 ranks x batch 3 (stride 6) -> 3 ranks x batch 4 (stride 12)."""
    X, Y = _data(48)
    old = [NDArrayIter(X, Y, batch_size=3, shuffle=True, seed=11,
                       num_parts=2, part_index=p) for p in range(2)]
    seen = []
    for _ in range(3):  # 18 samples consumed
        for it in old:
            it.next()
            seen.extend(int(v) for v in it.getindex())
    state = old[0].get_state()
    new = [NDArrayIter(X, Y, batch_size=5, shuffle=True, seed=0,
                       num_parts=3, part_index=p) for p in range(3)]
    for it in new:
        it.set_state(state)
    for _ in range(2):  # 2 more global batches x 15
        for it in new:
            it.next()
            seen.extend(int(v) for v in it.getindex())
    assert sorted(seen) == list(range(48)), "census: skipped/duplicated"


# ---------------------------------------------------------------------------
# resharding checkpoint restore (tentpole (a))
# ---------------------------------------------------------------------------
def _train_step(mesh, rules=None, opt="adam", steps=3, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.Dense(3)
    net.initialize(mx.init.Normal(0.5))
    step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=mesh,
                            optimizer=opt, rules=rules,
                            optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(0)
    data = nd.array(rng.rand(8, 6).astype(np.float32))
    label = nd.array(rng.rand(8, 3).astype(np.float32))
    for _ in range(steps):
        float(step.step(data, label))
    return step, (data, label)


def test_checkpoint_records_layout_and_opt_state(tmp_path):
    import jax

    step, _ = _train_step(make_mesh(devices=jax.devices()[:4]))
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()
    meta = json.load(open(tmp_path / "step-1" / "meta.json"))
    assert meta["world_size"] == 1
    lay = meta["layout"]
    assert dict(map(tuple, lay["mesh_axes"]))["dp"] == 4
    assert len(lay["device_ids"]) == 4
    assert set(lay["specs"]) == {"weight", "bias"}
    assert (tmp_path / "step-1" / "opt_state.nd").exists()
    assert "opt_state.nd" in meta["digests"]


def test_restore_reshards_onto_smaller_and_larger_mesh(tmp_path):
    """Save on dp4, restore on dp2 (shrink) and dp8 (grow): params AND
    Adam moments identical — the N->M correctness core the gang e2e
    rides on.  Training continues: bitwise-identical between two
    restores at the SAME new size, and within the documented GSPMD
    tolerance of the old mesh's trajectory (a different mesh size
    compiles a different reduction order)."""
    import jax

    step, (data, label) = _train_step(make_mesh(devices=jax.devices()[:4]))
    ref = step.state_dict()
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()
    ref_next = float(step.step(data, label))

    def restore_fresh(devs):
        mx.random.seed(0)
        net2 = gluon.nn.Dense(3)
        net2.initialize(mx.init.Normal(0.5))
        step2 = DataParallelStep(net2, gluon.loss.L2Loss(),
                                 mesh=make_mesh(devices=devs),
                                 optimizer="adam",
                                 optimizer_params={"learning_rate": 0.05})
        assert checkpoint.restore(str(tmp_path), step2) == 1
        return step2

    for devs in (jax.devices()[:2], jax.devices()):
        step2 = restore_fresh(devs)
        sd = step2.state_dict()
        for k, v in ref["params"].items():
            np.testing.assert_array_equal(v, sd["params"][k])
        for k, v in ref["opt_state"].items():
            np.testing.assert_array_equal(v, sd["opt_state"][k])
        nxt = float(step2.step(data, label))
        # same-new-size restores are bitwise self-consistent (what the
        # gang e2e's fixed-size-baseline parity rides on)...
        assert nxt == float(restore_fresh(devs).step(data, label))
        # ...and track the old mesh within GSPMD reduction-order drift
        np.testing.assert_allclose(nxt, ref_next, rtol=1e-5)


def test_restore_same_size_different_device_order(tmp_path):
    """ACCEPTANCE satellite: a mesh of the SAME size but a different
    device order is a different layout (device assignment is load-bearing
    — the AOT-cache lesson); restore must detect the mismatch, reshard,
    and produce identical values."""
    import jax

    from mxnet_tpu.parallel.data_parallel import _layouts_equal

    step, (data, label) = _train_step(make_mesh(devices=jax.devices()[:4]))
    ref = step.state_dict()
    saved_layout = step.layout()
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()
    ref_next = float(step.step(data, label))

    mx.random.seed(0)
    net2 = gluon.nn.Dense(3)
    net2.initialize(mx.init.Normal(0.5))
    mesh2 = make_mesh(devices=list(reversed(jax.devices()[:4])))
    step2 = DataParallelStep(net2, gluon.loss.L2Loss(), mesh=mesh2,
                             optimizer="adam",
                             optimizer_params={"learning_rate": 0.05})
    assert not _layouts_equal(saved_layout, {**saved_layout,
                                             "device_ids": [3, 2, 1, 0]})
    state = checkpoint.load_checkpoint_state(str(tmp_path), step=1)
    host = {"params": {k: v.asnumpy() for k, v in state["params"].items()},
            "opt_state": {k: v.asnumpy()
                          for k, v in state["opt_state"].items()}}
    info = step2.load_state_dict(host, saved_layout=state["layout"])
    assert info["resharded"], "reordered devices must count as a reshard"
    sd = step2.state_dict()
    for k, v in ref["params"].items():
        np.testing.assert_array_equal(v, sd["params"][k])
    assert float(step2.step(data, label)) == ref_next


def test_restore_reshards_tp_sharded_params(tmp_path):
    """Genuinely SHARDED (tensor-parallel) params round-trip through the
    gather-to-host baseline and land correctly on a different mesh."""
    import jax

    rules = ShardingRules([(r".*weight", (None, "tp"))])
    mesh = make_mesh(tp=2, devices=jax.devices()[:4])
    step, (data, label) = _train_step(mesh, rules=rules)
    ref = step.state_dict()
    lay = step.layout()
    assert lay["specs"]["weight"] == [None, "tp"]
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()

    mx.random.seed(0)
    net2 = gluon.nn.Dense(3)
    net2.initialize(mx.init.Normal(0.5))
    mesh2 = make_mesh(tp=2, devices=jax.devices()[4:6])
    step2 = DataParallelStep(net2, gluon.loss.L2Loss(), mesh=mesh2,
                             optimizer="adam", rules=rules,
                             optimizer_params={"learning_rate": 0.05})
    assert checkpoint.restore(str(tmp_path), step2) == 1
    sd = step2.state_dict()
    for k, v in ref["params"].items():
        np.testing.assert_array_equal(v, sd["params"][k])


def test_discard_mode_restored_unaligned_cursor_stays_uniform():
    """set_state under discard with a CHANGED stride may land on a
    cursor unaligned to the new stride; every emitted batch must still
    be full-shape on every rank (a straddling window would hand rank 1
    an empty batch into a sync collective) and the epoch tail shorter
    than one global window is discarded — discard semantics."""
    X, Y = _data(20)
    old = NDArrayIter(X, Y, batch_size=6, shuffle=True, seed=3,
                      num_parts=2, part_index=0,
                      last_batch_handle="discard")
    old.next()
    state = old.get_state()
    assert state["sample_cursor"] == 12
    new = [NDArrayIter(X, Y, batch_size=4, shuffle=True, seed=3,
                       num_parts=2, part_index=p,
                       last_batch_handle="discard") for p in range(2)]
    counts = []
    for it in new:
        it.set_state(state)
        n_batches = 0
        while True:
            try:
                b = it.next()
            except StopIteration:
                break
            n_batches += 1
            assert b.data[0].shape == (4, 1), b.data[0].shape
        counts.append(n_batches)
    # both ranks see the SAME number of full batches: window 12..20 fits
    # exactly once under stride 8
    assert counts == [1, 1], counts


def test_manual_resize_restore_records_marker_but_elastic_does_not(
        tmp_path, monkeypatch):
    """The `resize` segment marker is minted exactly once per logical
    resize: by the restore for supervisor-less (manual) world changes,
    by the rendezvous under --elastic — a later same-size restart that
    re-restores the old-world checkpoint must not double it."""
    import glob

    import jax

    from mxnet_tpu import telemetry

    step, _ = _train_step(make_mesh(devices=jax.devices()[:4]), steps=1)
    state = step.state_dict()
    saved = step.layout()
    saved["world_size"] = 3  # pretend the save came from a 3-proc gang

    def resize_events(run):
        monkeypatch.setenv("MX_TELEMETRY_DIR", "")
        telemetry.reset()
        d = str(tmp_path / run)
        telemetry.enable(d)
        mx.random.seed(0)
        net2 = gluon.nn.Dense(3)
        net2.initialize(mx.init.Normal(0.5))
        step2 = DataParallelStep(net2, gluon.loss.L2Loss(),
                                 mesh=make_mesh(devices=jax.devices()[4:6]),
                                 optimizer="adam",
                                 optimizer_params={"learning_rate": 0.05})
        info = step2.load_state_dict(state, saved_layout=saved)
        assert info["resharded"]
        telemetry.flush()
        telemetry.reset()
        events = [json.loads(line)
                  for f in glob.glob(os.path.join(d, "rank-*.jsonl"))
                  for line in open(f)]
        return [e for e in events if e.get("kind") == "resize"], \
               [e for e in events if e.get("kind") == "reshard"]

    monkeypatch.delenv("MX_ELASTIC", raising=False)
    monkeypatch.delenv("MX_PREV_NUM_PROCS", raising=False)
    resizes, reshards = resize_events("manual")
    assert len(resizes) == 1 and resizes[0]["old_world"] == 3
    assert reshards, "reshard detail event must record either way"

    # under the supervisor (any incarnation — incl. a same-size restart
    # after the resize, where MX_PREV_NUM_PROCS is no longer exported)
    # the rendezvous owns the marker
    monkeypatch.setenv("MX_ELASTIC", "1")
    resizes, reshards = resize_events("elastic")
    assert resizes == [], resizes
    assert reshards


def test_restore_rejects_optimizer_kind_mismatch(tmp_path):
    """An adam checkpoint restored into an sgd step must raise, not
    silently zero-fill every optimizer slot."""
    import jax

    step, _ = _train_step(make_mesh(devices=jax.devices()[:2]), steps=1)
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()
    mx.random.seed(0)
    net2 = gluon.nn.Dense(3)
    net2.initialize(mx.init.Normal(0.5))
    step2 = DataParallelStep(net2, gluon.loss.L2Loss(),
                             mesh=make_mesh(devices=jax.devices()[:2]),
                             optimizer="sgd")
    with pytest.raises(MXNetError, match="'adam'.*'sgd'"):
        checkpoint.restore(str(tmp_path), step2)


def test_nonwriter_checkpointer_counts_but_never_writes(tmp_path):
    """Shared-dir gang contract: writer=False ranks step-count, heartbeat
    and run the chaos hooks, but never publish (or prune) anything."""
    import jax

    step, _ = _train_step(make_mesh(devices=jax.devices()[:2]), steps=2)
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1,
                                      writer=False)
    assert ck.step(step) is False
    assert ck.save_now(step) == 0
    ck.close()
    assert not any(d.startswith("step-") for d in os.listdir(tmp_path))
    # a non-writer with an explicit resume step must not prune the shared
    # timeline the writer owns
    w = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    w.step(step)
    w.step(step)
    w.close()
    ro = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1,
                                      initial_step=1, writer=False)
    ro.close()
    assert os.path.isdir(tmp_path / "step-2"), "non-writer pruned the dir"


# ---------------------------------------------------------------------------
# fault grammar: if-world + crash-rendezvous (satellite)
# ---------------------------------------------------------------------------
def test_if_world_qualifier_gates_by_world_size(monkeypatch):
    from mxnet_tpu import fault

    f = fault.parse_spec("crash:step=8:rank=2:if-world=3")[0]
    assert f.if_world == 3
    monkeypatch.setenv("MX_PROC_ID", "2")
    monkeypatch.setenv("MX_NUM_PROCS", "3")
    assert f.applies_here()
    monkeypatch.setenv("MX_NUM_PROCS", "2")  # after the shrink: inert
    assert not f.applies_here()
    monkeypatch.delenv("MX_NUM_PROCS")
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")  # reference spelling
    assert f.applies_here()


def test_crash_rendezvous_grammar():
    from mxnet_tpu import fault

    f = fault.parse_spec("crash-rendezvous:rank=1:if-restart=2")[0]
    assert f.kind == "crash-rendezvous" and f.rank == 1
    with pytest.raises(MXNetError, match="step= does not apply"):
        fault.parse_spec("crash-rendezvous:step=3")


def test_crash_rendezvous_fires_in_subprocess(tmp_path):
    """on_rendezvous exits EXIT_INJECTED_CRASH when the spec applies —
    driven through the real dist hook in a subprocess (no gang needed:
    the crash fires BEFORE jax.distributed.initialize dials out)."""
    script = tmp_path / "w.py"
    script.write_text(
        "import sys; sys.path.insert(0, %r)\n"
        "from mxnet_tpu import fault\n"
        "fault.on_rendezvous()\n"
        "print('survived', flush=True)\n" % _REPO)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MX_FAULT_SPEC="crash-rendezvous:if-world=3",
               MX_NUM_PROCS="3", MX_PROC_ID="0")
    res = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 57, (res.stdout, res.stderr)
    assert "injected crash during rendezvous" in res.stdout
    env["MX_NUM_PROCS"] = "2"  # world qualifier gates it off
    res = subprocess.run([sys.executable, str(script)], env=env,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0 and "survived" in res.stdout


# ---------------------------------------------------------------------------
# resize-aware report tools (CI/tooling satellite)
# ---------------------------------------------------------------------------
def _write_stream(d, rank, events):
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, f"rank-{rank}.jsonl"), "w") as f:
        for ev in events:
            f.write(json.dumps(dict(ev, rank=rank)) + "\n")


def _steps(t0, n, wall=10.0, traced=False, dt=0.011):
    return [{"t": t0 + i * dt, "kind": "step", "step": i + 1,
             "wall_ms": wall, "traced": traced} for i in range(n)]


def test_trace_report_does_not_blame_resize_wall(tmp_path):
    """The teardown silence + recompile wall of an elastic resize must
    not read as a straggler or an event gap; the SAME streams without
    the resize marker ARE flagged (the control)."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import trace_report

    def build(d, with_resize):
        anchor = [{"t": 100.0, "kind": "clock_anchor", "mono": 0.0}]
        for rank in (0, 1):
            pre = _steps(100.0, 30)
            post = _steps(200.0, 30, traced=False)
            recompile = [{"t": 199.0, "kind": "step", "step": 31,
                          "wall_ms": 900.0, "traced": True}]
            resize = ([{"t": 198.5, "kind": "resize", "old_world": 3,
                        "new_world": 2}] if with_resize else [])
            _write_stream(d, rank, anchor + pre + resize + recompile + post)
        # rank 2 died before the resize: short clean pre-resize stream
        _write_stream(d, 2, anchor + _steps(100.0, 30))

    flagged = str(tmp_path / "no_marker")
    build(flagged, with_resize=False)
    rep = trace_report.build_report(flagged, gap_sec=30.0)
    assert rep["anomalies"], "control: the naked 70s gap must flag"

    clean = str(tmp_path / "marked")
    build(clean, with_resize=True)
    rep = trace_report.build_report(clean, gap_sec=30.0)
    assert rep["per_rank"]["0"]["resizes"] == 1
    assert rep["resizes"] and rep["resizes"][0]["new_world"] == 2
    gap_or_straggler = [a for a in rep["anomalies"]
                        if "gap" in a or "straggler" in a]
    assert not gap_or_straggler, rep["anomalies"]


def test_mem_report_leak_window_resets_at_resize(tmp_path):
    """A fresh post-resize incarnation ramping its allocations up must
    not read as a monotonic leak when the trailing window spans the
    restart; without the marker it does (the control)."""
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import mem_report

    def mems(t0, bytes0, n, grow):
        return [{"t": t0 + i, "kind": "mem",
                 "live_bytes": bytes0 + i * grow,
                 "watermark_bytes": bytes0 + i * grow,
                 "categories": {"params": {"nbytes": bytes0 + i * grow}}}
                for i in range(n)]

    # 6 old-incarnation samples at high watermark, then restart low and
    # ramp: strictly increasing across the 12-window only if the boundary
    # is ignored... make the joined series strictly increasing by
    # construction: old 1..6MB, new 7..13MB (fresh process ramp-up)
    old = mems(100.0, 1 << 20, 6, 1 << 20)
    new = mems(200.0, 7 << 20, 7, 1 << 20)
    control = str(tmp_path / "control")
    _write_stream(control, 0, old + new)
    rep = mem_report.build_report(control, window=12)
    assert rep["per_rank"]["0"]["leak"]["verdict"] == "leak", "control"

    marked = str(tmp_path / "marked")
    _write_stream(marked, 0,
                  old + [{"t": 199.5, "kind": "resize", "old_world": 3,
                          "new_world": 2}] + new)
    rep = mem_report.build_report(marked, window=12)
    assert rep["per_rank"]["0"]["leak"]["verdict"] != "leak", \
        rep["per_rank"]["0"]["leak"]


# ---------------------------------------------------------------------------
# --elastic supervisor machinery (no-jax workers: fast chaos tier, same
# pattern as test_dist_launch's supervisor tests)
# ---------------------------------------------------------------------------
def _run_elastic(tmp_path, script_body, n, extra_args=(), timeout=90):
    worker = tmp_path / "worker.py"
    worker.write_text(script_body)
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), "--restart-backoff", "0.05", "--elastic",
           *extra_args, "--", sys.executable, str(worker)]
    return subprocess.run(cmd, timeout=timeout, capture_output=True,
                          text=True)


@pytest.mark.chaos
def test_supervisor_shrinks_instead_of_failing(tmp_path):
    """Budget exhausted at world 3 with rank 2 always dying => shrink to
    2 survivors with MX_PREV_NUM_PROCS exported and a fresh budget, then
    clean exit."""
    res = _run_elastic(tmp_path, (
        "import os, sys\n"
        "n = os.environ['MX_NUM_PROCS']; r = os.environ['MX_PROC_ID']\n"
        "prev = os.environ.get('MX_PREV_NUM_PROCS', '-')\n"
        "print(f'rank {r}/{n} prev {prev} elastic '\n"
        "      f\"{os.environ.get('MX_ELASTIC')}\", flush=True)\n"
        "if n == '3' and r == '2':\n"
        "    sys.exit(7)\n"
    ), n=3, extra_args=("--max-restarts", "1"))
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "shrinking gang 3 -> 2" in res.stderr, res.stderr
    # two failed attempts at world 3, then the resized incarnation
    assert res.stdout.count("rank 2/3") == 2, res.stdout
    assert "rank 0/2 prev 3 elastic 1" in res.stdout, res.stdout
    assert "rank 2/2" not in res.stdout


@pytest.mark.chaos
def test_supervisor_gives_up_at_min_workers(tmp_path):
    """The floor holds: at --min-workers the exhausted budget fails the
    job with the world-size-annotated history."""
    res = _run_elastic(tmp_path, (
        "import os, sys\n"
        "sys.exit(9 if os.environ['MX_PROC_ID'] == '0' else 0)\n"
    ), n=2, extra_args=("--max-restarts", "0", "--min-workers", "2"))
    assert res.returncode == 9
    assert "giving up" in res.stderr
    assert "(world 2)" in res.stderr, res.stderr


@pytest.mark.chaos
def test_supervisor_regrows_to_target(tmp_path):
    """--initial-workers below target + --regrow-after: the healthy gang
    is preempted and re-spawned at the full target with the old world
    exported."""
    res = _run_elastic(tmp_path, (
        "import os, time\n"
        "n = os.environ['MX_NUM_PROCS']; r = os.environ['MX_PROC_ID']\n"
        "print(f\"rank {r}/{n} prev \"\n"
        "      f\"{os.environ.get('MX_PREV_NUM_PROCS', '-')}\", flush=True)\n"
        "if n == '2':\n"
        "    time.sleep(60)\n"
    ), n=3, extra_args=("--initial-workers", "2", "--regrow-after", "1",
                        "--term-timeout", "2"), timeout=60)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "growing gang 2 -> 3" in res.stderr, res.stderr
    assert "rank 2/3 prev 2" in res.stdout, res.stdout


@pytest.mark.chaos
def test_supervisor_regrow_steps_and_rearms(tmp_path):
    """The PR 11 'Known' fix: regrow steps +1 toward the target (1 -> 2
    -> 3, a fresh stability countdown at each size, NOT one jump to -n),
    and re-arms after a LATER culprit shrinks the regrown gang below
    target again — the grow -> shrink -> grow cycle converges back to
    the target instead of sticking at the shrunken size."""
    marker = tmp_path / "crashed.marker"
    res = _run_elastic(tmp_path, (
        "import os, sys, time\n"
        "n = os.environ['MX_NUM_PROCS']\n"
        f"marker = {str(marker)!r}\n"
        "print(f\"rank {os.environ['MX_PROC_ID']}/{n} prev \"\n"
        "      f\"{os.environ.get('MX_PREV_NUM_PROCS', '-')}\", flush=True)\n"
        "if n == '3':\n"
        "    if not os.path.exists(marker):\n"
        "        # first time at target: rank 2's host goes bad once\n"
        "        if os.environ['MX_PROC_ID'] == '2':\n"
        "            open(marker, 'w').write('x')\n"
        "            sys.exit(7)\n"
        "        time.sleep(30)\n"
        "    sys.exit(0)  # second regrow to target: healthy\n"
        "time.sleep(60)  # below target: wait for the regrow preemption\n"
    ), n=3, extra_args=("--max-restarts", "0", "--initial-workers", "1",
                        "--regrow-after", "1", "--term-timeout", "2"),
        timeout=120)
    assert res.returncode == 0, (res.stdout, res.stderr)
    # +1 stepping: two distinct growth steps on the way up
    assert "growing gang 1 -> 2" in res.stderr, res.stderr
    assert res.stderr.count("growing gang 2 -> 3") == 2, res.stderr
    # never a straight 1 -> 3 jump
    assert "growing gang 1 -> 3" not in res.stderr
    assert "shrinking gang 3 -> 2" in res.stderr, res.stderr
    # the re-regrown incarnation carries the resize export
    assert "rank 2/3 prev 2" in res.stdout, res.stdout


def test_cli_validates_elastic_flags():
    for args in (["--min-workers", "0"],
                 ["--min-workers", "5"],
                 ["--elastic", "--initial-workers", "9"],
                 ["--initial-workers", "2"],   # requires --elastic
                 ["--regrow-after", "5"]):     # requires --elastic
        res = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
             "-n", "3", *args, "--", "true"],
            capture_output=True, text=True)
        assert res.returncode != 0, args


# ---------------------------------------------------------------------------
# the gang e2e (slow tier): shrink 3->2 under chaos, grow 2->3 via regrow,
# each bitwise-matched against a fixed-size baseline resumed from the
# SAME checkpoint
# ---------------------------------------------------------------------------
def _launch(n, env, launcher_args=(), timeout=420):
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), "--force-cpu", *launcher_args, "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "elastic_worker.py")]
    return subprocess.run(cmd, cwd=_REPO, timeout=timeout,
                          capture_output=True, text=True, env=env)


def _baseline_from(ckpt_src, base_dir, n, resume_step, tag):
    """Run a FIXED n-rank gang restoring exactly `resume_step` from a
    copy of the elastic run's shared checkpoint dir."""
    os.makedirs(base_dir, exist_ok=True)
    shutil.copytree(ckpt_src, os.path.join(base_dir, "ckpt"))
    env = dict(os.environ, MX_ELASTIC_DIR=str(base_dir),
               MX_ELASTIC_TAG=tag, MX_RESUME_STEP=str(resume_step))
    res = _launch(n, env)
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert res.stdout.count(f"resuming at step {resume_step} world {n}") \
        == n, res.stdout
    return np.load(os.path.join(base_dir, f"final_{tag}.npz"))


def _assert_same_weights(a, b):
    assert set(a.files) == set(b.files)
    for k in a.files:
        assert np.array_equal(a[k], b[k]), k


@pytest.mark.chaos
@pytest.mark.slow
def test_elastic_shrink_end_to_end(tmp_path):
    """ACCEPTANCE: a 3-rank gang under MX_FAULT_SPEC loses rank 2
    permanently (if-world=3: it dies at step 8 of EVERY world-3
    incarnation), the --elastic supervisor exhausts the budget and
    re-rendezvouses at world size 2, training resumes from the resharded
    step-5 checkpoint, and the final weights are BITWISE identical to a
    fixed 2-rank gang trained from the same checkpoint (single device
    per rank)."""
    env = dict(os.environ, MX_ELASTIC_DIR=str(tmp_path),
               MX_ELASTIC_TAG="elastic",
               MX_FAULT_SPEC="crash:step=8:rank=2:if-world=3")
    res = _launch(3, env, launcher_args=(
        "--elastic", "--max-restarts", "1", "--term-timeout", "5",
        "--restart-backoff", "0.2"))
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert res.stdout.count("injected crash at step 8") == 2, res.stdout
    assert "shrinking gang 3 -> 2" in res.stderr, res.stderr
    # both survivors resumed at the agreed scheduled step, resharding the
    # world-3 checkpoint onto the world-2 mesh
    assert res.stdout.count(
        "resuming at step 5 world 2 resharded=True old_world=3") == 2, \
        res.stdout
    assert res.stdout.count("done") == 2, res.stdout
    elastic = np.load(tmp_path / "final_elastic.npz")

    base = _baseline_from(tmp_path / "ckpt", tmp_path / "baseline", n=2,
                          resume_step=5, tag="base2")
    _assert_same_weights(elastic, base)


@pytest.mark.chaos
@pytest.mark.slow
def test_elastic_grow_end_to_end(tmp_path):
    """ACCEPTANCE grow mirror: a gang degraded to 2 ranks
    (--initial-workers 2) regrows to the 3-rank target after stable
    running — planned preemption, re-rendezvous at world 3, resharded
    resume — and matches a fixed 3-rank baseline trained from the same
    checkpoint."""
    tdir = tmp_path / "tele"
    env = dict(os.environ, MX_ELASTIC_DIR=str(tmp_path),
               MX_ELASTIC_TAG="grown", MX_ELASTIC_STEP_SLEEP="0.1",
               MX_TELEMETRY_DIR=str(tdir))  # heartbeats arm the regrow
    res = _launch(3, env, launcher_args=(
        "--elastic", "--initial-workers", "2", "--regrow-after", "2",
        "--max-restarts", "1", "--term-timeout", "8",
        "--restart-backoff", "0.2"))
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert "growing gang 2 -> 3" in res.stderr, res.stderr
    m = re.findall(r"resuming at step (\d+) world 3 resharded=True "
                   r"old_world=2", res.stdout)
    assert len(m) == 3, res.stdout  # every rank of the grown gang
    resume_step = int(m[0])
    assert resume_step > 0 and resume_step % 5 == 0
    elastic = np.load(tmp_path / "final_grown.npz")

    # the resize event landed in the survivors' telemetry streams and
    # trace_report treats the recompile segment as such, not a straggler
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    import trace_report

    rep = trace_report.build_report(str(tdir))
    assert any(r["new_world"] == 3 for r in rep["resizes"]), rep["resizes"]
    assert not [a for a in rep["anomalies"] if "straggler" in a], \
        rep["anomalies"]

    base = _baseline_from(tmp_path / "ckpt", tmp_path / "baseline", n=3,
                          resume_step=resume_step, tag="base3")
    _assert_same_weights(elastic, base)
