"""Env-var drift guard: every MX_*/MXNET_* variable read anywhere in
mxnet_tpu/ or tools/ must be registered in mxnet_tpu.env_vars.ENV_VARS.

The registry is the single answer to "is MXNET_X supported here?" — a
variable consumed at some use-site but absent from the table silently
drifts out of the documentation, out of `env_vars.check()`'s
set-but-ineffective warnings, and out of docs/OBSERVABILITY.md's knob
list.  This test greps the tree so adding an env read without registering
it fails tier-1 immediately.
"""
import os
import re

from mxnet_tpu import env_vars

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a quoted MX_/MXNET_ name is (by project convention) an env-var use-site:
# os.environ.get("MX_X"), env_bool("MXNET_Y"), env dicts exported to
# workers.  Prose mentions in docstrings are unquoted (or backticked), so
# they don't match.
_NAME = re.compile(r"""["'](MX(?:NET)?_[A-Z0-9_]+)["']""")


def _scan():
    sites = {}
    for top in ("mxnet_tpu", "tools"):
        for dirpath, _dirnames, filenames in os.walk(os.path.join(_REPO, top)):
            for fname in filenames:
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path, encoding="utf-8", errors="replace") as f:
                    text = f.read()
                for m in _NAME.finditer(text):
                    rel = os.path.relpath(path, _REPO)
                    sites.setdefault(m.group(1), set()).add(rel)
    return sites


def test_every_env_var_in_tree_is_registered():
    sites = _scan()
    assert sites, "scanner found no env vars at all — regex or layout broke"
    missing = {name: sorted(files) for name, files in sorted(sites.items())
               if name not in env_vars.ENV_VARS}
    assert not missing, (
        "env vars read in the tree but not registered in "
        "mxnet_tpu/env_vars.py ENV_VARS (add an entry with disposition + "
        f"use-site): {missing}")


def test_registry_covers_telemetry_knobs():
    # the observability layer's knobs must stay documented
    for name in ("MX_TELEMETRY_DIR", "MX_TELEMETRY_FLUSH_SEC",
                 "MX_HEARTBEAT_SEC", "MX_TELEMETRY_RETRACE_LIMIT"):
        assert name in env_vars.ENV_VARS, name
        assert env_vars.ENV_VARS[name][0] == "honored", name
