"""Env-var drift guard: every MX_*/MXNET_* variable read anywhere in
mxnet_tpu/ or tools/ must be registered in mxnet_tpu.env_vars.ENV_VARS.

The registry is the single answer to "is MXNET_X supported here?" — a
variable consumed at some use-site but absent from the table silently
drifts out of the documentation, out of `env_vars.check()`'s
set-but-ineffective warnings, and out of docs/OBSERVABILITY.md's knob
list.

Since the mxlint PR this test delegates to the `env-unregistered` rule
(tools/mxlint.py): same convention — a quoted MX_/MXNET_ name is a
use-site — but at the AST level, so docstring mentions like "MX_FOO" no
longer false-positive the way the old quoted-string regex could, and the
finding carries the offending file.  Adding an env read without
registering it still fails tier-1 immediately.
"""
import importlib.util
import os
import re

from mxnet_tpu import env_vars

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "mxlint", os.path.join(_REPO, "tools", "mxlint.py"))
_mxlint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_mxlint)

_NAME_IN_MSG = re.compile(r"env var '(MX(?:NET)?_[A-Z0-9_]+)'")


def _scan(registry):
    """name -> sorted files, for every AST-level use-site the
    env-unregistered rule reports against `registry`."""
    findings, _stats = _mxlint.run_lint(
        ["mxnet_tpu", "tools"], root=_REPO, rules=["env-unregistered"],
        env_registry=registry)
    sites = {}
    # meta rules (bad-suppression, syntax-error) always run; their
    # findings are someone else's problem (test_lint's full-tree gate) —
    # only env-unregistered messages carry a var name to parse
    for f in findings:
        if f.rule != "env-unregistered":
            continue
        m = _NAME_IN_MSG.search(f.message)
        assert m, f"unparseable env-unregistered message: {f.message}"
        sites.setdefault(m.group(1), set()).add(f.path)
    return sites


def test_every_env_var_in_tree_is_registered():
    # one scan with an EMPTY registry reports every use-site; the missing
    # set is then a plain membership check against ENV_VARS.  Zero hits
    # means the scanner (or the tree layout) broke.
    sites = _scan(registry=set())
    assert sites, "scanner found no env vars at all — rule or layout broke"
    missing = {name: sorted(files) for name, files in sorted(sites.items())
               if name not in env_vars.ENV_VARS}
    assert not missing, (
        "env vars read in the tree but not registered in "
        "mxnet_tpu/env_vars.py ENV_VARS (add an entry with disposition + "
        f"use-site): {missing}")


def test_registry_covers_telemetry_knobs():
    # the observability layer's knobs must stay documented
    for name in ("MX_TELEMETRY_DIR", "MX_TELEMETRY_FLUSH_SEC",
                 "MX_HEARTBEAT_SEC", "MX_TELEMETRY_RETRACE_LIMIT"):
        assert name in env_vars.ENV_VARS, name
        assert env_vars.ENV_VARS[name][0] == "honored", name
