"""SSD detector model family: multi-scale head shapes, one-jit train step
convergence on synthetic boxes, decode+NMS inference.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import SSDTrainLoss, ssd_300


def _net(num_classes=2):
    mx.random.seed(0)
    net = ssd_300(num_classes=num_classes)
    net.initialize(mx.init.Xavier())
    return net


def test_ssd_forward_shapes():
    net = _net()
    x = nd.zeros((2, 3, 128, 128))
    anchors, cls_preds, box_preds = net(x)
    N = anchors.shape[1]
    assert anchors.shape == (1, N, 4)
    assert cls_preds.shape == (2, N, 3)
    assert box_preds.shape == (2, N * 4)
    # anchors normalized
    a = anchors.asnumpy()
    assert a.min() > -0.5 and a.max() < 1.5


def test_ssd_train_step_decreases_loss():
    """The whole SSD train step — multibox target assignment included —
    runs as ONE fused XLA program (the loop was the suite's #3 cost at
    77s eager, 71s hybridized; fused it's one compile + 12 cheap steps)."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net = _net(num_classes=1)
    loss_block = SSDTrainLoss()

    def loss_fn(out, labels):
        anchors, cls_preds, box_preds = out
        return loss_block(anchors, cls_preds, box_preds, labels)

    step = DataParallelStep(
        net, loss_fn,
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 1e-3})
    # synthetic: one box, class 0, fixed location
    B = 4
    x = nd.array(np.random.RandomState(0).rand(B, 3, 96, 96)
                 .astype(np.float32))
    labels = nd.array(np.tile(
        np.array([[0, 0.25, 0.25, 0.75, 0.75]], np.float32), (B, 1, 1)))
    losses = [float(np.asarray(step.step(x, labels))) for _ in range(12)]
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_ssd_detect_output_format():
    net = _net(num_classes=2)
    x = nd.zeros((1, 3, 128, 128))
    out = net.detect(x, threshold=0.0).asnumpy()
    assert out.ndim == 3 and out.shape[2] == 6
    ids = out[0, :, 0]
    # class ids are -1 (suppressed) or within range
    assert ((ids >= -1) & (ids < 2)).all()
    valid = ids >= 0
    scores = out[0, valid, 1]  # suppressed rows are filled with -1
    assert ((scores >= 0) & (scores <= 1)).all()


def test_ssd_hybridize_matches_eager():
    net = _net()
    x = nd.array(np.random.RandomState(1).rand(1, 3, 96, 96)
                 .astype(np.float32))
    a1, c1, b1 = net(x)
    net.hybridize()
    a2, c2, b2 = net(x)
    np.testing.assert_allclose(c1.asnumpy(), c2.asnumpy(), rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(b1.asnumpy(), b2.asnumpy(), rtol=2e-4,
                               atol=2e-5)
