"""Compat-tail coverage (VERDICT r2 #9): CTCLoss, legacy nd.save/load
format, deformable_convolution, adaptive_avg_pooling, histogram.

torch (CPU build, baked into the image) serves as the numerical oracle for
CTC and adaptive pooling — the same role numpy plays in the reference's
test_operator.py.
"""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


# ---------------------------------------------------------------------------
# legacy serialization
# ---------------------------------------------------------------------------
def test_legacy_save_load_roundtrip(tmp_path):
    f = str(tmp_path / "legacy.params")
    data = {"w": nd.array(np.random.rand(3, 4).astype(np.float32)),
            "b": nd.array(np.arange(5, dtype=np.int64)),
            "h": nd.array(np.random.rand(2, 2).astype(np.float16),
                          dtype=np.float16)}
    nd.save_legacy(f, data)
    back = nd.load(f)  # dispatches on the 0x112 magic
    assert set(back) == {"w", "b", "h"}
    for k in data:
        np.testing.assert_array_equal(back[k].asnumpy(), data[k].asnumpy())


def test_legacy_load_list(tmp_path):
    f = str(tmp_path / "legacy_list.nd")
    arrays = [nd.array(np.random.rand(2, 3).astype(np.float32)),
              nd.array(np.random.rand(4).astype(np.float64),
                       dtype=np.float64)]
    nd.save_legacy(f, arrays)
    back = nd.load(f)
    assert isinstance(back, list) and len(back) == 2
    for a, b in zip(arrays, back):
        np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_legacy_handcrafted_v1_record(tmp_path):
    # V1 record: u32 magic, u32 ndim, u32 dims, ctx, dtype, raw — written
    # byte-by-byte from the format spec (src/ndarray/ndarray.cc ~L1500)
    import struct

    arr = np.arange(6, dtype=np.float32).reshape(2, 3)
    buf = struct.pack("<QQQ", 0x112, 0, 1)
    buf += struct.pack("<I", 0xF993FAC8)  # V1: no stype field
    buf += struct.pack("<III", 2, 2, 3)  # ndim, dims u32
    buf += struct.pack("<iii", 1, 0, 0)  # cpu ctx, float32
    buf += arr.tobytes()
    buf += struct.pack("<Q", 1) + struct.pack("<Q", 3) + b"arr"
    f = str(tmp_path / "v1.nd")
    with open(f, "wb") as fh:
        fh.write(buf)
    back = nd.load(f)
    np.testing.assert_array_equal(back["arr"].asnumpy(), arr)


# ---------------------------------------------------------------------------
# histogram
# ---------------------------------------------------------------------------
def test_histogram_uniform_bins():
    x = np.random.RandomState(0).uniform(-2, 3, 100).astype(np.float32)
    counts, edges = nd.histogram(nd.array(x), bin_cnt=7, range=(-2.0, 3.0))
    ref_counts, ref_edges = np.histogram(x, bins=7, range=(-2.0, 3.0))
    np.testing.assert_array_equal(counts.asnumpy(), ref_counts)
    np.testing.assert_allclose(edges.asnumpy(), ref_edges, rtol=1e-6)


def test_histogram_explicit_edges():
    x = np.array([0.1, 0.4, 0.6, 0.6, 0.9, 1.0, -0.5], np.float32)
    edges = np.array([0.0, 0.5, 1.0], np.float32)
    counts, out_edges = nd.histogram(nd.array(x), nd.array(edges))
    ref_counts, _ = np.histogram(x, bins=edges)
    np.testing.assert_array_equal(counts.asnumpy(), ref_counts)


# ---------------------------------------------------------------------------
# adaptive average pooling
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("in_hw,out_sz", [((7, 7), 3), ((8, 6), (4, 3)),
                                          ((5, 5), 5), ((6, 6), 1)])
def test_adaptive_avg_pooling_vs_torch(in_hw, out_sz):
    import torch

    x = np.random.RandomState(1).rand(2, 3, *in_hw).astype(np.float32)
    out = nd.contrib.AdaptiveAvgPooling2D(nd.array(x), output_size=out_sz)
    ref = torch.nn.functional.adaptive_avg_pool2d(
        torch.from_numpy(x), out_sz).numpy()
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_adaptive_avg_pooling_grad():
    from mxnet_tpu.test_utils import check_numeric_gradient

    x = nd.array(np.random.RandomState(2).rand(1, 2, 5, 5).astype(np.float32))
    check_numeric_gradient(
        lambda a: nd.contrib.AdaptiveAvgPooling2D(a, output_size=2).sum(),
        [x], eps=1e-2, rtol=5e-2, atol=5e-3)


# ---------------------------------------------------------------------------
# deformable convolution
# ---------------------------------------------------------------------------
def test_deformable_conv_zero_offset_matches_conv():
    rng = np.random.RandomState(3)
    x = rng.rand(2, 4, 6, 6).astype(np.float32)
    w = rng.rand(5, 4, 3, 3).astype(np.float32)
    off = np.zeros((2, 2 * 9, 6, 6), np.float32)
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        num_filter=5, pad=(1, 1), no_bias=True)
    ref = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=5, pad=(1, 1), no_bias=True)
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_deformable_conv_offsets_shift_sampling():
    # integer offset (dy=1) equals sampling the next row: compare against
    # zero-offset output of a shifted input
    rng = np.random.RandomState(4)
    x = rng.rand(1, 2, 5, 5).astype(np.float32)
    w = rng.rand(2, 2, 1, 1).astype(np.float32)
    off = np.zeros((1, 2, 5, 5), np.float32)
    off[:, 0] = 1.0  # dy = +1 for the single 1x1 tap
    out = nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(1, 1),
        num_filter=2, no_bias=True).asnumpy()
    shifted = np.zeros_like(x)
    shifted[:, :, :-1] = x[:, :, 1:]  # row i samples row i+1 (zero bottom)
    ref = nd.Convolution(nd.array(shifted), nd.array(w), kernel=(1, 1),
                         num_filter=2, no_bias=True).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_deformable_conv_grad_finite():
    rng = np.random.RandomState(5)
    x = nd.array(rng.rand(1, 2, 4, 4).astype(np.float32))
    # offset spatial dims match the OUTPUT grid (3x3 for 4x4 input, k=2)
    off = nd.array(0.3 * rng.randn(1, 2 * 4, 3, 3).astype(np.float32))
    w = nd.array(rng.rand(3, 2, 2, 2).astype(np.float32))
    for v in (x, off, w):
        v.attach_grad()
    with autograd.record():
        out = nd.contrib.DeformableConvolution(
            x, off, w, kernel=(2, 2), num_filter=3, no_bias=True)
        loss = (out ** 2).sum()
    loss.backward()
    for v in (x, off, w):
        assert np.isfinite(v.grad.asnumpy()).all()
        assert np.abs(v.grad.asnumpy()).sum() > 0


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------
def _torch_ctc(logits_tnc, labels, input_lengths, label_lengths, blank):
    import torch

    lp = torch.from_numpy(logits_tnc).log_softmax(-1)
    flat = []
    for row, ln in zip(labels, label_lengths):
        flat.extend(row[:ln])
    return torch.nn.functional.ctc_loss(
        lp, torch.tensor(flat), torch.tensor(input_lengths),
        torch.tensor(label_lengths), blank=blank,
        reduction="none", zero_infinity=False).numpy()


def test_ctc_loss_matches_torch():
    rng = np.random.RandomState(6)
    T, N, C = 10, 3, 6  # blank = C-1 = 5 ('last', the gluon convention)
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 3, -1], [0, 0, -1, -1], [4, 2, 4, 1]],
                      np.float32)
    lens = [3, 2, 4]
    out = nd.ctc_loss(nd.array(logits), nd.array(labels),
                      blank_label="last").asnumpy()
    ref = _torch_ctc(logits, labels.astype(int), [T] * N, lens, blank=C - 1)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_variable_lengths():
    rng = np.random.RandomState(7)
    T, N, C = 12, 2, 5
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 0, 0], [3, 1, 2, 0]], np.float32)
    dlen = np.array([9, 12], np.float32)
    llen = np.array([2, 3], np.float32)
    out = nd.ctc_loss(nd.array(logits), nd.array(labels), nd.array(dlen),
                      nd.array(llen), use_data_lengths=True,
                      use_label_lengths=True, blank_label="last").asnumpy()
    ref = _torch_ctc(logits, labels.astype(int), [9, 12], [2, 3], blank=C - 1)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_legacy_v2_uint32_dims(tmp_path):
    # pre-1.5 V2 writers used uint32 TShape dims; small shapes like (3,4)
    # must not be misparsed as one int64 (regression)
    import struct

    arr = np.arange(12, dtype=np.float32).reshape(3, 4)
    buf = struct.pack("<QQQ", 0x112, 0, 1)
    buf += struct.pack("<Ii", 0xF993FAC9, 0)  # V2 magic + dense stype
    buf += struct.pack("<III", 2, 3, 4)  # ndim + u32 dims
    buf += struct.pack("<iii", 1, 0, 0)
    buf += arr.tobytes()
    buf += struct.pack("<Q", 0)
    f = str(tmp_path / "v2_u32.nd")
    with open(f, "wb") as fh:
        fh.write(buf)
    back = nd.load(f)
    np.testing.assert_array_equal(back[0].asnumpy(), arr)


def test_load_truncated_file_raises_mxnet_error(tmp_path):
    from mxnet_tpu.base import MXNetError

    f = str(tmp_path / "short.nd")
    with open(f, "wb") as fh:
        fh.write(b"abc")
    with pytest.raises(MXNetError):
        nd.load(f)


def test_ctc_loss_blank_first_zero_padding():
    # 'first' convention: 0 is blank AND the label padding value; real
    # labels are 1..C-1 (regression: 0-padding was counted as labels)
    rng = np.random.RandomState(9)
    T, N, C = 10, 2, 6
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.array([[1, 2, 0, 0], [3, 4, 5, 0]], np.float32)
    out = nd.ctc_loss(nd.array(logits), nd.array(labels),
                      blank_label="first").asnumpy()
    ref = _torch_ctc(logits, labels.astype(int), [T, T], [2, 3], blank=0)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_empty_label():
    # s_valid == 1: only the all-blank path — loss is -sum(log p_blank)
    rng = np.random.RandomState(10)
    T, N, C = 6, 1, 4
    logits = rng.randn(T, N, C).astype(np.float32)
    labels = np.full((1, 3), -1.0, np.float32)
    out = float(nd.ctc_loss(nd.array(logits), nd.array(labels),
                            blank_label="last").asnumpy()[0])
    lp = np.log(np.exp(logits - logits.max(-1, keepdims=True))
                / np.exp(logits - logits.max(-1, keepdims=True)).sum(
                    -1, keepdims=True))
    expect = -lp[:, 0, C - 1].sum()
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_gluon_ctc_loss_trains():
    mx.random.seed(8)
    T, N, C = 8, 4, 7
    net = gluon.nn.Dense(C, flatten=False)
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.CTCLoss()  # NTC layout
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    x = np.random.RandomState(8).rand(N, T, 5).astype(np.float32)
    labels = nd.array(np.array([[1, 2], [2, 1], [0, 3], [3, 3]], np.float32))
    first = last = None
    for i in range(25):
        with autograd.record():
            loss = loss_fn(net(nd.array(x)), labels).mean()
        loss.backward()
        trainer.step(N)
        v = float(loss.asnumpy())
        if first is None:
            first = v
        last = v
    assert np.isfinite(last)
    assert last < first, (first, last)


def test_rtc_raises_with_pallas_pointer():
    """mx.rtc exists and raises the documented descope error (reference
    src/common/rtc.cc; the TPU runtime-kernel path is Pallas)."""
    import mxnet_tpu as mx

    with pytest.raises(mx.MXNetError, match="Pallas"):
        mx.rtc.CudaModule("__global__ void k(){}")
    with pytest.raises(mx.MXNetError):
        mx.rtc.CudaKernel()
