"""Fleet-wide request tracing (ISSUE 18; docs/OBSERVABILITY.md
§Request tracing).

Covers: trace-header mint/format/parse, router→replica propagation over
a fake no-jax fleet (the header survives the hop and the replica's
trace matches the router's), failover keeping ONE trace with TWO
dispatch spans, head-sampling=0 dropping spans cleanly while the
request still serves, the /tracez surfaces (router ring + per-rank
recent ring), the serve_report analyzer (leg attribution, straggler
cause, SLO exit 3, unfinished trees), trace_report's serving-mode
deferral, the launch.py gang-death hook, and one real-engine
end-to-end merge asserting matched B/E pairs + request flow links in
the merged Chrome trace while traced output stays bitwise identical
to the untraced serve.
"""
import importlib.util
import json
import os
import subprocess
import sys
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.serving import Router, serve_portfile_path
from mxnet_tpu.serving.router import (TRACE_HEADER, format_trace_header,
                                      mint_trace, parse_trace_header,
                                      rqtrace_enabled)

PAD, BOS, EOS = 0, 1, 2
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SERVE_REPORT = os.path.join(_REPO, "tools", "serve_report.py")


@pytest.fixture
def tele():
    telemetry.reset()
    yield telemetry
    telemetry.reset()


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _events(directory, rank=0):
    return [json.loads(line)
            for line in open(telemetry.event_path(str(directory), rank))]


# ---------------------------------------------------------------------------
# fake no-jax worker that records the headers it saw
# ---------------------------------------------------------------------------
class _TracingWorker:
    """test_router's fake replica, plus header capture: every /generate
    records the ``X-MX-Trace`` value it arrived with."""

    def __init__(self, directory, rank):
        self.rank = rank
        self.seen = []
        self.trace_headers = []
        worker = self

        class H(BaseHTTPRequestHandler):
            def _send(self, code, payload):
                raw = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def do_GET(self):  # noqa: N802
                self._send(200, {"ok": True, "rank": worker.rank})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                worker.seen.append(body)
                worker.trace_headers.append(self.headers.get(TRACE_HEADER))
                self._send(200, {
                    "request_id": body.get("request_id", "r"),
                    "tokens": [worker.rank] + list(body["prompt"]),
                    "finish_reason": "length",
                    "replica": worker.rank,
                    "session": body.get("session")})

            def log_message(self, fmt, *args):
                pass

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()
        self.portfile = serve_portfile_path(directory, rank)
        tmp = self.portfile + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"rank": rank, "host": "127.0.0.1",
                       "port": self.port, "pid": os.getpid(),
                       "time": 0.0}, f)
        os.replace(tmp, self.portfile)

    def kill(self):
        self._server.shutdown()
        self._server.server_close()


@pytest.fixture
def fleet(tmp_path):
    d = str(tmp_path)
    workers = [_TracingWorker(d, r) for r in range(2)]
    router = Router(d, port=0, health_sec=60.0)
    yield d, workers, router
    router.stop()
    for w in workers:
        try:
            w.kill()
        except Exception:
            pass


def _post(port, body, path="/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30.0) as r:
        return json.load(r)


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30.0) as r:
        return json.load(r)


# ---------------------------------------------------------------------------
# trace context: mint / format / parse
# ---------------------------------------------------------------------------
def test_trace_header_roundtrip():
    hdr = format_trace_header("ab12cd34ef56ab78", 41, True)
    got = parse_trace_header(hdr)
    assert got == {"trace_id": "ab12cd34ef56ab78", "parent": 41,
                   "sampled": True}
    assert parse_trace_header(
        format_trace_header("f" * 16, 0, False))["sampled"] is False
    # garbage downgrades to untraced, never a 500 at the replica
    for bad in (None, "", ";;", "tid;parent=xyz;sampled=1"):
        assert parse_trace_header(bad) is None
    # a bare id from a foreign dialect still correlates
    assert parse_trace_header("justanid")["trace_id"] == "justanid"


def test_mint_trace_respects_kill_switch_and_rate(monkeypatch):
    monkeypatch.setenv("MX_RQTRACE", "0")
    assert not rqtrace_enabled()
    assert mint_trace() is None
    monkeypatch.setenv("MX_RQTRACE", "1")
    monkeypatch.setenv("MX_RQTRACE_SAMPLE", "0")
    t = mint_trace()
    assert t is not None and t["sampled"] is False
    assert len(t["trace_id"]) == 16
    monkeypatch.setenv("MX_RQTRACE_SAMPLE", "1.0")
    assert mint_trace()["sampled"] is True


# ---------------------------------------------------------------------------
# propagation over the fake fleet
# ---------------------------------------------------------------------------
def test_trace_propagates_router_to_replica(fleet, tele, tmp_path):
    """ACCEPTANCE: the trace id the router minted arrives at the replica
    in the X-MX-Trace header, with the router's open serve_route span id
    as parent — and the router's own stream shows the route/dispatch
    spans under that trace id."""
    d, workers, router = fleet
    tele.enable(d)
    router.start()
    out = _post(router.port, {"prompt": [5, 6]})
    tid = out["trace_id"]
    assert len(tid) == 16
    hdrs = [h for w in workers for h in w.trace_headers if h]
    assert len(hdrs) == 1
    ctx = parse_trace_header(hdrs[0])
    assert ctx["trace_id"] == tid
    assert ctx["sampled"] is True
    assert ctx["parent"] > 0, "open serve_route span id rides the header"
    tele.flush()
    evs = _events(d)
    route_b = [e for e in evs if e.get("kind") == "span_begin"
               and e.get("name") == "serve_route"]
    assert [e["trace_id"] for e in route_b] == [tid]
    assert route_b[0]["span"] == ctx["parent"]
    disp = [e for e in evs if e.get("kind") == "span"
            and e.get("name") == "serve_dispatch"]
    assert [e["trace_id"] for e in disp] == [tid]
    assert disp[0]["parent"] == route_b[0]["span"]


def test_failover_is_one_trace_with_two_dispatch_spans(fleet, tele):
    """ACCEPTANCE: a dead-replica failover stays ONE trace — its span
    tree just grows a second serve_dispatch child (the first carrying
    the connection error), and the router attributes cause=failover."""
    d, workers, router = fleet
    tele.enable(d)
    router.start()
    first = _post(router.port, {"prompt": [4], "session": "s"})
    home = first["routed_to"]
    workers[home].kill()
    out = _post(router.port, {"prompt": [4, 4], "session": "s"})
    tid = out["trace_id"]
    assert out["routed_to"] == 1 - home
    tele.flush()
    evs = _events(d)
    disp = [e for e in evs if e.get("kind") == "span"
            and e.get("name") == "serve_dispatch"
            and e.get("trace_id") == tid]
    assert len(disp) == 2
    assert disp[0]["replica"] == home and disp[0].get("error")
    assert disp[1]["replica"] == 1 - home and not disp[1].get("error")
    routes = [e for e in evs if e.get("kind") == "span_begin"
              and e.get("name") == "serve_route"
              and e.get("trace_id") == tid]
    assert len(routes) == 1, "one trace, not one per attempt"
    causes = [e for e in evs if e.get("kind") == "serve_cause"
              and e.get("trace_id") == tid]
    assert [e["cause"] for e in causes] == ["failover"]
    done = router.tracez()["completed"]
    mine = [c for c in done if c["trace_id"] == tid]
    assert len(mine) == 1 and len(mine[0]["attempts"]) == 2
    assert mine[0]["attempts"][0]["error"]


def test_sampling_zero_drops_spans_cleanly(fleet, tele, monkeypatch):
    """sample=0: the request serves normally and keeps its trace id (the
    /tracez ring still correlates), but no spans hit the stream."""
    monkeypatch.setenv("MX_RQTRACE_SAMPLE", "0")
    d, workers, router = fleet
    tele.enable(d)
    router.start()
    out = _post(router.port, {"prompt": [9]})
    tid = out["trace_id"]
    ctx = parse_trace_header(
        [h for w in workers for h in w.trace_headers if h][0])
    assert ctx["sampled"] is False
    tele.flush()
    evs = _events(d)
    assert not [e for e in evs
                if e.get("kind") in ("span", "span_begin")
                and str(e.get("name", "")).startswith("serve_")]
    done = router.tracez()["completed"]
    assert [c["sampled"] for c in done if c["trace_id"] == tid] == [False]


def test_rqtrace_off_is_the_untraced_fast_path(fleet, monkeypatch):
    monkeypatch.setenv("MX_RQTRACE", "0")
    _, workers, router = fleet
    router.start()
    out = _post(router.port, {"prompt": [3]})
    assert "trace_id" not in out
    assert [h for w in workers for h in w.trace_headers] == [None]
    tz = router.tracez()
    assert tz["enabled"] is False
    assert tz["completed"] == [] and tz["in_flight"] == []


def test_router_tracez_endpoint(fleet, tele):
    d, _, router = fleet
    tele.enable(d)
    router.start()
    outs = [_post(router.port, {"prompt": [i]}) for i in range(3)]
    tz = _get(router.port, "/tracez")
    assert tz["enabled"] is True
    assert [c["trace_id"] for c in tz["completed"]] == \
        [o["trace_id"] for o in outs]
    for c in tz["completed"]:
        assert c["code"] == 200 and c["latency_ms"] > 0
        assert len(c["attempts"]) == 1
    assert tz["in_flight"] == []


def test_recent_requests_ring_and_cause_rollup(tele, tmp_path,
                                               monkeypatch):
    monkeypatch.setenv("MX_RQTRACE_TRACEZ_K", "2")
    tele.enable(str(tmp_path))
    for i in range(4):
        tele.record_serve_request(
            queue_wait_ms=1.0, prefill_ms=2.0, decode_ms=30.0, tokens=6,
            ttft_ms=5.0, request_id=f"r{i}", trace_id=f"{i:016x}",
            cause="cache_miss" if i % 2 else "none")
    recent = tele.recent_requests()
    assert [r["request_id"] for r in recent] == ["r2", "r3"], \
        "bounded by MX_RQTRACE_TRACEZ_K"
    assert recent[-1]["cause"] == "cache_miss"
    srv = tele.summary()["serving"]
    assert srv["causes"] == {"cache_miss": 2}
    assert srv["cause_exemplars"]["cache_miss"]["trace_id"] == f"{3:016x}"
    prom = tele.render_prometheus()
    assert 'mx_serve_request_cause_total{rank="0",cause="cache_miss"} 2' \
        in prom
    assert "mx_serve_request_exemplar_latency_ms{" in prom
    assert f'trace_id="{3:016x}"' in prom


# ---------------------------------------------------------------------------
# serve_report: synthetic fleet streams
# ---------------------------------------------------------------------------
def _wstream(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _synth_fleet(d, slow_replica=2, n=8, fast_tpot=2.0, slow_tpot=40.0):
    """Router (rank 0) + two replicas (1 fast, 2 slow): n requests per
    replica, the slow replica's all breaching the TTFT SLO."""
    wall0 = 1000.0
    tids = {r: [f"{r:02d}{i:02d}" + "0" * 12 for i in range(n)]
            for r in (1, 2)}
    router_evs = [{"t": wall0, "kind": "clock_anchor", "rank": 0,
                   "wall": wall0, "mono": 0.0}]
    sid = 100
    for rep in (1, 2):
        for i, tid in enumerate(tids[rep]):
            t0 = float(rep * 100 + i)
            tpot = fast_tpot if rep == 1 else slow_tpot
            total = 5.0 + 10 * tpot + 10.0
            sid += 2
            router_evs.append({
                "t": wall0 + t0, "kind": "span_begin", "rank": 0,
                "name": "serve_route", "span": sid, "parent": 0,
                "depth": 0, "tid": 7, "mono": t0, "trace_id": tid})
            router_evs.append({
                "t": wall0 + t0, "kind": "span", "rank": 0,
                "name": "serve_dispatch", "span": sid + 1,
                "parent": sid, "depth": 1, "tid": 7, "mono": t0 + 0.001,
                "dur_ms": total + 4.0, "trace_id": tid, "replica": rep})
            router_evs.append({
                "t": wall0 + t0, "kind": "span_end", "rank": 0,
                "span": sid, "tid": 7, "mono": t0 + 0.01,
                "dur_ms": total + 6.0})
    _wstream(os.path.join(d, "rank-0.jsonl"), router_evs)
    for rep in (1, 2):
        evs = [{"t": wall0, "kind": "clock_anchor", "rank": rep,
                "wall": wall0, "mono": 0.0}]
        tpot = fast_tpot if rep == 1 else slow_tpot
        for i, tid in enumerate(tids[rep]):
            t0 = float(rep * 100 + i)
            decode = 10 * tpot
            evs.append({"t": wall0 + t0, "kind": "span", "rank": rep,
                        "name": "serve_handle", "span": 9000 + i,
                        "parent": 0, "depth": 0, "tid": 3,
                        "mono": t0 + 0.001, "dur_ms": 5.0 + decode + 10.0,
                        "trace_id": tid, "replica": rep})
            evs.append({"t": wall0 + t0, "kind": "serve_request",
                        "rank": rep, "queue_wait_ms": 3.0,
                        "prefill_ms": 2.0, "decode_ms": decode,
                        "latency_ms": 5.0 + decode,
                        "tokens": 10, "ttft_ms": 6.0 + (0 if rep == 1
                                                        else 100.0),
                        "request_id": f"q-{rep}-{i}", "reason": "length",
                        "cause": "none", "trace_id": tid})
            if rep == slow_replica:
                evs.append({"t": wall0 + t0,
                            "kind": "serve_slo_violation", "rank": rep,
                            "stage": "ttft", "value_ms": 106.0,
                            "threshold_ms": 50.0,
                            "request_id": f"q-{rep}-{i}",
                            "trace_id": tid})
        _wstream(os.path.join(d, f"rank-{rep}.jsonl"), evs)
    return tids


def test_serve_report_attributes_straggler_and_exits_3(tmp_path):
    """ACCEPTANCE: a seeded-slow replica's SLO-violating requests are
    attributed to the straggler cause (>=90%) and serve_report exits 3."""
    d = str(tmp_path)
    tids = _synth_fleet(d)
    res = subprocess.run([sys.executable, _SERVE_REPORT, d, "--json"],
                         capture_output=True, text=True, timeout=60)
    assert res.returncode == 3, res.stderr
    rep = json.loads(res.stdout)
    assert rep["requests"] == 16
    assert [s["replica"] for s in rep["straggler_replicas"]] == [2]
    slow = [rep["per_request"][tid] for tid in tids[2]]
    hit = sum(1 for r in slow if r["cause"] == "straggler")
    assert hit >= 0.9 * len(slow)
    assert all(v["stage"] == "ttft" for v in rep["slo_violations"])
    assert {v["cause"] for v in rep["slo_violations"]} == {"straggler"}
    # leg decomposition: the slow cohort's buckets are decode-dominated
    slow_rows = [row for row in rep["attribution"]
                 if row["count"] and row["latency_ms"] > 100]
    assert slow_rows and all(
        row["legs"]["decode_ms"] == max(row["legs"].values())
        for row in slow_rows)
    # human rendering names the straggler too
    txt = subprocess.run([sys.executable, _SERVE_REPORT, d],
                         capture_output=True, text=True, timeout=60)
    assert txt.returncode == 3
    assert "straggler replica 2" in txt.stdout
    assert "SLO violations" in txt.stdout


def test_serve_report_cause_priority_failover_wins(tmp_path):
    """A request that both failed over AND missed the prefix cache
    attributes to failover — it paid a whole dead attempt first."""
    d = str(tmp_path)
    evs = [{"t": 1000.0, "kind": "clock_anchor", "rank": 0,
            "wall": 1000.0, "mono": 0.0},
           {"t": 1000.5, "kind": "span", "rank": 0,
            "name": "serve_dispatch", "span": 2, "parent": 1, "depth": 1,
            "tid": 7, "mono": 0.5, "dur_ms": 30.0, "trace_id": "t1",
            "replica": 0, "error": "Connection refused"},
           {"t": 1000.6, "kind": "span", "rank": 0,
            "name": "serve_dispatch", "span": 3, "parent": 1, "depth": 1,
            "tid": 7, "mono": 0.6, "dur_ms": 50.0, "trace_id": "t1",
            "replica": 1},
           {"t": 1000.6, "kind": "serve_cause", "rank": 0,
            "cause": "failover", "trace_id": "t1"},
           {"t": 1000.7, "kind": "serve_request", "rank": 1,
            "queue_wait_ms": 1.0, "prefill_ms": 5.0, "decode_ms": 20.0,
            "latency_ms": 26.0, "tokens": 4, "ttft_ms": 7.0,
            "request_id": "q", "cause": "cache_miss", "trace_id": "t1"}]
    _wstream(os.path.join(d, "rank-0.jsonl"), evs)
    mod = _load_tool("serve_report")
    streams, warnings = mod.load_streams([d])
    rep = mod.build_report(streams, warnings=warnings)
    r = rep["per_request"]["t1"]
    assert r["cause"] == "failover"
    assert r["attempts"] == 2 and r["failed_attempts"] == 1


def test_serve_report_unfinished_requests_died_inside(tmp_path):
    d = str(tmp_path)
    evs = [{"t": 1000.0, "kind": "clock_anchor", "rank": 1,
            "wall": 1000.0, "mono": 0.0},
           {"t": 1000.1, "kind": "span_begin", "rank": 1,
            "name": "serve_handle", "span": 5, "parent": 0, "depth": 0,
            "tid": 3, "mono": 0.1, "trace_id": "dead1"},
           {"t": 1002.0, "kind": "serve_state", "rank": 1}]
    _wstream(os.path.join(d, "rank-1.jsonl"), evs)
    mod = _load_tool("serve_report")
    streams, warnings = mod.load_streams([d])
    rep = mod.build_report(streams, warnings=warnings)
    assert rep["unfinished"] == 1 and rep["requests"] == 0
    row = rep["unfinished_requests"][0]
    assert row["trace_id"] == "dead1"
    assert row["open_span"]["name"] == "serve_handle"
    assert row["open_span"]["open_ms"] == pytest.approx(1900.0, abs=50)
    assert "died inside" in mod.format_text(rep)


def test_serve_report_exit_codes(tmp_path):
    mod = _load_tool("serve_report")
    assert mod.main([str(tmp_path / "nope")]) == 2
    d = str(tmp_path)
    _wstream(os.path.join(d, "rank-0.jsonl"),
             [{"t": 1.0, "kind": "clock_anchor", "rank": 0,
               "wall": 1.0, "mono": 0.0}])
    assert mod.main([d]) == 0  # streams but no serving activity: clean


# ---------------------------------------------------------------------------
# trace_report defers serving streams
# ---------------------------------------------------------------------------
def test_trace_report_defers_serving_streams_to_serve_report(tmp_path):
    """The serving stream's driver-blocks-while-HTTP-threads-work shape
    must not produce a bogus idle-gap straggler verdict: trace_report
    recognizes serve_* vocabulary, excludes the rank from both
    straggler rules and points at serve_report."""
    d = str(tmp_path)
    # two ordinary training ranks with symmetric steps
    for r in (0, 1):
        evs = [{"t": 1000.0, "kind": "clock_anchor", "rank": r,
                "wall": 1000.0, "mono": 0.0}]
        evs += [{"t": 1000.0 + i, "kind": "step", "rank": r, "step": i,
                 "wall_ms": 50.0} for i in range(5)]
        _wstream(os.path.join(d, f"rank-{r}.jsonl"), evs)
    # one serving rank: huge unaccounted wall (blocked driver thread)
    evs = [{"t": 1000.0, "kind": "clock_anchor", "rank": 2,
            "wall": 1000.0, "mono": 0.0},
           {"t": 1000.1, "kind": "span", "rank": 2, "name": "serve_handle",
            "span": 1, "parent": 0, "depth": 0, "tid": 3, "mono": 0.1,
            "dur_ms": 5.0, "trace_id": "t1"},
           {"t": 1000.2, "kind": "serve_request", "rank": 2,
            "queue_wait_ms": 1.0, "prefill_ms": 1.0, "decode_ms": 3.0,
            "latency_ms": 5.0, "tokens": 2, "ttft_ms": 2.0,
            "request_id": "q", "trace_id": "t1"},
           {"t": 1900.0, "kind": "serve_state", "rank": 2}]
    _wstream(os.path.join(d, "rank-2.jsonl"), evs)
    mod = _load_tool("trace_report")
    rep = mod.build_report(d)
    assert rep["serving_ranks"] == [2]
    assert rep["per_rank"]["2"]["serving_mode"] is True
    assert rep["per_rank"]["0"]["serving_mode"] is False
    assert not any(s["rank"] == 2 for s in rep["stragglers"]), \
        "serving rank excluded from straggler verdicts"
    assert any("serve_report" in w for w in rep["warnings"])


# ---------------------------------------------------------------------------
# launch.py gang-death hook
# ---------------------------------------------------------------------------
def test_launch_serving_detection_and_hook(tmp_path, capsys):
    launch = _load_tool("launch")
    d = str(tmp_path)
    _wstream(os.path.join(d, "rank-0.jsonl"),
             [{"t": 1.0, "kind": "step", "rank": 0, "wall_ms": 5.0}])
    assert launch._serving_streams_present(d) is False
    _synth_fleet(d)  # overwrites rank-0 with the router stream
    assert launch._serving_streams_present(d) is True
    launch._print_serve_report(d)
    err = capsys.readouterr().err
    assert "serving request report" in err
    assert "SLO violations (exit 3)" in err
    assert "straggler" in err


# ---------------------------------------------------------------------------
# real engine end-to-end: merged Chrome trace + bitwise parity
# ---------------------------------------------------------------------------
def test_e2e_merged_trace_flow_links_and_bitwise_parity(tmp_path, tele,
                                                        monkeypatch):
    """ACCEPTANCE: a router-fronted real-engine request produces ONE
    flow-linked span tree in the merged Chrome trace (router dispatch
    slice chained to the replica's request tree, every B matched by an
    E) — and the traced tokens are bitwise identical to an untraced
    in-process serve."""
    import mxnet_tpu as mx
    from mxnet_tpu.models.transformer import Transformer
    from mxnet_tpu.serving import (ReplicaServer, Request, ServingEngine,
                                   TransformerAdapter)

    mx.random.seed(0)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=48, dropout=0.0)
    net.initialize(mx.init.Xavier())

    def eng():
        return ServingEngine(TransformerAdapter(net, src_max_len=6),
                             slots=2, page_size=4, max_len=12,
                             stream_every=4)

    prompt = [5, 6, 7]
    # untraced reference first, BEFORE telemetry/tracing exist at all
    monkeypatch.setenv("MX_RQTRACE", "0")
    want = eng().serve([Request(prompt, max_new_tokens=6, bos_id=BOS,
                                eos_id=EOS, request_id="w")])["w"]
    monkeypatch.setenv("MX_RQTRACE", "1")
    d = str(tmp_path)
    tele.enable(d)
    rep = ReplicaServer(eng(), bos_id=BOS, eos_id=EOS, port=0,
                        directory=d).start()
    router = Router(d, port=0, health_sec=60.0)
    try:
        router.start()
        out = _post(router.port, {"prompt": prompt, "max_new_tokens": 6})
        assert out["tokens"] == [int(t) for t in want], \
            "tracing must not perturb decode"
        tid = out["trace_id"]
        # the HTTP response returns at stream-finish; the engine's evict
        # (which records serve_request) lands a beat later — poll for it
        import time as _time
        for _ in range(100):
            tele.flush()
            evs = _events(d)
            if any(e.get("kind") == "serve_request" for e in evs):
                break
            _time.sleep(0.02)
        # the engine's request spans carry the SAME trace id the router
        # minted — the cross-layer propagation contract
        for name in ("serve_queue", "serve_decode"):
            mine = [e for e in evs if e.get("name") == name
                    and e.get("trace_id") == tid]
            assert mine, f"{name} span missing trace id {tid}"
        sreq = [e for e in evs if e.get("kind") == "serve_request"]
        assert [e.get("trace_id") for e in sreq] == [tid]
        path = tele.export_chrome_trace(d)
        trace = json.load(open(path))["traceEvents"]
        # every sampled request span B has its matching E
        for name in ("serve_route", "serve_handle"):
            b = [e for e in trace if e.get("ph") == "B"
                 and e.get("name") == name]
            e_ = [e for e in trace if e.get("ph") == "E"
                  and e.get("name") == name]
            assert len(b) == 1 and len(e_) == 1, name
            assert b[0]["args"]["trace_id"] == tid
        # the request flow: dispatch slice chained to the handle tree
        flows = [e for e in trace if e.get("cat") == "request"
                 and e.get("name") == tid]
        assert [e["ph"] for e in flows] == ["s", "t"]
        assert len({e["id"] for e in flows}) == 1
        # serve_report closes the loop over the same stream
        res = subprocess.run([sys.executable, _SERVE_REPORT, d, "--json"],
                             capture_output=True, text=True, timeout=60)
        assert res.returncode == 0, res.stderr
        report = json.loads(res.stdout)
        assert report["requests"] == 1
        assert report["per_request"][tid]["legs"]["decode_ms"] > 0
    finally:
        router.stop()
        rep.stop()
