"""Gang-wide trace analysis (docs/OBSERVABILITY.md §Tracing & analysis):
span API + kill switch, clock anchors, Chrome/Perfetto + Prometheus
exporters, the tools/trace_report.py straggler-hunting CLI, the
launch.py span-collapsed flight tail, and the spans-don't-perturb-
training parity guarantee."""
import importlib.util
import json
import os
import re
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, telemetry

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TRACE_REPORT = os.path.join(_REPO, "tools", "trace_report.py")


@pytest.fixture
def tele():
    telemetry.reset()
    yield telemetry
    telemetry.reset()


def _events(tmp_path, rank=0):
    return [json.loads(line)
            for line in open(telemetry.event_path(str(tmp_path), rank))]


# ---------------------------------------------------------------------------
# span API
# ---------------------------------------------------------------------------
def test_span_complete_event_and_nesting(tele, tmp_path):
    tele.enable(str(tmp_path))
    with tele.span("outer", executor="X"):
        with tele.span("inner"):
            time.sleep(0.002)
    tele.flush()
    spans = [e for e in _events(tmp_path) if e["kind"] == "span"]
    assert [s["name"] for s in spans] == ["inner", "outer"]  # exit order
    inner, outer = spans
    assert inner["parent"] == outer["span"] and inner["depth"] == 1
    assert outer["parent"] == 0 and outer["depth"] == 0
    assert outer["executor"] == "X"
    assert inner["dur_ms"] >= 2.0
    assert outer["dur_ms"] >= inner["dur_ms"]
    assert inner["mono"] >= outer["mono"]
    s = tele.summary()["spans"]
    assert s["outer"]["count"] == 1 and s["inner"]["count"] == 1
    assert s["outer"]["total_ms"] >= s["inner"]["total_ms"]


def test_span_paired_emits_begin_end(tele, tmp_path):
    tele.enable(str(tmp_path))
    with tele.span("blocking", paired=True, step=7):
        pass
    tele.flush()
    evs = _events(tmp_path)
    begin = [e for e in evs if e["kind"] == "span_begin"]
    end = [e for e in evs if e["kind"] == "span_end"]
    assert len(begin) == 1 and len(end) == 1
    assert begin[0]["span"] == end[0]["span"]
    assert begin[0]["step"] == 7 and begin[0]["name"] == "blocking"
    assert end[0]["dur_ms"] >= 0


def test_span_error_annotated(tele, tmp_path):
    tele.enable(str(tmp_path))
    with pytest.raises(ValueError):
        with tele.span("doomed"):
            raise ValueError("boom")
    tele.flush()
    sp = [e for e in _events(tmp_path) if e["kind"] == "span"][0]
    assert sp["error"] == "ValueError"


def test_record_span_retroactive(tele, tmp_path):
    tele.enable(str(tmp_path))
    with tele.span("outer"):
        tele.record_span("waited", 1.0, 1.25, executor="X")
    tele.flush()
    spans = [e for e in _events(tmp_path) if e["kind"] == "span"]
    waited = [s for s in spans if s["name"] == "waited"][0]
    assert waited["dur_ms"] == pytest.approx(250.0)
    assert waited["depth"] == 1  # nested under the open outer span
    assert waited["parent"] == [s for s in spans
                                if s["name"] == "outer"][0]["span"]


def test_span_kill_switch(tele, tmp_path, monkeypatch):
    monkeypatch.setenv("MX_TELEMETRY_SPANS", "0")
    tele.enable(str(tmp_path))
    assert not tele.spans_enabled()
    with tele.span("invisible"):
        pass
    tele.record_span("also_invisible", 0.0, 1.0)
    tele.flush()
    kinds = {e["kind"] for e in _events(tmp_path)}
    assert not kinds & {"span", "span_begin", "span_end"}
    assert tele.summary()["spans"] == {}
    # step events and heartbeats keep flowing with spans off
    tele.record_step("X", step=1, wall_s=0.01)
    tele.flush()
    assert "step" in {e["kind"] for e in _events(tmp_path)}


def test_spans_disabled_entirely_without_recorder(tele):
    assert not tele.spans_enabled()
    with tele.span("noop"):  # must not raise or allocate state
        pass
    assert tele.summary()["spans"] == {}


# ---------------------------------------------------------------------------
# clock anchors
# ---------------------------------------------------------------------------
def test_clock_anchor_at_enable_and_every_flush(tele, tmp_path):
    tele.enable(str(tmp_path))
    tele.record("x")
    tele.flush()
    tele.record("y")
    tele.flush()
    anchors = [e for e in _events(tmp_path) if e["kind"] == "clock_anchor"]
    assert len(anchors) >= 3  # one at enable + one per flush batch
    for a in anchors:
        assert {"wall", "mono"} <= set(a)
        # the pair is taken at one instant: wall ~ t
        assert abs(a["wall"] - a["t"]) < 1.0


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------
def _write_synthetic_rank(directory, rank, wall_ms=2.0, n=10,
                          spacing=None, anchor=True, collective=True):
    """A synthetic rank stream: nested spans + steps (+ collectives),
    using the same schema telemetry.py writes — the no-jax fixture.
    Default spacing models a tight compute-bound loop (each step starts
    just after the previous one's wall), the shape where step-wall skew
    is the straggler signal."""
    if spacing is None:
        spacing = wall_ms / 1e3 + 0.003
    t0, mono0 = 1000.0 + rank * 7.5, 5.0  # rank start-time skew
    lines = []
    if anchor:
        lines.append({"t": t0, "kind": "clock_anchor", "rank": rank,
                      "wall": t0, "mono": mono0})
    sid = rank * 10000
    for i in range(n):
        t = t0 + i * spacing
        mono = mono0 + i * spacing
        sid += 1
        outer = sid
        lines.append({"t": t, "kind": "span_begin", "rank": rank,
                      "name": "train_step", "span": outer, "parent": 0,
                      "depth": 0, "tid": 7, "mono": mono})
        sid += 1
        lines.append({"t": t, "kind": "span", "rank": rank,
                      "name": "dispatch", "span": sid, "parent": outer,
                      "depth": 1, "tid": 7,
                      "mono": mono + 0.0002, "dur_ms": wall_ms / 2})
        lines.append({"t": t + wall_ms / 1e3, "kind": "span_end",
                      "rank": rank, "name": "train_step", "span": outer,
                      "tid": 7, "mono": mono + wall_ms / 1e3,
                      "dur_ms": wall_ms})
        lines.append({"t": t, "kind": "step", "rank": rank,
                      "executor": "X", "step": i + 1, "wall_ms": wall_ms,
                      "traced": i == 0, "samples": 8,
                      "transfer_bytes": 128})
        if collective:
            lines.append({"t": t, "kind": "collective", "rank": rank,
                          "op": "global_allreduce", "nbytes": 4096,
                          "wall_ms": 0.5, "traced": i == 0})
    with open(os.path.join(str(directory), f"rank-{rank}.jsonl"),
              "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")


def _validate_chrome(trace_events):
    """Trace-event schema: chronological per track, matched B/E pairs."""
    stacks = {}
    last_ts = {}
    for e in trace_events:
        if e["ph"] == "M":
            continue
        key = (e["pid"], e.get("tid"))
        assert e["ts"] >= last_ts.get(key, 0.0), e
        last_ts[key] = e["ts"]
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["name"])
        elif e["ph"] == "E":
            assert stacks.get(key), f"E without B: {e}"
            assert stacks[key].pop() == e["name"], e
    open_spans = {k: v for k, v in stacks.items() if v}
    assert not open_spans, open_spans


def test_chrome_trace_two_rank_merge(tele, tmp_path):
    _write_synthetic_rank(tmp_path, 0)
    _write_synthetic_rank(tmp_path, 1)
    out = telemetry.export_chrome_trace(str(tmp_path))
    payload = json.load(open(out))
    evs = payload["traceEvents"]
    pids = {e["pid"] for e in evs}
    assert pids == {0, 1}
    # named process track per rank
    names = {e["pid"]: e["args"]["name"] for e in evs if e["ph"] == "M"}
    assert names == {0: "rank 0", 1: "rank 1"}
    _validate_chrome(evs)
    # paired spans became B/E; complete-form spans became X slices
    b_names = [e["name"] for e in evs if e["ph"] == "B" and e["pid"] == 0]
    assert "train_step" in b_names
    x_names = {e["name"] for e in evs if e["ph"] == "X" and e["pid"] == 0}
    assert "dispatch" in x_names
    # a nested dispatch X sits inside its train_step B/E extent
    b0 = min(e["ts"] for e in evs
             if e["ph"] == "B" and e["pid"] == 0
             and e["name"] == "train_step")
    d0 = min(e["ts"] for e in evs
             if e["ph"] == "X" and e["pid"] == 0
             and e["name"] == "dispatch")
    assert d0 >= b0
    # collectives became complete events + flow events chaining the ranks
    xs = [e for e in evs if e["ph"] == "X"
          and e["name"] == "global_allreduce"]
    assert xs and all(e["dur"] > 0 for e in xs)
    flows = [e for e in evs if e["ph"] in ("s", "t")]
    assert {e["ph"] for e in flows} == {"s", "t"}  # start + pass-through
    # the same occurrence shares one flow id across ranks
    ids0 = [e["id"] for e in flows if e["pid"] == 0]
    ids1 = [e["id"] for e in flows if e["pid"] == 1]
    assert set(ids0) == set(ids1)
    # clock anchors aligned the rank start-time skew: rank 1's first
    # train_step B sits ~7.5s (the synthetic skew) after rank 0's
    first = {pid: min(e["ts"] for e in evs
                      if e["ph"] == "B" and e["pid"] == pid)
             for pid in (0, 1)}
    assert first[1] - first[0] == pytest.approx(7.5e6, rel=0.01)


def test_chrome_trace_empty_dir_returns_none(tele, tmp_path):
    assert telemetry.export_chrome_trace(str(tmp_path)) is None


def test_prometheus_snapshot_parses(tele, tmp_path):
    tele.enable(str(tmp_path))
    tele.record_step("Exec\"A", step=1, wall_s=0.5, samples=0, traced=True)
    tele.record_step("Exec\"A", step=2, wall_s=0.1, samples=16)
    tele.record_collective("device_allreduce", nbytes=1024, wall_s=0.002)
    tele.record_checkpoint("save", step=2, wall_s=0.05, nbytes=4096)
    with tele.span("train_step"):
        pass
    tele.heartbeat(2, force=True)
    path = tele.export_prometheus(str(tmp_path / "metrics.prom"))
    lines = open(path).read().splitlines()
    assert lines[-1] == "# EOF"
    sample_re = re.compile(
        r'^[a-z_][a-z0-9_]*\{[^{}]*\} -?[0-9.eE+-]+$')
    for line in lines[:-1]:
        assert line.startswith("# TYPE ") or sample_re.match(line), line
    text = "\n".join(lines)
    assert 'mx_step_total{rank="0",executor="Exec\\"A"} 2' in text
    assert 'mx_collective_bytes_total{rank="0"} 1024' in text
    assert 'mx_span_total{rank="0",span="train_step"} 1' in text
    assert "mx_heartbeat_age_seconds" in text
    assert 'mx_checkpoint_saves_total{rank="0"} 1' in text


def test_trace_export_env_off_by_default(tele, tmp_path, monkeypatch):
    monkeypatch.delenv("MX_TRACE_EXPORT", raising=False)
    assert telemetry._trace_export_target() is None
    monkeypatch.setenv("MX_TRACE_EXPORT", "0")
    assert telemetry._trace_export_target() is None
    tele.enable(str(tmp_path))
    monkeypatch.setenv("MX_TRACE_EXPORT", "1")
    assert telemetry._trace_export_target() == str(tmp_path)
    monkeypatch.setenv("MX_TRACE_EXPORT", str(tmp_path / "out"))
    assert telemetry._trace_export_target() == str(tmp_path / "out")


def test_trace_export_at_exit_hook(tele, tmp_path, monkeypatch):
    tele.enable(str(tmp_path))
    tele.record_step("X", step=1, wall_s=0.01)
    monkeypatch.setenv("MX_TRACE_EXPORT", str(tmp_path / "export"))
    telemetry._export_at_exit()
    assert (tmp_path / "export" / "metrics-0.prom").exists()
    assert (tmp_path / "export" / "trace.json").exists()  # rank 0 merges


# ---------------------------------------------------------------------------
# trace_report.py CLI
# ---------------------------------------------------------------------------
def _report(directory, *args):
    return subprocess.run(
        [sys.executable, _TRACE_REPORT, str(directory), *args],
        capture_output=True, text=True, timeout=60)


def test_trace_report_clean_run_exits_zero(tmp_path):
    _write_synthetic_rank(tmp_path, 0, wall_ms=2.0)
    _write_synthetic_rank(tmp_path, 1, wall_ms=2.1)
    res = _report(tmp_path)
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "no anomalies detected" in res.stdout
    assert "collective bandwidth" in res.stdout
    assert "global_allreduce" in res.stdout


def test_trace_report_flags_step_wall_straggler(tmp_path):
    _write_synthetic_rank(tmp_path, 0, wall_ms=2.0)
    _write_synthetic_rank(tmp_path, 1, wall_ms=20.0)  # 10x slower
    res = _report(tmp_path, "--json")
    assert res.returncode == 3, (res.stdout, res.stderr)
    rep = json.loads(res.stdout)
    assert [s["rank"] for s in rep["stragglers"]] == [1]
    assert rep["stragglers"][0]["rule"] == "step-wall"
    assert rep["per_rank"]["0"]["window_mean_ms"] == pytest.approx(2.0)
    assert rep["per_rank"]["1"]["window_mean_ms"] == pytest.approx(20.0)
    assert rep["anomalies"]


def test_trace_report_flags_idle_gap_straggler(tmp_path):
    """The lock-step shape: equal step walls and cadence, but one rank's
    inter-step time is UNRECORDED host work while the peer's equal share
    of waiting sits in recorded loss_wait spans."""
    for rank, recorded in ((0, True), (1, False)):
        t0, mono0 = 1000.0, 5.0
        lines = [{"t": t0, "kind": "clock_anchor", "rank": rank,
                  "wall": t0, "mono": mono0}]
        sid = rank * 10000
        t, mono = t0, mono0
        for i in range(20):
            sid += 1
            lines.append({"t": t, "kind": "span", "rank": rank,
                          "name": "train_step", "span": sid, "parent": 0,
                          "depth": 0, "tid": 7, "mono": mono,
                          "dur_ms": 2.0})
            lines.append({"t": t, "kind": "step", "rank": rank,
                          "executor": "X", "step": i + 1, "wall_ms": 2.0,
                          "traced": False})
            t += 0.002
            mono += 0.002
            if recorded:  # peer: waits for the straggler, recorded
                sid += 1
                lines.append({"t": t, "kind": "span", "rank": rank,
                              "name": "loss_wait", "span": sid,
                              "parent": 0, "depth": 0, "tid": 7,
                              "mono": mono, "dur_ms": 50.0})
            t += 0.05
            mono += 0.05
        with open(tmp_path / f"rank-{rank}.jsonl", "w") as f:
            for line in lines:
                f.write(json.dumps(line) + "\n")
    res = _report(tmp_path, "--json")
    assert res.returncode == 3, (res.stdout, res.stderr)
    rep = json.loads(res.stdout)
    assert [s["rank"] for s in rep["stragglers"]] == [1]
    assert rep["stragglers"][0]["rule"] == "idle-gap"


def test_trace_report_warns_on_missing_anchor(tmp_path):
    _write_synthetic_rank(tmp_path, 0, anchor=False)
    res = _report(tmp_path)
    assert "no clock_anchor" in res.stdout, res.stdout


def test_trace_report_flags_event_gap_and_retrace(tmp_path):
    _write_synthetic_rank(tmp_path, 0)
    with open(tmp_path / "rank-0.jsonl", "a") as f:
        f.write(json.dumps({"t": 2000.0, "kind": "retrace", "rank": 0,
                            "executor": "X", "traces": 9,
                            "signature": "((7, 3), float32)"}) + "\n")
    res = _report(tmp_path, "--json", "--heartbeat-gap", "30")
    assert res.returncode == 3
    rep = json.loads(res.stdout)
    rules = {a.split(":")[0] for a in rep["anomalies"]}
    assert "retrace storm" in rules
    assert "event gap" in rules  # the 2000.0 stamp is ~1000s after t0
    assert rep["event_gaps"][0]["rank"] == 0


def test_trace_report_empty_dir_exits_two(tmp_path):
    res = _report(tmp_path)
    assert res.returncode == 2


# ---------------------------------------------------------------------------
# launch.py flight-tail span rendering
# ---------------------------------------------------------------------------
def _load_launch():
    spec = importlib.util.spec_from_file_location(
        "launch_for_test", os.path.join(_REPO, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_flight_tail_collapses_span_pairs(tmp_path):
    launch = _load_launch()
    lines = [
        {"t": 1.0, "kind": "clock_anchor", "rank": 0, "wall": 1.0,
         "mono": 0.0},
        {"t": 1.0, "kind": "step", "rank": 0, "step": 1, "wall_ms": 5.0},
        {"t": 1.1, "kind": "span_begin", "rank": 0, "name": "loss_wait",
         "span": 7, "parent": 0, "depth": 0, "tid": 9, "mono": 0.1,
         "executor": "X"},
        {"t": 1.2, "kind": "span_end", "rank": 0, "name": "loss_wait",
         "span": 7, "tid": 9, "mono": 0.2, "dur_ms": 100.0},
        {"t": 1.3, "kind": "span", "rank": 0, "name": "train_step",
         "span": 8, "parent": 0, "depth": 0, "tid": 9, "mono": 0.3,
         "dur_ms": 12.5, "executor": "X"},
        # still-open begin: the "died inside X" clue must survive as-is
        {"t": 1.4, "kind": "span_begin", "rank": 0,
         "name": "bucket_collective", "span": 9, "parent": 0, "depth": 0,
         "tid": 9, "mono": 0.4},
    ]
    with open(tmp_path / "rank-0.jsonl", "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    tail = launch._flight_tail(str(tmp_path), 0)
    evs = [json.loads(t) for t in tail]
    kinds = [e["kind"] for e in evs]
    # anchor dropped; pair collapsed to one "span" line with duration;
    # complete span stripped of plumbing; open begin kept verbatim
    assert kinds == ["step", "span", "span", "span_begin"], kinds
    assert evs[1]["name"] == "loss_wait" and evs[1]["dur_ms"] == 100.0
    assert evs[1]["executor"] == "X"
    assert "span" not in evs[2] and evs[2]["dur_ms"] == 12.5
    assert evs[3]["name"] == "bucket_collective"


def test_launch_reexports_authoritative_trace(tmp_path, monkeypatch):
    """With MX_TRACE_EXPORT on, the supervisor re-merges the gang trace
    after every rank is reaped: rank 0's own atexit merge can race peers
    still running and drop the straggler tail, so the supervisor's merge
    over the complete files must overwrite it."""
    launch = _load_launch()
    _write_synthetic_rank(tmp_path, 0)
    _write_synthetic_rank(tmp_path, 1)
    out = tmp_path / "trace.json"
    # rank 0's racy best-effort export: stale, missing rank 1 entirely
    out.write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 0, "ts": 0,
         "args": {"name": "rank 0"}}]}))
    monkeypatch.setenv("MX_TRACE_EXPORT", "1")
    launch._reexport_trace(str(tmp_path))
    evs = json.load(open(out))["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1}  # rank 1 restored
    _validate_chrome(evs)
    # kill switch: no target -> no child run, file untouched
    out.write_text("sentinel")
    monkeypatch.delenv("MX_TRACE_EXPORT")
    launch._reexport_trace(str(tmp_path))
    assert out.read_text() == "sentinel"


# ---------------------------------------------------------------------------
# spans must not perturb the computation
# ---------------------------------------------------------------------------
def _train_losses_and_weights(tmp_path, tag):
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    telemetry.reset()
    telemetry.enable(str(tmp_path / tag))
    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    step = DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(0)
    losses = []
    for _ in range(5):
        x = nd.array(rng.rand(8, 4).astype(np.float32))
        y = nd.array(rng.rand(8, 4).astype(np.float32))
        losses.append(float(step.step(x, y)))
    step.sync_to_block()
    weights = [p.data().asnumpy().copy()
               for p in net.collect_params().values()]
    return losses, weights


def test_spans_do_not_perturb_training(tele, tmp_path, monkeypatch):
    """Acceptance: losses/weights bitwise unchanged with spans enabled vs
    MX_TELEMETRY_SPANS=0 — observability must observe, not perturb."""
    monkeypatch.setenv("MX_TELEMETRY_SPANS", "1")
    on_losses, on_weights = _train_losses_and_weights(tmp_path, "on")
    # the span layer actually recorded in mode one
    assert telemetry.summary()["spans"]
    monkeypatch.setenv("MX_TELEMETRY_SPANS", "0")
    off_losses, off_weights = _train_losses_and_weights(tmp_path, "off")
    assert telemetry.summary()["spans"] == {}
    assert on_losses == off_losses  # float equality = bitwise for scalars
    for a, b in zip(on_weights, off_weights):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# real 2-rank gang: trace report + chrome export (acceptance shape)
# ---------------------------------------------------------------------------
@pytest.mark.dist
@pytest.mark.slow
def test_gang_trace_report_flags_injected_straggler(tmp_path):
    """Launch a real 2-rank gang with rank 1 sleep-instrumented as the
    straggler, then: trace_report flags it (nonzero exit), reports
    per-rank skew and collective bandwidth, and the exported Chrome trace
    validates (chronological, matched B/E per track)."""
    tdir = tmp_path / "telemetry"
    env = dict(os.environ, MX_TELEMETRY_DIR=str(tdir),
               MX_TELEMETRY_FLUSH_SEC="0.2", MX_HEARTBEAT_SEC="0.5",
               TRACE_STRAGGLER_RANK="1", TRACE_STRAGGLER_SLEEP="0.06",
               MX_TRACE_STRAGGLER_PCT="25")
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", "2", "--force-cpu", "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist", "trace_worker.py")]
    res = subprocess.run(cmd, cwd=_REPO, timeout=240, capture_output=True,
                         text=True, env=env)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("trace OK") == 2, res.stdout
    # --- trace_report: straggler flagged, skew + bandwidth reported
    rep_res = subprocess.run(
        [sys.executable, _TRACE_REPORT, str(tdir), "--json"],
        env=env, capture_output=True, text=True, timeout=60)
    assert rep_res.returncode == 3, (rep_res.stdout, rep_res.stderr)
    rep = json.loads(rep_res.stdout)
    assert 1 in [s["rank"] for s in rep["stragglers"]], rep["stragglers"]
    assert 0 not in [s["rank"] for s in rep["stragglers"]]
    assert rep["per_rank"]["0"]["window_mean_ms"] is not None
    assert rep["per_rank"]["1"]["window_mean_ms"] is not None
    colls = [row for row in rep["collectives"]
             if row["op"] == "global_allreduce"]
    assert {row["rank"] for row in colls} == {0, 1}
    assert all(row["bytes"] > 0 for row in colls)
    # the straggler's unaccounted time towers over the peer's
    assert (rep["per_rank"]["1"]["idle_gap_ms"]
            > rep["per_rank"]["0"]["idle_gap_ms"] + 500)
    # --- human-readable rendering names the straggler too
    txt_res = subprocess.run([sys.executable, _TRACE_REPORT, str(tdir)],
                             env=env, capture_output=True, text=True,
                             timeout=60)
    assert txt_res.returncode == 3
    assert "ANOMALIES" in txt_res.stdout
    # --- chrome trace for the same run validates against the schema
    out = telemetry.export_chrome_trace(str(tdir))
    payload = json.load(open(out))
    evs = payload["traceEvents"]
    assert {e["pid"] for e in evs} >= {0, 1}
    _validate_chrome(evs)
    span_names = {e["name"] for e in evs if e["ph"] in ("B", "X")}
    assert {"train_step", "dispatch", "loss_wait",
            "loss_allreduce"} <= span_names, span_names
