"""Faster-RCNN model family (BASELINE config 5 second half): target-op
semantics, forward shapes, one-block train loss convergence, detect format.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import FasterRCNNTrainLoss, faster_rcnn_small


def test_rpn_anchor_target_semantics():
    """gt box gets at least one fg anchor; far anchors are bg; targets are
    zero outside fg rows; layout length matches H*W*A."""
    cls_prob = nd.zeros((1, 6, 8, 8))  # A=3 -> 2A=6
    gt = nd.array(np.array([[[0, 8, 8, 24, 24]]], np.float32))
    lab, bt, bw = nd.contrib.RPNAnchorTarget(
        cls_prob, gt, scales=(2.0,), ratios=(0.5, 1.0, 2.0),
        feature_stride=8)
    lab_np, bw_np, bt_np = lab.asnumpy(), bw.asnumpy(), bt.asnumpy()
    assert lab_np.shape == (1, 8 * 8 * 3)
    assert (lab_np == 1).sum() >= 1          # best-anchor rule
    assert (lab_np == 0).sum() > 0           # plenty of background
    np.testing.assert_allclose(bt_np * (1 - bw_np), 0.0)  # masked targets
    # all-padding gt -> no fg anywhere
    gt_pad = nd.array(np.full((1, 1, 5), -1.0, np.float32))
    lab2, _, _ = nd.contrib.RPNAnchorTarget(
        cls_prob, gt_pad, scales=(2.0,), ratios=(0.5, 1.0, 2.0),
        feature_stride=8)
    assert (lab2.asnumpy() == 1).sum() == 0


def test_proposal_target_semantics():
    """gt rows join candidates (so fg always exists), labels are 1-based
    classes, targets live only in the matched class slot."""
    gt = nd.array(np.array(
        [[[1, 10, 10, 30, 30], [-1, 0, 0, 0, 0]]], np.float32))
    rois = np.zeros((4, 5), np.float32)
    rois[:, 1:] = [[40, 40, 60, 60], [0, 0, 5, 5],
                   [11, 11, 29, 29], [50, 0, 60, 10]]
    ro, lb, tg, wt = nd.contrib.ProposalTarget(
        nd.array(rois), gt, num_classes=3, batch_images=1, batch_rois=4,
        fg_fraction=0.5)
    lb_np, wt_np, tg_np = lb.asnumpy(), wt.asnumpy(), tg.asnumpy()
    assert ro.shape == (4, 5) and tg.shape == (4, 12)
    assert (lb_np == 2).sum() >= 1           # cls 1 -> label 2
    fg_rows = lb_np > 0
    # weights: exactly 4 ones in the matched class slot for fg rows
    assert (wt_np[fg_rows].sum(axis=1) == 4).all()
    assert (wt_np[~fg_rows] == 0).all()
    np.testing.assert_allclose(tg_np * (1 - wt_np), 0.0)


def _net(num_classes=1):
    mx.random.seed(0)
    net = faster_rcnn_small(num_classes=num_classes)
    net.initialize(mx.init.Xavier())
    return net


def _batch(B=2, size=64):
    x = nd.array(np.random.RandomState(0).rand(B, 3, size, size)
                 .astype(np.float32))
    gt = nd.array(np.tile(
        np.array([[[0, 16, 16, 48, 48]]], np.float32), (B, 1, 1)))
    im_info = nd.array(np.tile(
        np.array([[size, size, 1.0]], np.float32), (B, 1)))
    return x, gt, im_info


def test_faster_rcnn_forward_shapes():
    net = _net()
    x, gt, im_info = _batch()
    feat, rpn_cls, rpn_bbox = net(x)
    A = net._num_anchors
    assert feat.shape == (2, 64, 8, 8)
    assert rpn_cls.shape == (2, 2 * A, 8, 8)
    assert rpn_bbox.shape == (2, 4 * A, 8, 8)
    from mxnet_tpu import ndarray as F
    rois = net.proposals(F, rpn_cls, rpn_bbox, im_info)
    assert rois.shape == (2 * net._rpn_post, 5)
    cls_pred, bbox_pred = net.rcnn_head(F, feat, rois)
    assert cls_pred.shape == (2 * net._rpn_post, 2)
    assert bbox_pred.shape == (2 * net._rpn_post, 8)


def test_faster_rcnn_train_step_decreases_loss():
    """The 4-loss RPN+ROI train step — target assignment, NMS proposals
    and all — runs as ONE fused XLA program via DataParallelStep (the
    block IS the loss; a dummy label feeds the unused slot)."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net = _net()
    loss_block = FasterRCNNTrainLoss(net)
    x, gt, im_info = _batch()
    loss_block(x, gt, im_info)  # resolve deferred shapes (incl. the roi
    # head's dense layers) before the fused trace
    step = DataParallelStep(
        loss_block, lambda out, label: out,
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 1e-3})
    dummy = nd.zeros((2,))
    losses = [float(np.asarray(step.step((x, gt, im_info), dummy)))
              for _ in range(12)]
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


def test_faster_rcnn_detect_output_format():
    net = _net(num_classes=2)
    x, _, _ = _batch(B=1)
    out = net.detect(x, threshold=0.0).asnumpy()
    assert out.ndim == 3 and out.shape[2] == 6
    ids = out[0, :, 0]
    assert ((ids >= -1) & (ids < 2)).all()
    kept = out[0][ids >= 0]
    if len(kept):
        assert (kept[:, 1] >= 0).all() and (kept[:, 1] <= 1).all()
