"""Exercise the test_utils oracles themselves (check_numeric_gradient /
check_consistency / rand_ndarray / with_seed), per SURVEY §4.3: the
reference applies these per-op in test_operator.py; here the utilities are
driven through representative layer ops so they stay load-bearing.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.test_utils import (check_consistency, check_numeric_gradient,
                                  rand_ndarray, with_seed)


@with_seed(7)
def test_check_numeric_gradient_fc():
    x = rand_ndarray((3, 4))
    w = rand_ndarray((5, 4))
    b = rand_ndarray((5,))

    def loss(x_, w_, b_):
        return (nd.FullyConnected(x_, w_, b_, num_hidden=5) ** 2).sum()

    check_numeric_gradient(loss, [x, w, b])


@with_seed(8)
def test_check_numeric_gradient_conv_bn():
    x = rand_ndarray((2, 3, 5, 5))
    k = rand_ndarray((4, 3, 3, 3))

    def loss(x_, k_):
        out = nd.Convolution(x_, k_, kernel=(3, 3), num_filter=4,
                             no_bias=True, pad=(1, 1))
        return nd.tanh(out).sum()

    check_numeric_gradient(loss, [x, k], eps=1e-2, rtol=5e-2)


@with_seed(9)
def test_check_numeric_gradient_detects_wrong_grad():
    """The oracle must actually FAIL on a broken gradient."""
    x = rand_ndarray((4,))

    import jax

    @jax.custom_vjp
    def bad_square(a):
        return a * a

    def f(a):
        return a * a, a

    def b(res, g):
        return (g * res,)  # WRONG: should be 2*a*g

    bad_square.defvjp(f, b)

    def loss(x_):
        from mxnet_tpu.ops import registry as reg

        return reg.invoke_fn(bad_square, [x_]).sum()

    with pytest.raises(AssertionError):
        check_numeric_gradient(loss, [x])


def test_check_consistency_cpu_contexts():
    """Same computation across contexts (cpu vs cpu here; the tpu row runs
    under the real-chip environment via test_tpu_consistency.py)."""
    inputs = [np.random.RandomState(0).rand(4, 6).astype(np.float32)]

    def fn(x):
        return nd.softmax(nd.dot(x, x.T))

    check_consistency(fn, [mx.cpu(), mx.cpu(1)], inputs_np=inputs)
