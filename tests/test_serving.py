"""Inference serving: continuous batching + paged KV-cache decode
(docs/SERVING.md; ISSUE 11 acceptance).

Covers: bitwise paged-vs-dense attend parity, engine-greedy ==
standalone translate(beam_size=1) token-for-token, the one-executable
property on a mixed-length mid-flight trace (exactly one decode + one
prefill compile event), continuous-batching slot/page reuse, scheduler
backpressure, pool exhaustion, AOT executable round-trip, serve
telemetry + prometheus gauges, the Pallas ragged paged kernel, and the
FullPrefixAdapter decoder-only path.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.transformer import (DenseStepCache, Transformer,
                                          _attend_cached, label_smoothed_ce)
from mxnet_tpu.serving import (ContinuousBatchingScheduler, FullPrefixAdapter,
                               PagedKVCache, Request, ServingEngine,
                               TransformerAdapter, gather_pages, page_coords,
                               paged_attend, write_page)

PAD, BOS, EOS = 0, 1, 2


@pytest.fixture
def tele(tmp_path):
    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path))
    yield telemetry
    telemetry.reset()
    memwatch.reset()


def _tiny_model(vocab=16, max_length=48):
    mx.random.seed(0)
    net = Transformer(vocab, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=max_length, dropout=0.0)
    net.initialize(mx.init.Xavier())
    return net


def _reverse_batch(rng, B, L=6, vocab=16):
    src = np.zeros((B, L + 1), np.int32)
    tgt_in = np.zeros((B, L + 2), np.int32)
    tgt_out = np.zeros((B, L + 2), np.int32)
    for b in range(B):
        toks = rng.randint(3, vocab, L)
        src[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    return src, tgt_in, tgt_out


@pytest.fixture(scope="module")
def trained():
    """Tiny transformer memorizing the reverse task + its train batch —
    sharp logits so greedy decode is decision-stable across executables
    (the engine-vs-translate parity surface)."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net = _tiny_model(max_length=20)
    rng = np.random.RandomState(2)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)
    step = DataParallelStep(
        net, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))
    for _ in range(48):
        step.step((sb, tb), lb)
    step.sync_to_block()
    return net, src


# ---------------------------------------------------------------------------
# paged cache math
# ---------------------------------------------------------------------------
def test_paged_attend_bitwise_identical_to_dense():
    """ACCEPTANCE: gather-by-page-table attention over scattered pages is
    bitwise identical to the dense-cache _attend_cached for the same
    tokens (same values through the same eager op executables)."""
    rng = np.random.RandomState(0)
    S, H, hd, ps, P = 3, 4, 8, 4, 2
    C, Lmax = H * hd, ps * P
    dense_K = rng.randn(S, Lmax, C).astype(np.float32)
    dense_V = rng.randn(S, Lmax, C).astype(np.float32)
    q = nd.array(rng.randn(S, 1, C).astype(np.float32))
    # ragged validity per slot
    keep_np = np.zeros((S, Lmax), np.float32)
    for s, L in enumerate((5, 8, 1)):
        keep_np[s, :L] = 1.0
    keep = nd.array(keep_np)

    # scatter the dense rows into an arbitrarily-permuted page pool
    table_np = 1 + rng.permutation(S * P).reshape(S, P).astype(np.int32)
    kpool = np.zeros((S * P + 1, ps, H, hd), np.float32)
    vpool = np.zeros_like(kpool)
    for s in range(S):
        for j in range(P):
            rows = dense_K[s, j * ps:(j + 1) * ps].reshape(ps, H, hd)
            kpool[table_np[s, j]] = rows
            vpool[table_np[s, j]] = dense_V[s, j * ps:(j + 1) * ps] \
                .reshape(ps, H, hd)
    table = nd.array(table_np, dtype="int32")
    kp, vp = nd.array(kpool), nd.array(vpool)

    got_K = gather_pages(kp, table).asnumpy()
    assert (got_K == dense_K).all(), "gather must reconstruct exactly"

    ref = _attend_cached(nd, q, nd.array(dense_K), nd.array(dense_V), keep,
                         H, hd).asnumpy()
    out = paged_attend(nd, q, kp, vp, table, keep, H, hd).asnumpy()
    assert (out == ref).all(), "paged attend must be BITWISE dense attend"


def test_write_page_and_coords_roundtrip():
    rng = np.random.RandomState(1)
    S, H, hd, ps, P = 4, 2, 4, 4, 2
    pool = nd.zeros((S * P + 1, ps, H, hd))
    table = nd.array(1 + np.arange(S * P, dtype=np.int32).reshape(S, P),
                     dtype="int32")
    pos = nd.array(np.array([0, 3, 4, 7], np.int32), dtype="int32")
    vals = nd.array(rng.randn(S, H, hd).astype(np.float32))
    pages, rows = page_coords(table, pos, ps)
    pool = write_page(pool, pages, rows, vals)
    dense = gather_pages(pool, table).asnumpy()  # (S, P*ps, C)
    for s, p in enumerate((0, 3, 4, 7)):
        np.testing.assert_array_equal(
            dense[s, p], vals.asnumpy()[s].reshape(-1))
        assert (np.delete(dense[s], p, axis=0) == 0).all()


def test_paged_allocator_alloc_free_exhaustion():
    cache = PagedKVCache(1, 6, 4, 2, 4)  # 5 usable pages (page 0 trash)
    assert cache.pages_free == 5
    got = cache.alloc("a", 3)
    assert len(got) == 3 and 0 not in got
    assert cache.alloc("b", 3) is None, "all-or-nothing"
    assert cache.pages_free == 2
    assert cache.alloc("b", 2) is not None
    assert cache.pages_free == 0
    assert cache.free_slot("a") == 3
    assert cache.pages_free == 3
    row = cache.table_row("b", 4)
    assert row.shape == (4,) and (row[2:] == 0).all()
    with pytest.raises(MXNetError):
        PagedKVCache(1, 1, 4, 2, 4)  # no room for the trash page


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
def test_engine_greedy_matches_translate(trained):
    """ACCEPTANCE: greedy decode through the engine — mid-flight
    arrivals, shared slots, paged cache — matches standalone
    translate(beam_size=1) token-for-token on a fixed seed."""
    net, src = trained
    eng = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=3,
                        page_size=4, max_len=12, stream_every=4)
    reqs = [Request(src[i], max_new_tokens=9, bos_id=BOS, eos_id=EOS)
            for i in range(6)]
    out = eng.serve(reqs, arrival_steps=[0, 0, 0, 2, 5, 9])
    for i, r in enumerate(reqs):
        ref = net.translate(nd.array(src[i:i + 1], dtype="int32"),
                            bos_id=BOS, eos_id=EOS, max_len=10,
                            beam_size=1)[0, 1:]
        ref = list(ref)
        if EOS in ref:
            ref = ref[:ref.index(EOS) + 1]
        assert list(out[r.id]) == ref, f"request {i} diverged"
        # the memorized task actually decodes the reversal
        assert list(out[r.id][:6]) == list(src[i, :6][::-1])


def test_one_decode_executable_mixed_lengths(tele, tmp_path):
    """ACCEPTANCE: a mixed-length trace (7/19/33, arriving mid-flight)
    books exactly ONE decode compile event (plus one prefill) — no
    per-length retraces."""
    net = _tiny_model()
    eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=3,
                        page_size=8, max_len=34, stream_every=4)
    rng = np.random.RandomState(0)
    reqs = [Request(rng.randint(3, 16, 5), max_new_tokens=n,
                    bos_id=BOS, eos_id=EOS)
            for n in (7, 19, 33)]
    eng.serve(reqs, arrival_steps=[0, 3, 11])
    for r in reqs:
        assert len(r.stream) == r.max_new_tokens  # random net: length-cap
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    compiles = [e for e in events if e["kind"] == "compile"
                and e.get("executor") == "ServingEngine"]
    sites = sorted(e["site"] for e in compiles)
    assert sites == ["serving_decode", "serving_prefill"], sites


def test_continuous_batching_overlaps_and_frees_pages():
    """Slots and pages recycle mid-flight: 6 requests through 2 slots
    finish in far fewer steps than sequential, and every page returns to
    the pool."""
    net = _tiny_model()
    eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=2,
                        page_size=4, max_len=12, stream_every=4)
    rng = np.random.RandomState(1)
    lens = [4, 9, 5, 11, 6, 8]
    reqs = [Request(rng.randint(3, 16, 4), max_new_tokens=n, bos_id=BOS,
                    eos_id=EOS) for n in lens]
    out = eng.serve(reqs, arrival_steps=[0, 0, 2, 5, 7, 9])
    assert all(len(out[r.id]) == n for r, n in zip(reqs, lens))
    assert all(r.stream.finished for r in reqs)
    # 2-wide overlap: strictly fewer decode steps than one-at-a-time
    assert eng.step_count < sum(lens), eng.step_count
    assert eng._cache.pages_free == eng._cache.num_pages - 1
    assert all(m is None for m in eng._slots)


def test_scheduler_queue_bound_backpressure():
    sched = ContinuousBatchingScheduler(bound=2)
    sched.submit(Request([3], 4, BOS, EOS))
    sched.submit(Request([3], 4, BOS, EOS))
    with pytest.raises(MXNetError):
        sched.submit(Request([3], 4, BOS, EOS))
    assert sched.depth == 2
    ready = sched.pop_ready(free_slots=2, pages_free=1, page_size=4)
    assert len(ready) == 1, "one free page admits one request"


def test_pool_exhaustion_raises_with_knob_name():
    net = _tiny_model()
    # 2 usable pages x page_size 4 = 8 rows for TWO requests wanting 12
    eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=2,
                        page_size=4, pool_pages=3, max_len=12,
                        stream_every=4)
    reqs = [Request(np.array([5, 6, 7], np.int32), max_new_tokens=12,
                    bos_id=BOS, eos_id=EOS) for _ in range(2)]
    with pytest.raises(MXNetError, match="MX_SERVE_POOL_PAGES"):
        eng.serve(reqs)


def test_pool_pressure_preempts_youngest_and_completes(trained):
    """Under pool pressure the youngest request is preempted back to the
    queue head (recompute preemption) instead of crashing the batch: a
    pool that can only hold ~1.5 requests still serves both, tokens
    identical to an unpressured engine (greedy determinism)."""
    net, src = trained
    roomy = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                          page_size=1, max_len=6, stream_every=1)
    reqs_a = [Request(src[i], max_new_tokens=6, bos_id=BOS, eos_id=-1)
              for i in range(2)]
    want = roomy.serve(reqs_a)

    tight = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                          page_size=1, pool_pages=10, max_len=6,
                          stream_every=1)
    reqs_b = [Request(src[i], max_new_tokens=6, bos_id=BOS, eos_id=-1)
              for i in range(2)]
    out = tight.serve(reqs_b)
    for a, b in zip(reqs_a, reqs_b):
        np.testing.assert_array_equal(out[b.id], want[a.id])
        assert b.stream.finished
    assert tight._cache.pages_free == tight._cache.num_pages - 1
    # the pool genuinely couldn't hold both: preemption + recompute
    # means strictly more decode steps than the unpressured run
    assert tight.step_count > roomy.step_count, (tight.step_count,
                                                 roomy.step_count)


def test_fullprefix_rejects_buffer_overflow():
    eng = ServingEngine(FullPrefixAdapter(lambda F, buf: None, max_len=8),
                        slots=1, max_len=8, stream_every=2)
    with pytest.raises(MXNetError, match="buffer"):
        eng.submit(Request(np.arange(1, 6, dtype=np.int32),
                           max_new_tokens=5, bos_id=BOS, eos_id=-1))


def test_max_new_tokens_over_capacity_rejected():
    net = _tiny_model()
    eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=1,
                        page_size=4, max_len=8, stream_every=2)
    with pytest.raises(MXNetError, match="max_len"):
        eng.submit(Request(np.array([5], np.int32), max_new_tokens=20,
                           bos_id=BOS, eos_id=EOS))


def test_positional_capacity_fails_loudly():
    """Out-of-table decode positions must never silently clamp: the
    engine rejects max_len beyond the model's positional table at
    construction, and standalone translate rejects it at call time."""
    net = _tiny_model(max_length=16)
    with pytest.raises(MXNetError, match="max_positions"):
        ServingEngine(TransformerAdapter(net, src_max_len=6), slots=1,
                      page_size=4, max_len=32)
    with pytest.raises(MXNetError, match="positional table"):
        net.translate(nd.array(np.array([[5, 6]], np.int32),
                               dtype="int32"),
                      bos_id=BOS, eos_id=EOS, max_len=32, beam_size=1)


def test_fused_decision_in_aot_fingerprint():
    """The fused-attention decision changes the traced program without
    changing shapes — it must split the AOT-cache fingerprint, or a
    restart under a different MX_SERVE_FLASH would deserialize the
    wrong executable."""
    net = _tiny_model()
    parts = []
    for fused in (False, True):
        eng = ServingEngine(
            TransformerAdapter(net, src_max_len=6, fused=fused),
            slots=1, page_size=4, max_len=8, stream_every=2)
        parts.append(eng._fingerprint_parts(("decode", 4, 1), []))
    assert parts[0] != parts[1]
    assert memwatch.fingerprint(parts[0]) != memwatch.fingerprint(parts[1])


# ---------------------------------------------------------------------------
# satellites: telemetry, AOT cache, fused kernel, generic adapter
# ---------------------------------------------------------------------------
def test_serve_telemetry_rollup_and_prometheus(tele, tmp_path):
    net = _tiny_model()
    eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=2,
                        page_size=4, max_len=10, stream_every=4)
    rng = np.random.RandomState(3)
    reqs = [Request(rng.randint(3, 16, 4), max_new_tokens=6, bos_id=BOS,
                    eos_id=EOS) for _ in range(3)]
    eng.serve(reqs)
    s = telemetry.summary()["serving"]
    assert s["requests"] == 3
    assert s["tokens"] == 18
    assert s["p50_latency_ms"] > 0
    assert s["p99_latency_ms"] >= s["p50_latency_ms"]
    # per-request events reach the flight ring (post-mortem tail)
    tail_kinds = [e["kind"] for e in telemetry.flight_tail(256)]
    assert tail_kinds.count("serve_request") == 3
    prom = open(telemetry.export_prometheus()).read()
    assert 'mx_serve_requests_total{rank="0"} 3' in prom
    assert 'mx_serve_tokens_total{rank="0"} 18' in prom
    assert "mx_serve_latency_p99_ms" in prom
    assert "mx_serve_active_slots" in prom
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    serve_evs = [e for e in events if e["kind"] == "serve_request"]
    assert len(serve_evs) == 3
    for e in serve_evs:
        assert e["tokens"] == 6 and e["reason"] == "length"
        assert "queue_wait_ms" in e and "prefill_ms" in e \
            and "decode_ms" in e


_AOT_CHILD = r"""
import json, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.models.transformer import Transformer
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

mx.random.seed(0)
net = Transformer(16, units=32, hidden_size=64, num_heads=4, num_layers=2,
                  max_length=48, dropout=0.0)
net.initialize(mx.init.Xavier())
eng = ServingEngine(TransformerAdapter(net, src_max_len=6), slots=2,
                    page_size=4, max_len=8, stream_every=2)
rng = np.random.RandomState(4)
out = eng.serve([Request(rng.randint(3, 16, 4), max_new_tokens=5, bos_id=1,
                         eos_id=2)])
evs = [e for e in telemetry.flight_tail(256) if e["kind"] == "compile"
       and e.get("executor") == "ServingEngine"]
print("AOTEVS " + json.dumps({"compiles": evs,
                              "tokens": [int(t) for t in
                                         list(out.values())[0]]}))
"""


def test_aot_cache_roundtrip_deserializes(tmp_path):
    """Satellite: decode + prefill executables persist through the PR 9
    AOT cache — a restarted serving process deserializes instead of
    recompiling (cache_hit + deserialize_ms on its compile events, the
    python fn never retraced), and decodes the same tokens.

    Both phases run as subprocesses with a PRIVATE fresh
    JAX_COMPILATION_CACHE_DIR: on this jax/XLA:CPU, serializing an
    executable that jax itself loaded from its persistent compile cache
    produces an unloadable blob ('Symbols not found') — in production
    that degrades gracefully (cache_corrupt -> fresh compile +
    overwrite, asserted by test_superstep's corrupt-entry test), but
    here it would mask the round-trip under a warm test-suite cache."""
    import subprocess
    import sys

    def run_phase(tele_dir):
        env = dict(os.environ,
                   MX_EXECUTABLE_CACHE_DIR=str(tmp_path / "aot"),
                   MX_TELEMETRY_DIR=str(tmp_path / tele_dir),
                   JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
                   JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", _AOT_CHILD], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("AOTEVS ")][-1]
        return json.loads(line[len("AOTEVS "):])

    first = run_phase("tele1")
    assert len(first["compiles"]) == 2
    assert all(not e.get("cache_hit") for e in first["compiles"])
    assert len([f for f in os.listdir(tmp_path / "aot")
                if f.endswith(".jexec")]) == 2

    second = run_phase("tele2")
    assert len(second["compiles"]) == 2, second
    for e in second["compiles"]:
        assert e.get("cache_hit") is True, e
        assert e.get("deserialize_ms", 0) > 0
    assert second["tokens"] == first["tokens"]


def test_paged_flash_kernel_matches_dense_softmax():
    """Satellite: the Pallas ragged paged kernel (interpret mode on CPU)
    agrees with the dense softmax reference per slot, including an
    inactive (length 0) slot."""
    import jax.numpy as jnp

    from mxnet_tpu.ops.pallas.paged_attention import paged_decode_attention

    rng = np.random.RandomState(0)
    S, H, hd, ps, P = 3, 4, 8, 4, 3
    N = 1 + S * P
    q = jnp.asarray(rng.randn(S, H, hd).astype(np.float32))
    kp = jnp.asarray(rng.randn(N, ps, H, hd).astype(np.float32))
    vp = jnp.asarray(rng.randn(N, ps, H, hd).astype(np.float32))
    table = jnp.asarray(1 + np.arange(S * P, dtype=np.int32).reshape(S, P))
    lengths = jnp.asarray(np.array([5, 12, 0], np.int32))
    out = np.asarray(paged_decode_attention(q, kp, vp, table, lengths))
    for s in range(S):
        L = int(lengths[s])
        if L == 0:
            assert (out[s] == 0).all()
            continue
        K = np.asarray(kp)[np.asarray(table)[s]].reshape(P * ps, H, hd)[:L]
        V = np.asarray(vp)[np.asarray(table)[s]].reshape(P * ps, H, hd)[:L]
        sc = np.einsum("hd,lhd->hl", np.asarray(q[s]), K) / np.sqrt(hd)
        w = np.exp(sc - sc.max(-1, keepdims=True))
        w /= w.sum(-1, keepdims=True)
        ref = np.einsum("hl,lhd->hd", w, V)
        np.testing.assert_allclose(out[s], ref, rtol=1e-5, atol=1e-5)


def test_paged_step_cache_fused_matches_gather():
    """PagedStepCache(fused=True) — the Pallas kernel path — agrees with
    the bitwise gather path for the same write+attend."""
    from mxnet_tpu.serving import PagedStepCache

    class _Attn:  # the two attrs update_and_attend reads
        _num_heads, _head_dim = 4, 8

    rng = np.random.RandomState(5)
    S, H, hd, ps, P = 3, 4, 8, 4, 2
    C, Lmax = H * hd, ps * P
    table = nd.array(1 + np.arange(S * P, dtype=np.int32).reshape(S, P),
                     dtype="int32")
    pos_np = np.array([2, 5, 0], np.int32)
    pos = nd.array(pos_np, dtype="int32")
    lengths = nd.array(pos_np + 1, dtype="int32")
    keep = nd.array((np.arange(Lmax)[None] < (pos_np + 1)[:, None])
                    .astype(np.float32))
    pages, rows = page_coords(table, pos, ps)
    kp = nd.array(rng.randn(S * P + 1, ps, H, hd).astype(np.float32))
    vp = nd.array(rng.randn(S * P + 1, ps, H, hd).astype(np.float32))
    q = nd.array(rng.randn(S, 1, C).astype(np.float32))
    k_t = nd.array(rng.randn(S, 1, C).astype(np.float32))
    v_t = nd.array(rng.randn(S, 1, C).astype(np.float32))

    def attend(fused):
        cache = PagedStepCache(kp, vp, table, pages, rows, keep,
                               lengths=lengths, fused=fused)
        return cache.update_and_attend(nd, _Attn, q, k_t, v_t).asnumpy()

    np.testing.assert_allclose(attend(True), attend(False),
                               rtol=1e-5, atol=1e-5)


def test_fullprefix_adapter_serves_any_decoder(trained):
    """Satellite: the universal cached-decode fallback (prefill chunked
    into the decode step) serves a plain logits function — the ONNX-
    imported-decoder shape — and matches a host greedy loop over the
    same fixed buffer."""
    from mxnet_tpu import autograd

    net, _ = trained
    L = 10

    def lm_logits(F, buf):
        # decoder-only stand-in: the trained seq2seq's decoder over a
        # fixed source — logits (S, L, V) from the full token buffer
        S = buf.shape[0]
        src = F.ones((S, 3), dtype="int32") * 5
        return net._decode_h(F, buf, *net._encode_h(F, src))

    eng = ServingEngine(FullPrefixAdapter(lm_logits, max_len=L,
                                          pad_id=PAD),
                        slots=2, max_len=L, stream_every=2)
    prompts = [np.array([1, 14, 5], np.int32), np.array([1, 8], np.int32)]
    reqs = [Request(p, max_new_tokens=4, bos_id=BOS, eos_id=-1)
            for p in prompts]
    out = eng.serve(reqs)

    for p, r in zip(prompts, reqs):
        buf = np.full((1, L), PAD, np.int32)
        buf[0, :len(p)] = p
        pos = len(p) - 1
        want = []
        with autograd.pause():
            for _ in range(4):
                logits = lm_logits(nd, nd.array(buf, dtype="int32"))
                lp = logits.log_softmax(axis=-1).asnumpy()[0, pos]
                tok = int(lp.argmax())
                want.append(tok)
                pos += 1
                buf[0, pos] = tok
        assert list(out[r.id]) == want


def test_translate_sync_cadence_invariant(trained):
    """The device-side beam loop's early-exit cadence must not change
    outputs: never syncing mid-loop == syncing every step."""
    net, src = trained
    sb = nd.array(src[:2], dtype="int32")
    a = net.translate(sb, bos_id=BOS, eos_id=EOS, max_len=10, beam_size=3,
                      sync_every=1)
    b = net.translate(sb, bos_id=BOS, eos_id=EOS, max_len=10, beam_size=3,
                      sync_every=0)  # 0 = no mid-loop readback at all
    np.testing.assert_array_equal(a, b)
