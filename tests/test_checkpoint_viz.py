"""Async step checkpointing (SURVEY §5.3 upgrade over the reference's
epoch-granularity posture, RNG state included) and visualization
(reference: python/mxnet/visualization.py print_summary).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, sym
from mxnet_tpu.checkpoint import AsyncCheckpointer, load_checkpoint_state


def _train_setup(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    X = np.random.randn(32, 4).astype(np.float32)
    Y = (X @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32))
    return net, trainer, X, Y


def _run_steps(net, trainer, X, Y, n, ckpt=None):
    loss_fn = gluon.loss.L2Loss()
    losses = []
    for i in range(n):
        with autograd.record():
            loss = loss_fn(net(nd.array(X)), nd.array(Y))
        loss.backward()
        trainer.step(32)
        losses.append(float(loss.mean().asnumpy()))
        if ckpt is not None:
            ckpt.step(net, trainer=trainer, extra={"loss": losses[-1]})
    return losses


def test_async_checkpoint_write_rotate(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=3, keep=2)
    _run_steps(net, trainer, X, Y, 10, ckpt)
    ckpt.close()
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("step-"))
    assert dirs == ["step-6", "step-9"]  # rotation kept last 2
    state = load_checkpoint_state(str(tmp_path))
    assert state["step"] == 9
    assert "loss" in state["extra"]


def test_checkpoint_resume_continues_identically(tmp_path):
    # run A: 12 steps straight through
    net_a, tr_a, X, Y = _train_setup(seed=7)
    losses_a = _run_steps(net_a, tr_a, X, Y, 12)

    # run B: 6 steps, checkpoint, "crash", restore into fresh objects,
    # 6 more steps — must reproduce run A's tail exactly
    net_b, tr_b, X2, Y2 = _train_setup(seed=7)
    ckpt = AsyncCheckpointer(str(tmp_path), save_every=6)
    _run_steps(net_b, tr_b, X2, Y2, 6, ckpt)
    ckpt.close()

    # fresh process simulation: different seed AND different global name
    # counters — restore() maps by structural names, so both are fine
    net_c, tr_c, _, _ = _train_setup(seed=99)
    from mxnet_tpu import checkpoint as ckpt_mod

    start = ckpt_mod.restore(str(tmp_path), net_c, tr_c)
    assert start == 6
    losses_c = _run_steps(net_c, tr_c, X2, Y2, 6)
    np.testing.assert_allclose(losses_c, losses_a[6:], rtol=1e-5)


def test_checkpointer_resumes_step_numbering(tmp_path):
    net, trainer, X, Y = _train_setup()
    ck1 = AsyncCheckpointer(str(tmp_path), save_every=2, keep=5)
    _run_steps(net, trainer, X, Y, 4, ck1)
    ck1.close()
    # "crash" and restart: new checkpointer continues from step 4
    ck2 = AsyncCheckpointer(str(tmp_path), save_every=2, keep=5)
    _run_steps(net, trainer, X, Y, 2, ck2)
    ck2.close()
    dirs = sorted(d for d in os.listdir(tmp_path) if d.startswith("step-"))
    assert "step-6" in dirs, dirs
    state = load_checkpoint_state(str(tmp_path))
    assert state["step"] == 6


def test_checkpoint_writer_error_surfaces(tmp_path):
    net, trainer, X, Y = _train_setup()
    ckpt = AsyncCheckpointer(str(tmp_path / "sub"), save_every=1)
    # break the target directory to force a write failure
    import shutil

    ckpt.wait()
    shutil.rmtree(str(tmp_path / "sub"))
    with open(str(tmp_path / "sub"), "w") as f:
        f.write("not a dir")
    _run_steps(net, trainer, X, Y, 1, ckpt)
    with pytest.raises(Exception):
        ckpt.wait()
        _run_steps(net, trainer, X, Y, 1, ckpt)


# ---------------------------------------------------------------------------
# visualization
# ---------------------------------------------------------------------------
def test_print_summary(capsys):
    data = sym.Variable("data")
    h = sym.Convolution(data, name="c1", kernel=(3, 3), num_filter=8,
                        pad=(1, 1))
    h = sym.Activation(h, act_type="relu")
    h = sym.Pooling(h, kernel=(2, 2), stride=(2, 2), pool_type="max")
    h = sym.FullyConnected(h, name="fc", num_hidden=10)
    mx.visualization.print_summary(h, shape={"data": (1, 3, 8, 8)})
    out = capsys.readouterr().out
    assert "c1 (Convolution)" in out
    assert "fc (FullyConnected)" in out
    assert "Total params:" in out
    # conv: 8*3*3*3 + 8 = 224; fc: 10*(8*4*4) + 10 = 1290
    assert "1514" in out


def test_plot_network_gated():
    data = sym.Variable("data")
    out = sym.Activation(sym.FullyConnected(data, name="f", num_hidden=4),
                         act_type="relu")
    try:
        import graphviz  # noqa: F401

        dot = mx.visualization.plot_network(out)
        assert "f" in dot.source
    except ImportError:
        from mxnet_tpu.base import MXNetError

        with pytest.raises(MXNetError, match="graphviz"):
            mx.visualization.plot_network(out)
