"""Precision subsystem (docs/PRECISION.md; ISSUE 15 acceptance): graph-
level AMP pass, traced dynamic loss scaling, Plan/checkpoint round-trips.

Covers: cast-policy semantics at the op-dispatch point, bf16-policy
compiled steps tracking the fp32 oracle within tolerance, loss-scale
skip-step semantics (injected non-finite grads leave weights / optimizer
state / Adam's t untouched, scale halves, then regrows), superstep scan
parity of the scaler state machine, AMP-off runs staying bitwise f32,
executable-fingerprint splits on precision config, env parsing, and
``Plan.precision`` + scaler state surviving checkpoint save -> elastic
reshard -> restore.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import DataParallelStep, Plan, dp_plan, local_mesh
from mxnet_tpu.parallel.mesh import make_mesh
from mxnet_tpu.precision import (AmpPolicy, LossScaleConfig,
                                 PrecisionConfig, amp_scope)

LS = LossScaleConfig(init_scale=16.0, growth_interval=4)
PREC_BF16 = PrecisionConfig(amp=AmpPolicy(), loss_scale=LS)


def _data(n=16, d=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(n, d).astype(np.float32),
            rng.randint(0, classes, n).astype(np.float32))


def _make_step(precision=None, optimizer="sgd", lr=0.1, mesh=None,
               seed=0, clip_global=None):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    # in_units known -> parameters initialize HERE, under the seed just
    # set (deferred init would draw from wherever the global RNG stream
    # has advanced to by the first step — runs wouldn't be comparable)
    net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
            gluon.nn.Dense(4, in_units=16))
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = DataParallelStep(
        net, lambda o, l: loss_fn(o, l), mesh=mesh or local_mesh(),
        optimizer=optimizer, optimizer_params={"learning_rate": lr},
        clip_global_norm=clip_global, precision=precision)
    return step


def _host(x):
    return np.asarray(x)


# ---------------------------------------------------------------------------
# the cast policy at the dispatch point
# ---------------------------------------------------------------------------
def test_amp_scope_casts_low_and_widen_classes():
    import ml_dtypes

    a = nd.array(np.ones((4, 4), np.float32))
    with amp_scope(AmpPolicy()):
        low = nd.dot(a, a)                      # low class: bf16 compute
        assert low.dtype == ml_dtypes.bfloat16
        wide = low.softmax(axis=-1)             # widen class: back to f32
        assert wide.dtype == np.float32
    # scope off: nothing casts
    assert nd.dot(a, a).dtype == np.float32


def test_amp_policy_validation_and_custom_lists():
    with pytest.raises(MXNetError, match="ONE disposition"):
        AmpPolicy(low=("dot",), widen=("dot",))
    with pytest.raises(MXNetError, match="dtype"):
        AmpPolicy(dtype="int8")
    pol = AmpPolicy(low=("dot",), widen=())
    assert pol.op_class("dot") == "low"
    assert pol.op_class("FullyConnected") is None


def test_precision_config_env_parsing(monkeypatch):
    monkeypatch.delenv("MX_AMP", raising=False)
    assert PrecisionConfig.from_env() is None
    monkeypatch.setenv("MX_AMP", "bf16")
    cfg = PrecisionConfig.from_env()
    assert cfg.amp.dtype == "bfloat16" and cfg.loss_scale is None
    monkeypatch.setenv("MX_AMP", "fp16")
    cfg = PrecisionConfig.from_env()
    assert cfg.amp.dtype == "float16" and cfg.loss_scale is not None
    monkeypatch.setenv("MX_LOSS_SCALE", "128.0")
    cfg = PrecisionConfig.from_env()
    assert cfg.loss_scale.init_scale == 128.0 and not cfg.loss_scale.dynamic
    monkeypatch.setenv("MX_LOSS_SCALE", "off")
    assert PrecisionConfig.from_env().loss_scale is None
    monkeypatch.setenv("MX_AMP_POLICY", '{"low": ["dot"], "widen": []}')
    cfg = PrecisionConfig.from_env()
    assert cfg.amp.low == ("dot",)
    monkeypatch.setenv("MX_AMP", "int4")
    with pytest.raises(MXNetError, match="MX_AMP"):
        PrecisionConfig.from_env()


def test_precision_json_roundtrip_via_plan():
    from dataclasses import replace

    plan = replace(dp_plan(1), precision=PREC_BF16)
    rec = plan.to_json()
    assert rec["precision"]["amp"]["dtype"] == "bfloat16"
    back = Plan.from_json(rec)
    assert back.precision == PREC_BF16
    # absent precision round-trips as None (pre-precision checkpoints)
    rec2 = dp_plan(1).to_json()
    assert Plan.from_json(rec2).precision is None


# ---------------------------------------------------------------------------
# ACCEPTANCE: bf16 AMP parity + one-executable composition
# ---------------------------------------------------------------------------
def test_amp_bf16_step_tracks_fp32_oracle():
    """The bf16-policy compiled step's loss trajectory tracks the fp32
    oracle within documented tolerance, and still converges."""
    x, y = _data()
    f32 = _make_step(None)
    amp = _make_step(PREC_BF16)
    l32, lamp = [], []
    for _ in range(15):
        l32.append(float(f32.step(nd.array(x), nd.array(y))))
        lamp.append(float(amp.step(nd.array(x), nd.array(y))))
    assert lamp[-1] < lamp[0]
    # documented tolerance: bf16 carries ~3 decimal digits; the tiny-net
    # trajectories stay within 5e-2 absolute over 15 steps
    np.testing.assert_allclose(lamp, l32, atol=5e-2)
    # the env default wires the same config through the Plan
    assert amp.plan.precision == PREC_BF16
    # scale grew on schedule (15 finite steps / interval 4 -> 3 growths)
    assert float(_host(amp.scaler_state["scale"])) == 16.0 * 2 ** 3
    assert int(_host(amp.scaler_state["skipped"])) == 0


def test_amp_off_is_bitwise_f32():
    """ACCEPTANCE: without a precision config nothing in the program
    changes — two identically-seeded steps (one built through the
    precision kwarg explicitly None) are bitwise identical, f32 end to
    end, and their Plan carries no precision."""
    x, y = _data()
    a = _make_step(None)
    b = _make_step(precision=None)
    for _ in range(5):
        la = float(a.step(nd.array(x), nd.array(y)))
        lb = float(b.step(nd.array(x), nd.array(y)))
        assert la == lb
    assert a.plan.precision is None and a.scaler_state is None
    # gluon name counters differ between the two nets (dense0 vs dense2);
    # sorted order still pairs corresponding params
    for (_, arr_a), (_, arr_b) in zip(sorted(a.params.items()),
                                      sorted(b.params.items())):
        assert np.asarray(arr_a).dtype == np.float32
        np.testing.assert_array_equal(np.asarray(arr_a),
                                      np.asarray(arr_b))


def test_amp_env_default_attaches_to_plan(monkeypatch):
    monkeypatch.setenv("MX_AMP", "bf16")
    step = _make_step(None)
    assert step.plan.precision is not None
    assert step.plan.precision.amp.dtype == "bfloat16"
    assert step.plan.precision.loss_scale is None  # bf16 default: off
    x, y = _data()
    v = float(step.step(nd.array(x), nd.array(y)))
    assert np.isfinite(v)


def test_fp16_amp_with_dynamic_scaling_trains():
    prec = PrecisionConfig(amp=AmpPolicy(dtype="float16"),
                           loss_scale=LossScaleConfig(init_scale=2.0 ** 8,
                                                      growth_interval=50))
    x, y = _data()
    step = _make_step(prec, lr=0.05)
    losses = [float(step.step(nd.array(x), nd.array(y)))
              for _ in range(15)]
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]
    assert int(_host(step.scaler_state["skipped"])) == 0


# ---------------------------------------------------------------------------
# ACCEPTANCE: loss-scale skip-step semantics (traced, no host sync)
# ---------------------------------------------------------------------------
def test_skip_step_holds_state_halves_scale_then_regrows():
    x, y = _data()
    step = _make_step(PREC_BF16, optimizer="adam", lr=0.01)
    step.step(nd.array(x), nd.array(y)).wait()
    w0 = {n: _host(a).copy() for n, a in step.params.items()}
    m0 = {n: _host(a).copy() for n, a in step.opt_state[0].items()}
    t0 = int(_host(step.opt_state[2]))
    scale0 = float(_host(step.scaler_state["scale"]))

    bad = x.copy()
    bad[0, 0] = np.inf  # non-finite forward -> non-finite grads
    step.step(nd.array(bad), nd.array(y)).wait()
    # weights, Adam moments AND the bias-correction counter t all hold:
    # the skipped step is a traced no-op update
    for n in w0:
        np.testing.assert_array_equal(w0[n], _host(step.params[n]))
        np.testing.assert_array_equal(m0[n], _host(step.opt_state[0][n]))
    assert int(_host(step.opt_state[2])) == t0
    assert float(_host(step.scaler_state["scale"])) == scale0 * 0.5
    assert int(_host(step.scaler_state["skipped"])) == 1
    assert int(_host(step.scaler_state["growth"])) == 0

    # regrowth: growth_interval finite steps double the scale again
    for _ in range(LS.growth_interval):
        step.step(nd.array(x), nd.array(y)).wait()
    assert float(_host(step.scaler_state["scale"])) == scale0
    assert int(_host(step.scaler_state["skipped"])) == 1  # cumulative


def test_static_scale_never_moves_but_still_skips():
    prec = PrecisionConfig(
        amp=AmpPolicy(),
        loss_scale=LossScaleConfig(init_scale=32.0, dynamic=False))
    x, y = _data()
    step = _make_step(prec)
    step.step(nd.array(x), nd.array(y)).wait()
    w0 = {n: _host(a).copy() for n, a in step.params.items()}
    bad = x.copy()
    bad[0, 0] = np.nan
    step.step(nd.array(bad), nd.array(y)).wait()
    for n in w0:
        np.testing.assert_array_equal(w0[n], _host(step.params[n]))
    assert float(_host(step.scaler_state["scale"])) == 32.0
    assert int(_host(step.scaler_state["skipped"])) == 1


def test_loss_scale_composes_with_clip_global_norm():
    """Un-scaling folds into rescale BEFORE the global-norm clip, so the
    clipped update matches the unscaled step's update exactly (finite
    case)."""
    x, y = _data()
    a = _make_step(None, clip_global=0.5)
    b = _make_step(PrecisionConfig(loss_scale=LossScaleConfig(
        init_scale=64.0, dynamic=False)), clip_global=0.5)
    for _ in range(5):
        la = float(a.step(nd.array(x), nd.array(y)))
        lb = float(b.step(nd.array(x), nd.array(y)))
        np.testing.assert_allclose(la, lb, rtol=2e-6)
    for (_, arr_a), (_, arr_b) in zip(sorted(a.params.items()),
                                      sorted(b.params.items())):
        np.testing.assert_allclose(_host(arr_a), _host(arr_b),
                                   rtol=2e-5, atol=1e-7)


def test_superstep_scan_carries_scaler_faithfully(monkeypatch):
    """MX_SUPERSTEP: the scaler joins the scan carry — final weights,
    scale, and the per-step losses match sequential dispatch, including
    a skip step in the middle of a group."""
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    x, y = _data(n=8)
    bad = x.copy()
    bad[0, 0] = np.inf
    batches = [x, x, bad, x, x, x]

    def run(superstep):
        monkeypatch.setenv("MX_SUPERSTEP", "3" if superstep else "0")
        step = _make_step(PREC_BF16, optimizer="adam", lr=0.01)
        views = [step.step(nd.array(b), nd.array(y)) for b in batches]
        step.drain()
        losses = [float(v) for v in views]
        return step, losses

    seq, seq_losses = run(False)
    sup, sup_losses = run(True)
    finite = [i for i, b in enumerate(batches) if np.isfinite(b).all()]
    for i in finite:
        assert seq_losses[i] == sup_losses[i], (i, seq_losses, sup_losses)
    for k in ("scale", "growth", "skipped"):
        assert _host(seq.scaler_state[k]) == _host(sup.scaler_state[k]), k
    for (_, pa), (_, pb) in zip(sorted(seq.params.items()),
                                sorted(sup.params.items())):
        np.testing.assert_array_equal(_host(pa), _host(pb))


# ---------------------------------------------------------------------------
# executable identity: precision splits the fingerprint
# ---------------------------------------------------------------------------
def test_precision_splits_executable_fingerprint():
    from mxnet_tpu import memwatch

    sig = ((( (16, 8), "float32"),), ((16,), "float32"))
    base = _make_step(None)._fingerprint_parts((), sig)
    amp = _make_step(PREC_BF16)._fingerprint_parts((), sig)
    fp16 = _make_step(PrecisionConfig(
        amp=AmpPolicy(dtype="float16"),
        loss_scale=LS))._fingerprint_parts((), sig)
    static = _make_step(PrecisionConfig(
        amp=AmpPolicy(),
        loss_scale=LossScaleConfig(init_scale=16.0, growth_interval=4,
                                   dynamic=False)))._fingerprint_parts(
        (), sig)
    fps = [memwatch.fingerprint(p) for p in (base, amp, fp16, static)]
    assert len(set(fps)) == 4, fps


# ---------------------------------------------------------------------------
# ACCEPTANCE: Plan.precision + scaler state survive save -> reshard ->
# restore
# ---------------------------------------------------------------------------
def test_scaler_and_precision_survive_elastic_reshard(tmp_path):
    """Save on a dp4 mesh, restore onto dp2 (a real elastic reshard —
    layouts differ): Plan.precision rides the layout, amp.* scaler
    state rides opt_state, and the restored trajectory continues with
    the learned scale, not init_scale."""
    import jax

    from mxnet_tpu import checkpoint

    x, y = _data(n=16)
    step = _make_step(PREC_BF16, optimizer="adam", lr=0.01,
                      mesh=make_mesh(devices=jax.devices()[:4]))
    for _ in range(5):  # one growth at interval 4
        step.step(nd.array(x), nd.array(y))
    step.drain()
    assert float(_host(step.scaler_state["scale"])) == 32.0
    ck = checkpoint.AsyncCheckpointer(str(tmp_path), save_every=1)
    ck.step(step)
    ck.close()

    # the layout on disk carries the full precision config
    import json

    meta = json.load(open(tmp_path / "step-1" / "meta.json"))
    assert meta["layout"]["plan"]["precision"]["amp"]["dtype"] == \
        "bfloat16"
    assert meta["layout"]["plan"]["precision"]["loss_scale"][
        "growth_interval"] == 4

    step2 = _make_step(PREC_BF16, optimizer="adam", lr=0.01,
                       mesh=make_mesh(devices=jax.devices()[:2]),
                       seed=7)  # different init: restore must overwrite
    assert checkpoint.restore(str(tmp_path), step2) == 1
    assert float(_host(step2.scaler_state["scale"])) == 32.0
    assert int(_host(step2.scaler_state["growth"])) == \
        int(_host(step.scaler_state["growth"]))
    for (_, pa), (_, pb) in zip(sorted(step.params.items()),
                                sorted(step2.params.items())):
        np.testing.assert_array_equal(_host(pa), _host(pb))
    # training continues on the new mesh with the restored scale
    v = float(step2.step(nd.array(x), nd.array(y)))
    assert np.isfinite(v)


def test_restore_without_scaler_state_warns_and_inits_fresh(tmp_path, caplog):
    import logging

    x, y = _data()
    plain = _make_step(None)
    plain.step(nd.array(x), nd.array(y)).wait()
    sd = plain.state_dict()
    lay = plain.layout()
    assert not any(k.startswith("amp.") for k in sd["opt_state"])

    scaled = _make_step(PREC_BF16)
    with caplog.at_level(logging.WARNING):
        scaled.load_state_dict(sd, saved_layout=lay)
    assert any("FRESH scaler" in r.message for r in caplog.records)
    assert float(_host(scaled.scaler_state["scale"])) == LS.init_scale

    # and the mirror: scaler state in the checkpoint, step without
    scaled.step(nd.array(x), nd.array(y)).wait()
    sd2 = scaled.state_dict()
    plain2 = _make_step(None)
    with caplog.at_level(logging.WARNING):
        plain2.load_state_dict(sd2, saved_layout=scaled.layout())
    assert plain2.scaler_state is None


# ---------------------------------------------------------------------------
# satellites: quantize_net degenerate threshold, eager shim delegation
# ---------------------------------------------------------------------------
def test_quantize_net_degenerate_calibration_names_layer_and_mode():
    from mxnet_tpu.contrib.quantization import quantize_net

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(nd.array(np.zeros((4, 6), np.float32)))
    # all-zero calibration: layer 0 sees zeros -> degenerate threshold
    with pytest.raises(MXNetError) as ei:
        quantize_net(net, calib_data=[nd.array(np.zeros((4, 6),
                                                        np.float32))],
                     calib_mode="naive")
    msg = str(ei.value)
    assert "'0'" in msg and "naive" in msg and "degenerate" in msg


def test_eager_scaler_shim_single_fused_readback():
    """The contrib/amp DynamicLossScaler delegates overflow detection to
    ONE fused reduce (precision.loss_scale.overflow_flag) — semantics
    unchanged: finite grads -> False, any inf/nan -> True."""
    from mxnet_tpu import autograd
    from mxnet_tpu.contrib.amp import DynamicLossScaler

    mx.random.seed(0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).rand(4, 6).astype(np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    params = list(net.collect_params().values())
    scaler = DynamicLossScaler()
    assert scaler.has_overflow(params) is False
    g = params[0].grad()
    bad = np.array(g.asnumpy())
    bad[0, 0] = np.inf
    g._set_data(nd.array(bad)._data)
    assert scaler.has_overflow(params) is True


def test_overflow_flag_is_device_value():
    """overflow_flag returns a DEVICE scalar (no sync inside — the hot
    entry mxlint guards); the readback is the caller's explicit act."""
    import jax

    from mxnet_tpu.precision.loss_scale import overflow_flag

    arrs = [jax.numpy.ones((4,)), jax.numpy.ones((2, 2))]
    flag = overflow_flag(arrs)
    assert isinstance(flag, jax.Array)
    assert bool(np.asarray(flag)) is False
    arrs[0] = arrs[0].at[1].set(np.nan)
    assert bool(np.asarray(overflow_flag(arrs))) is True
