"""Registry-wide operator correctness sweep.

The reference's oracle discipline (tests/python/unittest/test_operator.py
~10k lines: check_numeric_gradient + numpy-forward per op;
tests/python/gpu/test_operator_gpu.py: check_consistency across
device/dtype) applied to this registry, per SURVEY §4.4:

  * forward vs a numpy reference (where one is cheap to state);
  * analytic gradient (autograd tape -> jax.vjp) vs central finite
    differences, through a fixed random projection so reductions in the
    op can't hide gradient structure;
  * a bfloat16 sweep: every case re-runs forward in bf16 against the f32
    result (dtype-aware tolerance) and, when differentiable, backward in
    bf16 asserting finite grads — this is the class of test whose absence
    let the round-2 bf16 bugs ship.

Shapes are tiny (<= ~36 elements) so the per-element FD loop stays fast.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Sequence, Tuple

import ml_dtypes
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

BF16 = ml_dtypes.bfloat16


@dataclasses.dataclass
class Case:
    id: str
    fn: Callable  # (*NDArray) -> NDArray or list of NDArray
    shapes: Sequence[Tuple[int, ...]]
    ref: Optional[Callable] = None  # (*np.ndarray) -> np.ndarray
    domain: Tuple[float, float] = (-1.0, 1.0)
    grad: bool = True  # finite-difference check
    bf16: bool = True  # bf16-vs-f32 consistency
    int_inputs: Sequence[int] = ()  # indices of inputs that are integer
    rtol: Optional[float] = None
    atol: Optional[float] = None
    separated: bool = False  # well-separated values (max/min FD stability)


def _inputs_np(case: Case, rng: np.random.RandomState):
    lo, hi = case.domain
    out = []
    for i, s in enumerate(case.shapes):
        if i in case.int_inputs:
            out.append(rng.randint(0, 3, size=s).astype(np.float32))
        elif case.separated:
            # distinct values spaced >> 2*eps so the FD probes can't flip
            # an argmax/argmin tie
            n = int(np.prod(s))
            vals = lo + (hi - lo) * (rng.permutation(n) + 0.5) / n
            out.append(vals.reshape(s).astype(np.float32))
        else:
            out.append(rng.uniform(lo, hi, size=s).astype(np.float32))
    return out


def _sum_all(x):
    if isinstance(x, (list, tuple)):
        return sum(o.sum() for o in x)
    return x.sum()


# ---------------------------------------------------------------------------
# unary math: (mx name, numpy ref, domain, differentiable)
# ---------------------------------------------------------------------------
_UNARY = [
    ("abs", np.abs, (0.2, 1.0), True),
    ("arccos", np.arccos, (-0.8, 0.8), True),
    ("arccosh", np.arccosh, (1.2, 2.5), True),
    ("arcsin", np.arcsin, (-0.8, 0.8), True),
    ("arcsinh", np.arcsinh, (-1.0, 1.0), True),
    ("arctan", np.arctan, (-1.0, 1.0), True),
    ("arctanh", np.arctanh, (-0.8, 0.8), True),
    ("cbrt", np.cbrt, (0.2, 2.0), True),
    ("ceil", np.ceil, (-2.0, 2.0), False),
    ("cos", np.cos, (-1.0, 1.0), True),
    ("cosh", np.cosh, (-1.0, 1.0), True),
    ("degrees", np.degrees, (-1.0, 1.0), True),
    ("erf", None, (-1.0, 1.0), True),
    ("exp", np.exp, (-1.0, 1.0), True),
    ("expm1", np.expm1, (-1.0, 1.0), True),
    ("fix", np.trunc, (-2.0, 2.0), False),
    ("floor", np.floor, (-2.0, 2.0), False),
    ("gamma", None, (0.5, 2.5), True),
    ("gammaln", None, (0.5, 2.5), True),
    ("log", np.log, (0.2, 2.5), True),
    ("log10", np.log10, (0.2, 2.5), True),
    ("log1p", np.log1p, (-0.5, 1.0), True),
    ("log2", np.log2, (0.2, 2.5), True),
    ("negative", np.negative, (-1.0, 1.0), True),
    ("radians", np.radians, (-1.0, 1.0), True),
    ("rcbrt", lambda x: 1 / np.cbrt(x), (0.3, 2.0), True),
    ("reciprocal", lambda x: 1 / x, (0.4, 2.0), True),
    ("relu", lambda x: np.maximum(x, 0), (-1.0, 1.0), True),
    ("rint", np.rint, (-2.0, 2.0), False),
    ("round", None, (-2.0, 2.0), False),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.3, 2.0), True),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-1.0, 1.0), True),
    ("sign", np.sign, (0.2, 1.0), False),
    ("sin", np.sin, (-1.0, 1.0), True),
    ("sinh", np.sinh, (-1.0, 1.0), True),
    ("softsign", lambda x: x / (1 + np.abs(x)), (-1.0, 1.0), True),
    ("sqrt", np.sqrt, (0.2, 2.0), True),
    ("square", np.square, (-1.0, 1.0), True),
    ("tan", np.tan, (-1.0, 1.0), True),
    ("tanh", np.tanh, (-1.0, 1.0), True),
    ("trunc", np.trunc, (-2.0, 2.0), False),
]

# binary broadcast ops
_BINARY = [
    ("broadcast_add", np.add, (-1.0, 1.0), True),
    ("broadcast_sub", np.subtract, (-1.0, 1.0), True),
    ("broadcast_mul", np.multiply, (-1.0, 1.0), True),
    ("broadcast_div", np.divide, (0.4, 2.0), True),
    ("broadcast_maximum", np.maximum, (-1.0, 1.0), True),
    ("broadcast_minimum", np.minimum, (-1.0, 1.0), True),
    ("broadcast_power", np.power, (0.4, 2.0), True),
    ("broadcast_hypot", np.hypot, (0.2, 1.0), True),
    ("elemwise_add", np.add, (-1.0, 1.0), True),
    ("elemwise_sub", np.subtract, (-1.0, 1.0), True),
    ("elemwise_mul", np.multiply, (-1.0, 1.0), True),
    ("elemwise_div", np.divide, (0.4, 2.0), True),
]

# scalar-arg ops: forward refs
_SCALAR = [
    ("_plus_scalar", lambda x: x + 0.5, True),
    ("_minus_scalar", lambda x: x - 0.5, True),
    ("_rminus_scalar", lambda x: 0.5 - x, True),
    ("_mul_scalar", lambda x: x * 0.5, True),
    ("_div_scalar", lambda x: x / 0.5, True),
    ("_rdiv_scalar", lambda x: 0.5 / x, True),
    ("_power_scalar", lambda x: x**2.0, True),
    ("_maximum_scalar", lambda x: np.maximum(x, 0.1), True),
    ("_minimum_scalar", lambda x: np.minimum(x, 0.1), True),
]


def _build_cases():
    cases = []
    for name, ref, domain, diff in _UNARY:
        op = getattr(nd, name)
        cases.append(Case(id=f"unary_{name}", fn=op, shapes=[(2, 5)], ref=ref,
                          domain=domain, grad=diff))
    for name, ref, domain, diff in _BINARY:
        op = getattr(nd, name)
        shapes = ([(2, 3, 2), (2, 3, 2)] if name.startswith("elemwise")
                  else [(2, 3, 2), (1, 3, 1)])
        cases.append(Case(id=f"binary_{name}", fn=op, shapes=shapes, ref=ref,
                          domain=domain, grad=diff))
    for name, ref, diff in _SCALAR:
        op = getattr(nd, name)
        scalar = 2.0 if "power" in name else 0.5
        if "maximum" in name or "minimum" in name:
            scalar = 0.1
        fn = (lambda op, s: lambda x: op(x, scalar=s))(op, scalar)
        cases.append(Case(id=f"scalar_{name}", fn=fn, shapes=[(2, 5)], ref=ref,
                          domain=(0.3, 1.0), grad=diff))

    # reductions
    for name, ref in [("sum", np.sum), ("mean", np.mean), ("prod", np.prod),
                      ("max", np.max), ("min", np.min)]:
        op = getattr(nd, name)
        sep = name in ("max", "min")
        cases.append(Case(id=f"reduce_{name}_all", fn=op, shapes=[(2, 3, 2)],
                          ref=ref, domain=(0.3, 1.0), separated=sep))
        cases.append(Case(
            id=f"reduce_{name}_ax1",
            fn=(lambda op: lambda x: op(x, axis=1))(op),
            shapes=[(2, 3, 2)],
            ref=(lambda ref: lambda x: ref(x, axis=1))(ref),
            domain=(0.3, 1.0), separated=sep))
    cases.append(Case(id="reduce_norm",
                      fn=lambda x: nd.norm(x),
                      shapes=[(2, 5)],
                      ref=lambda x: np.linalg.norm(x).reshape(1),
                      domain=(0.3, 1.0)))
    cases.append(Case(id="reduce_nansum", fn=lambda x: nd.nansum(x),
                      shapes=[(2, 5)], ref=np.sum, domain=(0.3, 1.0),
                      grad=False))

    # matrix / shape ops
    cases += [
        Case(id="dot", fn=nd.dot, shapes=[(3, 4), (4, 2)],
             ref=lambda a, b: a @ b),
        Case(id="batch_dot", fn=nd.batch_dot, shapes=[(2, 3, 4), (2, 4, 2)],
             ref=lambda a, b: a @ b),
        Case(id="transpose", fn=lambda x: nd.transpose(x, axes=(1, 0)),
             shapes=[(3, 4)], ref=np.transpose),
        Case(id="swapaxes", fn=lambda x: nd.swapaxes(x, dim1=0, dim2=2),
             shapes=[(2, 3, 2)], ref=lambda x: np.swapaxes(x, 0, 2)),
        Case(id="reshape", fn=lambda x: nd.reshape(x, shape=(4, 3)),
             shapes=[(3, 4)], ref=lambda x: x.reshape(4, 3)),
        Case(id="expand_dims", fn=lambda x: nd.expand_dims(x, axis=1),
             shapes=[(3, 4)], ref=lambda x: x[:, None, :]),
        Case(id="squeeze", fn=lambda x: nd.squeeze(x),
             shapes=[(3, 1, 4)], ref=np.squeeze),
        Case(id="flip", fn=lambda x: nd.flip(x, axis=1),
             shapes=[(3, 4)], ref=lambda x: np.flip(x, 1)),
        Case(id="tile", fn=lambda x: nd.tile(x, reps=(2, 2)),
             shapes=[(2, 3)], ref=lambda x: np.tile(x, (2, 2))),
        Case(id="repeat", fn=lambda x: nd.repeat(x, repeats=2, axis=1),
             shapes=[(2, 3)], ref=lambda x: np.repeat(x, 2, 1)),
        Case(id="slice", fn=lambda x: nd.slice(x, begin=(0, 1), end=(2, 3)),
             shapes=[(3, 4)], ref=lambda x: x[0:2, 1:3]),
        Case(id="slice_axis",
             fn=lambda x: nd.slice_axis(x, axis=1, begin=1, end=3),
             shapes=[(3, 4)], ref=lambda x: x[:, 1:3]),
        Case(id="clip", fn=lambda x: nd.clip(x, a_min=-0.5, a_max=0.5),
             shapes=[(3, 4)], ref=lambda x: np.clip(x, -0.5, 0.5)),
        Case(id="concat", fn=lambda a, b: nd.concat(a, b, dim=1),
             shapes=[(2, 3), (2, 2)],
             ref=lambda a, b: np.concatenate([a, b], axis=1)),
        Case(id="stack", fn=lambda a, b: nd.stack(a, b, axis=0),
             shapes=[(2, 3), (2, 3)], ref=lambda a, b: np.stack([a, b])),
        Case(id="split",
             fn=lambda x: nd.split(x, num_outputs=2, axis=1),
             shapes=[(2, 4)], grad=True,
             ref=None),
        Case(id="where", fn=lambda c, a, b: nd.where(c, a, b),
             shapes=[(2, 3), (2, 3), (2, 3)], int_inputs=[0],
             ref=lambda c, a, b: np.where(c != 0, a, b), grad=False),
        Case(id="take", fn=lambda w, i: nd.take(w, i),
             shapes=[(4, 3), (2, 2)], int_inputs=[1],
             ref=lambda w, i: w[i.astype(int)], grad=False),
        Case(id="one_hot", fn=lambda i: nd.one_hot(i, depth=4),
             shapes=[(5,)], int_inputs=[0],
             ref=lambda i: np.eye(4, dtype=np.float32)[i.astype(int)],
             grad=False),
        Case(id="pick", fn=lambda x, i: nd.pick(x, i, axis=1),
             shapes=[(3, 4), (3,)], int_inputs=[1],
             ref=lambda x, i: x[np.arange(3), i.astype(int)], grad=False),
        Case(id="gather_nd",
             fn=lambda x: nd.gather_nd(x, nd.array(np.array([[0, 1], [1, 0]]).T)),
             shapes=[(2, 3)],
             ref=lambda x: np.stack([x[0, 1], x[1, 0]]), grad=False),
        Case(id="pad",
             fn=lambda x: nd.pad(x, mode="constant",
                                 pad_width=(0, 0, 0, 0, 1, 1, 1, 1)),
             shapes=[(1, 1, 2, 3)],
             ref=lambda x: np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1))),
             grad=True),
        Case(id="diag", fn=lambda x: nd.diag(x), shapes=[(3, 3)],
             ref=np.diag, grad=False),
        Case(id="depth_to_space", fn=lambda x: nd.depth_to_space(x, block_size=2),
             shapes=[(1, 4, 2, 2)], grad=True),
        Case(id="space_to_depth", fn=lambda x: nd.space_to_depth(x, block_size=2),
             shapes=[(1, 1, 4, 4)], grad=True),
        Case(id="smooth_l1", fn=lambda x: nd.smooth_l1(x, scalar=1.0),
             shapes=[(2, 5)], domain=(-2.0, 2.0), grad=True),
        Case(id="softmax", fn=lambda x: nd.softmax(x, axis=-1),
             shapes=[(3, 4)],
             ref=lambda x: (np.exp(x - x.max(-1, keepdims=True))
                            / np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))),
        Case(id="log_softmax", fn=lambda x: nd.log_softmax(x, axis=-1),
             shapes=[(3, 4)],
             ref=lambda x: x - x.max(-1, keepdims=True)
             - np.log(np.exp(x - x.max(-1, keepdims=True)).sum(-1, keepdims=True))),
    ]

    # NN layer ops
    cases += [
        Case(id="FullyConnected",
             fn=lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=3),
             shapes=[(2, 4), (3, 4), (3,)],
             ref=lambda x, w, b: x @ w.T + b),
        Case(id="FullyConnected_nobias",
             fn=lambda x, w: nd.FullyConnected(x, w, num_hidden=3,
                                               no_bias=True),
             shapes=[(2, 4), (3, 4)], ref=lambda x, w: x @ w.T),
        Case(id="Convolution_1x1",
             fn=lambda x, w: nd.Convolution(x, w, kernel=(1, 1), num_filter=2,
                                            no_bias=True),
             shapes=[(1, 3, 4, 4), (2, 3, 1, 1)],
             ref=lambda x, w: np.einsum("bchw,fcij->bfhw", x, w)),
        Case(id="Convolution_3x3",
             fn=lambda x, w: nd.Convolution(x, w, kernel=(3, 3), num_filter=2,
                                            pad=(1, 1), no_bias=True),
             shapes=[(1, 2, 4, 4), (2, 2, 3, 3)]),
        Case(id="Deconvolution",
             fn=lambda x, w: nd.Deconvolution(x, w, kernel=(2, 2), stride=(2, 2),
                                              num_filter=2, no_bias=True),
             shapes=[(1, 2, 3, 3), (2, 2, 2, 2)]),
        Case(id="Pooling_max",
             fn=lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                                     pool_type="max"),
             shapes=[(1, 2, 4, 4)], separated=True),
        Case(id="Pooling_avg",
             fn=lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                                     pool_type="avg"),
             shapes=[(1, 2, 4, 4)]),
        Case(id="Pooling_global",
             fn=lambda x: nd.Pooling(x, global_pool=True, pool_type="avg"),
             shapes=[(1, 2, 4, 4)],
             ref=lambda x: x.mean(axis=(2, 3), keepdims=True)),
        Case(id="LayerNorm",
             fn=lambda x, g, b: nd.LayerNorm(x, g, b),
             shapes=[(3, 6), (6,), (6,)]),
        Case(id="BatchNorm_infer",
             fn=lambda x, g, b, m, v: nd.BatchNorm(
                 x, g, b, m, v, fix_gamma=False, use_global_stats=True),
             shapes=[(2, 3, 2, 2), (3,), (3,), (3,), (3,)],
             domain=(0.3, 1.0), grad=False),
        Case(id="L2Normalization",
             fn=lambda x: nd.L2Normalization(x),
             shapes=[(2, 6)],
             ref=lambda x: x / np.sqrt((x**2).sum(1, keepdims=True) + 1e-10)),
        Case(id="Activation_tanh",
             fn=lambda x: nd.Activation(x, act_type="tanh"),
             shapes=[(2, 5)], ref=np.tanh),
        Case(id="LeakyReLU",
             fn=lambda x: nd.LeakyReLU(x, act_type="leaky", slope=0.1),
             shapes=[(2, 5)], domain=(-1.0, 1.0),
             ref=lambda x: np.where(x > 0, x, 0.1 * x)),
        Case(id="Embedding",
             fn=lambda i, w: nd.Embedding(i, w, input_dim=4, output_dim=3),
             shapes=[(2, 2), (4, 3)], int_inputs=[0], grad=False,
             ref=lambda i, w: w[i.astype(int)]),
        Case(id="softmax_cross_entropy",
             fn=lambda x, lab: nd.softmax_cross_entropy(x, lab),
             shapes=[(3, 4), (3,)], int_inputs=[1], grad=False),
    ]
    return cases


CASES = _build_cases()
_IDS = [c.id for c in CASES]


@pytest.fixture(autouse=True)
def _rng():
    np.random.seed(7)
    yield


@pytest.mark.parametrize("case", CASES, ids=_IDS)
def test_forward(case):
    rng = np.random.RandomState(11)
    arrs = _inputs_np(case, rng)
    out = case.fn(*[nd.array(a) for a in arrs])
    outs = out if isinstance(out, (list, tuple)) else [out]
    for o in outs:
        v = o.asnumpy()
        assert np.isfinite(v.astype(np.float64)).all(), case.id
    if case.ref is not None:
        expect = case.ref(*arrs)
        np.testing.assert_allclose(
            outs[0].asnumpy().astype(np.float64),
            np.asarray(expect).astype(np.float64),
            rtol=case.rtol or 1e-4, atol=case.atol or 1e-5,
            err_msg=f"forward mismatch: {case.id}")


@pytest.mark.parametrize("case", [c for c in CASES if c.grad],
                         ids=[c.id for c in CASES if c.grad])
def test_gradient(case):
    """Analytic (tape) gradient vs central finite differences through a
    fixed random projection (reference: check_numeric_gradient)."""
    from mxnet_tpu.test_utils import check_numeric_gradient

    rng = np.random.RandomState(13)
    arrs = _inputs_np(case, rng)
    inputs = [nd.array(a) for a in arrs]
    # fixed projection so e.g. softmax's row-sum==1 structure stays visible
    probe = {}

    def loss_fn(*xs):
        out = case.fn(*xs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        total = None
        for k, o in enumerate(outs):
            if k not in probe:
                probe[k] = nd.array(
                    np.random.RandomState(17 + k).uniform(0.5, 1.5, o.shape)
                    .astype(np.float32))
            term = (o * probe[k]).sum()
            total = term if total is None else total + term
        return total

    diff_idx = [i for i in range(len(inputs)) if i not in case.int_inputs]
    check_numeric_gradient(loss_fn, [inputs[i] for i in diff_idx]
                           if len(diff_idx) == len(inputs) else inputs,
                           eps=1e-2, rtol=5e-2, atol=5e-3)


@pytest.mark.parametrize("case", [c for c in CASES if c.bf16],
                         ids=[c.id for c in CASES if c.bf16])
def test_bf16_consistency(case):
    """f32-vs-bf16 sweep (reference: check_consistency dtype axis)."""
    rng = np.random.RandomState(19)
    arrs = _inputs_np(case, rng)

    def run(dtype):
        ins = []
        for i, a in enumerate(arrs):
            if i in case.int_inputs:
                ins.append(nd.array(a))
            else:
                ins.append(nd.array(a.astype(dtype), dtype=dtype))
        out = case.fn(*ins)
        outs = out if isinstance(out, (list, tuple)) else [out]
        return [o.asnumpy().astype(np.float64) for o in outs]

    f32 = run(np.float32)
    b16 = run(BF16)
    for a, b in zip(f32, b16):
        scale = max(1.0, float(np.abs(a).max()))
        np.testing.assert_allclose(
            a, b, rtol=0.1, atol=0.05 * scale,
            err_msg=f"bf16 inconsistent with f32: {case.id}")


@pytest.mark.parametrize("case",
                         [c for c in CASES if c.grad and c.bf16],
                         ids=[c.id for c in CASES if c.grad and c.bf16])
def test_bf16_backward_finite(case):
    """Backward runs and is finite in bf16 (crash-class regression net)."""
    rng = np.random.RandomState(23)
    arrs = _inputs_np(case, rng)
    inputs = []
    for i, a in enumerate(arrs):
        if i in case.int_inputs:
            inputs.append(nd.array(a))
        else:
            inputs.append(nd.array(a.astype(BF16), dtype=BF16))
    for i, x in enumerate(inputs):
        if i not in case.int_inputs:
            x.attach_grad()
    with autograd.record():
        loss = _sum_all(case.fn(*inputs))
    loss.backward()
    for i, x in enumerate(inputs):
        if i not in case.int_inputs and x.grad is not None:
            g = x.grad.asnumpy().astype(np.float64)
            assert np.isfinite(g).all(), case.id
