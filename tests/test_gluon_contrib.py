"""gluon.contrib: conv RNN cells, VariationalDropoutCell, LSTMPCell,
Estimator fit/evaluate with event handlers.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon.contrib import rnn as crnn
from mxnet_tpu.gluon.contrib.estimator import (CheckpointHandler,
                                               EarlyStoppingHandler,
                                               Estimator)


def test_conv2d_lstm_cell_step_and_unroll():
    B, C, H, W, HC = 2, 3, 8, 8, 4
    cell = crnn.Conv2DLSTMCell(input_shape=(C, H, W), hidden_channels=HC,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(B, C, H, W).astype(np.float32))
    states = cell.begin_state(batch_size=B)
    out, new_states = cell(x, states)
    assert out.shape == (B, HC, H, W)
    assert len(new_states) == 2 and new_states[1].shape == (B, HC, H, W)
    # unroll over time
    seq = nd.array(np.random.rand(B, 5, C, H, W).astype(np.float32))
    outputs, _ = cell.unroll(5, seq, layout="NTC", merge_outputs=False)
    assert len(outputs) == 5


def test_conv1d_gru_and_rnn_cells():
    B, C, L, HC = 2, 3, 10, 5
    for cls in (crnn.Conv1DGRUCell, crnn.Conv1DRNNCell):
        cell = cls(input_shape=(C, L), hidden_channels=HC, i2h_kernel=3,
                   h2h_kernel=3, i2h_pad=1)
        cell.initialize(mx.init.Xavier())
        x = nd.array(np.random.rand(B, C, L).astype(np.float32))
        out, states = cell(x, cell.begin_state(batch_size=B))
        assert out.shape == (B, HC, L)


def test_conv_cell_even_h2h_kernel_rejected():
    with pytest.raises(mx.base.MXNetError):
        crnn.Conv2DLSTMCell(input_shape=(3, 8, 8), hidden_channels=4,
                            i2h_kernel=3, h2h_kernel=2)


def test_variational_dropout_same_mask_across_steps():
    base = gluon.rnn.RNNCell(16)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.ones((4, 8), np.float32))
    states = cell.begin_state(batch_size=4)
    with autograd.record(train_mode=True):
        out1, states = cell(x, states)
        out2, states = cell(x, states)
    m1 = (out1.asnumpy() == 0)
    m2 = (out2.asnumpy() == 0)
    # identical zero pattern across time steps (the variational property);
    # extremely unlikely by chance with 64 elements at p=0.5
    np.testing.assert_array_equal(m1, m2)
    assert m1.sum() > 0


def test_lstmp_cell_projection_shapes():
    cell = crnn.LSTMPCell(hidden_size=12, projection_size=5)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.random.rand(3, 7).astype(np.float32))
    states = cell.begin_state(batch_size=3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 5)          # projected
    assert new_states[0].shape == (3, 5)
    assert new_states[1].shape == (3, 12)  # cell state full size
    # unroll works and trains
    seq = nd.array(np.random.rand(3, 4, 7).astype(np.float32))
    outputs, _ = cell.unroll(4, seq, layout="NTC", merge_outputs=True)
    assert outputs.shape == (3, 4, 5)


class _Toy:
    """Tiny binary-classification iterable."""

    def __init__(self, n=64, batch=16):
        rng = np.random.RandomState(0)
        self.x = rng.randn(n, 10).astype(np.float32)
        w = rng.randn(10, 1).astype(np.float32)
        self.y = (self.x @ w > 0).astype(np.float32).ravel()
        self.batch = batch

    def __iter__(self):
        for i in range(0, len(self.x), self.batch):
            yield (nd.array(self.x[i:i + self.batch]),
                   nd.array(self.y[i:i + self.batch]))


def test_estimator_fit_and_evaluate(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy(),
                    trainer=gluon.Trainer(net.collect_params(), "adam",
                                          {"learning_rate": 0.05}))
    data = _Toy()
    ckpt = CheckpointHandler(str(tmp_path), monitor=est.train_loss_metric,
                             save_best=True, mode="min")
    est.fit(data, epochs=8, event_handlers=[ckpt])
    scores = est.evaluate(data)
    acc = [v for k, v in scores.items() if k == "accuracy"][0]
    assert acc > 0.9, scores
    import os

    assert os.path.exists(str(tmp_path / "model-epoch8.params"))
    assert os.path.exists(str(tmp_path / "model-best.params"))


def test_estimator_early_stopping():
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    # min_delta large enough that small late-training improvements do not
    # count, so the stop fires deterministically after the initial drop
    stopper = EarlyStoppingHandler(monitor=est.train_loss_metric,
                                   patience=2, min_delta=0.2, mode="min")
    est.fit(_Toy(), epochs=50, event_handlers=[stopper])
    assert stopper.current_epoch < 50  # stopped early


def test_int_pow_fractional_promotes():
    x = nd.array(np.array([9, 4], np.int32), dtype="int32")
    out = x ** 0.5
    np.testing.assert_allclose(out.asnumpy(), [3.0, 2.0])
    out2 = x ** 2
    assert np.dtype(out2.dtype) == np.int32


def test_checkpoint_handler_pruning(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import CheckpointHandler

    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    ckpt = CheckpointHandler(str(tmp_path), max_checkpoints=2)
    est.fit(_Toy(), epochs=5, event_handlers=[ckpt])
    import glob

    saved = sorted(glob.glob(str(tmp_path / "model-epoch*.params")))
    assert len(saved) == 2  # pruned to max_checkpoints
    assert saved[-1].endswith("epoch5.params")


def test_checkpoint_resume(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import CheckpointHandler

    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(_Toy(), epochs=2,
            event_handlers=[CheckpointHandler(str(tmp_path))])
    w_trained = net.collect_params()
    snap = {k: v.data().asnumpy().copy() for k, v in w_trained.items()}

    net2 = gluon.nn.Dense(2)
    net2.initialize(mx.init.Xavier())
    est2 = Estimator(net2, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                     metrics=mx.metric.Accuracy())
    resume = CheckpointHandler(str(tmp_path), resume_from_checkpoint=True)
    est2.fit(_Toy(), epochs=0, event_handlers=[resume])  # load, train 0
    for (k, v), (k2, v2) in zip(sorted(snap.items()),
                                sorted(net2.collect_params().items())):
        np.testing.assert_allclose(v, v2.data().asnumpy(), rtol=1e-6)


def test_checkpoint_resume_continues_numbering(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import CheckpointHandler

    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    est = Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss(),
                    metrics=mx.metric.Accuracy())
    est.fit(_Toy(), epochs=3,
            event_handlers=[CheckpointHandler(str(tmp_path),
                                              max_checkpoints=2)])
    est.fit(_Toy(), epochs=2,
            event_handlers=[CheckpointHandler(
                str(tmp_path), max_checkpoints=2,
                resume_from_checkpoint=True)])
    import glob

    saved = sorted(glob.glob(str(tmp_path / "model-epoch*.params")))
    # resumed run continues at epoch4/epoch5 and pruning holds at 2 files
    assert len(saved) == 2, saved
    assert saved[-1].endswith("epoch5.params"), saved
