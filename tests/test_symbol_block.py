"""HybridBlock.export / SymbolBlock.imports round-trip (reference spec:
test_gluon.py export/SymbolBlock tests ~L1500)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd, sym
from mxnet_tpu.gluon import nn


def _net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"))
        net.add(nn.Dense(4))
    return net


def test_export_emits_symbol_json(tmp_path):
    net = _net()
    net.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(2, 8))
    y = net(x)
    path = str(tmp_path / "net")
    out_sym = net.export(path, 0)
    assert isinstance(out_sym, sym.Symbol)
    loaded = sym.load(f"{path}-symbol.json")
    args = loaded.list_arguments()
    assert "data" in args
    assert any(a.endswith("weight") for a in args)


def test_symbolblock_imports_matches_original(tmp_path):
    net = _net()
    net.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(3, 8))
    y_ref = net(x).asnumpy()
    path = str(tmp_path / "net")
    net.export(path, 0)

    sb = gluon.SymbolBlock.imports(f"{path}-symbol.json", ["data"],
                                   f"{path}-0000.params", ctx=mx.cpu())
    y2 = sb(x).asnumpy()
    np.testing.assert_allclose(y2, y_ref, rtol=1e-5, atol=1e-6)


def test_symbolblock_trains(tmp_path):
    net = _net()
    net.initialize(mx.init.Xavier())
    path = str(tmp_path / "net")
    net(nd.random.uniform(shape=(2, 8)))
    net.export(path, 0)
    sb = gluon.SymbolBlock.imports(f"{path}-symbol.json", ["data"],
                                   f"{path}-0000.params", ctx=mx.cpu())
    x = nd.random.uniform(shape=(4, 8))
    from mxnet_tpu import autograd

    with autograd.record():
        out = sb(x)
        loss = out.sum()
    loss.backward()
    grads = [p.grad(mx.cpu()) for p in sb.collect_params().values()
             if p.grad_req != "null"]
    assert any(float(np.abs(g.asnumpy()).sum()) > 0 for g in grads)


def test_export_with_batchnorm(tmp_path):
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, kernel_size=3, padding=1))
        net.add(nn.BatchNorm())
        net.add(nn.Activation("relu"))
    net.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(2, 3, 8, 8))
    y_ref = net(x).asnumpy()
    path = str(tmp_path / "cnn")
    net.export(path, 0)
    sb = gluon.SymbolBlock.imports(f"{path}-symbol.json", ["data"],
                                   f"{path}-0000.params", ctx=mx.cpu())
    y2 = sb(x).asnumpy()
    np.testing.assert_allclose(y2, y_ref, rtol=1e-4, atol=1e-5)
