"""Warp/sampling ops + legacy op-tail additions: GridGenerator,
BilinearSampler, SpatialTransformer, Correlation, Pad, Crop, moments,
SVMOutput, im2col/col2im, RNN (flat-parameter facade), all_finite,
digamma, ravel/unravel aliases.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd


def test_grid_generator_affine_identity():
    B, H, W = 2, 4, 5
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (B, 1))
    grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                            target_shape=(H, W)).asnumpy()
    assert grid.shape == (B, 2, H, W)
    np.testing.assert_allclose(grid[0, 0, 0], np.linspace(-1, 1, W), atol=1e-6)
    np.testing.assert_allclose(grid[0, 1, :, 0], np.linspace(-1, 1, H),
                               atol=1e-6)


def test_bilinear_sampler_identity_grid():
    B, C, H, W = 1, 2, 5, 6
    data = np.random.rand(B, C, H, W).astype(np.float32)
    theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (B, 1))
    grid = nd.GridGenerator(nd.array(theta), transform_type="affine",
                            target_shape=(H, W))
    out = nd.BilinearSampler(nd.array(data), grid).asnumpy()
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)


def test_bilinear_sampler_shift_zero_pad():
    # grid entirely outside the image -> zeros
    data = np.ones((1, 1, 4, 4), np.float32)
    grid = np.full((1, 2, 4, 4), 5.0, np.float32)
    out = nd.BilinearSampler(nd.array(data), nd.array(grid)).asnumpy()
    np.testing.assert_array_equal(out, np.zeros_like(out))


def test_spatial_transformer_identity():
    B, C, H, W = 2, 3, 6, 6
    data = np.random.rand(B, C, H, W).astype(np.float32)
    loc = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (B, 1))
    out = nd.SpatialTransformer(nd.array(data), nd.array(loc),
                                target_shape=(H, W)).asnumpy()
    np.testing.assert_allclose(out, data, rtol=1e-5, atol=1e-5)


def test_spatial_transformer_grad_flows():
    data = nd.array(np.random.rand(1, 1, 4, 4).astype(np.float32))
    loc = nd.array(np.array([[1, 0, 0.1, 0, 1, -0.1]], np.float32))
    data.attach_grad()
    loc.attach_grad()
    with autograd.record():
        out = nd.SpatialTransformer(data, loc, target_shape=(4, 4))
        s = out.sum()
    s.backward()
    assert np.isfinite(data.grad.asnumpy()).all()
    assert np.isfinite(loc.grad.asnumpy()).all()


def test_correlation_self_identity():
    # zero displacement channel of Correlation(x, x) is mean(x^2, C)
    B, C, H, W = 1, 3, 6, 6
    x = np.random.rand(B, C, H, W).astype(np.float32)
    out = nd.Correlation(nd.array(x), nd.array(x), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1, is_multiply=True).asnumpy()
    D = 3
    assert out.shape == (B, D * D, H, W)
    center = out[:, (D * D) // 2]
    np.testing.assert_allclose(center, (x * x).mean(axis=1), rtol=1e-5)


def test_correlation_displacement():
    # data2 = data1 shifted right by 1: the (dy=0,dx=1) channel matches
    B, C, H, W = 1, 2, 5, 5
    x = np.random.rand(B, C, H, W).astype(np.float32)
    x2 = np.zeros_like(x)
    x2[:, :, :, 1:] = x[:, :, :, :-1]
    out = nd.Correlation(nd.array(x), nd.array(x2), kernel_size=1,
                         max_displacement=1, stride1=1, stride2=1,
                         pad_size=1, is_multiply=True).asnumpy()
    # displacement-major: (dy,dx) row-major over 3x3, (0,+1) is index 5
    chan = out[0, 5]
    expect = (x * x).mean(axis=1)[0]
    np.testing.assert_allclose(chan[:, :-1], expect[:, :-1], rtol=1e-4)


def test_correlation_kernel3_mean_of_products():
    """kernel_size>1 must average the per-pixel products over the patch
    (mean of products), not multiply patch means."""
    B, C, H, W = 1, 2, 9, 9
    rng = np.random.RandomState(0)
    x1 = rng.rand(B, C, H, W).astype(np.float32)
    x2 = rng.rand(B, C, H, W).astype(np.float32)
    k, md = 3, 1
    out = nd.Correlation(nd.array(x1), nd.array(x2), kernel_size=k,
                         max_displacement=md, stride1=1, stride2=1,
                         pad_size=0, is_multiply=True).asnumpy()
    D = 2 * md + 1
    border = k // 2 + md
    Ho = H - 2 * border
    # zero-displacement channel at output origin: mean over C and the 3x3
    # patch centred at (border, border) of x1*x2
    patch1 = x1[0, :, border - 1:border + 2, border - 1:border + 2]
    patch2 = x2[0, :, border - 1:border + 2, border - 1:border + 2]
    expect = (patch1 * patch2).mean()
    np.testing.assert_allclose(out[0, (D * D) // 2, 0, 0], expect, rtol=1e-5)
    assert out.shape[2] == Ho


def test_pad_modes():
    x = np.arange(12, dtype=np.float32).reshape(1, 1, 3, 4)
    pw = (0, 0, 0, 0, 1, 1, 2, 2)
    out = nd.Pad(nd.array(x), mode="constant", pad_width=pw,
                 constant_value=7).asnumpy()
    np.testing.assert_allclose(
        out, np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)),
                    constant_values=7))
    out = nd.Pad(nd.array(x), mode="edge", pad_width=pw).asnumpy()
    np.testing.assert_allclose(
        out, np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="edge"))
    out = nd.Pad(nd.array(x), mode="reflect", pad_width=pw).asnumpy()
    np.testing.assert_allclose(
        out, np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)), mode="reflect"))


def test_crop():
    x = np.random.rand(1, 2, 8, 8).astype(np.float32)
    out = nd.Crop(nd.array(x), offset=(1, 2), h_w=(4, 5),
                  num_args=1).asnumpy()
    np.testing.assert_array_equal(out, x[:, :, 1:5, 2:7])
    like = nd.zeros((1, 1, 3, 3))
    out = nd.Crop(nd.array(x), like, num_args=2, center_crop=True).asnumpy()
    np.testing.assert_array_equal(out, x[:, :, 2:5, 2:5])


def test_moments():
    x = np.random.rand(2, 3, 4).astype(np.float32)
    mean, var = nd.moments(nd.array(x), axes=(0, 2))
    np.testing.assert_allclose(mean.asnumpy(), x.mean(axis=(0, 2)), rtol=1e-5)
    np.testing.assert_allclose(var.asnumpy(), x.var(axis=(0, 2)), rtol=1e-4)
    mean2, var2 = nd.moments(nd.array(x), axes=(1,), keepdims=True)
    assert var2.shape == (2, 1, 4)


def test_svm_output_grad():
    x = np.array([[2.0, 1.0, -1.0]], np.float32)
    y = np.array([0.0], np.float32)
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(data, nd.array(y), margin=1.0,
                           regularization_coefficient=0.5, use_linear=True)
    out.backward()
    # forward is identity
    np.testing.assert_array_equal(out.asnumpy(), x)
    g = data.grad.asnumpy()
    # class1: 1 - 2 + 1 = 0 violation (not > 0) -> 0; class2: 1-2-1=-2 -> 0
    np.testing.assert_allclose(g, np.zeros_like(g))
    x = np.array([[0.5, 1.0, -1.0]], np.float32)
    data = nd.array(x)
    data.attach_grad()
    with autograd.record():
        out = nd.SVMOutput(data, nd.array(y), margin=1.0,
                           regularization_coefficient=0.5, use_linear=True)
    out.backward()
    g = data.grad.asnumpy()
    # class1 violates (1 - 0.5 + 1 = 1.5 > 0): +reg there, -reg at y
    np.testing.assert_allclose(g, [[-0.5, 0.5, 0.0]])


def test_im2col_col2im_roundtrip():
    x = np.random.rand(2, 3, 6, 6).astype(np.float32)
    cols = nd.im2col(nd.array(x), kernel=(3, 3), stride=(1, 1), pad=(1, 1))
    assert cols.shape == (2, 27, 36)
    # col2im(im2col(x)) == x * (number of windows covering each pixel)
    back = nd.col2im(cols, output_size=(6, 6), kernel=(3, 3), stride=(1, 1),
                     pad=(1, 1)).asnumpy()
    ones = np.ones_like(x)
    cols1 = nd.im2col(nd.array(ones), kernel=(3, 3), stride=(1, 1),
                      pad=(1, 1))
    counts = nd.col2im(cols1, output_size=(6, 6), kernel=(3, 3),
                       stride=(1, 1), pad=(1, 1)).asnumpy()
    np.testing.assert_allclose(back, x * counts, rtol=1e-5)


def test_rnn_flat_param_op_matches_gluon():
    """nd.RNN with the packed flat parameter vector must match the gluon
    LSTM layer (which uses the per-array _fused_rnn)."""
    from mxnet_tpu import gluon

    T, B, I, H = 3, 2, 4, 5
    x = np.random.randn(T, B, I).astype(np.float32)
    layer = gluon.rnn.LSTM(H, num_layers=1)
    layer.initialize(mx.init.Xavier())
    out_ref = layer(nd.array(x)).asnumpy()

    p = {k.split(".")[-1]: v for k, v in layer.collect_params().items()}
    names = [n for n in p]
    get = lambda frag: next(v for n, v in p.items() if frag in n)
    flat = np.concatenate([
        get("l0_i2h_weight").data().asnumpy().ravel(),
        get("l0_h2h_weight").data().asnumpy().ravel(),
        get("l0_i2h_bias").data().asnumpy().ravel(),
        get("l0_h2h_bias").data().asnumpy().ravel(),
    ])
    h0 = np.zeros((1, B, H), np.float32)
    c0 = np.zeros((1, B, H), np.float32)
    out, hN, cN = nd.RNN(nd.array(x), nd.array(flat), nd.array(h0),
                         nd.array(c0), state_size=H, num_layers=1,
                         mode="lstm", state_outputs=True)
    np.testing.assert_allclose(out.asnumpy(), out_ref, rtol=1e-5, atol=1e-5)
    assert hN.shape == (1, B, H) and cN.shape == (1, B, H)


def test_all_finite():
    assert float(nd.all_finite(nd.array(np.ones(4, np.float32)))
                 .asnumpy()[0]) == 1.0
    bad = np.array([1.0, np.inf], np.float32)
    assert float(nd.all_finite(nd.array(bad)).asnumpy()[0]) == 0.0
    ok = nd.multi_all_finite(nd.array(np.ones(3, np.float32)),
                             nd.array(bad), num_arrays=2)
    assert float(ok.asnumpy()[0]) == 0.0


def test_digamma_and_ravel_aliases():
    x = np.array([0.5, 1.0, 2.5], np.float32)
    out = nd.digamma(nd.array(x)).asnumpy()
    # digamma(1) = -euler_gamma
    np.testing.assert_allclose(out[1], -0.5772157, rtol=1e-4)
    idx = nd.array(np.array([[0, 1], [2, 3]], np.float32))
    flat = nd.ravel_multi_index(idx, shape=(3, 4)).asnumpy()
    np.testing.assert_array_equal(flat, [2, 7])  # (0,2)->2, (1,3)->7
    back = nd.unravel_index(nd.array(np.array([2, 7], np.float32)),
                            shape=(3, 4)).asnumpy()
    np.testing.assert_array_equal(back, [[0, 1], [2, 3]])
