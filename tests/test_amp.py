"""AMP end-to-end (reference: python/mxnet/contrib/amp/amp.py —
init/init_trainer/scale_loss; BASELINE config 2 requires the AMP workflow).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.contrib import amp


def _net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def test_amp_bf16_workflow_trains():
    mx.random.seed(0)
    amp.init(target_dtype="bfloat16")
    net = _net()
    amp.convert_hybrid_block(net)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    assert trainer._optimizer.multi_precision
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = np.random.RandomState(0).rand(16, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, 16).astype(np.float32)
    import ml_dtypes

    xb = nd.array(x.astype(ml_dtypes.bfloat16), dtype=ml_dtypes.bfloat16)
    first = last = None
    for _ in range(30):
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out, nd.array(y))
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(16)
        v = float(loss.mean().asnumpy().astype(np.float32))
        first = first if first is not None else v
        last = v
    assert last < first, (first, last)
    # master-weight path keeps bf16 exposed weights
    assert net[0].weight.data().dtype == ml_dtypes.bfloat16


def test_amp_fp16_loss_scaling_trains():
    mx.random.seed(1)
    amp.init(target_dtype="float16")
    net = _net()
    amp.convert_hybrid_block(net, target_dtype="float16")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05})
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    assert scaler is not None and scaler.loss_scale > 1
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = nd.array(np.random.RandomState(2).rand(8, 8).astype(np.float16),
                 dtype=np.float16)
    y = nd.array(np.random.RandomState(3).randint(0, 4, 8).astype(np.float32))
    first = last = None
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(x), y)
        with amp.scale_loss(loss, trainer) as scaled:
            scaled.backward()
        trainer.step(8)
        v = float(loss.mean().asnumpy().astype(np.float32))
        first = first if first is not None else v
        last = v
    assert np.isfinite(last) and last < first, (first, last)


def test_amp_fp16_overflow_recovery():
    amp.init(target_dtype="float16")
    net = _net()
    net.cast("float16")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    amp.init_trainer(trainer)
    scaler = trainer._amp_loss_scaler
    scale0 = scaler.loss_scale
    x = nd.array(np.random.rand(4, 8).astype(np.float16), dtype=np.float16)
    with autograd.record():
        out = net(x)
        loss = (out * 6e4).sum()  # overflows fp16 grads
    with amp.scale_loss(loss, trainer) as scaled:
        scaled.backward()
    # overflow detected: scale halved, grads zeroed so step is a no-op
    assert scaler.loss_scale < scale0
    for p in net.collect_params().values():
        if p.grad_req != "null":
            assert float(np.abs(p.grad().asnumpy().astype(np.float32)).sum()) == 0.0
