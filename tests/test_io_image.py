"""IO / image / recordio tests (reference models: test_io.py, test_image.py,
test_recordio.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio


def test_ndarray_iter():
    data = np.arange(40, dtype=np.float32).reshape(10, 4)
    label = np.arange(10, dtype=np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=4, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3
    # discard mode
    it2 = mx.io.NDArrayIter(data, label, batch_size=4,
                            last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_recordio_roundtrip(tmp_path):
    rec_path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(rec_path, "w")
    for i in range(5):
        w.write(f"record-{i}".encode())
    w.close()
    r = recordio.MXRecordIO(rec_path, "r")
    for i in range(5):
        assert r.read() == f"record-{i}".encode()
    assert r.read() is None
    r.close()


def test_indexed_recordio_and_pack(tmp_path):
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, f"payload{i}".encode()))
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == [0, 1, 2, 3]
    header, payload = recordio.unpack(r.read_idx(2))
    assert header.label == 2.0
    assert payload == b"payload2"


def test_image_encode_decode():
    img = (np.random.rand(32, 24, 3) * 255).astype(np.uint8)
    buf = mx.image.imencode(img, ".png")  # lossless round trip
    back = mx.image.imdecode(buf)
    assert back.shape == (32, 24, 3)
    np.testing.assert_array_equal(back.asnumpy(), img)
    resized = mx.image.imresize(back, 12, 16)
    assert resized.shape == (16, 12, 3)


def test_image_record_iter(tmp_path):
    # pack a tiny synthetic image dataset then stream it back
    rec_path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(12):
        img = (np.random.rand(40, 40, 3) * 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        w.write_idx(i, recordio.pack(header, mx.image.imencode(img, ".jpg")))
    w.close()

    it = mx.io.ImageRecordIter(
        path_imgrec=rec_path, data_shape=(3, 32, 32), batch_size=4,
        shuffle=True, preprocess_threads=2, rand_crop=True, rand_mirror=True)
    count = 0
    for _ in it:  # one full pass via the iterator protocol
        pass
    it.reset()  # then a counted pass via the explicit DataIter protocol
    while True:
        try:
            batch = it.next()
        except StopIteration:
            break
        assert batch.data[0].shape == (4, 3, 32, 32)
        assert batch.label[0].shape == (4, 1)
        count += 1
    assert count == 3


def test_csv_iter(tmp_path):
    data_csv = str(tmp_path / "d.csv")
    np.savetxt(data_csv, np.arange(24).reshape(8, 3), delimiter=",")
    it = mx.io.CSVIter(data_csv=data_csv, data_shape=(3,), batch_size=4)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (4, 3)


def test_image_iter_imglist(tmp_path):
    # write images to disk, drive ImageIter via imglist
    paths = []
    for i in range(4):
        img = (np.random.rand(28, 28, 3) * 255).astype(np.uint8)
        p = str(tmp_path / f"img{i}.png")
        with open(p, "wb") as f:
            f.write(mx.image.imencode(img, ".png"))
        paths.append([float(i), p])
    it = mx.image.ImageIter(batch_size=2, data_shape=(3, 28, 28),
                            imglist=paths, path_root="")
    batch = it.next()
    assert batch.data[0].shape == (2, 3, 28, 28)


def test_profiler_and_runtime():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert not feats.is_enabled("CUDA")
    # profiler facade should start/stop cleanly on CPU
    mx.profiler.set_config(filename="/tmp/mxtpu_prof.json")
    mx.profiler.start()
    (nd.ones((4, 4)) * 2).wait_to_read()
    mx.profiler.stop()


def test_amp_bf16_flow():
    from mxnet_tpu.contrib import amp
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    amp.init("bfloat16")
    net = nn.Dense(4, in_units=8)
    net.initialize()
    amp.convert_hybrid_block(net)
    assert net.weight.dtype == "bfloat16"
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    amp.init_trainer(trainer)
    x = nd.random.uniform(shape=(2, 8), dtype="bfloat16")
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    with amp.scale_loss(loss, trainer) as scaled:
        scaled.backward()
    trainer.step(2)
    # master weights fp32 exist in optimizer state
    st = trainer._updaters[0].states[0] if trainer._updaters else None
    assert st is not None


def test_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "model")
    arg = {"fc_weight": nd.ones((2, 2))}
    aux = {"bn_mean": nd.zeros((2,))}
    mx.model.save_checkpoint(prefix, 3, None, arg, aux)
    _, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_allclose(arg2["fc_weight"].asnumpy(), np.ones((2, 2)))
    assert "bn_mean" in aux2
