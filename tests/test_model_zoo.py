"""Model zoo smoke tests (reference model: tests/python/unittest/
test_gluon_model_zoo.py — constructs each family and runs a tiny forward)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon.model_zoo.vision import get_model

# (name, input_size) — small inputs where the architecture allows it
SMALL = [
    ("resnet18_v1", 32),
    ("resnet18_v2", 32),
    ("mobilenet0.25", 32),
    ("mobilenetv2_0.25", 32),
    ("squeezenet1.1", 64),
    ("densenet121", 32),
]


@pytest.mark.parametrize("name,size", SMALL)
def test_model_forward(name, size):
    mx.random.seed(0)
    net = get_model(name, classes=10)
    net.initialize(mx.init.Xavier())
    x = nd.random.uniform(shape=(1, 3, size, size))
    y = net(x)
    assert y.shape == (1, 10)
    assert np.isfinite(y.asnumpy()).all()


def test_alexnet_vgg_forward():
    # fixed-size dense heads need >= 224 spatial input
    mx.random.seed(0)
    for name in ("alexnet", "vgg11"):
        net = get_model(name, classes=10)
        net.initialize(mx.init.Xavier())
        y = net(nd.zeros((1, 3, 224, 224)))
        assert y.shape == (1, 10)


def test_inception_forward():
    mx.random.seed(0)
    net = get_model("inceptionv3", classes=10)
    net.initialize(mx.init.Xavier())
    y = net(nd.zeros((1, 3, 299, 299)))
    assert y.shape == (1, 10)


def test_get_model_unknown():
    with pytest.raises(mx.MXNetError):
        get_model("resnet9999")


def test_model_zoo_hybridize():
    mx.random.seed(0)
    net = get_model("mobilenet0.25", classes=10)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.random.uniform(shape=(2, 3, 32, 32))
    y1 = net(x)
    y2 = net(x)
    np.testing.assert_allclose(y1.asnumpy(), y2.asnumpy(), rtol=1e-5)
