"""Shard-granular checkpoint format (ISSUE 16 tentpole;
docs/FAULT_TOLERANCE.md §Shard-granular checkpoints).

Covers: the on-disk format-2 contract (per-rank shard files, atomic
shard markers, manifest + layout in meta.json), bitwise save/restore
parity on the same mesh, elastic resharding onto different meshes /
device orders vs the gathered-format oracle, legacy format-1
checkpoints loading through the same restore path, torn-shard
step-level fallback, the ``torn-write:shard=R`` fault grammar, the
rank-local ``save_now`` preemption path, the ``MX_CKPT_SHARDED`` knob,
the checkpoint_save telemetry shape, and the ``tools/ckpt_report.py``
offline audit CLI (exit 0/2/3).
"""
import hashlib
import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, fault, gluon, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import DataParallelStep, make_mesh
from mxnet_tpu.parallel.sharding import ShardingRules

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# every Dense weight/bias splits its leading axis over tp: on a tp=2
# mesh each param has >= 2 shards, the multi-shard manifest surface
_RULES = ShardingRules([
    (r".*weight$", ("tp", None)),
    (r".*bias$", ("tp",)),
])


def _make_step(seed=0, mesh=None):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"))
    net.add(gluon.nn.Dense(4))
    net.initialize(mx.init.Normal(0.5))
    return DataParallelStep(net, gluon.loss.L2Loss(),
                            mesh=mesh if mesh is not None
                            else make_mesh(tp=2),
                            optimizer="adam",
                            optimizer_params={"learning_rate": 1e-2},
                            rules=_RULES)


def _train(step, n, ckpts=()):
    rng = np.random.RandomState(7)
    X = rng.randn(8, 6).astype(np.float32)
    Y = rng.randn(8, 4).astype(np.float32)
    for _ in range(n):
        step.step(nd.array(X), nd.array(Y))
        for ck in ckpts:
            ck.step(step)
    step.drain()
    for ck in ckpts:
        ck.wait()


@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    """One trained tp=2 step checkpointed BOTH ways at the same state:
    sharded format 2 and the gathered format-1 oracle, plus the bitwise
    reference state_dict they both captured (step 4 = the final step)."""
    step = _make_step(seed=0)
    root = tmp_path_factory.mktemp("ckpt_sharded")
    sharded_dir = str(root / "sharded")
    gathered_dir = str(root / "gathered")
    ck_s = checkpoint.AsyncCheckpointer(sharded_dir, save_every=2, keep=3,
                                        sharded=True)
    ck_g = checkpoint.AsyncCheckpointer(gathered_dir, save_every=4, keep=2)
    _train(step, 4, ckpts=(ck_s, ck_g))
    ck_s.close()
    ck_g.close()
    ref = step.state_dict()
    return {"step": step, "sharded": sharded_dir, "gathered": gathered_dir,
            "ref": ref}


def _assert_bitwise(ref, other, opt=True):
    for k in ref["params"]:
        np.testing.assert_array_equal(ref["params"][k], other["params"][k],
                                      err_msg=f"param {k}")
    if opt:
        for k in ref["opt_state"]:
            np.testing.assert_array_equal(ref["opt_state"][k],
                                          other["opt_state"][k],
                                          err_msg=f"slot {k}")


# ---------------------------------------------------------------------------
# on-disk format
# ---------------------------------------------------------------------------
def test_sharded_format_manifest_and_digests(saved):
    d = os.path.join(saved["sharded"], "step-4")
    files = set(os.listdir(d))
    assert {"meta.json", "shard-0.json", "params-shard-0.nd",
            "optstate-shard-0.nd"} <= files
    assert "params.nd" not in files  # no gathered payload in format 2
    meta = json.load(open(os.path.join(d, "meta.json")))
    assert meta["format"] == 2 and meta["step"] == 4
    assert meta["world_size"] == 1
    manifest = meta["manifest"]
    # manifest is the global tensor map: every param carries shape,
    # dtype and a shard list; the tp split makes them multi-shard
    multi = {n: e for n, e in manifest["params"].items()
             if len(e["shards"]) > 1}
    assert multi, manifest["params"]
    for name, ent in manifest["params"].items():
        assert tuple(ent["shape"]) and ent["dtype"]
        for sh in ent["shards"]:
            assert sh["rank"] == 0  # single-process: rank 0 owns all
            assert len(sh["slice"]) == len(ent["shape"])
    # a tp-split weight's shards tile axis 0 disjointly
    name, ent = sorted(multi.items())[0]
    starts = sorted(tuple(s["slice"][0]) for s in ent["shards"])
    assert starts[0][0] == 0 and starts[-1][1] == ent["shape"][0]
    # adam slots ride the same format in optstate-shard-R.nd
    assert manifest["opt_state"], meta
    # the per-rank marker's digests must verify against the shard files
    marker = json.load(open(os.path.join(d, "shard-0.json")))
    assert marker["rank"] == 0 and marker["step"] == 4
    for fname, want in marker["digests"].items():
        got = hashlib.sha256(
            open(os.path.join(d, fname), "rb").read()).hexdigest()
        assert got == want, fname
    # layout rides next to the manifest: the elastic-resume inputs
    assert meta["layout"]["optimizer"] == "adam"
    assert checkpoint.latest_valid_step(saved["sharded"]) == 4


def test_sharded_roundtrip_bitwise_same_mesh(saved):
    step2 = _make_step(seed=1)  # different init: restore must overwrite
    assert checkpoint.restore(saved["sharded"], step2) == 4
    _assert_bitwise(saved["ref"], step2.state_dict())


# ---------------------------------------------------------------------------
# elastic reshard + mixed-version loads
# ---------------------------------------------------------------------------
def test_elastic_reshard_matches_gathered_oracle(saved):
    """tp=2 shards restored onto a dp-only mesh must equal the SAME
    state restored from the gathered-format oracle — the N->M resize
    path never changes values, only placement."""
    import jax

    from_sharded = _make_step(seed=2, mesh=make_mesh())
    assert checkpoint.restore(saved["sharded"], from_sharded) == 4
    from_gathered = _make_step(seed=3, mesh=make_mesh())
    assert checkpoint.restore(saved["gathered"], from_gathered) == 4
    _assert_bitwise(saved["ref"], from_sharded.state_dict())
    _assert_bitwise(from_gathered.state_dict(), from_sharded.state_dict())
    # grow/shrink the dp extent (4-device vs 2-device submesh): each
    # target materializes only its own shards, values stay bitwise
    devs = jax.devices()
    for sub in (devs[:4], devs[:2]):
        tgt = _make_step(seed=4, mesh=make_mesh(devices=sub))
        assert checkpoint.restore(saved["sharded"], tgt) == 4
        _assert_bitwise(saved["ref"], tgt.state_dict())


def test_reshard_same_size_different_device_order(saved):
    """Same mesh SHAPE but a permuted device assignment (the restarted
    gang that enumerated devices differently) still restores bitwise."""
    import jax

    tgt = _make_step(seed=5, mesh=make_mesh(tp=2,
                                            devices=jax.devices()[::-1]))
    assert checkpoint.restore(saved["sharded"], tgt) == 4
    _assert_bitwise(saved["ref"], tgt.state_dict())


def test_legacy_gathered_checkpoint_loads(saved):
    """Format-1 checkpoints (no ``format`` key / no manifest) keep
    loading through the same restore path — mixed-version fleets."""
    meta = json.load(open(os.path.join(saved["gathered"], "step-4",
                                       "meta.json")))
    assert int(meta.get("format", 1)) == 1 and "manifest" not in meta
    tgt = _make_step(seed=6)
    assert checkpoint.restore(saved["gathered"], tgt) == 4
    _assert_bitwise(saved["ref"], tgt.state_dict())


# ---------------------------------------------------------------------------
# torn shards: fallback + fault grammar
# ---------------------------------------------------------------------------
def _corrupt(path):
    with open(path, "r+b") as f:
        f.truncate(max(os.path.getsize(path) // 2, 16))


def test_corrupt_single_shard_falls_back_a_step(saved, tmp_path):
    d = str(tmp_path / "c")
    shutil.copytree(saved["sharded"], d)
    _corrupt(os.path.join(d, "step-4", "params-shard-0.nd"))
    # one torn shard invalidates the STEP, not the directory: validation
    # rejects step 4 and the scheduled step 2 is the newest valid one
    assert checkpoint.latest_valid_step(d) == 2
    assert checkpoint.latest_valid_step(d, multiple_of=2) == 2
    assert checkpoint.agree_resume_step(
        checkpoint.latest_valid_step(d, multiple_of=2)) == 2
    tgt = _make_step(seed=7)
    assert checkpoint.restore(d, tgt) == 2
    # pinning the torn step explicitly must refuse LOUDLY, not half-load
    with pytest.raises(MXNetError):
        checkpoint.load_checkpoint_state(d, step=4)
    with pytest.raises(MXNetError):
        checkpoint.restore(d, _make_step(seed=8), step=4)


def test_missing_shard_marker_invalidates_step(saved, tmp_path):
    """A rank that never committed its marker (mid-preemption death)
    leaves an incomplete step that validation rejects."""
    d = str(tmp_path / "m")
    shutil.copytree(saved["sharded"], d)
    os.unlink(os.path.join(d, "step-4", "shard-0.json"))
    assert checkpoint.latest_valid_step(d) == 2


def test_torn_write_shard_grammar_and_injection(tmp_path, monkeypatch):
    (f,) = fault.parse_spec("torn-write:step=4:shard=0")
    assert f.kind == "torn-write" and f.shard == 0 and f.step == 4
    with pytest.raises(MXNetError, match="shard=R only applies"):
        fault.parse_spec("crash:step=4:shard=0")
    with pytest.raises(MXNetError, match="shard"):
        fault.parse_spec("torn-write:step=4:shard=x")
    # end-to-end: the injected tear hits exactly rank 0's param shard
    # file of step 4, post-publish — restore falls back to step 2
    monkeypatch.setenv("MX_FAULT_SPEC", "torn-write:step=4:shard=0")
    d = str(tmp_path / "torn")
    step = _make_step(seed=9)
    ck = checkpoint.AsyncCheckpointer(d, save_every=2, keep=3, sharded=True)
    _train(step, 4, ckpts=(ck,))
    ck.close()
    monkeypatch.delenv("MX_FAULT_SPEC")
    assert os.path.exists(os.path.join(d, "step-4", "params-shard-0.nd"))
    assert checkpoint.latest_valid_step(d) == 2


# ---------------------------------------------------------------------------
# preemption save_now + writer narrowing + knobs
# ---------------------------------------------------------------------------
def test_save_now_sharded_off_cycle(tmp_path):
    """The SIGTERM path: an off-schedule rank-local shard snapshot at
    whatever step preemption caught us, restorable bitwise."""
    d = str(tmp_path / "now")
    step = _make_step(seed=10)
    ck = checkpoint.AsyncCheckpointer(d, save_every=50, sharded=True)
    _train(step, 3, ckpts=(ck,))
    assert ck.save_now(step) == 3
    ck.close()
    meta = json.load(open(os.path.join(d, "step-3", "meta.json")))
    assert meta["format"] == 2
    assert checkpoint.latest_valid_step(d) == 3
    tgt = _make_step(seed=11)
    assert checkpoint.restore(d, tgt) == 3
    _assert_bitwise(step.state_dict(), tgt.state_dict())


def test_non_writer_rank_still_writes_its_shards(tmp_path):
    """writer=False narrows a rank to per-shard writing instead of
    sitting saves out entirely: it commits its shard files + marker into
    the gang-shared staging dir; only the writer=True leader publishes
    (so a lone peer leaves a staged-but-unpublished step)."""
    d = str(tmp_path / "nw")
    step = _make_step(seed=12)
    ck = checkpoint.AsyncCheckpointer(d, save_every=2, writer=False,
                                      sharded=True)
    _train(step, 2, ckpts=(ck,))
    ck.close()
    staged = os.path.join(d, ".tmp-2-shard")
    assert {"params-shard-0.nd", "optstate-shard-0.nd",
            "shard-0.json"} <= set(os.listdir(staged))
    assert not os.path.exists(os.path.join(d, "step-2"))  # no leader
    assert checkpoint.latest_valid_step(d) == 0


def test_mx_ckpt_sharded_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("MX_CKPT_SHARDED", "1")
    ck = checkpoint.AsyncCheckpointer(str(tmp_path / "a"))
    assert ck.sharded
    ck.close()
    monkeypatch.setenv("MX_CKPT_SHARDED", "0")
    ck = checkpoint.AsyncCheckpointer(str(tmp_path / "b"))
    assert not ck.sharded
    ck.close()
    monkeypatch.delenv("MX_CKPT_SHARDED")
    ck = checkpoint.AsyncCheckpointer(str(tmp_path / "c"))
    assert not ck.sharded  # gathered stays the default
    ck.close()


def test_sharded_save_telemetry_event(tmp_path):
    """Each rank's save books ONE checkpoint_save event tagged
    sharded=true with its OWN payload bytes — the zero-collective
    audit trail the dist chaos test reads per rank."""
    telemetry.reset()
    telemetry.enable(str(tmp_path / "tele"))
    try:
        d = str(tmp_path / "t")
        step = _make_step(seed=13)
        ck = checkpoint.AsyncCheckpointer(d, save_every=2, sharded=True)
        _train(step, 2, ckpts=(ck,))
        ck.close()
        telemetry.flush()
        events = [json.loads(line) for line in
                  open(telemetry.event_path(str(tmp_path / "tele"), 0))]
        saves = [e for e in events if e["kind"] == "checkpoint_save"
                 and e.get("sharded")]
        assert len(saves) == 1, events
        assert saves[0]["rank"] == 0 and saves[0]["nbytes"] > 0
        assert saves[0]["step"] == 2
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# offline audit CLI
# ---------------------------------------------------------------------------
def _report(*args):
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "ckpt_report.py"),
         *args], capture_output=True, text=True, timeout=60)


def test_ckpt_report_clean_corrupt_and_usage(saved, tmp_path):
    res = _report(saved["sharded"])
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "sharded" in res.stdout and "all checkpoints verify" in res.stdout
    res = _report(saved["sharded"], "--json")
    assert res.returncode == 0
    rep = json.loads(res.stdout)
    assert rep["latest"] == 4 and not rep["anomalies"]
    assert all(s["valid"] and s["format"] == 2 for s in rep["steps"])
    assert rep["steps"][-1]["ranks"]["0"]["shards"] > 0
    # corrupt one shard: exit 3 and a rank-attributed digest complaint
    d = str(tmp_path / "bad")
    shutil.copytree(saved["sharded"], d)
    _corrupt(os.path.join(d, "step-4", "params-shard-0.nd"))
    res = _report(d)
    assert res.returncode == 3, res.stdout
    assert "rank 0" in res.stdout and "INVALID" in res.stdout
    res = _report(d, "--step", "2")  # the surviving step alone is clean
    assert res.returncode == 0, res.stdout
    assert _report(str(tmp_path / "nope")).returncode == 2
