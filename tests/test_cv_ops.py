"""Detection/CV op tests (reference spec:
tests/python/unittest/test_contrib_operator.py box_nms/multibox tests)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_box_iou():
    a = nd.array([[0, 0, 2, 2], [1, 1, 3, 3]])
    b = nd.array([[0, 0, 2, 2], [10, 10, 11, 11]])
    iou = nd.contrib.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[0, 0], 1.0, rtol=1e-5)
    np.testing.assert_allclose(iou[1, 0], 1.0 / 7.0, rtol=1e-4)
    assert iou[0, 1] == 0.0


def test_box_nms_suppresses_overlaps():
    # rows: [cls, score, x1, y1, x2, y2]
    dets = nd.array([
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 1, 1, 10.5, 10.5],   # overlaps the first -> suppressed
        [0, 0.7, 20, 20, 30, 30],     # far away -> kept
        [0, 0.05, 5, 5, 6, 6],        # below valid_thresh -> invalid
    ])
    out = nd.contrib.box_nms(dets, overlap_thresh=0.5, valid_thresh=0.1,
                             coord_start=2, score_index=1,
                             id_index=0).asnumpy()
    kept = out[out[:, 0] >= 0]
    assert kept.shape[0] == 2
    np.testing.assert_allclose(sorted(kept[:, 1].tolist()), [0.7, 0.9])


def test_box_nms_class_aware():
    dets = nd.array([
        [0, 0.9, 0, 0, 10, 10],
        [1, 0.8, 1, 1, 10.5, 10.5],   # overlaps but different class
    ])
    out = nd.contrib.box_nms(dets, overlap_thresh=0.5, valid_thresh=0.0,
                             coord_start=2, score_index=1, id_index=0,
                             force_suppress=False).asnumpy()
    assert (out[:, 0] >= 0).sum() == 2
    out2 = nd.contrib.box_nms(dets, overlap_thresh=0.5, valid_thresh=0.0,
                              coord_start=2, score_index=1, id_index=0,
                              force_suppress=True).asnumpy()
    assert (out2[:, 0] >= 0).sum() == 1


def test_multibox_prior_shapes_and_values():
    data = nd.zeros((1, 3, 4, 4))
    anchors = nd.contrib.MultiBoxPrior(data, sizes=(0.5, 0.25),
                                       ratios=(1, 2)).asnumpy()
    # S + R - 1 = 3 anchors per cell
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    # first anchor centered at (.125, .125) with size .5
    np.testing.assert_allclose(anchors[0, 0],
                               [0.125 - 0.25, 0.125 - 0.25,
                                0.125 + 0.25, 0.125 + 0.25], atol=1e-6)


def test_multibox_target_matching():
    anchors = nd.array([[[0.0, 0.0, 0.5, 0.5],
                         [0.5, 0.5, 1.0, 1.0],
                         [0.0, 0.5, 0.5, 1.0]]])
    # one gt box matching anchor 0 exactly, class 3
    label = nd.array([[[3, 0.0, 0.0, 0.5, 0.5],
                       [-1, 0, 0, 0, 0]]])
    cls_pred = nd.zeros((1, 5, 3))
    loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred)
    cls_t = cls_t.asnumpy()
    assert cls_t[0, 0] == 4.0          # class + 1
    assert cls_t[0, 1] == 0.0          # background
    mask = loc_mask.asnumpy().reshape(1, 3, 4)
    assert mask[0, 0].sum() == 4 and mask[0, 1].sum() == 0
    # exact match -> zero offsets
    lt = loc_t.asnumpy().reshape(1, 3, 4)
    np.testing.assert_allclose(lt[0, 0], np.zeros(4), atol=1e-5)


def test_multibox_detection_decodes():
    anchors = nd.array([[[0.1, 0.1, 0.3, 0.3],
                         [0.6, 0.6, 0.9, 0.9]]])
    cls_prob = nd.array([[[0.1, 0.9],    # background prob
                          [0.9, 0.05],   # class 0
                          [0.0, 0.05]]])  # class 1
    loc_pred = nd.zeros((1, 8))
    out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                       threshold=0.3).asnumpy()
    valid = out[0][out[0, :, 0] >= 0]
    assert valid.shape[0] == 1
    assert valid[0, 0] == 0.0          # class id 0
    np.testing.assert_allclose(valid[0, 1], 0.9, rtol=1e-5)
    np.testing.assert_allclose(valid[0, 2:], [0.1, 0.1, 0.3, 0.3], atol=1e-5)


def test_roi_align_identity():
    # a 1x1 ROI over a constant region pools that constant
    data = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = nd.array([[0, 0, 0, 3, 3]])
    out = mx.nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                                 spatial_scale=1.0).asnumpy()
    assert out.shape == (1, 1, 2, 2)
    # averages should increase along both axes
    assert out[0, 0, 0, 0] < out[0, 0, 0, 1] < out[0, 0, 1, 1]


def test_roi_pooling():
    data = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = nd.array([[0, 0, 0, 3, 3]])
    out = nd.ROIPooling(data, rois, pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    # max pooling of quadrants
    np.testing.assert_allclose(out[0, 0], [[5, 7], [13, 15]])


def test_proposal_shapes():
    b, a, fh, fw = 1, 12, 4, 4  # 4 scales x 3 ratios
    rs = np.random.RandomState(0)
    cls_prob = nd.array(rs.rand(b, 2 * a, fh, fw).astype(np.float32))
    bbox_pred = nd.array((rs.rand(b, 4 * a, fh, fw) * 0.1).astype(np.float32))
    im_info = nd.array([[64, 64, 1.0]])
    rois = nd.contrib.Proposal(cls_prob, bbox_pred, im_info,
                               rpn_pre_nms_top_n=50, rpn_post_nms_top_n=10,
                               feature_stride=16).asnumpy()
    assert rois.shape == (10, 5)
    assert (rois[:, 0] == 0).all()
    assert (rois[:, 1:] >= 0).all() and (rois[:, 1:] <= 63).all()


def test_bipartite_matching():
    scores = nd.array([[0.9, 0.1], [0.8, 0.7]])
    row, col = nd.contrib.bipartite_matching(scores, threshold=0.5)
    row, col = row.asnumpy(), col.asnumpy()
    assert row[0] == 0          # row 0 takes col 0 (0.9)
    assert row[1] == 1          # row 1 falls back to col 1 (0.7)
    assert col[0] == 0 and col[1] == 1


def test_multibox_target_padded_labels():
    """Padded -1 label rows must not clobber forced matches (regression)."""
    anchors = nd.array([[[0.0, 0.0, 0.4, 0.4],
                         [0.5, 0.5, 1.0, 1.0]]])
    # gt overlaps anchor 0 with IoU < 0.5 -> only the forced match applies;
    # second row is padding
    label = nd.array([[[2, 0.0, 0.0, 0.3, 0.55],
                       [-1, 0, 0, 0, 0]]])
    cls_pred = nd.zeros((1, 4, 2))
    loc_t, loc_mask, cls_t = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5)
    assert cls_t.asnumpy()[0, 0] == 3.0  # class 2 + 1, forced match kept
    assert loc_mask.asnumpy().sum() == 4.0


def test_roi_pooling_out_of_bounds():
    """ROIs beyond the feature map clamp instead of producing -inf."""
    data = nd.array(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    rois = nd.array([[0, 2, 2, 7, 7]])
    out = nd.ROIPooling(data, rois, pooled_size=(2, 2),
                        spatial_scale=1.0).asnumpy()
    assert np.isfinite(out).all()
    assert out.max() == 15.0
