"""Channel-last (NHWC-family) layout support.

Reference parity: MXNet's layout= parameter on Convolution/Pooling and the
gluon conv/pool layers (python/mxnet/gluon/nn/conv_layers.py), used by the
reference for cuDNN tensor-core paths (src/operator/nn/convolution.cu).
On TPU, NHWC is the MXU-native tiling and the bench's training layout, so
NHWC-vs-NCHW parity is load-bearing for the headline number.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def _to_nhwc(x):
    return np.transpose(x, (0, 2, 3, 1))


@pytest.mark.smoke
def test_conv2d_nhwc_matches_nchw():
    x = np.random.rand(2, 5, 8, 8).astype(np.float32)
    c1 = nn.Conv2D(7, 3, strides=2, padding=1, in_channels=5)
    c1.initialize(mx.init.Xavier())
    out1 = c1(nd.array(x)).asnumpy()

    c2 = nn.Conv2D(7, 3, strides=2, padding=1, in_channels=5, layout="NHWC")
    c2.initialize(mx.init.Xavier())
    # OIHW -> OHWI
    c2.weight.set_data(nd.array(c1.weight.data().asnumpy().transpose(0, 2, 3, 1)))
    c2.bias.set_data(c1.bias.data())
    out2 = c2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(_to_nhwc(out1), out2, rtol=1e-5, atol=1e-5)


def test_conv2d_nhwc_grouped():
    x = np.random.rand(2, 6, 8, 8).astype(np.float32)
    c1 = nn.Conv2D(8, 3, padding=1, groups=2, in_channels=6, use_bias=False)
    c1.initialize(mx.init.Xavier())
    out1 = c1(nd.array(x)).asnumpy()
    c2 = nn.Conv2D(8, 3, padding=1, groups=2, in_channels=6, use_bias=False,
                   layout="NHWC")
    c2.initialize(mx.init.Xavier())
    c2.weight.set_data(nd.array(c1.weight.data().asnumpy().transpose(0, 2, 3, 1)))
    out2 = c2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(_to_nhwc(out1), out2, rtol=1e-5, atol=1e-5)


def test_conv1d_nwc():
    x = np.random.rand(2, 4, 9).astype(np.float32)
    c1 = nn.Conv1D(5, 3, padding=1, in_channels=4)
    c1.initialize(mx.init.Xavier())
    out1 = c1(nd.array(x)).asnumpy()
    c2 = nn.Conv1D(5, 3, padding=1, in_channels=4, layout="NWC")
    c2.initialize(mx.init.Xavier())
    c2.weight.set_data(nd.array(c1.weight.data().asnumpy().transpose(0, 2, 1)))
    c2.bias.set_data(c1.bias.data())
    out2 = c2(nd.array(x.transpose(0, 2, 1))).asnumpy()
    np.testing.assert_allclose(out1, out2.transpose(0, 2, 1), rtol=1e-5,
                               atol=1e-5)


def test_pooling_nhwc():
    x = np.random.rand(2, 3, 9, 9).astype(np.float32)
    for mk_nchw, mk_nhwc in [
        (nn.MaxPool2D(3, 2, 1), nn.MaxPool2D(3, 2, 1, layout="NHWC")),
        (nn.AvgPool2D(2, 2, ceil_mode=True),
         nn.AvgPool2D(2, 2, ceil_mode=True, layout="NHWC")),
        (nn.GlobalAvgPool2D(), nn.GlobalAvgPool2D(layout="NHWC")),
    ]:
        out1 = mk_nchw(nd.array(x)).asnumpy()
        out2 = mk_nhwc(nd.array(_to_nhwc(x))).asnumpy()
        np.testing.assert_allclose(_to_nhwc(out1), out2, rtol=1e-6, atol=1e-6)


def test_batchnorm_last_axis_train_and_eval():
    x = np.random.rand(4, 6, 5, 3).astype(np.float32)  # NHWC, C=3
    bn1 = nn.BatchNorm(axis=1)
    bn2 = nn.BatchNorm(axis=-1)
    bn1.initialize()
    bn2.initialize()
    xt = np.transpose(x, (0, 3, 1, 2))
    with autograd.record():
        o1 = bn1(nd.array(xt))
    with autograd.record():
        o2 = bn2(nd.array(x))
    np.testing.assert_allclose(o1.asnumpy(), np.transpose(o2.asnumpy(), (0, 3, 1, 2)),
                               rtol=1e-5, atol=1e-5)
    # moving stats must match (train-mode reduction over N,H,W only)
    np.testing.assert_allclose(
        bn1.running_mean.data().asnumpy(), bn2.running_mean.data().asnumpy(),
        rtol=1e-5, atol=1e-6)
    o1e = bn1(nd.array(xt)).asnumpy()
    o2e = bn2(nd.array(x)).asnumpy()
    np.testing.assert_allclose(o1e, np.transpose(o2e, (0, 3, 1, 2)),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.smoke
def test_resnet18_nhwc_forward_parity():
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1

    mx.random.seed(0)
    net1 = resnet18_v1()
    net1.initialize(mx.init.Xavier())
    x = np.random.rand(2, 3, 32, 32).astype(np.float32)
    out1 = net1(nd.array(x)).asnumpy()

    net2 = resnet18_v1(layout="NHWC")
    net2.initialize(mx.init.Xavier())
    net2(nd.array(_to_nhwc(x)))  # resolve deferred shapes
    p1, p2 = net1.collect_params(), net2.collect_params()
    k1s, k2s = sorted(p1.keys()), sorted(p2.keys())
    for k1, k2 in zip(k1s, k2s):
        a = p1[k1].data().asnumpy()
        if a.ndim == 4:
            a = a.transpose(0, 2, 3, 1)
        p2[k2].set_data(nd.array(a))
    out2 = net2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=1e-4, atol=2e-4)


def test_resnet_nhwc_train_step():
    """Hybridized fused train step in NHWC (the bench path) runs and learns."""
    import jax

    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    mx.random.seed(0)
    net = resnet18_v1(layout="NHWC")
    net.initialize(mx.init.Xavier())
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = DataParallelStep(
        net, loss_fn, mesh=local_mesh(devices=jax.devices("cpu")[:1]),
        optimizer="sgd", optimizer_params={"learning_rate": 0.05})
    x = nd.array(np.random.rand(4, 24, 24, 3).astype(np.float32))
    y = nd.array(np.random.randint(0, 10, 4).astype(np.float32))
    losses = [float(np.asarray(step.step(x, y))) for _ in range(4)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_deconv_nhwc_matches_nchw():
    x = np.random.rand(2, 4, 6, 6).astype(np.float32)
    d1 = nn.Conv2DTranspose(5, 3, strides=2, padding=1, output_padding=1,
                            in_channels=4)
    d1.initialize(mx.init.Xavier())
    out1 = d1(nd.array(x)).asnumpy()
    d2 = nn.Conv2DTranspose(5, 3, strides=2, padding=1, output_padding=1,
                            in_channels=4, layout="NHWC")
    d2.initialize(mx.init.Xavier())
    # IOHW -> IHWO
    d2.weight.set_data(nd.array(d1.weight.data().asnumpy().transpose(0, 2, 3, 1)))
    d2.bias.set_data(d1.bias.data())
    out2 = d2(nd.array(_to_nhwc(x))).asnumpy()
    np.testing.assert_allclose(_to_nhwc(out1), out2, rtol=1e-5, atol=1e-5)
