"""Gluon Block/HybridBlock/Trainer tests (reference model:
tests/python/unittest/test_gluon.py — the key behavioral spec per SURVEY §4.2)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.gluon import nn


def make_lenet():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(6, kernel_size=5, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Conv2D(16, kernel_size=3, activation="relu"),
                nn.MaxPool2D(2, 2),
                nn.Flatten(),
                nn.Dense(32, activation="relu"),
                nn.Dense(10))
    return net


def test_dense_deferred_init():
    net = nn.Dense(4)
    net.initialize()
    x = nd.random.uniform(shape=(2, 3))
    y = net(x)
    assert y.shape == (2, 4)
    assert net.weight.shape == (4, 3)
    assert net.bias.shape == (4,)


def test_parameter_api():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    params = net.collect_params()
    assert any(k.endswith("weight") for k in params.keys())
    w = net.weight.data()
    assert w.shape == (2, 3)
    net.weight.set_data(nd.ones((2, 3)))
    np.testing.assert_allclose(net.weight.data().asnumpy(), np.ones((2, 3)))
    g = net.weight.grad()
    assert g.shape == (2, 3)


def test_sequential_forward():
    net = make_lenet()
    net.initialize()
    x = nd.random.uniform(shape=(2, 1, 28, 28))
    y = net(x)
    assert y.shape == (2, 10)


def test_hybridize_matches_eager():
    net = make_lenet()
    net.initialize()
    x = nd.random.uniform(shape=(2, 1, 28, 28))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_hybrid, rtol=2e-5, atol=2e-5)
    # second call goes through the cached executable
    y2 = net(x).asnumpy()
    np.testing.assert_allclose(y_hybrid, y2, rtol=1e-6)


def test_hybridize_grad_matches_eager():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(1))
    net.initialize()
    x = nd.random.uniform(shape=(4, 5))

    def loss_grads():
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        return [p.grad().asnumpy().copy()
                for p in net.collect_params().values()]

    g_eager = loss_grads()
    net.hybridize()
    g_hybrid = loss_grads()
    for a, b in zip(g_eager, g_hybrid):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_batchnorm_running_stats_update():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = nd.random.normal(loc=5.0, scale=2.0, shape=(8, 3, 4, 4))
    rm0 = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    rm1 = net.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1), "running mean should move in training"
    # inference mode: stats not updated, used for normalization
    y = net(x)
    rm2 = net.running_mean.data().asnumpy()
    np.testing.assert_allclose(rm1, rm2)


def test_batchnorm_running_stats_update_hybridized():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    net.hybridize()
    x = nd.random.normal(loc=5.0, scale=2.0, shape=(8, 3, 4, 4))
    rm0 = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    rm1 = net.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1), \
        "hybridized BN must still update running stats (aux collector)"


def test_dropout_hybridized_differs_per_call():
    net = nn.Dropout(0.5)
    net.initialize()
    net.hybridize()
    x = nd.ones((100,))
    with autograd.record():
        y1 = net(x).asnumpy()
        y2 = net(x).asnumpy()
    assert not np.allclose(y1, y2), "different RNG keys per call"
    # eval mode: identity
    y3 = net(x).asnumpy()
    np.testing.assert_allclose(y3, np.ones(100))


def test_trainer_convergence():
    """Convergence smoke (reference: tests/python/train/) on synthetic
    separable data with a small MLP."""
    np.random.seed(0)
    n = 256
    x_np = np.random.randn(n, 10).astype(np.float32)
    w_true = np.random.randn(10, 3).astype(np.float32)
    y_np = np.argmax(x_np @ w_true, axis=1).astype(np.float32)

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x, y = nd.array(x_np), nd.array(y_np)

    for epoch in range(60):
        with autograd.record():
            out = net(x)
            loss = loss_fn(out, y)
        loss.backward()
        trainer.step(n)
    acc = mx.metric.Accuracy()
    acc.update(y, net(x))
    assert acc.get()[1] > 0.95, f"accuracy {acc.get()[1]} too low"


def test_trainer_adam_and_state_io(tmp_path):
    net = nn.Dense(2, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = nd.random.uniform(shape=(8, 4))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(8)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    trainer.load_states(f)


def test_save_load_parameters(tmp_path):
    net = make_lenet()
    net.initialize()
    x = nd.random.uniform(shape=(1, 1, 28, 28))
    y0 = net(x).asnumpy()
    f = str(tmp_path / "lenet.params")
    net.save_parameters(f)

    net2 = make_lenet()
    net2.load_parameters(f)
    y1 = net2(x).asnumpy()
    np.testing.assert_allclose(y0, y1, rtol=1e-5, atol=1e-6)


def test_constant_and_grad_req():
    class Scaled(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            with self.name_scope():
                self.const = self.params.get_constant(
                    "const", np.array([2.0], np.float32))

        def hybrid_forward(self, F, x, const):
            return x * const

    net = Scaled()
    net.initialize()
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = net(x)
    y.backward()
    np.testing.assert_allclose(y.asnumpy(), [6.0])
    np.testing.assert_allclose(x.grad.asnumpy(), [2.0])


def test_lstm_layer():
    lstm = gluon.rnn.LSTM(16, num_layers=2)
    lstm.initialize()
    x = nd.random.uniform(shape=(5, 3, 8))  # TNC
    out = lstm(x)
    assert out.shape == (5, 3, 16)
    states = lstm.begin_state(3)
    out, new_states = lstm(x, states)
    assert out.shape == (5, 3, 16)
    assert new_states[0].shape == (2, 3, 16)
    assert new_states[1].shape == (2, 3, 16)


def test_gru_bidirectional():
    gru = gluon.rnn.GRU(8, num_layers=1, bidirectional=True, layout="NTC")
    gru.initialize()
    x = nd.random.uniform(shape=(2, 7, 4))
    out = gru(x)
    assert out.shape == (2, 7, 16)


def test_lstm_cell_unroll():
    cell = gluon.rnn.LSTMCell(10)
    cell.initialize()
    x = nd.random.uniform(shape=(2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, merge_outputs=True)
    assert outputs.shape == (2, 5, 10)
    assert len(states) == 2


def test_dataloader():
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    xs = np.random.randn(20, 3).astype(np.float32)
    ys = np.arange(20).astype(np.float32)
    ds = ArrayDataset(xs, ys)
    loader = DataLoader(ds, batch_size=6, shuffle=True, last_batch="keep")
    seen = 0
    for data, label in loader:
        assert data.shape[1] == 3
        seen += data.shape[0]
    assert seen == 20


class _SquareTransformDataset:
    """Module-level (picklable) dataset with a GIL-bound python transform —
    the workload DataLoader process workers exist for."""

    def __init__(self, n=24, dim=9000):
        self._rng_data = np.arange(n * dim, dtype=np.float32).reshape(n, dim)

    def __len__(self):
        return len(self._rng_data)

    def __getitem__(self, i):
        row = self._rng_data[i]
        # pure-python loop: holds the GIL, so only processes parallelize it
        s = 0.0
        for k in range(64):
            s += (k % 7) * 0.5
        return row * 2.0 + s, np.float32(i)


def test_dataloader_process_workers_shm():
    """num_workers>0 default path: spawn process pool + shared-memory
    transport; order and values must match the serial loader exactly
    (reference: gluon/data/dataloader.py multiprocessing workers ~L400)."""
    from mxnet_tpu.gluon.data import DataLoader

    ds = _SquareTransformDataset()
    serial = DataLoader(ds, batch_size=5, last_batch="keep")
    workers = DataLoader(ds, batch_size=5, last_batch="keep", num_workers=2)
    got = list(workers)
    want = list(serial)
    assert len(got) == len(want) == len(workers)
    for (gd, gl), (wd, wl) in zip(got, want):
        # rows are >= _SHM_MIN_BYTES -> the shm path carried them
        np.testing.assert_allclose(gd.asnumpy(), wd.asnumpy())
        np.testing.assert_allclose(gl.asnumpy(), wl.asnumpy())
    # pool is persistent across iterations
    again = list(workers)
    assert len(again) == len(want)


def test_loss_functions():
    pred = nd.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    label = nd.array([2, 0])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    expected = -np.log(np.exp(3) / np.exp([1, 2, 3]).sum())
    np.testing.assert_allclose(l.asnumpy(), [expected, expected], rtol=1e-5)

    l2 = gluon.loss.L2Loss()(nd.array([1.0, 2.0]), nd.array([0.0, 0.0]))
    np.testing.assert_allclose(l2.asnumpy(), [0.5, 2.0])  # w/2 * (p-l)^2


def test_metrics():
    acc = mx.metric.Accuracy()
    acc.update(nd.array([0, 1, 1]), nd.array([[0.9, 0.1], [0.3, 0.7], [0.6, 0.4]]))
    assert abs(acc.get()[1] - 2.0 / 3) < 1e-6
    topk = mx.metric.TopKAccuracy(top_k=2)
    topk.update(nd.array([2]), nd.array([[0.3, 0.1, 0.2]]))
    assert topk.get()[1] == 1.0
    comp = mx.metric.CompositeEvalMetric()
    comp.add(mx.metric.Accuracy())
    comp.add(mx.metric.MAE())
    names, values = comp.get()
    assert len(names) == 2


def test_save_load_parameters_structural_roundtrip():
    """save_parameters uses scope-independent structural names, so loading
    into a freshly-built (even uninitialized) net works — reference
    gluon/block.py _collect_params_with_prefix semantics."""
    import os
    import tempfile

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd

    def build():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
        return net

    net = build()
    net.initialize(mx.init.Xavier())
    x = nd.array(np.random.RandomState(0).randn(4, 5).astype("float32"))
    y1 = net(x).asnumpy()
    f = os.path.join(tempfile.mkdtemp(), "p.params")
    net.save_parameters(f)
    net2 = build()
    net2.load_parameters(f)
    np.testing.assert_allclose(y1, net2(x).asnumpy(), rtol=1e-6, atol=1e-6)
