"""Superstep compiled training + persistent AOT executable cache
(docs/PERFORMANCE.md §Superstep & AOT executable cache): K steps per
compiled lax.scan dispatch with bitwise parity across modes, the
transparent MX_SUPERSTEP step() routing with its CPU-mesh gate, stacked
loss semantics, and the MX_EXECUTABLE_CACHE_DIR restart cache
(round-trip, corruption fallback, kill switch, supervised gang
restart)."""
import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import aot_cache, gluon, nd
from mxnet_tpu.parallel import (AsyncLoss, DataParallelStep,
                                StackedAsyncLoss, SuperstepLossView,
                                local_mesh, superstep_k)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tele(tmp_path):
    from mxnet_tpu import memwatch, telemetry

    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path / "tele"))
    yield telemetry
    telemetry.flush()
    telemetry.reset()
    memwatch.reset()


def _build(opt="sgd", one_dev=True, prefix=None):
    """prefix: pass a FIXED block prefix when the test needs two builds
    to share one executable fingerprint (param names are part of the
    restart-stable identity; gluon's global name counter would otherwise
    make every in-process rebuild a distinct program)."""
    import jax

    mx.random.seed(0)
    net = gluon.nn.Dense(4, prefix=prefix)
    net.initialize(mx.init.Xavier())
    mesh = (local_mesh(devices=[jax.devices()[0]]) if one_dev
            else local_mesh())
    return DataParallelStep(net, gluon.loss.L2Loss(), mesh=mesh,
                            optimizer=opt)


def _events(tele):
    tele.flush()
    return [json.loads(line)
            for f in glob.glob(os.path.join(tele.summary()["dir"],
                                            "rank-*.jsonl"))
            for line in open(f)]


def _batches(n, b=8, d=4):
    rng = np.random.RandomState(0)
    return [(nd.array(rng.rand(b, d).astype(np.float32)),
             nd.array(rng.rand(b, 4).astype(np.float32)))
            for _ in range(n)]


def _weights(step):
    import jax

    # gluon's global name counter gives each _build() a fresh block
    # prefix — strip it so runs compare
    return {n.split("_", 1)[-1]: np.asarray(jax.device_get(a))
            for n, a in step.params.items()}


def _run_mode(monkeypatch, batches, k, opt="sgd", one_dev=True):
    """Train len(batches) steps with MX_SUPERSTEP=k (0 = off) ->
    (per-step losses, final weights)."""
    monkeypatch.setenv("MX_SUPERSTEP", str(k))
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    step = _build(opt=opt, one_dev=one_dev)
    handles = [step.step(x, y) for x, y in batches]
    step.drain()
    losses = [np.asarray(h.asnumpy()) for h in handles]
    return losses, _weights(step)


# ---------------------------------------------------------------------------
# parity: superstep changes HOW MANY steps one dispatch carries, never
# what is computed
# ---------------------------------------------------------------------------
def test_losses_and_weights_bitwise_identical_across_superstep_modes(
        monkeypatch):
    """Acceptance: MX_SUPERSTEP=0, 1 and 4 produce bitwise-identical
    per-step losses AND final weights on the same model/data (CPU
    force-on, single-device mesh)."""
    batches = _batches(8)
    base_l, base_w = _run_mode(monkeypatch, batches, 0)
    for k in (1, 4):
        l, w = _run_mode(monkeypatch, batches, k)
        for i, (a, b) in enumerate(zip(base_l, l)):
            assert np.array_equal(a, b), (k, i, a, b)
        assert base_w.keys() == w.keys()
        for name in base_w:
            assert np.array_equal(base_w[name], w[name]), (k, name)


def test_adam_parity_and_lr_schedule_scans(monkeypatch):
    """Stateful optimizer (Adam's t counter rides the scan carry) and a
    per-step lr schedule (lr becomes a scanned array) both stay bitwise
    faithful to sequential dispatch."""
    import jax

    batches = _batches(8)

    def run(k):
        monkeypatch.setenv("MX_SUPERSTEP", str(k))
        monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
        from mxnet_tpu.optimizer.lr_scheduler import FactorScheduler

        mx.random.seed(0)
        net = gluon.nn.Dense(4)
        net.initialize(mx.init.Xavier())
        step = DataParallelStep(
            net, gluon.loss.L2Loss(),
            mesh=local_mesh(devices=[jax.devices()[0]]), optimizer="adam",
            optimizer_params={
                "learning_rate": 0.01,
                "lr_scheduler": FactorScheduler(step=2, factor=0.5)})
        handles = [step.step(x, y) for x, y in batches]
        step.drain()
        return ([np.asarray(h.asnumpy()) for h in handles],
                _weights(step))

    l0, w0 = run(0)
    l4, w4 = run(4)
    for a, b in zip(l0, l4):
        assert np.array_equal(a, b)
    for name in w0:
        assert np.array_equal(w0[name], w4[name]), name


def test_scan_family_self_consistent_across_lengths_multi_device(
        monkeypatch):
    """On a multi-device mesh the scan executable family (K=1, 2, 4 —
    incl. partial-group lengths) is bitwise self-consistent: chunking
    never changes the trajectory.  (The plain non-scan path may differ
    from the scan family at ~1 ulp on multi-device meshes — XLA fuses
    the inlined body differently — which is why the 0-vs-K acceptance
    parity is asserted on a single-device mesh above.)"""
    batches = _batches(8)
    l1, w1 = _run_mode(monkeypatch, batches, 1, one_dev=False)
    for k in (2, 4):
        l, w = _run_mode(monkeypatch, batches, k, one_dev=False)
        for a, b in zip(l1, l):
            assert np.array_equal(a, b), k
        for name in w1:
            assert np.array_equal(w1[name], w[name]), (k, name)


def test_explicit_superstep_matches_sequential(monkeypatch):
    batches = _batches(8)
    base_l, base_w = _run_mode(monkeypatch, batches, 0)
    monkeypatch.setenv("MX_SUPERSTEP", "0")
    step = _build()
    h1 = step.superstep(batches[:4])
    h2 = step.superstep(batches[4:])
    step.drain()
    got = list(h1.asnumpy()) + list(h2.asnumpy())
    for a, b in zip(base_l, got):
        assert np.array_equal(np.asarray(a).ravel(), np.asarray(b).ravel())
    w = _weights(step)
    for name in base_w:
        assert np.array_equal(base_w[name], w[name]), name


# ---------------------------------------------------------------------------
# transparent-mode semantics
# ---------------------------------------------------------------------------
def test_superstep_defaults_off_on_cpu_mesh(monkeypatch):
    """Acceptance: MX_SUPERSTEP=4 WITHOUT the force override is inert on
    a CPU mesh — step() stays on the plain path and returns a plain
    AsyncLoss, not a superstep view."""
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.delenv("MX_SUPERSTEP_FORCE_CPU", raising=False)
    step = _build()
    assert superstep_k(step.mesh) == 0
    h = step.step(*_batches(1)[0])
    assert isinstance(h, AsyncLoss)
    assert not isinstance(h, SuperstepLossView)
    assert step._open_group is None
    step.drain()
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    assert superstep_k(step.mesh) == 4


def test_stacked_loss_semantics_and_views(monkeypatch):
    """StackedAsyncLoss: len/vector/scalar contracts; views resolve to
    their own step's loss; forcing a view mid-group dispatches the
    partial group as a shorter scan (no deadlock, order preserved)."""
    batches = _batches(8)
    base_l, _ = _run_mode(monkeypatch, batches, 0)
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    step = _build()
    v0 = step.step(*batches[0])
    v1 = step.step(*batches[1])
    assert isinstance(v0, SuperstepLossView)
    assert len(step._open_group.entries) == 2
    # forcing v0 dispatches the partial (K'=2) group
    assert np.array_equal(np.asarray(v0.asnumpy()), np.asarray(base_l[0]))
    assert step._open_group is None
    # remaining steps open a fresh group; explicit superstep returns the
    # stacked handle with vector + scalar semantics
    h = step.superstep(batches[2:6])
    assert isinstance(h, StackedAsyncLoss)
    assert len(h) == 4
    vec = h.asnumpy()
    assert vec.shape == (4,)
    assert float(h) == vec[-1]
    assert h.steps == (3, 4, 5, 6)
    for i, v in enumerate(vec):
        assert np.array_equal(np.float32(v),
                              np.float32(np.asarray(base_l[2 + i]))), i
    assert np.array_equal(np.asarray(v1.asnumpy()), base_l[1])
    step.drain()


def test_superstep_one_step_event_one_compile_per_group(monkeypatch, tele):
    """One telemetry step event (superstep=K, samples summed over the
    group) and ONE compile event per superstep executable — not one per
    covered step."""
    from mxnet_tpu import memwatch

    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    batches = _batches(8)
    step = _build()
    for x, y in batches:
        step.step(x, y)
    step.drain()
    evs = _events(tele)
    steps = [e for e in evs if e.get("kind") == "step"
             and e.get("executor", "").startswith("DataParallelStep")]
    assert len(steps) == 2, steps
    assert all(e["superstep"] == 4 for e in steps)
    assert all(e["samples"] == 4 * 8 for e in steps)
    assert [e["step"] for e in steps] == [4, 8]
    comps = [e for e in evs if e.get("kind") == "compile"
             and e.get("site") == "superstep"]
    assert len(comps) == 1, comps
    assert memwatch.summary()["compiles"]["count"] == 1


def test_superstep_rides_inflight_ring(monkeypatch, tele):
    """The in-flight window bounds dispatched SUPERSTEPS: one ring
    admission per group, depth never exceeds MX_ASYNC_INFLIGHT."""
    monkeypatch.setenv("MX_SUPERSTEP", "2")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    monkeypatch.setenv("MX_ASYNC_INFLIGHT", "2")
    step = _build()
    for x, y in _batches(12):
        step.step(x, y)
    step.drain()
    evs = _events(tele)
    depths = [e["inflight_depth"] for e in evs if e.get("kind") == "step"]
    assert depths and max(depths) <= 2, depths


def test_superstep_with_device_prefetcher(monkeypatch, tele):
    """DevicePrefetchIter auto-sizes its queue to K and its staged
    batches are consumed without a second H2D (h2d_overlapped > 0 on
    superstep records); losses match the unprefetched run bitwise."""
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    batches = _batches(8)

    class _Iter(mx.io.DataIter):
        def __init__(self):
            super().__init__(batch_size=8)
            self.i = 0

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= len(batches):
                raise StopIteration
            x, y = batches[self.i]
            self.i += 1
            return mx.io.DataBatch([x], [y])

    base_l, base_w = _run_mode(monkeypatch, batches, 4)
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    step = _build()
    it = mx.io.DevicePrefetchIter(_Iter(), step)
    assert it._QUEUE_DEPTH == 4
    views = [step.step(b.data[0], b.label[0]) for b in it]
    step.drain()
    for a, b in zip(base_l, [np.asarray(v.asnumpy()) for v in views]):
        assert np.array_equal(a, b)
    w = _weights(step)
    for name in base_w:
        assert np.array_equal(base_w[name], w[name])
    evs = _events(tele)
    sups = [e for e in evs if e.get("kind") == "step" and e.get("superstep")]
    assert sups and any(e.get("h2d_overlapped", 0) > 0 for e in sups)


def test_ragged_final_batch_closes_group_instead_of_crashing(monkeypatch):
    """A shape change mid-group (the classic no-drop-last final batch)
    flushes the open group as a shorter scan and starts a fresh one —
    the buffered full steps land instead of dying in jnp.stack."""
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    rng = np.random.RandomState(0)
    full = [(nd.array(rng.rand(8, 4).astype(np.float32)),
             nd.array(rng.rand(8, 4).astype(np.float32)))
            for _ in range(3)]
    tail = (nd.array(rng.rand(5, 4).astype(np.float32)),
            nd.array(rng.rand(5, 4).astype(np.float32)))

    def run(k):
        monkeypatch.setenv("MX_SUPERSTEP", str(k))
        step = _build()
        views = [step.step(x, y) for x, y in full + [tail]]
        step.drain()
        return ([np.asarray(v.asnumpy()) for v in views], _weights(step))

    base_l, base_w = run(0)
    l, w = run(4)
    for a, b in zip(base_l, l):
        assert np.array_equal(a, b)
    for name in base_w:
        assert np.array_equal(base_w[name], w[name]), name


def test_dispatched_group_releases_its_input_buffers(monkeypatch):
    """Loss views outlive their group; the group's K placed input
    buffers must not ride along (an epoch of retained views would pin
    every batch on device)."""
    monkeypatch.setenv("MX_SUPERSTEP", "2")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    step = _build()
    views = [step.step(x, y) for x, y in _batches(4)]
    step.drain()
    for v in views:
        group = v._dispatch_fn.__defaults__[0]
        assert group.handle is not None
        assert group.entries == []
    # and the views still resolve after the release
    assert all(np.isfinite(float(np.asarray(v.asnumpy()))) for v in views)


def test_aot_alternating_signatures_reuse_in_memory(tmp_path, tele,
                                                    monkeypatch):
    """Two interleaved input shapes each deserialize/compile at most
    once — subsequent steps reuse the per-signature executable in
    memory instead of re-reading the disk entry every step."""
    cache = tmp_path / "aot"
    monkeypatch.setenv("MX_EXECUTABLE_CACHE_DIR", str(cache))
    loads = []
    real_load = aot_cache.load
    monkeypatch.setattr(aot_cache, "load",
                        lambda key: loads.append(key) or real_load(key))
    rng = np.random.RandomState(0)
    a = (nd.array(rng.rand(8, 4).astype(np.float32)),
         nd.array(rng.rand(8, 4).astype(np.float32)))
    b = (nd.array(rng.rand(4, 4).astype(np.float32)),
         nd.array(rng.rand(4, 4).astype(np.float32)))
    step = _build()
    for _ in range(5):
        step.step(*a)
        step.step(*b)
    step.drain()
    assert len(step._aot_execs) == 2
    assert len(loads) == 2, loads


def test_superstep_deferred_error_names_step(monkeypatch):
    """A chaos fault injected mid-group surfaces at the group dispatch
    wrapped with the failing step's number; the ring never wedges."""
    from mxnet_tpu.base import MXNetError

    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    monkeypatch.setenv("MX_FAULT_SPEC", "oom:step=3")
    step = _build()
    batches = _batches(4)
    step.step(*batches[0])
    step.step(*batches[1])
    with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
        for x, y in batches[2:]:
            step.step(x, y)
        step.drain()
    monkeypatch.delenv("MX_FAULT_SPEC")
    # the step object keeps working after the poisoned group
    h = step.step(*batches[0])
    step.drain()
    assert np.isfinite(float(h.asnumpy().ravel()[-1]))


# ---------------------------------------------------------------------------
# AOT executable cache
# ---------------------------------------------------------------------------
_CACHE_SCRIPT = r"""
import os, sys, json, time
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, sys.argv[1])
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, memwatch, nd, telemetry
from mxnet_tpu.parallel import DataParallelStep, local_mesh
import jax

telemetry.enable(sys.argv[2])
mx.random.seed(0)
net = gluon.nn.Dense(4)
net.initialize(mx.init.Xavier())
step = DataParallelStep(net, gluon.loss.L2Loss(),
                        mesh=local_mesh(devices=[jax.devices()[0]]),
                        optimizer="adam")
rng = np.random.RandomState(0)
x = nd.array(rng.rand(8, 4).astype(np.float32))
y = nd.array(rng.rand(8, 4).astype(np.float32))
t0 = time.perf_counter()
losses = [float(step.step(x, y)) for _ in range(2)]
ttfs = time.perf_counter() - t0
h = step.superstep([(x, y)] * 3)  # superstep executable cached too
losses += [float(v) for v in np.asarray(h.asnumpy())]
step.drain()
# fused-updater site via a toy Trainer
net2 = gluon.nn.Dense(3)
net2.initialize(mx.init.Xavier())
tr = gluon.Trainer(net2.collect_params(), "sgd",
                   {"learning_rate": 1e-3, "momentum": 0.9})
with autograd.record():
    l2 = (net2(x) ** 2).sum()
l2.backward()
tr.step(8)
tr.drain()
telemetry.flush()
print(json.dumps({"losses": losses,
                  "compiles": memwatch.summary()["compiles"]}))
"""


def _run_cache_proc(tele_dir, cache_dir, extra_env=None):
    env = dict(os.environ, MX_EXECUTABLE_CACHE_DIR=str(cache_dir))
    env.pop("MX_SUPERSTEP", None)
    env.update(extra_env or {})
    res = subprocess.run(
        [sys.executable, "-c", _CACHE_SCRIPT, _REPO, str(tele_dir)],
        capture_output=True, text=True, timeout=240, env=env, cwd=_REPO)
    assert res.returncode == 0, (res.stdout, res.stderr)
    return json.loads(res.stdout.strip().splitlines()[-1])


def _compile_events(tele_dir):
    evs = [json.loads(line)
           for f in glob.glob(os.path.join(str(tele_dir), "rank-*.jsonl"))
           for line in open(f)]
    return [e for e in evs if e.get("kind") == "compile"]


@pytest.mark.slow
def test_aot_cache_restart_round_trip_two_processes(tmp_path):
    """Acceptance: the second process books ZERO fresh compiles at the
    DataParallelStep (single-step + superstep) and FusedUpdater jit
    sites — every compile event carries cache_hit + deserialize_ms —
    and computes bitwise-identical losses.  (Sequential by necessity:
    process B needs process A's cache on disk.)"""
    cache = tmp_path / "aot"
    a = _run_cache_proc(tmp_path / "tele_a", cache)
    assert a["compiles"]["cache_hits"] == 0
    assert len(glob.glob(str(cache / "*.jexec"))) >= 3
    b = _run_cache_proc(tmp_path / "tele_b", cache)
    assert b["losses"] == a["losses"]
    evs = _compile_events(tmp_path / "tele_b")
    assert evs, "second process booked no compile events at all"
    fresh = [e for e in evs if not e.get("cache_hit")]
    assert not fresh, f"second process compiled fresh: {fresh}"
    assert all(e.get("deserialize_ms", 0) > 0 for e in evs)
    assert b["compiles"]["cache_hits"] == len(evs)


def test_aot_corrupt_entry_falls_back_cleanly(tmp_path, tele, monkeypatch):
    """Truncated and garbage cache entries are a MISS, never a crash:
    the site recompiles fresh (cache_corrupt marked) and overwrites the
    bad entry with a good one."""
    cache = tmp_path / "aot"
    monkeypatch.setenv("MX_EXECUTABLE_CACHE_DIR", str(cache))
    batches = _batches(2)
    # fixed prefix: rebuilds must share the executable fingerprint, as a
    # restarted process would (gluon's name counter resets per process)
    s1 = _build(prefix="sstep_")
    l1 = [np.asarray(s1.step(x, y).asnumpy()) for x, y in batches]
    s1.drain()
    files = glob.glob(str(cache / "*.jexec"))
    assert len(files) == 1
    good = open(files[0], "rb").read()
    key = os.path.basename(files[0])[:-len(".jexec")]

    for blob in (good[: len(good) // 2], b"not a pickle at all"):
        with open(files[0], "wb") as f:
            f.write(blob)
        loaded, info = aot_cache.load(key)
        assert loaded is None and info.get("cache_corrupt")
        s2 = _build(prefix="sstep_")
        l2 = [np.asarray(s2.step(x, y).asnumpy()) for x, y in batches]
        s2.drain()
        for x, y_ in zip(l1, l2):
            assert np.array_equal(x, y_)
        # the fresh compile overwrote the corrupt entry with a loadable one
        loaded, info = aot_cache.load(key)
        assert loaded is not None and info.get("cache_hit"), info


def test_aot_kill_switch_disables_all_persistence(tmp_path, tele,
                                                  monkeypatch):
    """Acceptance: MX_EXECUTABLE_CACHE=0 disables AOT persistence even
    with a cache dir set — nothing written, nothing loaded, compile
    events carry no cache fields."""
    cache = tmp_path / "aot"
    cache.mkdir()
    monkeypatch.setenv("MX_EXECUTABLE_CACHE_DIR", str(cache))
    monkeypatch.setenv("MX_EXECUTABLE_CACHE", "0")
    assert not aot_cache.enabled()
    step = _build()
    for x, y in _batches(2):
        step.step(x, y)
    step.drain()
    assert glob.glob(str(cache / "*")) == []
    tele.flush()
    evs = _compile_events(str(tele._state.dir))
    assert evs and all("cache_hit" not in e for e in evs)
    # and without a dir at all the cache is simply off
    monkeypatch.delenv("MX_EXECUTABLE_CACHE")
    monkeypatch.delenv("MX_EXECUTABLE_CACHE_DIR")
    assert not aot_cache.enabled()


def test_mem_report_marks_cached_executables(tmp_path):
    """tools/mem_report.py's executable table distinguishes "loaded in
    0.2s" (aot column: hit) from "compiled in 40s" (aot column: -)."""
    lines = [
        {"t": 1.0, "kind": "compile", "rank": 0,
         "executor": "DataParallelStep:Dense#1",
         "fingerprint": "ab12cd34ef56ab12", "site": "superstep",
         "wall_ms": 40000.0},
        {"t": 2.0, "kind": "compile", "rank": 0,
         "executor": "DataParallelStep:Dense#2",
         "fingerprint": "ab12cd34ef56ab13", "site": "superstep",
         "wall_ms": 210.0, "cache_hit": True, "deserialize_ms": 180.0},
    ]
    with open(tmp_path / "rank-0.jsonl", "w") as f:
        for line in lines:
            f.write(json.dumps(line) + "\n")
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "mem_report.py"),
         str(tmp_path), "--json"],
        capture_output=True, text=True, timeout=60)
    assert res.returncode == 0, (res.stdout, res.stderr)
    rep = json.loads(res.stdout)
    by_fp = {r["fingerprint"]: r for r in rep["executables"]}
    assert by_fp["ab12cd34ef56ab12"]["cache_hit"] is False
    assert by_fp["ab12cd34ef56ab13"]["cache_hit"] is True
    assert by_fp["ab12cd34ef56ab13"]["deserialize_ms"] == 180.0
    txt = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "mem_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert "hit(0.2s)" in txt.stdout, txt.stdout


# ---------------------------------------------------------------------------
# supervised gang kill-and-restart with a warm cache (slow e2e)
# ---------------------------------------------------------------------------
def _launch_ssr(tmp_path, phase, extra_env=None, launcher_args=(),
                timeout=300):
    env = dict(os.environ,
               MX_SSR_PHASE=phase, MX_SSR_DIR=str(tmp_path),
               MX_SUPERSTEP="4", MX_SUPERSTEP_FORCE_CPU="1",
               MX_EXECUTABLE_CACHE_DIR=str(tmp_path / "aot"),
               MX_TELEMETRY_FLUSH_SEC="0.2")
    env.update(extra_env or {})
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", "2", "--force-cpu", "--restart-backoff", "0.2",
           *launcher_args, "--",
           sys.executable,
           os.path.join(_REPO, "tests", "dist",
                        "superstep_restart_worker.py")]
    return subprocess.run(cmd, timeout=timeout, capture_output=True,
                          text=True, env=env, cwd=_REPO)


@pytest.mark.dist
@pytest.mark.slow
@pytest.mark.chaos
def test_supervised_restart_with_warm_cache_resumes_bitwise(tmp_path):
    """Acceptance (slow gang e2e): rank 1 dies mid-run at step 24,
    tools/launch.py --max-restarts re-spawns the gang, the restarted
    incarnation resumes from the step-20 checkpoint with a WARM AOT
    cache (zero fresh scan compiles) and finishes bitwise-identical to
    the uninterrupted baseline."""
    res0 = _launch_ssr(tmp_path, "baseline")
    assert res0.returncode == 0, (res0.stdout[-2000:], res0.stderr[-1000:])
    assert res0.stdout.count("baseline OK") == 2, res0.stdout

    res = _launch_ssr(tmp_path, "supervised",
                      launcher_args=("--max-restarts", "1",
                                     "--term-timeout", "5"))
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert "self-kill at step 24" in res.stdout
    assert "restarting gang (1/1)" in res.stderr
    assert "rank 1: incarnation 1 resuming at step 20" in res.stdout
    assert "warm-cache restart OK" in res.stdout
    # rank 1's final incarnation must match; rank 0 matches in whichever
    # incarnation(s) it completed (it may finish before the gang dies,
    # then re-verify at resume — two prints are legitimate)
    assert "rank 1: matches uninterrupted baseline" in res.stdout
    assert "rank 0: matches uninterrupted baseline" in res.stdout


# ---------------------------------------------------------------------------
# preemption-path flush of buffered groups (ISSUE 12 satellite: the PR 9
# known issue — drain_all() used to skip buffered-but-undispatched
# _SuperstepGroup entries, silently dropping up to K-1 steps from a
# SIGTERM's final sync checkpoint)
# ---------------------------------------------------------------------------
def test_drain_all_flushes_buffered_superstep_groups(monkeypatch):
    """drain_all DISPATCHES an open partial group (as a shorter scan)
    before draining the rings — the buffered steps land in the params
    instead of vanishing."""
    from mxnet_tpu.parallel import async_loss

    batches = _batches(6)
    base_l, base_w = _run_mode(monkeypatch, batches, 0)
    monkeypatch.setenv("MX_SUPERSTEP", "4")
    monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
    step = _build()
    for x, y in batches:  # 4 dispatch as one group, 2 stay buffered
        step.step(x, y)
    assert step._open_group is not None \
        and len(step._open_group.entries) == 2
    errors = async_loss.drain_all()
    assert errors == []
    assert step._open_group is None or not step._open_group.entries
    w = _weights(step)
    for name in base_w:
        assert np.array_equal(base_w[name], w[name]), name


_PREEMPT_SUPERSTEP_WORKER = """\
import os, sys, time
sys.path.insert(0, {repo!r})
os.environ["MX_SUPERSTEP"] = "4"
os.environ["MX_SUPERSTEP_FORCE_CPU"] = "1"
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu import checkpoint, fault, gluon, nd
from mxnet_tpu.parallel import DataParallelStep, local_mesh

ckdir = sys.argv[1]
mx.random.seed(0)
net = gluon.nn.Dense(4)
net.initialize(mx.init.Xavier())
step = DataParallelStep(net, gluon.loss.L2Loss(),
                        mesh=local_mesh(devices=[jax.devices()[0]]),
                        optimizer="sgd")
ckpt = checkpoint.AsyncCheckpointer(ckdir, save_every=1000)
fault.install_preemption_handler(ckpt, step)
rng = np.random.RandomState(0)
batches = [(nd.array(rng.rand(8, 4).astype(np.float32)),
            nd.array(rng.rand(8, 4).astype(np.float32)))
           for _ in range(6)]
for x, y in batches:
    step.step(x, y)
    ckpt.step(step)
# 4 steps dispatched as one scan; steps 5-6 still buffered when SIGTERM hits
assert step._open_group is not None and len(step._open_group.entries) == 2
open(os.path.join(ckdir, "ready"), "w").close()
while True:
    time.sleep(0.05)
"""


@pytest.mark.chaos
def test_preemption_checkpoint_includes_buffered_superstep_steps(tmp_path):
    """CHAOS acceptance for the satellite: SIGTERM lands with 2 of 6
    steps still buffered in an open K=4 group; the final preemption
    checkpoint must carry ALL 6 steps' updates (bitwise vs the 6-step
    sequential oracle), not silently drop the buffered two."""
    import signal
    import subprocess as sp
    import time as _time

    from mxnet_tpu import checkpoint

    # sequential oracle in-process
    mp = pytest.MonkeyPatch()
    try:
        batches = _batches(6)
        _l, oracle = _run_mode(mp, batches, 0)
    finally:
        mp.undo()

    ckdir = tmp_path / "ck"
    os.makedirs(ckdir)
    script = tmp_path / "worker.py"
    script.write_text(_PREEMPT_SUPERSTEP_WORKER.format(repo=_REPO))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = sp.Popen([sys.executable, str(script), str(ckdir)], env=env,
                    stdout=sp.PIPE, stderr=sp.PIPE, text=True)
    ready = ckdir / "ready"
    deadline = _time.monotonic() + 240
    while not ready.exists():
        assert proc.poll() is None, proc.communicate()
        assert _time.monotonic() < deadline, "worker never became ready"
        _time.sleep(0.1)
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=120)
    assert proc.returncode == 83, (out, err[-2000:])
    assert "final checkpoint at step 6" in out, (out, err[-1000:])
    state = checkpoint.load_checkpoint_state(str(ckdir))
    assert state["step"] == 6
    for name in oracle:
        got = state["params"][name].asnumpy()
        assert np.array_equal(oracle[name], got), name
