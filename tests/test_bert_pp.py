"""Pipeline-parallel BERT (models/bert_pp.py): scan-vs-pipeline parity,
dp×pp training through DataParallelStep, stacked-param sharding."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.models import bert_pp_small
from mxnet_tpu.models.bert_pp import bert_pp_sharding_rules
from mxnet_tpu.parallel import DataParallelStep, make_mesh, local_mesh


def _mlm_loss():
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    return mlm


def _data(B=8, T=16, V=512):
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, V, (B, T)).astype(np.int32)
    return tokens, tokens.astype(np.float32)


def _run(mesh, steps=4, **step_kwargs):
    mx.random.seed(3)
    net = bert_pp_small()
    net.initialize(mx.init.Normal(0.02))
    step = DataParallelStep(net, _mlm_loss(), mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            rules=bert_pp_sharding_rules(), **step_kwargs)
    tokens, labels = _data()
    losses = []
    for _ in range(steps):
        loss = step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
        losses.append(float(np.asarray(loss)))
    return losses, step


def test_pp_bert_matches_dp_only():
    """The SAME model trained dp4 (scan path, pp=1) and dp2×pp2 (GPipe
    path) must follow the same loss trajectory — the pipeline schedule is
    semantics-preserving end to end (fwd + bwd + adam)."""
    import jax

    devices = jax.devices("cpu")[:4]
    dp_losses, _ = _run(make_mesh(devices=devices))          # dp4
    pp_losses, step = _run(make_mesh(pp=2, devices=devices))  # dp2 x pp2
    np.testing.assert_allclose(pp_losses, dp_losses, rtol=2e-4,
                               err_msg=f"{pp_losses} vs {dp_losses}")
    assert dp_losses[-1] < dp_losses[0]
    # stacked encoder params actually carry the pp sharding
    enc = [n for n in step.params if "enc_stack" in n]
    assert enc and all(
        "pp" in str(step.params[n].sharding.spec) for n in enc)


def test_pp_microbatch_validation():
    import jax

    mesh = make_mesh(pp=2, devices=jax.devices("cpu")[:2])
    mx.random.seed(0)
    net = bert_pp_small()
    net.initialize(mx.init.Normal(0.02))
    step = DataParallelStep(net, _mlm_loss(), mesh=mesh,
                            rules=bert_pp_sharding_rules(),
                            pp_microbatches=3)
    tokens, labels = _data(B=8)
    with pytest.raises(mx.MXNetError):
        step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
    with pytest.raises(mx.MXNetError):
        DataParallelStep(net, _mlm_loss(), pp_microbatches=0)


def test_stacked_encoder_eager_scan_matches_pipeline_off_mesh():
    """Eager forward (scan) == forward under a pp scope on a pp-only mesh."""
    import jax

    from mxnet_tpu.parallel.scope import pipeline_parallel_scope

    mx.random.seed(1)
    net = bert_pp_small(num_layers=2)
    net.initialize(mx.init.Normal(0.02))
    tokens, _ = _data(B=4)
    tb = nd.array(tokens, dtype="int32")
    ref = net(tb).asnumpy()
    mesh = make_mesh(pp=2, devices=jax.devices("cpu")[:2])
    with pipeline_parallel_scope(mesh, (), microbatches=2):
        got = net(tb).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)
