"""Pipeline-parallel BERT (models/bert_pp.py): scan-vs-pipeline parity,
dp×pp training through DataParallelStep, stacked-param sharding."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.models import bert_pp_small
from mxnet_tpu.models.bert_pp import bert_pp_sharding_rules
from mxnet_tpu.parallel import DataParallelStep, make_mesh, local_mesh


def _mlm_loss():
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    return mlm


def _data(B=8, T=16, V=512):
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, V, (B, T)).astype(np.int32)
    return tokens, tokens.astype(np.float32)


def _run(mesh, steps=4, **step_kwargs):
    mx.random.seed(3)
    net = bert_pp_small()
    net.initialize(mx.init.Normal(0.02))
    step = DataParallelStep(net, _mlm_loss(), mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            rules=bert_pp_sharding_rules(), **step_kwargs)
    tokens, labels = _data()
    losses = []
    for _ in range(steps):
        loss = step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
        losses.append(float(np.asarray(loss)))
    return losses, step


def test_pp_bert_matches_dp_only():
    """The SAME model trained dp4 (scan path, pp=1) and dp2×pp2 (GPipe
    path) must follow the same loss trajectory — the pipeline schedule is
    semantics-preserving end to end (fwd + bwd + adam)."""
    import jax

    devices = jax.devices("cpu")[:4]
    dp_losses, _ = _run(make_mesh(devices=devices))          # dp4
    pp_losses, step = _run(make_mesh(pp=2, devices=devices))  # dp2 x pp2
    # 2e-3: this jax build's GSPMD collectives drift ~1e-3 relative vs the
    # dp-only trajectory over a few optimizer steps — don't tighten
    np.testing.assert_allclose(pp_losses, dp_losses, rtol=2e-3,
                               err_msg=f"{pp_losses} vs {dp_losses}")
    assert dp_losses[-1] < dp_losses[0]
    # stacked encoder params actually carry the pp sharding
    enc = [n for n in step.params if "enc_stack" in n]
    assert enc and all(
        "pp" in str(step.params[n].sharding.spec) for n in enc)


def test_pp_microbatch_validation():
    import jax

    mesh = make_mesh(pp=2, devices=jax.devices("cpu")[:2])
    mx.random.seed(0)
    net = bert_pp_small()
    net.initialize(mx.init.Normal(0.02))
    step = DataParallelStep(net, _mlm_loss(), mesh=mesh,
                            rules=bert_pp_sharding_rules(),
                            pp_microbatches=3)
    tokens, labels = _data(B=8)
    with pytest.raises(mx.MXNetError):
        step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
    with pytest.raises(mx.MXNetError):
        DataParallelStep(net, _mlm_loss(), pp_microbatches=0)


def test_stacked_encoder_eager_scan_matches_pipeline_off_mesh():
    """Eager forward (scan) == forward under a pp scope on a pp-only mesh."""
    import jax

    from mxnet_tpu.parallel.scope import pipeline_parallel_scope

    mx.random.seed(1)
    net = bert_pp_small(num_layers=2)
    net.initialize(mx.init.Normal(0.02))
    tokens, _ = _data(B=4)
    tb = nd.array(tokens, dtype="int32")
    ref = net(tb).asnumpy()
    mesh = make_mesh(pp=2, devices=jax.devices("cpu")[:2])
    with pipeline_parallel_scope(mesh, (), microbatches=2):
        got = net(tb).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)


def test_bert_fused_warmup_decay_schedule():
    """r4 verdict #3 done-criterion: BERT trains through the fused path
    with a warmup+decay schedule, and the schedule visibly changes the
    updates (warmup ramps lr up, decay brings it down) with no retrace."""
    from mxnet_tpu.optimizer.lr_scheduler import PolyScheduler

    mx.random.seed(5)
    net = bert_pp_small(num_layers=2)
    net.initialize(mx.init.Normal(0.02))
    sched = PolyScheduler(max_update=8, base_lr=1e-3, pwr=1, final_lr=0.0,
                          warmup_steps=3, warmup_begin_lr=0.0)
    step = DataParallelStep(net, _mlm_loss(), mesh=local_mesh(),
                            optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3,
                                              "lr_scheduler": sched},
                            clip_global_norm=1.0)
    tokens, labels = _data(B=8)
    lrs, norms = [], []
    prev = None
    for _ in range(6):
        lrs.append(step.learning_rate)  # lr the upcoming step will use
        step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
        cur = {n: np.asarray(v) for n, v in step.params.items()}
        if prev is not None:
            delta = np.sqrt(sum(
                float(((cur[n] - prev[n]) ** 2).sum()) for n in cur
                if "embed" not in n))
            norms.append(delta)
        prev = cur
    # warmup (num_update is 1-based): lr ramps base/3 -> 2base/3 -> base,
    # then poly-decays
    assert lrs[0] == pytest.approx(1e-3 / 3, rel=1e-5)
    assert lrs[0] < lrs[1] < lrs[2], lrs
    assert lrs[2] == pytest.approx(1e-3, rel=1e-6)
    assert lrs[3] < lrs[2] and lrs[4] < lrs[3], lrs
    assert all(n > 0 for n in norms)  # every lr>0 step moved the params


def test_pp_tp_dp_3d_parity():
    """Full 3D parallelism in ONE program: dp2 x pp2 x tp2 over 8 devices
    (GPipe schedule over pp, Megatron column/row shards + psum inside the
    stage, dp-sharded batch) matches plain dp8 training exactly."""
    import jax

    devices = jax.devices("cpu")[:8]
    d3_losses, step = _run(make_mesh(pp=2, tp=2, devices=devices),
                           pp_microbatches=2)
    dp_losses, _ = _run(make_mesh(devices=devices), pp_microbatches=2)
    # 2e-3: same GSPMD collective drift as test_pp_bert_matches_dp_only
    np.testing.assert_allclose(d3_losses, dp_losses, rtol=2e-3,
                               err_msg=f"{d3_losses} vs {dp_losses}")
    qkv = [n for n in step.params if n.endswith("qkv_weight")]
    spec = str(step.params[qkv[0]].sharding.spec)
    assert "pp" in spec and "tp" in spec, spec
