"""Unified parallelism Plan + analytic auto-sharding planner
(docs/PERFORMANCE.md §Plan & planner).

Four invariants:
  1. the five legacy strategy entry points (dp kwargs, ShardingRules tp,
     pipeline, ring, ulysses) produce Plans whose compiled step is
     BITWISE identical to the pre-refactor kwargs path on the same mesh
     (same mesh => same program; cross-mesh comparisons keep the
     documented ~1e-3 GSPMD tolerance of test_parallel);
  2. the planner's cost model is hand-checkable: on the three synthetic
     fixtures (dp-wins, tp-wins, memory-forces-sharding) it ranks the
     known-optimal layout first, with every cost term matching the
     closed-form formulas;
  3. every enumerated Plan is LEGAL (axes exist, specs divide shapes,
     stages divide layers, batch divides over dp) and serializes
     losslessly;
  4. the platform features — superstep scan, AOT executable cache,
     elastic reshard — work THROUGH the Plan path, plus the PR-satellite
     AOT coverage of kvstore._reduce_collective and CachedOp.__call__.
"""
import glob
import json
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.parallel import (DataParallelStep, Plan,
                                compile_step_with_plan, dp_plan, local_mesh,
                                make_mesh, pipeline_plan, ring_plan,
                                tensor_parallel_plan, ulysses_plan)
from mxnet_tpu.parallel import planner
from mxnet_tpu.parallel.planner import Hardware, ModelSignature
from mxnet_tpu.parallel.sharding import ShardingRules

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def tele(tmp_path):
    from mxnet_tpu import memwatch, telemetry

    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path / "tele"))
    yield telemetry
    telemetry.flush()
    telemetry.reset()
    memwatch.reset()


def _events(tele):
    tele.flush()
    return [json.loads(line)
            for f in glob.glob(os.path.join(tele.summary()["dir"],
                                            "rank-*.jsonl"))
            for line in open(f)]


# ---------------------------------------------------------------------------
# Plan dataclass: validation, serialization, factories
# ---------------------------------------------------------------------------
def test_plan_validation():
    with pytest.raises(MXNetError):   # duplicate axis
        Plan(mesh_axes=(("dp", 2), ("dp", 2)))
    with pytest.raises(MXNetError):   # axis size < 1
        Plan(mesh_axes=(("dp", 0),))
    with pytest.raises(MXNetError):   # unknown batch axis
        Plan(mesh_axes=(("dp", 2),), batch_axes=("nope",))
    with pytest.raises(MXNetError):   # bad seq_axis
        Plan(mesh_axes=(("dp", 2),), batch_axes=("dp",), seq_axis=2)
    with pytest.raises(MXNetError):   # bad sp mode
        Plan(mesh_axes=(("dp", 2),), batch_axes=("dp",),
             sp_attention="bogus")
    with pytest.raises(MXNetError):   # ring without an sp axis
        Plan(mesh_axes=(("dp", 2),), batch_axes=("dp",),
             sp_attention="ring")
    with pytest.raises(MXNetError):
        Plan(mesh_axes=(("dp", 2),), batch_axes=("dp",), accum_steps=0)
    with pytest.raises(MXNetError):
        Plan(mesh_axes=(("dp", 2),), batch_axes=("dp",),
             pp_microbatches=0)


def test_plan_factories_and_roundtrip():
    from mxnet_tpu.models.bert import bert_sharding_rules

    plans = {
        "dp": dp_plan(n_devices=8),
        "tp": tensor_parallel_plan(bert_sharding_rules(), tp=2,
                                   n_devices=8),
        "pp": pipeline_plan(2, microbatches=2, n_devices=8),
        "ring": ring_plan(2, n_devices=8),
        "ulysses": ulysses_plan(2, n_devices=8),
    }
    assert plans["dp"].strategy == "dp"
    assert plans["tp"].strategy == "dp+tp"
    assert plans["pp"].strategy == "dp+pp"
    assert plans["ring"].strategy == "dp+ring"
    assert plans["ulysses"].strategy == "dp+ulysses"
    for name, p in plans.items():
        assert p.n_devices == 8, name
        rt = Plan.from_json(json.loads(json.dumps(p.to_json())))
        assert rt == p, name   # lossless through REAL json text
    # the sharding rules survive the round trip functionally
    rt = Plan.from_json(plans["tp"].to_json())
    spec = rt.rules.spec_for("encoder0_qkv_weight", 2)
    assert spec == plans["tp"].rules.spec_for("encoder0_qkv_weight", 2)
    # predicted never participates in identity
    assert plans["dp"].with_predicted({"step_s": 1.0}) == plans["dp"]
    # an explicitly-empty batch_axes (a mesh with no dp/sp axes) must
    # round-trip as empty, not regrow the default (review finding)
    empty = Plan(mesh_axes=(("batch", 2),), batch_axes=())
    assert Plan.from_json(empty.to_json()).batch_axes == ()
    # rules hash follows rules equality through the to_json
    # normalization (list vs tuple spec entries; review finding)
    a = ShardingRules([(r"w", (None, ["dp", "tp"]))])
    b = ShardingRules([(r"w", (None, ("dp", "tp")))])
    assert a == b and hash(a) == hash(b)
    hash(plans["tp"])  # frozen Plans embedding rules stay hashable


def test_plan_and_kwargs_clash_rejected():
    net = nn.Dense(2)
    net.initialize(mx.init.Xavier())
    with pytest.raises(MXNetError):
        DataParallelStep(net, gluon.loss.L2Loss(), plan=dp_plan(n_devices=8),
                         accum_steps=2)
    with pytest.raises(MXNetError):   # plan/mesh mismatch
        import jax

        compile_step_with_plan(
            net, gluon.loss.L2Loss(), dp_plan(n_devices=8),
            mesh=local_mesh(devices=jax.devices("cpu")[:4]))


# ---------------------------------------------------------------------------
# shim parity: each legacy entry point vs its Plan on the SAME mesh
# ---------------------------------------------------------------------------
def _dense_net():
    mx.random.seed(0)
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize(mx.init.Xavier())
    return net


def _weights(step):
    import jax

    return {n.split("_", 1)[-1]: np.asarray(jax.device_get(a))
            for n, a in step.params.items()}


def _run_steps(step, n=3, b=8, d=6):
    mx.random.seed(1)
    rng = np.random.RandomState(0)
    X = rng.rand(b, d).astype(np.float32)
    Y = rng.rand(b, 4).astype(np.float32)
    return [float(np.asarray(step.step(nd.array(X), nd.array(Y))))
            for _ in range(n)]


def test_dp_shim_parity_bitwise():
    """Legacy kwargs construction vs compile_step_with_plan(dp_plan) on
    the same 8-device mesh: bitwise losses and weights."""
    legacy = DataParallelStep(_dense_net(), gluon.loss.L2Loss(),
                              mesh=local_mesh(), optimizer="sgd",
                              optimizer_params={"learning_rate": 0.1,
                                                "momentum": 0.9})
    planned = compile_step_with_plan(
        _dense_net(), gluon.loss.L2Loss(), dp_plan(n_devices=8),
        mesh=local_mesh(), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    assert _run_steps(legacy) == _run_steps(planned)
    wl, wp = _weights(legacy), _weights(planned)
    for k in wl:
        np.testing.assert_array_equal(wl[k], wp[k])
    # the legacy constructor built the equivalent Plan internally
    assert legacy.plan.strategy == planned.plan.strategy == "dp"


def _bert_net_for_plan():
    from mxnet_tpu.models import bert_small

    mx.random.seed(0)
    net = bert_small(dropout=0.0)
    net.initialize(mx.init.Normal(0.02))
    return net


def _mlm_loss():
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    return mlm_loss


def _bert_step(mesh, **kw):
    from mxnet_tpu.models.bert import bert_sharding_rules

    net = _bert_net_for_plan()
    kw.setdefault("rules", bert_sharding_rules())
    return DataParallelStep(net, _mlm_loss(), mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3}, **kw)


def _bert_losses(step, n=2):
    mx.random.seed(1)
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 512, (4, 16)).astype(np.int32)
    return [float(np.asarray(step.step(nd.array(tokens, dtype="int32"),
                                       nd.array(tokens.astype(np.float32)))))
            for _ in range(n)]


def test_tp_shim_parity_bitwise():
    """ShardingRules tp strategy: legacy rules= kwarg vs
    tensor_parallel_plan on the same dp2 x tp2 mesh — bitwise, and the
    qkv weights carry the tp sharding either way."""
    import jax

    from mxnet_tpu.models.bert import bert_sharding_rules

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(tp=2, devices=devices)
    legacy = _bert_step(mesh)
    plan = tensor_parallel_plan(bert_sharding_rules(), tp=2, dp=2)
    planned = compile_step_with_plan(
        _bert_net_for_plan(), _mlm_loss(), plan, mesh=mesh,
        optimizer="adam", optimizer_params={"learning_rate": 1e-3})
    assert _bert_losses(legacy) == _bert_losses(planned)
    qkv = [n for n in planned.params if n.endswith("qkv_weight")]
    assert qkv and "tp" in str(planned.params[qkv[0]].sharding.spec)
    assert legacy.plan.strategy == planned.plan.strategy == "dp+tp"


def test_ring_and_ulysses_shim_parity_bitwise():
    """ring/ulysses SP strategies: legacy ring_attention= kwarg vs
    ring_plan/ulysses_plan on the same dp2 x sp2 mesh — bitwise."""
    import jax

    from mxnet_tpu.models.bert import bert_sharding_rules

    devices = jax.devices("cpu")[:4]
    for mode, factory in (("ring", ring_plan), ("ulysses", ulysses_plan)):
        mesh = make_mesh(sp=2, devices=devices)
        legacy = _bert_step(mesh, ring_attention=(True if mode == "ring"
                                                  else "ulysses"))
        plan = factory(2, dp=2, rules=bert_sharding_rules())
        planned = compile_step_with_plan(
            _bert_net_for_plan(), _mlm_loss(), plan, mesh=mesh,
            optimizer="adam", optimizer_params={"learning_rate": 1e-3})
        assert _bert_losses(legacy) == _bert_losses(planned), mode
        assert planned.plan.sp_attention == mode
        assert legacy.plan.sp_attention == mode  # shimmed equivalently


def test_pp_shim_parity_bitwise():
    """pipeline strategy: legacy pp_microbatches kwarg vs pipeline_plan
    on the same dp2 x pp2 mesh — bitwise (the pp scope activates either
    way; a non-stacked model duplicates dp work across pp, which is
    exactly what the pre-refactor path did)."""
    import jax

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(pp=2, devices=devices)
    legacy = DataParallelStep(_dense_net(), gluon.loss.L2Loss(),
                              mesh=mesh, optimizer="sgd",
                              pp_microbatches=2,
                              optimizer_params={"learning_rate": 0.1})
    planned = compile_step_with_plan(
        _dense_net(), gluon.loss.L2Loss(),
        pipeline_plan(2, microbatches=2, dp=2), mesh=mesh,
        optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    assert _run_steps(legacy) == _run_steps(planned)
    assert legacy.plan.pp_microbatches == planned.plan.pp_microbatches == 2
    assert legacy.plan.strategy == planned.plan.strategy == "dp+pp"


# ---------------------------------------------------------------------------
# planner cost fixtures: hand-computed, known-optimal layouts
# ---------------------------------------------------------------------------
_HW = Hardware(flops_per_device=1e12, ici_bw=1e11, opt_slots=2.0)


def test_planner_dp_wins_fixture():
    """Tiny params, fat activations: the dp grad allreduce is ~free and
    anything that shards activations pays collective volume — pure dp
    must rank first, and every cost term matches the formulas."""
    sig = ModelSignature(param_shapes={"w": (16, 16)},
                         batch_shape=(64, 8),
                         flops_per_step=1e9, act_bytes=1e6)
    ranked = planner.enumerate_plans(sig, 2, hw=_HW)
    assert ranked, "nothing legal"
    best = ranked[0]
    assert best.plan.strategy == "dp"
    # hand-check: P = 16*16*4 = 1024 B; dp2 allreduce 2*(1/2)*1024/bw
    dp_cost = best.cost
    assert dp_cost["comm"]["dp"] == pytest.approx(
        2 * 0.5 * 1024 / 1e11)
    assert dp_cost["compute_s"] == pytest.approx(1e9 / (2 * 1e12))
    # the sp2 candidate pays activation collectives instead: 4*(1/2)*
    # (1e6/2)/bw — three orders of magnitude worse
    sp = [c for c in ranked if c.plan.axis_size("sp") == 2]
    assert sp and sp[0].cost["comm"]["sp"] == pytest.approx(
        4 * 0.5 * (1e6 / 2) / 1e11)
    assert sp[0].step_s > best.step_s


def test_planner_tp_wins_fixture():
    """Huge tp-shardable params, tiny activations: replicating the
    params makes the dp grad allreduce the bottleneck; tp shards it
    away — tp must rank first."""
    rules = ShardingRules([(r"w", (None, "tp"))])
    sig = ModelSignature(param_shapes={"w": (4096, 4096)},
                         batch_shape=(8,), rules=rules,
                         flops_per_step=1e9, act_bytes=1024.0)
    P = 4096 * 4096 * 4
    ranked = planner.enumerate_plans(sig, 2, hw=_HW)
    assert ranked[0].plan.strategy == "tp"
    tp_cost = ranked[0].cost
    assert tp_cost["comm"]["tp"] == pytest.approx(4 * 0.5 * 1024 / 1e11)
    dp = [c for c in ranked if c.plan.axis_size("dp") == 2][0]
    assert dp.cost["comm"]["dp"] == pytest.approx(2 * 0.5 * P / 1e11)
    assert dp.step_s > ranked[0].step_s
    # chosen plan carries the rules so compile_step_with_plan shards
    assert ranked[0].plan.rules.spec_for("w", 2) is not None


def test_planner_memory_forces_sharding_fixture():
    """dp would be fastest but replicated params + optimizer slots blow
    the per-device budget; only the tp layout fits — the planner must
    rank it first even at a worse predicted step time."""
    rules = ShardingRules([(r"w", (None, "tp"))])
    P = 1024 * 1024 * 4                        # 4 MiB params
    # act = P: dp's param allreduce (2*(1/2)*P) beats tp's activation
    # collectives (4*(1/2)*P) on SPEED — only memory forces tp
    sig = ModelSignature(param_shapes={"w": (1024, 1024)},
                         batch_shape=(8,), rules=rules,
                         flops_per_step=1e12, act_bytes=float(P))
    hw = Hardware(flops_per_device=1e12, ici_bw=1e11, opt_slots=2.0,
                  mem_per_device=3.2 * P)
    ranked = planner.enumerate_plans(sig, 2, hw=hw)
    best = ranked[0]
    assert best.plan.strategy == "tp"
    assert best.cost["mem_ok"]
    # tp: (2 + opt_slots) * P/2 + full acts (dp=1) = 2P + P = 3P fits
    assert best.cost["mem_bytes"] == pytest.approx(3 * P)
    dp = [c for c in ranked if c.plan.axis_size("dp") == 2][0]
    assert not dp.cost["mem_ok"]
    # dp=2 halves the activation share but still replicates all 4P of
    # param+grad+slots state: 4P + P/2 > 3.2P budget
    assert dp.cost["mem_bytes"] == pytest.approx(4 * P + P / 2)
    # ...and dp IS the faster plan: memory is the only forcer
    assert dp.step_s < best.step_s
    unbounded = Hardware(flops_per_device=1e12, ici_bw=1e11,
                         opt_slots=2.0)
    assert planner.enumerate_plans(
        sig, 2, hw=unbounded)[0].plan.strategy == "dp"


def test_planner_pp_bubble_and_legality():
    """pp plans only appear when stacked layers divide, and the bubble
    factor (M + pp - 1)/M lands in the compute term."""
    sig = ModelSignature(param_shapes={"w": (64, 64)},
                         batch_shape=(16,), stacked_layers=4,
                         flops_per_step=1e9, act_bytes=1e3)
    ranked = planner.enumerate_plans(sig, 4, hw=_HW, microbatches=4)
    pp = [c for c in ranked if c.plan.axis_size("pp") == 4]
    assert pp, "pp4 divides 4 stacked layers — must be enumerated"
    assert pp[0].cost["bubble"] == pytest.approx((4 + 4 - 1) / 4)
    assert pp[0].cost["compute_s"] == pytest.approx(
        1e9 / (4 * 1e12) * (7 / 4))
    # 3 layers: pp=4 and pp=2 both illegal (no divisibility)
    sig3 = ModelSignature(param_shapes={"w": (64, 64)},
                          batch_shape=(16,), stacked_layers=3,
                          flops_per_step=1e9, act_bytes=1e3)
    assert not any(c.plan.axis_size("pp") > 1
                   for c in planner.enumerate_plans(sig3, 4, hw=_HW))


def test_enumerated_plans_are_legal_property():
    """Property sweep: every enumerated plan of every random signature
    is structurally legal and serializes losslessly."""
    rng = np.random.RandomState(7)
    for trial in range(12):
        n = int(rng.choice([2, 4, 6, 8, 12]))
        batch = int(rng.choice([4, 6, 8, 16, 24]))
        seq = int(rng.choice([0, 4, 8, 12]))
        layers = int(rng.choice([0, 2, 3, 4, 8]))
        dim = int(rng.choice([8, 12, 16]))
        rules = (ShardingRules([(r".*w.*", (None, "tp"))])
                 if rng.rand() < 0.7 else None)
        sig = ModelSignature(
            param_shapes={"w1": (dim, dim), "w2": (dim, dim), "b": (dim,)},
            batch_shape=(batch, seq) if seq else (batch,),
            stacked_layers=layers or None, rules=rules)
        for choice in planner.enumerate_plans(sig, n, hw=_HW):
            plan, cost = choice.plan, choice.cost
            dp, tp = plan.axis_size("dp"), plan.axis_size("tp")
            pp, sp = plan.axis_size("pp"), plan.axis_size("sp")
            assert dp * tp * pp * sp == n
            assert batch % dp == 0
            if sp > 1:
                assert seq and seq % sp == 0
            if pp > 1:
                assert layers and layers % pp == 0
                assert (batch // dp) % plan.pp_microbatches == 0
            if tp > 1:
                assert rules is not None
                for name, shape in sig.param_shapes.items():
                    spec = tuple(plan.rules.spec_for(name, len(shape)))
                    for i, entry in enumerate(spec):
                        if entry == "tp" or (isinstance(entry, tuple)
                                             and "tp" in entry):
                            assert shape[i] % tp == 0, (name, shape, tp)
            assert cost["step_s"] > 0 and cost["mem_bytes"] > 0
            assert Plan.from_json(plan.to_json()) == plan


def test_plan_for_override_and_errors(monkeypatch):
    rules = ShardingRules([(r"w", (None, "tp"))])
    # fat activations: dp (param allreduce only) is the auto argmin
    sig = ModelSignature(param_shapes={"w": (64, 64)}, batch_shape=(16, 8),
                         rules=rules, stacked_layers=2,
                         flops_per_step=1e9, act_bytes=1e6)
    # auto: argmin (tiny params -> dp)
    monkeypatch.delenv("MX_PLAN", raising=False)
    assert planner.plan_for(sig, 4, hw=_HW).strategy == "dp"
    # env override pins the family even when dp ranks first
    monkeypatch.setenv("MX_PLAN", "tp")
    chosen = planner.plan_for(sig, 4, hw=_HW)
    assert chosen.axis_size("tp") > 1
    assert chosen.predicted["override"] == "tp"
    monkeypatch.setenv("MX_PLAN", "pp")
    assert planner.plan_for(sig, 4, hw=_HW,
                            microbatches=2).axis_size("pp") > 1
    monkeypatch.setenv("MX_PLAN", "ring")
    ring = planner.plan_for(sig, 4, hw=_HW)
    assert ring.axis_size("sp") > 1 and ring.sp_attention == "ring"
    monkeypatch.setenv("MX_PLAN", "ulysses")
    assert planner.plan_for(sig, 4, hw=_HW).sp_attention == "ulysses"
    # arg beats env; bogus value is loud
    assert planner.plan_for(sig, 4, hw=_HW, strategy="dp").strategy == "dp"
    monkeypatch.setenv("MX_PLAN", "bogus")
    with pytest.raises(MXNetError):
        planner.plan_for(sig, 4, hw=_HW)
    # no legal layout at all is loud too (batch 5 over 4 devices, dp
    # required but not divisible in any factorization using dp>1; tp
    # variants are capped by w's 64-dim? no — 5 % dp blocks dp>1 and
    # sp needs seq... tp4 IS legal, so use a rule-less sig)
    sig_bad = ModelSignature(param_shapes={"w": (64, 64)},
                             batch_shape=(5,), flops_per_step=1e9,
                             act_bytes=1e3)
    with pytest.raises(MXNetError):
        planner.plan_for(sig_bad, 4, hw=_HW)
    # the predicted ranking rides on the chosen plan
    monkeypatch.delenv("MX_PLAN", raising=False)
    best = planner.plan_for(sig, 4, hw=_HW)
    assert best.predicted["ranking"][0]["strategy"] == best.strategy
    assert best.predicted["step_s"] > 0


def test_signature_of_block():
    net = _dense_net()
    # materialize deferred-init shapes (in_units comes from data)
    net(nd.array(np.zeros((8, 6), np.float32)))
    sig = planner.signature_of(net, (8, 6))
    assert sig.param_shapes and sig.batch == 8
    assert sig.flops_per_step > 0 and sig.act_bytes > 0
    # matmul params only contribute to the 6ND flops estimate
    mats = sum(1 for s in sig.param_shapes.values() if len(s) >= 2)
    assert mats >= 2


# ---------------------------------------------------------------------------
# plan telemetry event
# ---------------------------------------------------------------------------
def test_plan_telemetry_event(tele):
    sig = ModelSignature(param_shapes={"w": (16, 16)}, batch_shape=(8, 4),
                         flops_per_step=1e9, act_bytes=1e3)
    plan = planner.plan_for(sig, 1, hw=_HW)
    import jax

    step = compile_step_with_plan(
        _dense_net(), gluon.loss.L2Loss(), plan,
        mesh=local_mesh(devices=[jax.devices("cpu")[0]]),
        optimizer="sgd", optimizer_params={"learning_rate": 0.1})
    _run_steps(step, n=1)
    evs = [e for e in _events(tele) if e.get("kind") == "plan"]
    assert len(evs) == 1
    ev = evs[0]
    assert ev["strategy"] == plan.strategy
    assert ev["plan"]["mesh_axes"] == [[n, s] for n, s in plan.mesh_axes]
    # predicted costs ride along for the trace_report predicted-vs-
    # measured comparison
    assert ev["predicted"]["step_s"] > 0
    assert ev["predicted"]["ranking"]
    # and the step events to compare against are in the same stream
    assert any(e.get("kind") == "step" for e in _events(tele))


# ---------------------------------------------------------------------------
# platform features THROUGH the Plan path
# ---------------------------------------------------------------------------
def test_superstep_through_plan_path(monkeypatch):
    """MX_SUPERSTEP=2 over a plan-built step: bitwise identical to the
    K=0 plan-built run on a single-device mesh."""
    import jax

    def run(k):
        monkeypatch.setenv("MX_SUPERSTEP", str(k))
        monkeypatch.setenv("MX_SUPERSTEP_FORCE_CPU", "1")
        step = compile_step_with_plan(
            _dense_net(), gluon.loss.L2Loss(), dp_plan(n_devices=1),
            mesh=local_mesh(devices=[jax.devices("cpu")[0]]),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})
        losses = _run_steps(step, n=4)
        step.drain()
        return losses, _weights(step)

    l0, w0 = run(0)
    l2, w2 = run(2)
    assert l0 == l2
    for kk in w0:
        np.testing.assert_array_equal(w0[kk], w2[kk])


def test_aot_cache_through_plan_path(tele, tmp_path, monkeypatch):
    """A second plan-built step over the same program deserializes the
    persistent AOT executable (cache_hit compile event) instead of
    recompiling — the restart SLO, through the Plan path."""
    import jax

    monkeypatch.setenv("MX_EXECUTABLE_CACHE_DIR", str(tmp_path / "aot"))

    def build():
        mx.random.seed(0)
        net = nn.Dense(4, prefix="planaot_")   # fixed prefix: param
        net.initialize(mx.init.Xavier())       # names are identity
        return compile_step_with_plan(
            net, gluon.loss.L2Loss(), dp_plan(n_devices=1),
            mesh=local_mesh(devices=[jax.devices("cpu")[0]]),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1})

    _run_steps(build(), n=1)
    _run_steps(build(), n=1)
    compiles = [e for e in _events(tele) if e.get("kind") == "compile"
                and e.get("site") == "data_parallel"]
    assert len(compiles) == 2
    assert not compiles[0].get("cache_hit")
    assert compiles[1].get("cache_hit") and \
        compiles[1].get("deserialize_ms") is not None


def test_elastic_reshard_through_plan_path(tele):
    """state_dict from a dp2 plan-built step restores onto a dp4
    plan-built step (reshard), the layout round-trips the Plan, and the
    restored weights are bitwise the saved ones."""
    import jax

    devices = jax.devices("cpu")

    def build(ndev):
        return compile_step_with_plan(
            _dense_net(), gluon.loss.L2Loss(), dp_plan(n_devices=ndev),
            mesh=local_mesh(devices=devices[:ndev]),
            optimizer="sgd", optimizer_params={"learning_rate": 0.1,
                                               "momentum": 0.9})

    src = build(2)
    _run_steps(src, n=2)
    state, layout = src.state_dict(), src.layout()
    assert Plan.from_json(layout["plan"]) == src.plan

    dst = build(4)
    info = dst.load_state_dict(state, saved_layout=layout)
    assert info["resharded"]
    for k, v in _weights(src).items():
        np.testing.assert_array_equal(v, _weights(dst)[k])
    # and training continues through the plan path on the new mesh
    assert np.isfinite(_run_steps(dst, n=1)[0])


# ---------------------------------------------------------------------------
# PR satellites: AOT coverage of the two remaining jit sites
# ---------------------------------------------------------------------------
_SAT_CODE = """
import os, numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry, memwatch
telemetry.enable(os.environ["SAT_TELE"])
from mxnet_tpu.gluon import nn

# CachedOp site (BatchNorm included: aux rebinding must survive the
# no-trace warm load)
net = nn.HybridSequential(prefix="sat_")
with net.name_scope():
    net.add(nn.Dense(8, activation="relu"), nn.BatchNorm(), nn.Dense(4))
net.initialize(mx.init.Constant(0.05))
net.hybridize()
x = nd.array(np.linspace(0, 1, 24).reshape(4, 6).astype(np.float32))
out = net(x)
print("OUT", repr(float(np.asarray(out._data).sum())))

# kvstore collective-reduce site
kv = mx.kvstore.create("device")
ctxs = [mx.cpu(i) for i in range(4)]
kv.init("w", nd.zeros((3, 4), ctx=ctxs[0]))
kv.push("w", [nd.ones((3, 4), ctx=c) * (i + 1) for i, c in enumerate(ctxs)])
outp = nd.zeros((3, 4), ctx=ctxs[0])
kv.pull("w", outp)
print("KV", repr(float(outp.asnumpy().sum())))
comp = memwatch.summary()["compiles"]
print("HITS", comp.get("cache_hits", 0))
"""


def _run_sat(aot_dir, tele_dir):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8",
               MX_EXECUTABLE_CACHE_DIR=aot_dir, SAT_TELE=tele_dir,
               PYTHONPATH=_REPO)
    r = subprocess.run([sys.executable, "-c", _SAT_CODE], env=env,
                       capture_output=True, text=True, cwd=_REPO,
                       timeout=240)
    assert r.returncode == 0, r.stderr[-4000:]
    out = {l.split()[0]: l.split(None, 1)[1]
           for l in r.stdout.splitlines()
           if l.startswith(("OUT", "KV", "HITS"))}
    return out


def test_kvstore_and_cachedop_aot_restart_roundtrip(tmp_path):
    """The PR 9 'Known' closure: a restarted process deserializes the
    kvstore._reduce_collective psum AND the CachedOp forward from the
    persistent cache (cache hits booked, zero fresh value drift) —
    including the CachedOp structural meta (n_out/treedef/aux names)
    that a no-trace warm load cannot learn from tracing."""
    aot = str(tmp_path / "aot")
    os.makedirs(aot)
    first = _run_sat(aot, str(tmp_path / "t1"))
    assert first["HITS"] == "0"
    n_entries = len(os.listdir(aot))
    assert n_entries >= 2   # >=1 cachedop + 1 reduce executable
    second = _run_sat(aot, str(tmp_path / "t2"))
    assert int(second["HITS"]) >= 2, second
    assert second["OUT"] == first["OUT"]
    assert second["KV"] == first["KV"]
    assert len(os.listdir(aot)) == n_entries  # hits, not re-stores


def test_cachedop_aot_disabled_is_inert(tmp_path, monkeypatch):
    """Kill switch: MX_EXECUTABLE_CACHE=0 writes nothing at either new
    site and the values are byte-for-byte the plain-jit ones."""
    monkeypatch.setenv("MX_EXECUTABLE_CACHE_DIR", str(tmp_path / "aot"))
    monkeypatch.setenv("MX_EXECUTABLE_CACHE", "0")
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(mx.init.Constant(0.1))
    net.hybridize()
    out = net(nd.array(np.ones((2, 3), np.float32)))
    assert np.isfinite(np.asarray(out._data)).all()
    kv = mx.kvstore.create("device")
    kv.init("w", nd.zeros((2, 2), ctx=mx.cpu(0)))
    kv.push("w", [nd.ones((2, 2), ctx=mx.cpu(i)) for i in range(2)])
    assert not os.path.exists(str(tmp_path / "aot")) or \
        not os.listdir(str(tmp_path / "aot"))
