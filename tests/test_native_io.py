"""Native C++ IO pipeline tests (reference spec: tests/python/unittest/
test_io.py ImageRecordIter tests; format compat per recordio.h).

Builds libmxio.so via `make -C src` if missing; skips when the toolchain
or OpenCV headers are unavailable.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ensure_lib():
    lib = os.path.join(REPO, "mxnet_tpu", "lib", "libmxio.so")
    if not os.path.exists(lib):
        r = subprocess.run(["make", "-C", os.path.join(REPO, "src")],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build libmxio.so: {r.stderr[-500:]}")
    from mxnet_tpu.io import native

    if not native.available():
        pytest.skip("libmxio.so not loadable")


@pytest.fixture(scope="module")
def rec_dataset(tmp_path_factory):
    """30 synthetic JPEG records with known labels."""
    _ensure_lib()
    from mxnet_tpu import recordio

    d = tmp_path_factory.mktemp("recio")
    prefix = str(d / "train")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    rs = np.random.RandomState(0)
    images = []
    for i in range(30):
        # constant-ish color per record makes decode verification robust
        # to JPEG loss
        base = rs.randint(30, 220, size=3)
        img = np.ones((40, 48, 3), np.uint8) * base.astype(np.uint8)
        header = recordio.IRHeader(flag=0, label=float(i % 10), id=i, id2=0)
        rec.write_idx(i, recordio.pack_img(header, img, quality=95))
        images.append((float(i % 10), base))
    rec.close()
    return prefix, images


def test_native_iter_shapes_and_labels(rec_dataset):
    from mxnet_tpu import io

    prefix, images = rec_dataset
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            data_shape=(3, 32, 32), batch_size=10,
                            preprocess_threads=2)
    assert it._native is not None, "native pipeline should be active"
    batches = list(it)
    assert len(batches) == 3
    seen = []
    for b in batches:
        assert b.data[0].shape == (10, 3, 32, 32)
        assert b.label[0].shape == (10, 1)
        seen.extend(b.label[0].asnumpy().ravel().tolist())
    assert sorted(seen) == sorted(lab for lab, _ in images)


def test_native_decode_values(rec_dataset):
    from mxnet_tpu import io

    prefix, images = rec_dataset
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            data_shape=(3, 32, 32), batch_size=30,
                            preprocess_threads=2)
    b = next(it)
    data = b.data[0].asnumpy()
    labels = b.label[0].asnumpy().ravel()
    by_label = {}
    for lab, base in images:
        by_label.setdefault(lab, []).append(base)
    for row, lab in zip(data, labels):
        mean_rgb = row.reshape(3, -1).mean(axis=1)
        # one of the source images with this label must match closely
        ok = any(np.abs(mean_rgb - base).max() < 6.0
                 for base in by_label[lab])
        assert ok, f"decoded pixels do not match source for label {lab}"


def test_native_shuffle_and_reset(rec_dataset):
    from mxnet_tpu import io

    prefix, _ = rec_dataset
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            data_shape=(3, 32, 32), batch_size=10,
                            shuffle=True, seed=7, preprocess_threads=2)
    first = [b.label[0].asnumpy().copy() for b in it]
    it.reset()
    second = [b.label[0].asnumpy().copy() for b in it]
    # epochs reshuffle (overwhelmingly likely to differ)
    assert not all((a == b).all() for a, b in zip(first, second))
    # all records still covered
    assert sorted(np.concatenate(first).ravel()) == \
        sorted(np.concatenate(second).ravel())


def test_native_matches_python_fallback(rec_dataset):
    from mxnet_tpu import io

    prefix, _ = rec_dataset
    nat = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                             data_shape=(3, 32, 32), batch_size=30,
                             preprocess_threads=2)
    assert nat._native is not None
    os.environ["MXNET_USE_NATIVE_IO"] = "0"
    try:
        import mxnet_tpu.io.native as native_mod

        native_mod._TRIED = False
        native_mod._LIB = None
        py = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                data_shape=(3, 32, 32), batch_size=30,
                                preprocess_threads=2)
        assert py._native is None
    finally:
        os.environ.pop("MXNET_USE_NATIVE_IO")
        native_mod._TRIED = False
        native_mod._LIB = None

    a = next(nat).data[0].asnumpy()
    b = next(py).data[0].asnumpy()
    # same records in same order; decode paths may differ by JPEG rounding
    assert np.abs(a - b).mean() < 2.0


def test_im2rec_roundtrip(tmp_path):
    _ensure_lib()
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import im2rec
    finally:
        sys.path.pop(0)
    from mxnet_tpu import io
    from mxnet_tpu.image import imencode

    # build a tiny class-per-directory dataset
    root = tmp_path / "imgs"
    rs = np.random.RandomState(1)
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(4):
            img = rs.randint(0, 255, (36, 36, 3), np.uint8)
            with open(root / cls / f"{i}.jpg", "wb") as f:
                f.write(imencode(img))
    prefix = str(tmp_path / "ds")
    im2rec.main(["--list", "--recursive", prefix, str(root)])
    im2rec.main([prefix, str(root), "--resize", "34"])

    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            data_shape=(3, 32, 32), batch_size=4)
    labels = []
    for b in it:
        labels.extend(b.label[0].asnumpy().ravel().tolist())
    assert len(labels) == 8
    assert sorted(set(labels)) == [0.0, 1.0]


def test_libsvm_iter(tmp_path):
    p = tmp_path / "data.libsvm"
    p.write_text("1 0:1.5 3:2.0\n0 1:0.5\n1 2:3.0 3:1.0\n0 0:2.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=2)
    batches = []
    for b in it:
        assert b.data[0].stype == "csr"
        batches.append((b.data[0].asnumpy(), b.label[0].asnumpy()))
    assert len(batches) == 2
    np.testing.assert_allclose(batches[0][0][0], [1.5, 0, 0, 2.0])
    np.testing.assert_allclose(batches[0][1], [1, 0])


def test_prefetching_iter(tmp_path):
    data = np.arange(24, dtype=np.float32).reshape(6, 4)
    label = np.arange(6, dtype=np.float32)
    base = mx.io.NDArrayIter(data, label, batch_size=2)
    it = mx.io.PrefetchingIter(base)
    seen = []
    for b in it:
        seen.append(b.data[0].asnumpy().copy())
    assert len(seen) == 3
    np.testing.assert_allclose(np.concatenate(seen), data)
    it.reset()
    again = sum(1 for _ in it)
    assert again == 3


def test_libsvm_iter_one_based_detection(tmp_path):
    # liblinear convention: indices 1..n_feat
    p = tmp_path / "one.libsvm"
    p.write_text("1 1:1.5 4:2.0\n0 2:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=2)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy()[0], [1.5, 0, 0, 2.0])
    # out-of-range index raises instead of shifting silently
    p2 = tmp_path / "bad.libsvm"
    p2.write_text("1 0:1.0 7:2.0\n")
    with pytest.raises(mx.base.MXNetError):
        mx.io.LibSVMIter(data_libsvm=str(p2), data_shape=(4,), batch_size=1)


def test_prefetching_iter_error_and_exhaustion():
    class Boom(mx.io.DataIter):
        def __init__(self):
            super().__init__(batch_size=1)
            self.n = 0

        def reset(self):
            self.n = 0

        def next(self):
            self.n += 1
            if self.n == 2:
                raise ValueError("boom")
            if self.n > 2:
                raise StopIteration
            from mxnet_tpu import nd

            return mx.io.DataBatch([nd.zeros((1, 2))], [nd.zeros((1,))])

    it = mx.io.PrefetchingIter(Boom())
    it.next()
    with pytest.raises(ValueError):
        it.next()
    # exhausted: StopIteration is repeatable, no deadlock
    with pytest.raises(StopIteration):
        it.next()
    with pytest.raises(StopIteration):
        it.next()
    # rename mapping applies to descriptors
    base = mx.io.NDArrayIter(np.zeros((4, 2), np.float32),
                             np.zeros(4, np.float32), batch_size=2)
    it2 = mx.io.PrefetchingIter(base, rename_data=[{"data": "x"}])
    assert it2.provide_data[0].name == "x"


class _FlakyIter(mx.io.DataIter):
    """Yields `good` batches, raises once at batch `fail_at` on the FIRST
    epoch only, then behaves normally after reset()."""

    def __init__(self, good=4, fail_at=None):
        super().__init__(batch_size=2)
        self._good, self._fail_at = good, fail_at
        self._epoch, self.n = 0, 0

    def reset(self):
        self._epoch += 1
        self.n = 0

    def next(self):
        from mxnet_tpu import nd

        self.n += 1
        if self._epoch == 0 and self._fail_at is not None \
                and self.n == self._fail_at:
            def inner():
                raise ValueError("flaky worker boom")
            inner()  # a real frame below, so the traceback has depth
        if self.n > self._good:
            raise StopIteration
        return mx.io.DataBatch(
            [nd.full((2, 3), float(self.n))],
            [nd.full((2,), float(self.n))])


def test_prefetching_iter_error_carries_worker_traceback():
    import traceback

    it = mx.io.PrefetchingIter(_FlakyIter(good=4, fail_at=1))
    with pytest.raises(ValueError, match="flaky worker boom") as ei:
        it.next()
    # the ORIGINAL worker traceback rides along: the raising frame
    # (inner, inside the wrapped iterator's next) is visible, not just
    # the consumer-side re-raise site
    frames = [f.name for f in traceback.extract_tb(ei.value.__traceback__)]
    assert "inner" in frames and "next" in frames, frames
    # exactly once: afterwards plain StopIteration, repeatably
    with pytest.raises(StopIteration):
        it.next()
    with pytest.raises(StopIteration):
        it.next()


def test_prefetching_iter_reset_after_worker_error_restarts_cleanly():
    it = mx.io.PrefetchingIter(_FlakyIter(good=4, fail_at=3))
    seen = [it.next().data[0].asnumpy()[0, 0] for _ in range(2)]
    assert seen == [1.0, 2.0]
    with pytest.raises(ValueError, match="flaky worker boom"):
        while True:
            it.next()
    # regression: the _done/error interplay used to leave the iterator
    # permanently exhausted here — reset() must produce a full epoch
    it.reset()
    vals = [b.data[0].asnumpy()[0, 0] for b in it]
    assert vals == [1.0, 2.0, 3.0, 4.0]
    # and another reset keeps working
    it.reset()
    assert sum(1 for _ in it) == 4


def test_prefetching_iter_reset_after_partial_consume():
    it = mx.io.PrefetchingIter(_FlakyIter(good=6))
    first = it.next().data[0].asnumpy()[0, 0]
    assert first == 1.0
    # reset mid-epoch while the worker holds prefetched batches: the next
    # epoch must start from batch 1 with nothing stale, dropped, or
    # double-consumed
    it.reset()
    vals = [b.data[0].asnumpy()[0, 0] for b in it]
    assert vals == [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]
    # immediate back-to-back resets don't wedge the generation machinery
    it.reset()
    it.reset()
    assert sum(1 for _ in it) == 6
