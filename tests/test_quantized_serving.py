"""Int8 quantized serving (docs/PRECISION.md §Int8 serving; ISSUE 15
acceptance).

Covers: quantize->dequantize round-trip vs the ops/quantization.py
oracle, the calibrated int8 engine's top-1 agreement with the fp32
engine on the reverse-task model, the ONE-int8-decode-executable
property (telemetry compile events), AOT fingerprint miss on changed
quant config + round-trip in a second process with cache_hit, the
MX_QUANTIZE env gate, precision telemetry labels, and the `quantized`
memwatch census category.
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.transformer import Transformer, label_smoothed_ce
from mxnet_tpu.precision import (QuantizedAdapter, maybe_quantize_adapter,
                                 quantize_adapter)
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

PAD, BOS, EOS = 0, 1, 2


@pytest.fixture
def tele(tmp_path):
    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path))
    yield telemetry
    telemetry.reset()
    memwatch.reset()


def _reverse_batch(rng, B, L=6, vocab=16):
    src = np.zeros((B, L + 1), np.int32)
    tgt_in = np.zeros((B, L + 2), np.int32)
    tgt_out = np.zeros((B, L + 2), np.int32)
    for b in range(B):
        toks = rng.randint(3, vocab, L)
        src[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    return src, tgt_in, tgt_out


@pytest.fixture(scope="module")
def trained():
    """Reverse-task transformer (the test_serving recipe): sharp logits
    so greedy decode is decision-stable across the fp32 and int8
    executables."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    mx.random.seed(0)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=20, dropout=0.0)
    net.initialize(mx.init.Xavier())
    rng = np.random.RandomState(2)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)
    step = DataParallelStep(
        net, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))
    for _ in range(48):
        step.step((sb, tb), lb)
    step.sync_to_block()
    return net, src


def _quantize(net, src, calib_mode="naive", exclude=()):
    adapter = TransformerAdapter(net, src_max_len=7)

    def calib_fn(batch):
        net.translate(nd.array(batch, dtype="int32"), bos_id=BOS,
                      eos_id=EOS, max_len=10, beam_size=1)

    return quantize_adapter(adapter, [src[i:i + 1] for i in range(len(src))],
                            calib_fn, calib_mode=calib_mode,
                            exclude=exclude)


# ---------------------------------------------------------------------------
# int8 math round-trip vs the ops oracle
# ---------------------------------------------------------------------------
def test_quantize_dequantize_roundtrip_vs_oracle():
    """contrib.quantize_v2 -> dequantize reconstructs within one scale
    step of the symmetric 127-level oracle, and matches the numpy
    reference scheme exactly."""
    rng = np.random.RandomState(0)
    x = (rng.randn(64).astype(np.float32) * 3).astype(np.float32)
    t = float(np.abs(x).max())
    q, mn, mx_ = nd.contrib.quantize_v2(nd.array(x), min_calib_range=-t,
                                        max_calib_range=t)
    assert q.dtype == np.int8
    ref_q = np.clip(np.round(x * (127.0 / t)), -127, 127).astype(np.int8)
    np.testing.assert_array_equal(q.asnumpy(), ref_q)
    back = nd.contrib.dequantize(q, mn, mx_).asnumpy()
    np.testing.assert_allclose(back, x, atol=t / 127.0 + 1e-6)
    np.testing.assert_allclose(back, ref_q.astype(np.float32) * (t / 127.0),
                               rtol=1e-6)


def test_quantized_dense_twin_matches_eager_quantized_ops(trained):
    """The traced int8 Dense twin computes exactly what composing the
    eager ops/quantization.py primitives computes."""
    from mxnet_tpu.precision.quantize import collect_quantizable

    net, _src = trained
    qad = _quantize(net, _src)
    path, layer = collect_quantizable(net)[0]
    twin = qad._by_path[path]
    impl = twin._impl  # the contrib eager twin owning the int8 lowering
    bias = layer.bias.data() if layer.bias is not None else None
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(3, impl._qweight.shape[1]).astype(np.float32))
    got = twin(nd, x, bias).asnumpy()
    t = twin.act_thresh
    qx, mn, mx_ = nd.contrib.quantize_v2(x, min_calib_range=-t,
                                         max_calib_range=t)
    acc, amn, amx = nd.contrib.quantized_fully_connected(
        qx, impl._qweight, bias if bias is not None else impl._bias,
        mn, mx_, impl._w_min, impl._w_max, num_hidden=impl._units,
        no_bias=impl._no_bias, flatten=impl._flatten)
    want = nd.contrib.dequantize(acc, amn, amx).asnumpy()
    if impl._act_type:
        want = nd.Activation(nd.array(want),
                             act_type=impl._act_type).asnumpy()
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# ACCEPTANCE: calibrated int8 engine vs fp32 engine
# ---------------------------------------------------------------------------
def test_int8_engine_top1_agreement_and_param_bytes(trained):
    net, src = trained
    eng32 = ServingEngine(TransformerAdapter(net, src_max_len=7), slots=3,
                          page_size=4, max_len=12, stream_every=4)
    reqs32 = [Request(src[i], max_new_tokens=9, bos_id=BOS, eos_id=EOS)
              for i in range(6)]
    out32 = eng32.serve(reqs32, arrival_steps=[0, 0, 0, 2, 5, 9])

    qad = _quantize(net, src)
    # params-bytes: the int8 graph holds well under half the fp32 bytes
    assert qad.quantized_param_bytes() < 0.5 * qad.fp32_param_bytes()
    engq = ServingEngine(qad, slots=3, page_size=4, max_len=12,
                         stream_every=4)
    reqsq = [Request(src[i], max_new_tokens=9, bos_id=BOS, eos_id=EOS)
             for i in range(6)]
    outq = engq.serve(reqsq, arrival_steps=[0, 0, 0, 2, 5, 9])

    agree, total = 0, 0
    for a, b in zip(reqs32, reqsq):
        ta, tb = list(out32[a.id]), list(outq[b.id])
        n = min(len(ta), len(tb))
        agree += sum(1 for i in range(n) if ta[i] == tb[i])
        total += max(len(ta), len(tb))
    assert total > 0
    # the memorized reverse task decodes identically through int8 on
    # this model; the acceptance floor is 90% top-1 agreement
    assert agree / total >= 0.9, (agree, total)
    # and the task is actually solved, not just agreed upon
    for i, r in enumerate(reqsq[:3]):
        assert list(outq[r.id][:6]) == list(src[i, :6][::-1])


def test_one_int8_decode_executable(tele, tmp_path, trained):
    """ACCEPTANCE: the quantized engine books exactly ONE decode compile
    event (plus one prefill) on a mixed-length mid-flight trace — the
    int8 rewrite lives inside the one executable, not per layer."""
    net, src = trained
    qad = _quantize(net, src)
    eng = ServingEngine(qad, slots=3, page_size=4, max_len=12,
                        stream_every=4)
    reqs = [Request(src[i], max_new_tokens=n, bos_id=BOS, eos_id=EOS)
            for i, n in enumerate((5, 9, 11))]
    eng.serve(reqs, arrival_steps=[0, 2, 6])
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    compiles = [e for e in events if e["kind"] == "compile"
                and e.get("executor") == "ServingEngine"]
    sites = sorted(e["site"] for e in compiles)
    assert sites == ["serving_decode", "serving_prefill"], sites


def test_quant_config_splits_aot_fingerprint(trained):
    """ACCEPTANCE: a different quant config (calib mode, excluded
    layers, or fp32 vs int8) produces a different AOT-cache fingerprint
    — a restart under different MX_QUANTIZE settings misses instead of
    deserializing the wrong program."""
    net, src = trained
    naive = _quantize(net, src, calib_mode="naive")
    entropy = _quantize(net, src, calib_mode="entropy")
    excl = _quantize(net, src, exclude=(next(iter(naive._by_path)),))
    engines = [
        ServingEngine(TransformerAdapter(net, src_max_len=7), slots=2,
                      page_size=4, max_len=8, stream_every=2),
        ServingEngine(naive, slots=2, page_size=4, max_len=8,
                      stream_every=2),
        ServingEngine(entropy, slots=2, page_size=4, max_len=8,
                      stream_every=2),
        ServingEngine(excl, slots=2, page_size=4, max_len=8,
                      stream_every=2),
    ]
    parts = [e._fingerprint_parts(("decode", 4, 2), []) for e in engines]
    fps = [memwatch.fingerprint(p) for p in parts]
    assert len(set(fps)) == len(fps), fps


def test_precision_telemetry_labels(tele, tmp_path, trained):
    net, src = trained
    qad = _quantize(net, src)
    eng = ServingEngine(qad, slots=2, page_size=4, max_len=10,
                        stream_every=4)
    reqs = [Request(src[i], max_new_tokens=5, bos_id=BOS, eos_id=EOS)
            for i in range(2)]
    eng.serve(reqs)
    s = telemetry.summary()["serving"]
    assert s["precision"] == "int8"
    prom = open(telemetry.export_prometheus()).read()
    assert 'mx_serve_precision_info{rank="0",precision="int8"} 1' in prom
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    serve_evs = [e for e in events if e["kind"] == "serve_request"]
    assert serve_evs and all(e["precision"] == "int8" for e in serve_evs)


def test_quantized_census_category(trained):
    net, src = trained
    qad = _quantize(net, src)
    eng = ServingEngine(qad, slots=2, page_size=4, max_len=8,
                        stream_every=2)
    census = memwatch.census()
    cats = census["categories"]
    assert "quantized" in cats, sorted(cats)
    # every int8 weight buffer is attributed (22 Dense layers x 3 arrays)
    assert cats["quantized"]["count"] >= len(qad._entries)
    del eng


def test_maybe_quantize_env_gate(monkeypatch, trained):
    net, src = trained
    adapter = TransformerAdapter(net, src_max_len=7)
    monkeypatch.delenv("MX_QUANTIZE", raising=False)
    assert maybe_quantize_adapter(adapter) is adapter
    monkeypatch.setenv("MX_QUANTIZE", "int8")
    with pytest.raises(MXNetError, match="calibration data"):
        maybe_quantize_adapter(adapter)

    def calib_fn(batch):
        net.translate(nd.array(batch, dtype="int32"), bos_id=BOS,
                      eos_id=EOS, max_len=8, beam_size=1)

    monkeypatch.setenv("MX_QUANT_CALIB", "naive")
    q = maybe_quantize_adapter(adapter, [src[:1]], calib_fn)
    assert isinstance(q, QuantizedAdapter)
    assert q.precision == "int8"
    monkeypatch.setenv("MX_QUANTIZE", "int4")
    with pytest.raises(MXNetError, match="MX_QUANTIZE"):
        maybe_quantize_adapter(adapter, [src[:1]], calib_fn)


def test_degenerate_calibration_fails_loudly(trained):
    """All-zero calibration activations raise naming the layer path and
    calib mode (the quantize_net satellite, via the shared check)."""
    net, src = trained
    adapter = TransformerAdapter(net, src_max_len=7)

    from mxnet_tpu.precision.quantize import calibrate, collect_quantizable

    layers = collect_quantizable(net)
    with pytest.raises(MXNetError) as ei:
        # observe() never fires (calib_fn does nothing) -> the
        # calibrator has no data for any layer
        calibrate(layers, [src[:1]], lambda batch: None,
                  calib_mode="naive")
    assert "no calibration data" in str(ei.value)


def test_quantize_adapter_requires_model():
    from mxnet_tpu.serving import FullPrefixAdapter

    ad = FullPrefixAdapter(lambda F, buf: None, max_len=8)
    with pytest.raises(MXNetError, match="model"):
        QuantizedAdapter(ad, {})


def test_calibrate_observes_through_hybridized_blocks():
    """Forward-pre hooks never fire through a CachedOp fast path, so
    calibrate(root=...) must deactivate hybridized blocks for the eager
    pass (the quantize_net recipe) and restore them after — without
    root, a hybridized serving model would raise 'no calibration data'
    for every layer."""
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.precision.quantize import calibrate, collect_quantizable

    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu", in_units=4),
                nn.Dense(4, in_units=8))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    x = nd.array(np.random.RandomState(0).rand(2, 4).astype(np.float32))
    net(x)  # build the cached graph
    assert net._active

    layers = collect_quantizable(net)
    # without root the hooks never observe through the cached graph
    with pytest.raises(MXNetError, match="no calibration data"):
        calibrate(layers, [x], lambda b: net(b), calib_mode="naive")
    thresholds = calibrate(layers, [x], lambda b: net(b),
                           calib_mode="naive", root=net)
    assert set(thresholds) == {p for p, _ in layers}
    assert all(t > 0 for t in thresholds.values())
    assert net._active  # hybridization restored after the pass


# ---------------------------------------------------------------------------
# AOT round-trip in a second process (the restart story)
# ---------------------------------------------------------------------------
_AOT_CHILD = r"""
import json, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.models.transformer import Transformer
from mxnet_tpu.precision import quantize_adapter
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

mx.random.seed(0)
net = Transformer(16, units=32, hidden_size=64, num_heads=4, num_layers=2,
                  max_length=48, dropout=0.0)
net.initialize(mx.init.Xavier())
rng = np.random.RandomState(4)
prompts = [rng.randint(3, 16, 4) for _ in range(3)]

def calib_fn(batch):
    net.translate(nd.array(batch.reshape(1, -1), dtype="int32"), bos_id=1,
                  eos_id=2, max_len=6, beam_size=1)

qad = quantize_adapter(TransformerAdapter(net, src_max_len=6), prompts,
                       calib_fn, calib_mode="naive")
eng = ServingEngine(qad, slots=2, page_size=4, max_len=8, stream_every=2)
out = eng.serve([Request(prompts[0], max_new_tokens=5, bos_id=1, eos_id=2)])
evs = [e for e in telemetry.flight_tail(256) if e["kind"] == "compile"
       and e.get("executor") == "ServingEngine"]
print("QAOT " + json.dumps({"compiles": evs,
                            "tokens": [int(t) for t in
                                       list(out.values())[0]]}))
"""


def test_quantized_aot_cache_roundtrip(tmp_path):
    """ACCEPTANCE: the int8 decode + prefill executables persist through
    the AOT cache — a restarted quantized serving process asserts
    cache_hit on both compile events and decodes identical tokens.
    Fresh private jax compile cache per phase (the test_serving
    recipe: serializing a jax-compile-cache-loaded executable is
    unloadable on this XLA:CPU)."""
    import subprocess
    import sys

    def run_phase(tele_dir):
        env = dict(os.environ,
                   MX_EXECUTABLE_CACHE_DIR=str(tmp_path / "aot"),
                   MX_TELEMETRY_DIR=str(tmp_path / tele_dir),
                   JAX_COMPILATION_CACHE_DIR=str(tmp_path / "jaxcache"),
                   JAX_PLATFORMS="cpu")
        out = subprocess.run([sys.executable, "-c", _AOT_CHILD], env=env,
                             capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("QAOT ")][-1]
        return json.loads(line[len("QAOT "):])

    first = run_phase("tele1")
    assert len(first["compiles"]) == 2
    assert all(not e.get("cache_hit") for e in first["compiles"])

    second = run_phase("tele2")
    assert len(second["compiles"]) == 2, second
    for e in second["compiles"]:
        assert e.get("cache_hit") is True, e
        assert e.get("deserialize_ms", 0) > 0
    assert second["tokens"] == first["tokens"]
