"""Mesh/sharding/fused-train-step tests over the virtual 8-device CPU mesh
(SURVEY §4.4 item 4: multi-device testing without hardware multiplicity)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon import nn
from mxnet_tpu.models import bert_small
from mxnet_tpu.models.bert import bert_sharding_rules
from mxnet_tpu.parallel import DataParallelStep, make_mesh, local_mesh


def test_make_mesh_axes():
    mesh = make_mesh(tp=2)
    assert mesh.axis_names == ("dp", "pp", "sp", "tp", "ep")
    assert mesh.devices.shape == (4, 1, 1, 2, 1)
    mesh2 = local_mesh()
    assert mesh2.devices.size == 8


def test_fused_dp_step_converges():
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    X = np.random.randn(64, 10).astype(np.float32)
    W = np.random.randn(10, 3).astype(np.float32)
    Y = np.argmax(X @ W, axis=1).astype(np.float32)

    step = DataParallelStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            mesh=local_mesh(),
                            optimizer="sgd",
                            optimizer_params={"learning_rate": 0.5,
                                              "momentum": 0.9})
    losses = []
    for _ in range(40):
        loss = step.step(nd.array(X), nd.array(Y))
        losses.append(float(np.asarray(loss)))
    assert losses[-1] < 0.1 * losses[0], f"no convergence: {losses[:3]}...{losses[-3:]}"
    # write back and check eager forward agrees
    step.sync_to_block()
    acc = mx.metric.Accuracy()
    acc.update(nd.array(Y), net(nd.array(X)))
    assert acc.get()[1] > 0.95


def test_bert_tp_dp_step():
    """BERT-small training step sharded dp=4 x tp=2 over 8 devices."""
    mesh = make_mesh(tp=2)
    net = bert_small()
    net.initialize(mx.init.Normal(0.02))

    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    step = DataParallelStep(net, mlm_loss, mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            rules=bert_sharding_rules())
    B, T, V = 8, 16, 512
    tokens = np.random.randint(0, V, (B, T)).astype(np.int32)
    labels = tokens.astype(np.float32)
    l0 = None
    for i in range(8):
        loss = step.step(nd.array(tokens, dtype="int32"), nd.array(labels))
        if i == 0:
            l0 = float(np.asarray(loss))
    l_last = float(np.asarray(loss))
    assert np.isfinite(l_last)
    assert l_last < l0, "loss should decrease while memorizing a fixed batch"
    # verify the qkv weights actually carry a tp sharding
    qkv_names = [n for n in step.params if n.endswith("qkv_weight")]
    assert qkv_names
    sh = step.params[qkv_names[0]].sharding
    assert "tp" in str(sh.spec), f"expected tp sharding, got {sh.spec}"


def test_kvstore_device_collective_reduce():
    """Distinct-device pushes aggregate via the compiled psum all-reduce
    (replicated result, no lead-device funnel — r3 weak #4); semantics are
    identical to the staged-sum path."""
    kv = mx.kvstore.create("device")
    ctxs = [mx.cpu(i) for i in range(8)]
    shape = (3, 4)
    kv.init("w", nd.zeros(shape, ctx=ctxs[0]))
    grads = [nd.ones(shape, ctx=c) * (i + 1) for i, c in enumerate(ctxs)]
    kv.push("w", grads)
    # collective path actually taken: the stored value is mesh-replicated
    stored = kv._store["w"]
    assert len(stored._data.sharding.device_set) == 8, stored._data.sharding
    outs = [nd.zeros(shape, ctx=c) for c in ctxs]
    kv.pull("w", outs)
    expect = np.full(shape, sum(range(1, 9)), np.float32)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), expect)

    # updater path: server-side optimizer against the single-device store
    kv2 = mx.kvstore.create("device")
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv2.init(0, nd.ones(shape, ctx=ctxs[0]))
    kv2.push(0, [nd.ones(shape, ctx=c) for c in ctxs])  # grad sum = 8
    w = nd.zeros(shape, ctx=ctxs[0])
    kv2.pull(0, w)
    np.testing.assert_allclose(w.asnumpy(), np.full(shape, 1.0 - 0.1 * 8),
                               rtol=1e-6)

    # updater installed AFTER a replicated non-updater push: the store
    # value is mesh-replicated at that point and must be localized before
    # the eager updater mixes device sets (r4 review finding)
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push("w", [nd.ones(shape, ctx=c) for c in ctxs])
    w2 = nd.zeros(shape, ctx=ctxs[3])
    kv.pull("w", w2)
    np.testing.assert_allclose(
        w2.asnumpy(), expect - 0.1 * 8, rtol=1e-6)


def test_kvstore_semantics():
    kv = mx.kvstore.create("device")
    kv.init(3, nd.ones((2, 2)))
    # push/pull aggregation without updater: pull returns the pushed sum
    kv.push(3, [nd.ones((2, 2)), nd.ones((2, 2)) * 2])
    out = nd.zeros((2, 2))
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), 3 * np.ones((2, 2)))
    with pytest.raises(mx.MXNetError):
        mx.kvstore.create("dist_async")


def test_sequence_parallel_shards_T_dim():
    """With an active sp axis, DataParallelStep shards the sequence dim of
    the inputs over it (true SP: GSPMD inserts the attention collectives),
    and the loss matches the dp-only run."""
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.models import bert_small
    from mxnet_tpu.models.bert import bert_sharding_rules
    from mxnet_tpu.parallel import DataParallelStep, make_mesh
    from mxnet_tpu.parallel.sharding import shard_batch_seq

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(sp=2, devices=devices)  # dp2 x sp2

    # the sharding object itself splits dim 1
    sh = shard_batch_seq(mesh, 2)
    assert sh.spec == jax.sharding.PartitionSpec("dp", "sp")

    def run(m):
        mx.random.seed(0)
        net = bert_small()
        net.initialize(mx.init.Normal(0.02))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        def mlm_loss(logits, labels):
            return loss_fn(logits.reshape(-1, logits.shape[-1]),
                           labels.reshape(-1))

        step = DataParallelStep(net, mlm_loss, mesh=m, optimizer="adam",
                                optimizer_params={"learning_rate": 1e-3},
                                rules=bert_sharding_rules())
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 512, (4, 16)).astype(np.int32)
        return float(np.asarray(step.step(
            nd.array(tokens, dtype="int32"),
            nd.array(tokens.astype(np.float32)))))

    sp_loss = run(mesh)
    dp_loss = run(make_mesh(devices=devices))  # pure dp4
    # 2e-3: this jax build's GSPMD collectives drift ~1e-3 relative vs the
    # dp-only trajectory (same tolerance the bert_pp/sp parity tests use)
    np.testing.assert_allclose(sp_loss, dp_loss, rtol=2e-3)


def test_ring_attention_training_step_parity():
    """DataParallelStep(ring_attention=True) on a dp2 x sp2 mesh: the
    model's fused-attention op lowers to the ring kernel (ppermute K/V
    rotation) and the loss matches the GSPMD all-gather path."""
    import jax

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(sp=2, devices=devices)

    def run(ring):
        mx.random.seed(0)
        net = bert_small(dropout=0.0)  # attention-prob dropout off -> the
        # MultiHeadAttention flash path (where ring hooks in) is taken
        net.initialize(mx.init.Normal(0.02))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        def mlm_loss(logits, labels):
            return loss_fn(logits.reshape(-1, logits.shape[-1]),
                           labels.reshape(-1))

        step = DataParallelStep(net, mlm_loss, mesh=mesh, optimizer="adam",
                                optimizer_params={"learning_rate": 1e-3},
                                rules=bert_sharding_rules(),
                                ring_attention=ring)
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, 512, (4, 16)).astype(np.int32)
        losses = []
        for _ in range(2):
            losses.append(float(np.asarray(step.step(
                nd.array(tokens, dtype="int32"),
                nd.array(tokens.astype(np.float32))))))
        return losses

    base = run(False)  # one GSPMD baseline serves both comparisons
    np.testing.assert_allclose(run(True), base, rtol=2e-4)
    # Ulysses mode: same losses through the all-to-all SP route
    np.testing.assert_allclose(run("ulysses"), base, rtol=2e-4)

    # routing proof: under the scope the op lowers to ppermute rotations
    # (collective-permute in the compiled module), not a K/V all-gather
    import jax.numpy as jnp

    from mxnet_tpu.ops import pallas as _pk
    from mxnet_tpu.ops.registry import get_op
    from mxnet_tpu.parallel import ring_attention_scope

    op = get_op("_contrib_flash_attention")
    qj = jnp.asarray(np.random.RandomState(1).randn(4, 16, 8).astype(np.float32))
    with _pk.compute_on("cpu"), ring_attention_scope(mesh):
        txt = jax.jit(lambda a, b, c: op.fn(a, b, c, causal=True)).lower(
            qj, qj, qj).compile().as_text()
    assert "collective-permute" in txt
    # ...and the ulysses mode lowers to all-to-all resharding, so its
    # parity above cannot have passed vacuously through the dense path
    with _pk.compute_on("cpu"), ring_attention_scope(mesh, mode="ulysses"):
        txt_u = jax.jit(lambda a, b, c: op.fn(a, b, c, causal=True)).lower(
            qj, qj, qj).compile().as_text()
    assert "all-to-all" in txt_u, txt_u[:500]
    with pytest.raises(mx.MXNetError):
        with ring_attention_scope(mesh, mode="ullyses"):
            pass


def test_pipeline_apply_matches_sequential():
    """GPipe-style pipeline over pp=4: outputs and gradients match running
    the stacked layers sequentially (the §2.3 PP capability row)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.parallel import make_mesh, pipeline_apply

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(pp=4, devices=devices)

    L, C, M, B = 8, 6, 8, 2  # 8 layers -> 2 per stage; 8 microbatches
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(L, C, C).astype(np.float32) * 0.3)
    b = jnp.asarray(rng.randn(L, C).astype(np.float32) * 0.1)
    x = jnp.asarray(rng.randn(M, B, C).astype(np.float32))

    def layer(p, h):
        w_l, b_l = p
        return jnp.tanh(h @ w_l + b_l)

    def sequential(params, xm):
        out, _ = jax.lax.scan(lambda c, pl: (layer(pl, c), None), xm, params)
        return out

    out_pipe = pipeline_apply(mesh, layer, (W, b), x)
    out_seq = jax.vmap(lambda xm: sequential((W, b), xm))(x)
    np.testing.assert_allclose(np.asarray(out_pipe), np.asarray(out_seq),
                               rtol=1e-5, atol=1e-6)

    # gradients flow through the pipelined schedule identically
    g_pipe = jax.grad(lambda w: pipeline_apply(
        mesh, layer, (w, b), x).sum())(W)
    g_seq = jax.grad(lambda w: jax.vmap(
        lambda xm: sequential((w, b), xm))(x).sum())(W)
    np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                               rtol=1e-4, atol=1e-5)


def test_accum_steps_matches_single_pass():
    """accum_steps=2 (microbatch loop inside the one XLA program) computes
    the same mean gradient as a single full-batch pass: identical losses
    step after step (Dense-only net — BN batch stats would legitimately
    differ per microbatch)."""
    import jax

    devices = jax.devices("cpu")[:2]

    def run(accum):
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize(mx.init.Xavier())
        step = DataParallelStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                mesh=local_mesh(devices=devices),
                                optimizer="sgd", accum_steps=accum,
                                optimizer_params={"learning_rate": 0.1,
                                                  "momentum": 0.9})
        rng = np.random.RandomState(5)
        x = nd.array(rng.rand(8, 10).astype(np.float32))
        y = nd.array(rng.randint(0, 4, 8).astype(np.float32))
        return [float(np.asarray(step.step(x, y))) for _ in range(4)]

    np.testing.assert_allclose(run(1), run(2), rtol=1e-5)

    # BN aux state flows through the accumulated step (averaged over
    # microbatches) and training still descends
    mx.random.seed(0)
    net_bn = nn.HybridSequential()
    with net_bn.name_scope():
        net_bn.add(nn.Dense(16), nn.BatchNorm(), nn.Activation("relu"),
                   nn.Dense(4))
    net_bn.initialize(mx.init.Xavier())
    stepb = DataParallelStep(net_bn, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mesh=local_mesh(devices=devices),
                             optimizer="sgd", accum_steps=2,
                             optimizer_params={"learning_rate": 0.1})
    rng = np.random.RandomState(6)
    xb = nd.array(rng.rand(8, 10).astype(np.float32))
    yb = nd.array(rng.randint(0, 4, 8).astype(np.float32))
    lb = [float(np.asarray(stepb.step(xb, yb))) for _ in range(6)]
    assert all(np.isfinite(lb)) and lb[-1] < lb[0]
    stepb.sync_to_block()
    rm = net_bn.collect_params()[
        [k for k in net_bn.collect_params() if "running_mean" in k][0]]
    assert float(np.abs(rm.data().asnumpy()).sum()) > 0  # stats moved

    # indivisible batch is a caller error
    net = nn.Dense(2)
    net.initialize(mx.init.Xavier())
    bad = DataParallelStep(net, gluon.loss.L2Loss(),
                           mesh=local_mesh(devices=devices),
                           optimizer="sgd", accum_steps=3)
    with pytest.raises(mx.MXNetError):
        bad.step(nd.array(np.random.rand(8, 4).astype(np.float32)),
                 nd.array(np.random.rand(8, 2).astype(np.float32)))


def test_remat_step_matches_plain():
    """remat=True (jax.checkpoint over the forward) must change memory, not
    math: same loss as the plain fused step."""
    import jax

    devices = jax.devices("cpu")[:2]

    def run(remat):
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            # BatchNorm included deliberately: its aux-state updates carry
            # string names, which the remat wrapper must keep OUT of the
            # checkpointed region (r4 review finding)
            net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(),
                    nn.Dense(4))
        net.initialize(mx.init.Xavier())
        step = DataParallelStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                                mesh=local_mesh(devices=devices),
                                optimizer="sgd", remat=remat,
                                optimizer_params={"learning_rate": 0.1})
        rng = np.random.RandomState(3)
        x = nd.array(rng.rand(8, 10).astype(np.float32))
        y = nd.array(rng.randint(0, 4, 8).astype(np.float32))
        return [float(np.asarray(step.step(x, y))) for _ in range(3)]

    np.testing.assert_allclose(run(False), run(True), rtol=1e-6)


def test_sp_mesh_image_batch_falls_back_to_dp(tmp_path):
    """r3 advisor (medium): on an sp>1 mesh, image batches — whose dim 1 is
    channels (NCHW) or height (NHWC), not a sequence — must NOT be
    sequence-sharded in auto mode when dim 1 isn't divisible; the batch dim
    is sharded over dp*sp instead, as in r2."""
    import jax

    devices = jax.devices("cpu")[:4]
    mesh = make_mesh(sp=2, devices=devices)  # dp2 x sp2

    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(4, 3, padding=1, in_channels=3),
                nn.GlobalAvgPool2D(), nn.Flatten(), nn.Dense(3))
    net.initialize(mx.init.Xavier())
    step = DataParallelStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            mesh=mesh, optimizer="sgd",
                            optimizer_params={"learning_rate": 0.1})
    # NCHW: dim 1 = 3 channels, not divisible by sp=2 -> dp*sp fallback
    x = nd.array(np.random.rand(8, 3, 6, 6).astype(np.float32))
    y = nd.array(np.random.randint(0, 3, 8).astype(np.float32))
    loss = float(np.asarray(step.step(x, y)))
    assert np.isfinite(loss)

    # explicit opt-out works even for divisible dims
    net2 = nn.HybridSequential()
    with net2.name_scope():
        net2.add(nn.Dense(3))
    net2.initialize(mx.init.Xavier())
    step2 = DataParallelStep(net2, gluon.loss.SoftmaxCrossEntropyLoss(),
                             mesh=mesh, optimizer="sgd", seq_axis=-1,
                             optimizer_params={"learning_rate": 0.1})
    x2 = nd.array(np.random.rand(8, 4).astype(np.float32))
    loss2 = float(np.asarray(step2.step(x2, y)))
    assert np.isfinite(loss2)

    with pytest.raises(mx.MXNetError):
        DataParallelStep(net2, gluon.loss.SoftmaxCrossEntropyLoss(),
                         mesh=mesh, seq_axis=2)


def test_fused_step_lr_schedule():
    """lr is a device-scalar step argument: an lr_scheduler changes the
    update magnitude step to step WITHOUT retracing, and matches the
    Optimizer's post-increment num_update convention."""
    from mxnet_tpu.optimizer.lr_scheduler import FactorScheduler

    def make(scheduled):
        mx.random.seed(7)
        np.random.seed(7)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(4))
        net.initialize(mx.init.Xavier())
        params = {"learning_rate": 0.2, "momentum": 0.0}
        if scheduled:
            params["lr_scheduler"] = FactorScheduler(step=1, factor=0.5)
        return DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                                optimizer="sgd", optimizer_params=params)

    X = np.random.RandomState(0).rand(8, 6).astype(np.float32)
    Y = np.random.RandomState(1).rand(8, 4).astype(np.float32)
    runs = {}
    for scheduled in (False, True):
        s = make(scheduled)
        assert s.learning_rate == pytest.approx(0.2)
        snaps = []
        for _ in range(2):
            s.step(nd.array(X), nd.array(Y))
            snaps.append({n: np.asarray(v) for n, v in s.params.items()})
        runs[scheduled] = snaps
        if scheduled:  # property reports the NEXT step's lr: num_update=3
            assert s.learning_rate == pytest.approx(0.05)
    # step 1 identical (both lr=0.2), step 2 diverges (0.2 vs 0.1);
    # param names carry distinct block-counter prefixes -> zip sorted
    pairs = list(zip(sorted(runs[True][0]), sorted(runs[False][0])))
    for a, b in pairs:
        np.testing.assert_allclose(runs[True][0][a], runs[False][0][b],
                                   rtol=1e-6)
    assert any(not np.allclose(runs[True][1][a], runs[False][1][b])
               for a, b in pairs)
    # retrace check: the jitted step compiled exactly once per run
    # (lr rides as an argument, not a trace constant)


def test_fused_step_set_learning_rate():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(2))
    net.initialize(mx.init.Xavier())
    s = DataParallelStep(net, gluon.loss.L2Loss(), mesh=local_mesh(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.0})
    X = nd.array(np.random.rand(8, 3).astype(np.float32))
    Y = nd.array(np.random.rand(8, 2).astype(np.float32))
    s.step(X, Y)
    before = {n: np.asarray(v) for n, v in s.params.items()}
    s.set_learning_rate(0.0)
    s.step(X, Y)
    for n, v in s.params.items():
        np.testing.assert_allclose(np.asarray(v), before[n], atol=1e-7)


def test_fused_step_clip_matches_trainer():
    """Per-element clip_gradient in the fused step == Trainer/Optimizer
    semantics (clip after rescale, before wd)."""
    clip, lr, wd = 1e-3, 0.5, 0.01

    def init_net():
        mx.random.seed(11)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(4, in_units=6))
        net.initialize(mx.init.Xavier())
        return net

    rs = np.random.RandomState(3)
    X = (100.0 * rs.rand(8, 6)).astype(np.float32)  # big grads -> clip active
    Y = rs.rand(8, 4).astype(np.float32)

    # Trainer path
    net_t = init_net()
    trainer = gluon.Trainer(net_t.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9, "wd": wd,
                             "clip_gradient": clip})
    from mxnet_tpu import autograd
    loss_fn = gluon.loss.L2Loss()
    with autograd.record():
        loss = loss_fn(net_t(nd.array(X)), nd.array(Y))
    loss.backward()
    trainer.step(X.shape[0])

    # fused path: mean loss == sum/B, so rescale_grad stays 1.0
    net_f = init_net()
    s = DataParallelStep(net_f, loss_fn, mesh=local_mesh(), optimizer="sgd",
                         optimizer_params={"learning_rate": lr,
                                           "momentum": 0.9, "wd": wd,
                                           "clip_gradient": clip})
    s.step(nd.array(X), nd.array(Y))
    s.sync_to_block()
    pt = net_t.collect_params()
    pf = net_f.collect_params()
    for nt, nf in zip(sorted(pt), sorted(pf)):  # prefixes carry counters
        np.testing.assert_allclose(np.asarray(pf[nf].data()._data),
                                   np.asarray(pt[nt].data()._data),
                                   rtol=1e-5, atol=1e-7)


def test_fused_step_global_norm_clip():
    """clip_global_norm scales the whole gradient tree to the target L2
    norm (gluon.utils.clip_global_norm semantics, compiled)."""
    cmax, lr = 0.5, 1.0

    def init_net():
        mx.random.seed(13)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(3, in_units=5, use_bias=False))
        net.initialize(mx.init.Xavier())
        return net

    rs = np.random.RandomState(5)
    X = (50.0 * rs.rand(8, 5)).astype(np.float32)
    Y = rs.rand(8, 3).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    # reference gradients, eagerly
    net_r = init_net()
    from mxnet_tpu import autograd
    with autograd.record():
        loss = loss_fn(net_r(nd.array(X)), nd.array(Y))
    loss.backward()
    w = list(net_r.collect_params().values())[0]
    g = np.asarray(w.grad()._data) / X.shape[0]  # mean-loss gradient
    gnorm = np.sqrt((g ** 2).sum())
    assert gnorm > cmax, "test needs an active clip"
    expected = np.asarray(w.data()._data) - lr * g * (cmax / gnorm)

    net_f = init_net()
    s = DataParallelStep(net_f, loss_fn, mesh=local_mesh(), optimizer="sgd",
                         optimizer_params={"learning_rate": lr,
                                           "momentum": 0.0},
                         clip_global_norm=cmax)
    s.step(nd.array(X), nd.array(Y))
    s.sync_to_block()
    got = np.asarray(list(net_f.collect_params().values())[0].data()._data)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-6)
