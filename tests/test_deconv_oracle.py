"""Deconvolution vs the torch oracle (reference:
src/operator/nn/deconvolution-inl.h — transposed conv = gradient of conv).

The r5 ONNX review exposed that the dilated-conv formulation was missing
the spatial kernel FLIP (plain deconv was numerically wrong, not just
grouped deconv broken) — loss-decrease tests can't catch kernel
orientation, so this pins every config against torch.conv_transpose2d."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from mxnet_tpu import nd  # noqa: E402


@pytest.mark.parametrize(
    "cin,cout_per_g,groups,kernel,stride,pad,adj,dilate",
    [
        (4, 3, 1, (3, 3), (2, 2), (1, 1), (1, 1), (1, 1)),
        (4, 2, 2, (3, 3), (2, 2), (1, 1), (1, 1), (1, 1)),
        (6, 2, 3, (2, 2), (1, 1), (0, 0), (0, 0), (1, 1)),
        (4, 3, 1, (2, 3), (1, 1), (0, 0), (0, 0), (2, 2)),  # asymmetric k
        (4, 3, 1, (3, 3), (3, 3), (2, 2), (2, 2), (1, 1)),
    ])
def test_deconvolution_matches_torch(cin, cout_per_g, groups, kernel,
                                     stride, pad, adj, dilate):
    rng = np.random.RandomState(0)
    x = rng.randn(2, cin, 5, 5).astype(np.float32)
    w = rng.randn(cin, cout_per_g, *kernel).astype(np.float32)
    b = rng.randn(cout_per_g * groups).astype(np.float32)

    y_ref = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), torch.tensor(b),
        stride=stride, padding=pad, output_padding=adj,
        dilation=dilate, groups=groups).numpy()
    y = nd.Deconvolution(
        nd.array(x), nd.array(w), nd.array(b), kernel=kernel,
        stride=stride, pad=pad, adj=adj, dilate=dilate,
        num_filter=cout_per_g * groups, num_group=groups,
        no_bias=False).asnumpy()
    np.testing.assert_allclose(y_ref, y, atol=5e-5, rtol=1e-4)
