"""CPU-vs-TPU consistency oracle (reference:
tests/python/gpu/test_operator_gpu.py check_consistency — the framework's
main correctness check for a new backend, SURVEY §4.4 item 1).

The suite's conftest pins this process to the virtual CPU mesh, so the TPU
half runs in a SUBPROCESS with the default (axon) platform.  Skips cleanly
when no TPU is reachable (tunnel down / CPU-only environment).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

_PROBE_TIMEOUT = 90

_CHILD = r"""
import json, sys
import numpy as np

def main():
    import jax
    devs = jax.devices()
    if all(d.platform == "cpu" for d in devs):
        print(json.dumps({"skip": "cpu-only"}))
        return
    import mxnet_tpu as mx
    from mxnet_tpu import nd, autograd, gluon

    mx.random.seed(0)
    ctx = mx.tpu()
    rng = np.random.RandomState(0)
    out = {}

    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(6, 8).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    out["fc"] = np.asarray(nd.FullyConnected(
        nd.array(x, ctx=ctx), nd.array(w, ctx=ctx), nd.array(b, ctx=ctx),
        num_hidden=6).asnumpy()).tolist()

    img = rng.randn(2, 3, 8, 8).astype(np.float32)
    k = rng.randn(4, 3, 3, 3).astype(np.float32)
    out["conv"] = np.asarray(nd.Convolution(
        nd.array(img, ctx=ctx), nd.array(k, ctx=ctx), kernel=(3, 3),
        num_filter=4, no_bias=True, pad=(1, 1)).asnumpy()).tolist()

    out["softmax"] = np.asarray(nd.softmax(
        nd.array(x, ctx=ctx)).asnumpy()).tolist()

    # gradient consistency through the tape
    xs = nd.array(x, ctx=ctx)
    xs.attach_grad()
    with autograd.record():
        loss = (nd.tanh(xs) ** 2).sum()
    loss.backward()
    out["tanh_sq_grad"] = np.asarray(xs.grad.asnumpy()).tolist()
    print(json.dumps(out))

main()
"""


def _tpu_results():
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # let the axon default platform load
    if os.path.isdir("/root/.axon_site"):
        env["PYTHONPATH"] = "/root/.axon_site"
        env["JAX_PLATFORMS"] = "axon"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # liveness probe, session-cached (r4 verdict #8): the first pytest run
    # of a session pays ~90s against a dead relay, every later run reads
    # the cached verdict (negatives age out per bench.PROBE_TTL)
    sys.path.insert(0, root)
    import bench as _bench

    if not _bench._probe_tpu([], use_cache=True, attempts=1):
        pytest.skip("TPU unreachable (session-cached probe verdict)")
    try:
        proc = subprocess.run([sys.executable, "-c", _CHILD],
                              capture_output=True, text=True,
                              timeout=360, env=env, cwd=root)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU unreachable (subprocess timed out)")
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    if proc.returncode != 0 or not lines:
        pytest.skip(f"TPU subprocess failed: {proc.stderr[-400:]}")
    payload = json.loads(lines[-1])
    if "skip" in payload:
        pytest.skip(f"no TPU: {payload['skip']}")
    return payload


def test_cpu_vs_tpu_consistency():
    tpu = _tpu_results()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    mx.random.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype(np.float32)
    w = rng.randn(6, 8).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    fc = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b),
                           num_hidden=6).asnumpy()
    np.testing.assert_allclose(fc, np.array(tpu["fc"], np.float32),
                               rtol=2e-2, atol=1e-3)

    img = rng.randn(2, 3, 8, 8).astype(np.float32)
    k = rng.randn(4, 3, 3, 3).astype(np.float32)
    conv = nd.Convolution(nd.array(img), nd.array(k), kernel=(3, 3),
                          num_filter=4, no_bias=True, pad=(1, 1)).asnumpy()
    np.testing.assert_allclose(conv, np.array(tpu["conv"], np.float32),
                               rtol=2e-2, atol=1e-3)

    sm = nd.softmax(nd.array(x)).asnumpy()
    np.testing.assert_allclose(sm, np.array(tpu["softmax"], np.float32),
                               rtol=1e-3, atol=1e-5)

    xs = nd.array(x)
    xs.attach_grad()
    with autograd.record():
        loss = (nd.tanh(xs) ** 2).sum()
    loss.backward()
    np.testing.assert_allclose(
        xs.grad.asnumpy(), np.array(tpu["tanh_sq_grad"], np.float32),
        rtol=1e-3, atol=1e-5)


def test_registry_sweep_consistency():
    """The REAL oracle (r3 verdict #5): replay a registry-wide slice of
    test_op_sweep cases chip-vs-host through tools/check_consistency.py —
    one implementation shared with the standalone tool; the full sweep is
    `python tools/check_consistency.py` with no --limit."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.setdefault("BENCH_PROBE_TIMEOUT", str(_PROBE_TIMEOUT))
    out_path = os.path.join(root, "CONSISTENCY.json")
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "check_consistency.py"),
             "--limit", "60", "--out", out_path],
            capture_output=True, text=True, timeout=900, env=env, cwd=root)
    except subprocess.TimeoutExpired:
        pytest.skip("TPU unreachable (oracle timed out)")
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, proc.stderr[-500:]
    report = json.loads(lines[-1])
    if report.get("skipped"):
        pytest.skip(f"no TPU: {report.get('reason')}")
    assert proc.returncode == 0, (proc.stdout[-800:], proc.stderr[-400:])
    assert report["cases_compared"] > 0
    assert report["mismatches"] == 0 and report["tpu_errors"] == 0, report
