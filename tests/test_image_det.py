"""ImageDetIter + bbox-aware augmentation (reference:
python/mxnet/image/detection.py; the SSD-512 input path of BASELINE
config 5) and the new pixel augmenters / native iterator options.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image as img_mod
from mxnet_tpu import nd, recordio
from mxnet_tpu.image.detection import (_parse_det_label, pack_det_label,
                                       DetHorizontalFlipAug,
                                       DetRandomCropAug, DetRandomPadAug,
                                       CreateDetAugmenter, ImageDetIter)

cv2 = pytest.importorskip("cv2")


def _make_det_rec(tmp_path, n=12, size=48):
    """Write a tiny .rec/.idx of synthetic images with det labels."""
    rec_path = str(tmp_path / "det.rec")
    idx_path = str(tmp_path / "det.idx")
    rng = np.random.RandomState(0)
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), np.uint8)
        objs = np.array([[i % 3, 0.2, 0.3, 0.6, 0.7],
                         [(i + 1) % 3, 0.1, 0.1, 0.4, 0.5]], np.float32)
        label = pack_det_label(objs)
        header = recordio.IRHeader(0, label, i, 0)
        packed = recordio.pack_img(header, arr, quality=90)
        writer.write_idx(i, packed)
    writer.close()
    return rec_path


def test_pack_parse_roundtrip():
    objs = np.array([[1, 0.1, 0.2, 0.5, 0.6], [2, 0.3, 0.3, 0.9, 0.8]],
                    np.float32)
    flat = pack_det_label(objs)
    back, w = _parse_det_label(flat)
    assert w == 5
    np.testing.assert_allclose(back, objs)


def test_det_hflip_flips_boxes():
    import random as pyrandom

    pyrandom.seed(0)
    aug = DetHorizontalFlipAug(p=1.0)
    src = np.arange(2 * 4 * 3, dtype=np.uint8).reshape(2, 4, 3)
    label = np.array([[0, 0.1, 0.2, 0.4, 0.6]], np.float32)
    out, lab = aug(src, label)
    np.testing.assert_array_equal(out, src[:, ::-1])
    np.testing.assert_allclose(lab[0], [0, 0.6, 0.2, 0.9, 0.6], atol=1e-6)


def test_det_random_crop_keeps_valid_boxes():
    import random as pyrandom

    pyrandom.seed(1)
    aug = DetRandomCropAug(min_object_covered=0.1, area_range=(0.3, 1.0))
    src = np.zeros((64, 64, 3), np.uint8)
    label = np.array([[1, 0.4, 0.4, 0.6, 0.6]], np.float32)
    for _ in range(10):
        out, lab = aug(src, label)
        assert lab.shape[1] == 5
        if lab.shape[0]:
            assert (lab[:, 1:] >= 0).all() and (lab[:, 1:] <= 1).all()
            assert (lab[:, 3] >= lab[:, 1]).all()


def test_det_random_crop_small_object_coverage():
    # regression: the accept criterion is object COVERAGE (inter/box area),
    # not crop-vs-box IoU — a crop containing a tiny box must be accepted
    import random as pyrandom

    pyrandom.seed(4)
    aug = DetRandomCropAug(min_object_covered=0.9, area_range=(0.5, 0.9))
    src = np.zeros((64, 64, 3), np.uint8)
    label = np.array([[1, 0.48, 0.48, 0.54, 0.54]], np.float32)  # tiny box
    accepted = 0
    for _ in range(20):
        out, lab = aug(src, label)
        if out.shape[:2] != (64, 64):
            accepted += 1
    assert accepted > 0, "crop never accepted despite full tiny-box coverage"


def test_det_random_pad_shrinks_boxes():
    import random as pyrandom

    pyrandom.seed(2)
    aug = DetRandomPadAug(area_range=(2.0, 2.0))
    src = np.full((32, 32, 3), 255, np.uint8)
    label = np.array([[0, 0.0, 0.0, 1.0, 1.0]], np.float32)
    out, lab = aug(src, label)
    assert out.shape[0] > 32 and out.shape[1] > 32
    w = lab[0, 3] - lab[0, 1]
    assert 0.4 < w < 0.9  # 1/sqrt(2) ~ 0.707


def test_image_det_iter_end_to_end(tmp_path):
    rec = _make_det_rec(tmp_path)
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32), path_imgrec=rec,
                      shuffle=True,
                      aug_list=CreateDetAugmenter((3, 32, 32), rand_crop=0.5,
                                                  rand_pad=0.5,
                                                  rand_mirror=True,
                                                  brightness=0.1))
    nbatch = 0
    for batch in it:
        data = batch.data[0]
        label = batch.label[0]
        assert data.shape == (4, 3, 32, 32)
        assert label.shape[0] == 4 and label.shape[2] == 5
        lab = label.asnumpy()
        valid = lab[lab[:, :, 0] >= 0]
        assert (valid[:, 1:] >= 0).all() and (valid[:, 1:] <= 1).all()
        nbatch += 1
    assert nbatch == 3


def test_image_det_iter_reshape(tmp_path):
    rec = _make_det_rec(tmp_path, n=4)
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32), path_imgrec=rec,
                      aug_list=[])
    it.reshape(data_shape=(3, 24, 24))
    batch = next(it)
    assert batch.data[0].shape == (2, 3, 24, 24)


def test_pixel_augmenters_shapes_and_ranges():
    import random as pyrandom

    pyrandom.seed(3)
    src = np.random.RandomState(3).randint(0, 255, (16, 16, 3),
                                           np.uint8).astype(np.float32)
    for aug in (img_mod.BrightnessJitterAug(0.2),
                img_mod.ContrastJitterAug(0.2),
                img_mod.SaturationJitterAug(0.2),
                img_mod.HueJitterAug(0.1),
                img_mod.LightingAug(0.1, np.array([55.46, 4.794, 1.148]),
                                    np.random.rand(3, 3)),
                img_mod.RandomGrayAug(1.0),
                img_mod.ColorNormalizeAug([123, 116, 103], [58, 57, 57])):
        out = aug(src)
        assert out.shape == src.shape, type(aug).__name__
        assert np.isfinite(np.asarray(out)).all(), type(aug).__name__


def test_create_augmenter_includes_color_pipeline():
    augs = img_mod.CreateAugmenter((3, 16, 16), rand_mirror=True,
                                   brightness=0.1, contrast=0.1,
                                   saturation=0.1, hue=0.1, pca_noise=0.05,
                                   rand_gray=0.05, mean=True, std=True)
    names = [type(a).__name__ for a in augs]
    for expect in ("ColorJitterAug", "HueJitterAug", "LightingAug",
                   "RandomGrayAug", "ColorNormalizeAug"):
        assert expect in names, names


def test_native_iter_new_augmenters(tmp_path):
    """hue/pca/chunked-shuffle options reach the C++ pipeline."""
    from mxnet_tpu.io import ImageRecordIter
    from mxnet_tpu.io import native as native_mod

    if not native_mod.available():
        pytest.skip("libmxio.so not built")
    rec = _make_det_rec(tmp_path, n=16)
    it = ImageRecordIter(path_imgrec=rec, data_shape=(3, 32, 32),
                         batch_size=4, shuffle=True, shuffle_chunk_size=1,
                         random_h=10, pca_noise=0.05, saturation=0.1,
                         label_width=1, preprocess_threads=2)
    count = 0
    for batch in it:
        assert batch.data[0].shape == (4, 3, 32, 32)
        assert np.isfinite(batch.data[0].asnumpy()).all()
        count += 1
    assert count == 4
