"""Serving front door, engine side (ISSUE 17; docs/SERVING.md
§Sampling, §Prefix cache, §Speculative decoding).

Covers: temperature=0 sampling BITWISE equal to the greedy-only engine
(the parity pin), seeded top-k/top-p decode reproducible across engine
restarts and different slot layouts, speculative decoding bitwise equal
to plain greedy at K in {1, 4} with a live acceptance rate, COW
prefix-cache forks bitwise equal to cold teacher-forcing plus the
forced-prefix continuation property, batched beam serving ==
standalone translate, the jax-free /statusz snapshot, and the
prefix/spec telemetry rollups + prometheus gauges.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.transformer import Transformer, label_smoothed_ce
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

PAD, BOS, EOS = 0, 1, 2


@pytest.fixture
def tele(tmp_path):
    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path))
    yield telemetry
    telemetry.reset()
    memwatch.reset()


def _tiny_model(vocab=16, max_length=48):
    mx.random.seed(0)
    net = Transformer(vocab, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=max_length, dropout=0.0)
    net.initialize(mx.init.Xavier())
    return net


def _reverse_batch(rng, B, L=6, vocab=16):
    src = np.zeros((B, L + 1), np.int32)
    tgt_in = np.zeros((B, L + 2), np.int32)
    tgt_out = np.zeros((B, L + 2), np.int32)
    for b in range(B):
        toks = rng.randint(3, vocab, L)
        src[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    return src, tgt_in, tgt_out


@pytest.fixture(scope="module")
def trained():
    """Reverse-task memorizer (test_serving.py idiom) — sharp logits so
    greedy decisions are stable across executables, the bitwise parity
    surface for sampling/spec/prefix."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net = _tiny_model(max_length=20)
    rng = np.random.RandomState(2)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)
    step = DataParallelStep(
        net, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))
    for _ in range(48):
        step.step((sb, tb), lb)
    step.sync_to_block()
    return net, src


def _engine(net, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_len", 16)
    kw.setdefault("stream_every", 4)
    return ServingEngine(TransformerAdapter(net, src_max_len=7), **kw)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------
def test_sampling_temp_zero_bitwise_greedy(trained):
    """ACCEPTANCE: temperature=0 through the sampling decode body is
    BITWISE the greedy-only engine — per-slot where(temp>0) keeps the
    argmax lane exact, so turning sampling on costs zero parity."""
    net, src = trained
    mk = lambda r: Request(src[r], max_new_tokens=9, bos_id=BOS,
                           eos_id=EOS)
    greedy = _engine(net).serve([mk(i) for i in range(4)],
                                arrival_steps=[0, 0, 2, 5])
    samp_reqs = [mk(i) for i in range(4)]
    samp = _engine(net, sampling=True).serve(samp_reqs,
                                             arrival_steps=[0, 0, 2, 5])
    for a, b in zip(greedy.values(), samp.values()):
        np.testing.assert_array_equal(a, b)
    assert all(r.temperature == 0.0 for r in samp_reqs)


def test_seeded_sampling_reproducible_across_restarts():
    """ACCEPTANCE: seeded top-k/top-p decode is a pure function of the
    request (seed included) — a fresh engine with a DIFFERENT slot
    count replays identical tokens for every request, and the sampled
    streams genuinely diverge from greedy."""
    net = _tiny_model()
    rng = np.random.RandomState(5)
    prompts = [rng.randint(3, 16, 5) for _ in range(4)]

    def decode(slots, temp):
        eng = _engine(net, slots=slots, sampling=True)
        reqs = [Request(p, max_new_tokens=8, bos_id=BOS, eos_id=-1,
                        temperature=temp, top_k=6, top_p=0.9,
                        seed=100 + i) for i, p in enumerate(prompts)]
        out = eng.serve(reqs)
        return [list(out[r.id]) for r in reqs]

    first = decode(slots=3, temp=0.9)
    again = decode(slots=2, temp=0.9)  # restart + different slot layout
    assert first == again
    greedy = decode(slots=3, temp=0.0)
    assert first != greedy, "temp 0.9 on flat logits must not be argmax"
    # distinct seeds → distinct streams (same prompt-free randomness)
    assert len({tuple(s) for s in first}) > 1


def test_sampling_rejected_on_greedy_engine():
    net = _tiny_model()
    eng = _engine(net)  # sampling defaulted OFF: parity-pinned build
    with pytest.raises(MXNetError, match="MX_SERVE_SAMPLING"):
        eng.submit(Request(np.array([3, 4], np.int32), max_new_tokens=4,
                           bos_id=BOS, eos_id=EOS, temperature=0.7))


# ---------------------------------------------------------------------------
# speculative decoding
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("K", [1, 4])
def test_spec_decode_greedy_bitwise(trained, K):
    """ACCEPTANCE: draft-propose + one ("verify", K) ragged dispatch per
    boundary emits token-for-token what the plain greedy engine emits —
    rejection resampling degenerates to argmax equality under greedy, so
    speculation is invisible in the output."""
    net, src = trained
    mk = lambda r: Request(src[r], max_new_tokens=9, bos_id=BOS,
                           eos_id=EOS)
    plain = _engine(net).serve([mk(i) for i in range(4)],
                               arrival_steps=[0, 0, 3, 6])
    eng = _engine(net, spec_k=K)
    spec = eng.serve([mk(i) for i in range(4)],
                     arrival_steps=[0, 0, 3, 6])
    for a, b in zip(plain.values(), spec.values()):
        np.testing.assert_array_equal(a, b)
    # the speculation actually ran and accepted something
    assert eng._spec_proposed > 0
    assert 0 < eng._spec_accepted <= eng._spec_proposed


# ---------------------------------------------------------------------------
# prefix cache
# ---------------------------------------------------------------------------
def test_prefix_fork_bitwise_and_continuation(trained):
    """ACCEPTANCE: (a) a forced decoder prefix continues EXACTLY where
    the plain greedy decode left off (teacher-forcing writes the same KV
    rows free decode would have), and (b) a prefix-cache HIT — COW
    page fork off the registered entry — is bitwise the cold
    teacher-forced miss, cache on or off."""
    net, src = trained
    plain = _engine(net).serve(
        [Request(src[0], max_new_tokens=10, bos_id=BOS,
                 eos_id=-1, request_id="p")])["p"]
    prefix = np.asarray(plain[:4], np.int32)

    def cont(prefix_cache):
        eng = _engine(net, prefix_cache=prefix_cache)
        reqs = [Request(src[0], max_new_tokens=6, bos_id=BOS, eos_id=-1,
                        prefix=prefix) for _ in range(2)]
        eng.serve([reqs[0]])   # cold: miss + ingest (+ register)
        eng.serve([reqs[1]])   # warm: COW fork when the cache is on
        return [list(r.stream) for r in reqs], eng

    (cold, warm), eng_on = cont(prefix_cache=True)
    # continuation property: forced prefix resumes the plain stream
    assert cold == list(plain[4:10])
    assert warm == cold, "fork must be bitwise the teacher-forced miss"
    assert eng_on._prefix.hits >= 1 and eng_on._prefix.misses >= 1
    (cold_off, warm_off), eng_off = cont(prefix_cache=False)
    assert cold_off == cold and warm_off == cold
    # cache OFF: every page recycles once the requests finish; cache ON:
    # only the registered entry's pages stay resident, and dropping the
    # entry (the evict-before-preempt lever) returns them to the pool
    assert eng_off._cache.pages_free == eng_off._cache.num_pages - 1
    assert eng_on._cache.pages_free < eng_on._cache.num_pages - 1
    while eng_on._drop_one_prefix_entry():
        pass
    assert eng_on._cache.pages_free == eng_on._cache.num_pages - 1


def test_prefix_over_capacity_rejected():
    net = _tiny_model()
    eng = _engine(net, prefix_cache=True)  # max_len 16
    with pytest.raises(MXNetError, match="max_len"):
        eng.submit(Request(np.array([3], np.int32), max_new_tokens=9,
                           bos_id=BOS, eos_id=EOS,
                           prefix=np.arange(3, 11, dtype=np.int32)))


# ---------------------------------------------------------------------------
# batched beam serving
# ---------------------------------------------------------------------------
def test_beam_serving_matches_translate(trained):
    """serve_beam batches grouped requests through the device-side beam
    loop — hypotheses identical to standalone translate(beam_size=3)
    per request."""
    net, src = trained
    eng = _engine(net)
    reqs = [Request(src[i], max_new_tokens=9, bos_id=BOS, eos_id=EOS)
            for i in range(3)]
    out = eng.serve_beam(reqs, beam_size=3)
    for i, r in enumerate(reqs):
        ref = net.translate(nd.array(src[i:i + 1], dtype="int32"),
                            bos_id=BOS, eos_id=EOS, max_len=10,
                            beam_size=3)[0, 1:]
        ref = list(ref)
        if EOS in ref:
            ref = ref[:ref.index(EOS) + 1]
        assert list(out[r.id]) == ref[:9], f"request {i} diverged"
        assert r.stream.finished


# ---------------------------------------------------------------------------
# statusz + telemetry
# ---------------------------------------------------------------------------
def test_statusz_snapshot_host_side_facts():
    net = _tiny_model()
    eng = _engine(net, sampling=True, spec_k=2, prefix_cache=True)
    eng.serve([Request(np.array([3, 4, 5], np.int32), max_new_tokens=4,
                       bos_id=BOS, eos_id=EOS)])
    snap = eng.statusz_snapshot()
    assert snap["slots"] == 3 and snap["active_slots"] == 0
    assert snap["queue_depth"] == 0 and snap["steps"] > 0
    assert snap["sampling"] is True and snap["spec_k"] == 2
    assert snap["pages_total"] > snap["pages_free"] >= 0 or \
        snap["pages_free"] == snap["pages_total"]
    assert snap["prefix_entries"] >= 0
    assert snap["weight_generation"] == 0


def test_prefix_and_spec_telemetry_rollup(tele, trained):
    net, src = trained
    prefix = np.asarray(src[0, :3], np.int32)
    eng = _engine(net, prefix_cache=True)
    for _ in range(2):
        eng.serve([Request(src[0], max_new_tokens=4, bos_id=BOS,
                           eos_id=-1, prefix=prefix)])
    spec = _engine(net, spec_k=2)
    spec.serve([Request(src[i], max_new_tokens=9, bos_id=BOS,
                        eos_id=EOS) for i in range(4)])
    s = telemetry.summary()["serving"]
    # request 2 hits BOTH entry kinds: the reused prefill rows and the
    # forked prefix pages (request 1 missed both)
    assert s["prefix_cache"]["hits"] == 2
    assert s["prefix_cache"]["misses"] == 2
    assert s["prefix_cache"]["hit_rate"] == 0.5
    assert s["prefix_cache"]["tokens_reused"] >= 3
    assert s["spec"]["rounds"] > 0 and s["spec"]["proposed"] > 0
    assert 0 < s["spec"]["accept_rate"] <= 1
    prom = telemetry.render_prometheus()
    assert 'mx_serve_prefix_hits_total{rank="0"} 2' in prom
    assert "mx_serve_prefix_hit_rate" in prom
    assert "mx_serve_spec_rounds_total" in prom
    assert "mx_serve_spec_accept_rate" in prom
