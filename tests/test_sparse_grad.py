"""row_sparse gradients + lazy optimizer updates (VERDICT r2 #6; reference:
src/operator/tensor/indexing_op.h EmbeddingOpBackward row_sparse path and
src/operator/optimizer_op.cc SGDUpdateRspImpl / lazy_update semantics).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.ndarray.sparse import RowSparseNDArray

VOCAB, DIM = 12, 4


def _embed_net(sparse_grad):
    mx.random.seed(3)
    net = gluon.nn.Embedding(VOCAB, DIM, sparse_grad=sparse_grad)
    net.initialize(mx.init.Normal(0.1))
    return net


def test_embedding_sparse_grad_is_row_sparse():
    net = _embed_net(True)
    x = nd.array(np.array([[1, 3], [3, 5]], np.float32))
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    g = net.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    ids = np.unique(np.asarray(g.indices.asnumpy()))
    assert set(ids) <= {0, 1, 3, 5}  # 0 can appear as zero-valued padding
    # dense equivalence: sparse grad densifies to the dense-path grad
    dense_net = _embed_net(False)  # same seed -> same weights
    with autograd.record():
        out = dense_net(x)
        loss = (out * out).sum()
    loss.backward()
    np.testing.assert_allclose(g.asnumpy(),
                               dense_net.weight.grad().asnumpy(), rtol=1e-5)


def test_sgd_lazy_update_touches_only_looked_up_rows():
    net = _embed_net(True)
    w0 = net.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "wd": 0.1,
                             "momentum": 0.9})
    x = nd.array(np.array([[1, 3]], np.float32))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(1)
    w1 = net.weight.data().asnumpy()
    touched = {1, 3}
    for r in range(VOCAB):
        if r in touched:
            assert not np.allclose(w1[r], w0[r]), f"row {r} should update"
        else:
            # lazy semantics: untouched rows see NO update — not even wd
            np.testing.assert_array_equal(w1[r], w0[r])


def test_duplicate_ids_do_not_touch_row0():
    """Regression: duplicate ids in a batch once produced zero-padded
    (id=0) aggregation slots, giving row 0 spurious wd/momentum updates."""
    net = _embed_net(True)
    w0 = net.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "wd": 0.3,
                             "momentum": 0.9})
    x = nd.array(np.array([[5, 5, 5, 3]], np.float32))  # duplicates, no 0
    for _ in range(3):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(1)
    w1 = net.weight.data().asnumpy()
    np.testing.assert_array_equal(w1[0], w0[0])  # row 0 never looked up
    assert not np.allclose(w1[5], w0[5])
    assert not np.allclose(w1[3], w0[3])


def test_sparse_training_matches_dense(monkeypatch):
    """With wd=0 sparse-lazy SGD must match dense SGD exactly."""
    xs = [np.array([[1, 3], [5, 7]], np.float32),
          np.array([[0, 2], [3, 3]], np.float32)]
    results = []
    for sparse in (False, True):
        net = _embed_net(sparse)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.2})
        for x in xs:
            with autograd.record():
                loss = (net(nd.array(x)) ** 2).sum()
            loss.backward()
            trainer.step(2)
        results.append(net.weight.data().asnumpy())
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5, atol=1e-6)


def test_adam_sparse_update_runs_and_is_lazy():
    net = _embed_net(True)
    w0 = net.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = nd.array(np.array([[2, 4]], np.float32))
    for _ in range(3):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(1)
    w1 = net.weight.data().asnumpy()
    assert not np.allclose(w1[2], w0[2])
    assert not np.allclose(w1[4], w0[4])
    np.testing.assert_array_equal(w1[7], w0[7])
    assert np.isfinite(w1).all()


def test_autograd_grad_returns_row_sparse():
    mx.random.seed(5)
    w = nd.array(np.random.rand(VOCAB, DIM).astype(np.float32))
    x = nd.array(np.array([1, 1, 6], np.float32))
    w.attach_grad()
    with autograd.record():
        out = nd.Embedding(x, w, input_dim=VOCAB, output_dim=DIM,
                           sparse_grad=True)
        loss = out.sum()
    g = autograd.grad(loss, w)
    assert isinstance(g, RowSparseNDArray)
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[1], np.full(DIM, 2.0))  # id 1 twice
    np.testing.assert_allclose(dense[6], np.full(DIM, 1.0))
    np.testing.assert_allclose(dense[0], np.zeros(DIM))


def test_zero_grad_resets_sparse_buffer():
    net = _embed_net(True)
    x = nd.array(np.array([[1]], np.float32))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    assert net.weight.grad()._data.shape[0] > 0
    net.collect_params().zero_grad()
    assert net.weight.grad()._data.shape[0] == 0
