"""Pallas fused kernels + ring attention vs plain-jax references.

Mirrors the reference's check_consistency oracle (tests/python/gpu/
test_operator_gpu.py ~check_consistency): same math, two backends.
Kernels run in interpret mode on the CPU mesh.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.ops import pallas as pk
from mxnet_tpu.parallel import ring_self_attention
from mxnet_tpu.parallel.mesh import device_mesh


def _ref_attention(q, k, v, causal=False, sm_scale=None):
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("nqd,nkd->nqk", q, k) * sm_scale
    if causal:
        lq, lk = q.shape[1], k.shape[1]
        # kernel semantics: query i attends keys 0..i (positions from 0)
        mask = np.tril(np.ones((lq, lk), bool))
        s = jnp.where(mask[None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("nqk,nkd->nqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("lq,lk,d", [(64, 64, 32), (40, 72, 16)])
def test_flash_attention_forward(causal, lq, lk, d):
    if causal and lq != lk:
        pytest.skip("causal needs square")
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(3, lq, d), jnp.float32)
    k = jnp.asarray(rng.randn(3, lk, d), jnp.float32)
    v = jnp.asarray(rng.randn(3, lk, d), jnp.float32)
    out = pk.flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grad(causal):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)
    v = jnp.asarray(rng.randn(2, 32, 16), jnp.float32)

    def f_flash(q, k, v):
        return pk.flash_attention(q, k, v, causal=causal, block_q=16,
                                  block_k=16).sum()

    def f_ref(q, k, v):
        return _ref_attention(q, k, v, causal=causal).sum()

    g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_flash_attention_4d_and_jit():
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 4, 24, 8), jnp.float32)
    out = jax.jit(lambda q: pk.flash_attention(q, q, q))(q)
    ref = _ref_attention(q.reshape(8, 24, 8), q.reshape(8, 24, 8),
                         q.reshape(8, 24, 8)).reshape(2, 4, 24, 8)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_softmax_cross_entropy():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(37, 11), jnp.float32)
    y = jnp.asarray(rng.randint(0, 11, 37), jnp.int32)
    loss = pk.softmax_cross_entropy(x, y)
    ref = -jax.nn.log_softmax(x)[jnp.arange(37), y]
    np.testing.assert_allclose(np.asarray(loss), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # gradient
    g = jax.grad(lambda x: pk.softmax_cross_entropy(x, y).sum())(x)
    gref = jax.grad(lambda x: (-jax.nn.log_softmax(x)[jnp.arange(37), y]
                               ).sum())(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(gref),
                               rtol=1e-5, atol=1e-5)


def test_softmax_cross_entropy_ignore_label():
    x = jnp.asarray(np.random.RandomState(4).randn(8, 5), jnp.float32)
    y = jnp.asarray([0, 1, -1, 2, -1, 3, 4, 0], jnp.int32)
    loss = pk.softmax_cross_entropy(x, y, ignore_label=-1)
    assert float(loss[2]) == 0.0 and float(loss[4]) == 0.0
    g = jax.grad(lambda x: pk.softmax_cross_entropy(x, y, -1).sum())(x)
    assert np.abs(np.asarray(g)[2]).sum() == 0.0


def test_layer_norm():
    rng = np.random.RandomState(5)
    x = jnp.asarray(rng.randn(19, 33), jnp.float32)
    gm = jnp.asarray(rng.randn(33), jnp.float32)
    bt = jnp.asarray(rng.randn(33), jnp.float32)

    def ref(x, gm, bt):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) * jax.lax.rsqrt(var + 1e-5) * gm + bt

    out = pk.layer_norm(x, gm, bt)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(x, gm, bt)),
                               rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda *a: pk.layer_norm(*a).sum(), argnums=(0, 1, 2))(
        x, gm, bt)
    g2 = jax.grad(lambda *a: ref(*a).sum(), argnums=(0, 1, 2))(x, gm, bt)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = device_mesh(("sp",), (8,))
    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(4, 64, 16), jnp.float32)
    k = jnp.asarray(rng.randn(4, 64, 16), jnp.float32)
    v = jnp.asarray(rng.randn(4, 64, 16), jnp.float32)
    out = ring_self_attention(mesh, q, k, v, causal=causal)
    ref = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    """All-to-all SP (Ulysses): exact parity with full attention; the
    second §5.7 long-context mechanism next to the ring."""
    from mxnet_tpu.parallel import ulysses_self_attention

    mesh = device_mesh(("sp",), (8,))
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(8, 64, 16), jnp.float32)  # N=8 heads, S=8
    k = jnp.asarray(rng.randn(8, 64, 16), jnp.float32)
    v = jnp.asarray(rng.randn(8, 64, 16), jnp.float32)
    out = ulysses_self_attention(mesh, q, k, v, causal=causal)
    ref = _ref_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_ulysses_attention_grad():
    from mxnet_tpu.parallel import ulysses_self_attention

    mesh = device_mesh(("sp",), (8,))
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(8, 32, 8), jnp.float32)
    k = jnp.asarray(rng.randn(8, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(8, 32, 8), jnp.float32)

    def f_uly(q, k, v):
        return ulysses_self_attention(mesh, q, k, v, causal=True).sum()

    def f_ref(q, k, v):
        return _ref_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(f_uly, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_ring_attention_grad():
    mesh = device_mesh(("sp",), (8,))
    rng = np.random.RandomState(7)
    q = jnp.asarray(rng.randn(2, 32, 8), jnp.float32)
    k = jnp.asarray(rng.randn(2, 32, 8), jnp.float32)
    v = jnp.asarray(rng.randn(2, 32, 8), jnp.float32)

    def f_ring(q, k, v):
        return ring_self_attention(mesh, q, k, v, causal=True).sum()

    def f_ref(q, k, v):
        return _ref_attention(q, k, v, causal=True).sum()

    g1 = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)
