"""Symbol / Executor tests (reference behavioral spec:
tests/python/unittest/test_symbol.py and test_executor.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def _mlp():
    data = sym.Variable("data")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def test_list_arguments_order():
    net = _mlp()
    assert net.list_arguments() == [
        "data", "fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias",
        "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape_auto_params():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(8, 10))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["fc1_weight"] == (16, 10)
    assert d["fc1_bias"] == (16,)
    assert d["fc2_weight"] == (4, 16)
    assert d["softmax_label"] == (8,)
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_infer_shape_conv_batchnorm():
    data = sym.Variable("data")
    conv = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                           name="conv1")
    bn = sym.BatchNorm(conv, name="bn1")
    net = sym.Pooling(bn, kernel=(2, 2), stride=(2, 2), pool_type="max")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 8, 8))
    d = dict(zip(net.list_arguments(), arg_shapes))
    assert d["conv1_weight"] == (8, 3, 3, 3)
    assert d["bn1_gamma"] == (8,)
    assert net.list_auxiliary_states() == ["bn1_moving_mean",
                                           "bn1_moving_var"]
    assert aux_shapes == [(8,), (8,)]
    assert out_shapes == [(2, 8, 4, 4)]


def test_executor_forward_matches_nd():
    data = sym.Variable("data")
    fc = sym.FullyConnected(data, num_hidden=3, name="fc")
    exe = fc.simple_bind(ctx=mx.cpu(), data=(4, 5))
    x = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    w = np.random.RandomState(1).rand(3, 5).astype(np.float32)
    b = np.random.RandomState(2).rand(3).astype(np.float32)
    exe.arg_dict["fc_weight"]._set_data(nd.array(w)._data)
    exe.arg_dict["fc_bias"]._set_data(nd.array(b)._data)
    (out,) = exe.forward(is_train=False, data=x)
    np.testing.assert_allclose(out.asnumpy(), x @ w.T + b, rtol=1e-5)


def test_executor_backward_grads():
    # loss = sum((x*w)^2) -> dw = 2*w*x^2 summed over batch
    data = sym.Variable("data")
    w = sym.Variable("w")
    out = sym.sum(sym.square(data * w))
    exe = out.simple_bind(ctx=mx.cpu(), grad_req="write", data=(3,), w=(3,))
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    wv = np.array([0.5, -1.0, 2.0], np.float32)
    exe.forward(is_train=True, data=xv, w=wv)
    exe.backward()
    gw = exe.grad_dict["w"].asnumpy()
    np.testing.assert_allclose(gw, 2 * wv * xv * xv, rtol=1e-5)


def test_softmax_output_backward():
    data = sym.Variable("data")
    net = sym.SoftmaxOutput(data, name="softmax")
    exe = net.simple_bind(ctx=mx.cpu(), grad_req={"data": "write"},
                          data=(2, 3))
    x = np.array([[1.0, 2.0, 3.0], [0.0, 0.0, 0.0]], np.float32)
    label = np.array([2, 0], np.float32)
    exe.forward(is_train=True, data=x, softmax_label=label)
    exe.backward()
    p = exe.outputs[0].asnumpy()
    onehot = np.zeros((2, 3), np.float32)
    onehot[np.arange(2), label.astype(int)] = 1
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(),
                               (p - onehot), rtol=1e-4, atol=1e-6)


def test_json_round_trip(tmp_path):
    net = _mlp()
    fname = str(tmp_path / "net-symbol.json")
    net.save(fname)
    loaded = sym.load(fname)
    assert loaded.list_arguments() == net.list_arguments()
    assert loaded.list_outputs() == net.list_outputs()
    # same numerics after reload
    shapes = {"data": (2, 6)}
    a1, o1, _ = net.infer_shape(**shapes)
    a2, o2, _ = loaded.infer_shape(**shapes)
    assert a1 == a2 and o1 == o2


def test_group_and_getitem():
    a = sym.Variable("a")
    b = sym.Variable("b")
    s1 = a + b
    s2 = a * b
    g = sym.Group([s1, s2])
    assert len(g.list_outputs()) == 2
    exe = g.bind(ctx=mx.cpu(), args={"a": nd.array([2.0]),
                                     "b": nd.array([3.0])}, grad_req="null")
    outs = exe.forward()
    assert outs[0].asnumpy()[0] == 5.0
    assert outs[1].asnumpy()[0] == 6.0
    first = g[0]
    assert first.list_outputs() == g.list_outputs()[:1]


def test_compose():
    data = sym.Variable("data")
    net1 = sym.FullyConnected(data, num_hidden=4, name="fc1")
    data2 = sym.Variable("data2")
    net2 = sym.Activation(data2, act_type="relu", name="act")
    composed = net2(data2=net1)
    args = composed.list_arguments()
    assert "data" in args and "fc1_weight" in args and "data2" not in args


def test_scalar_arith_and_internals():
    a = sym.Variable("a")
    s = (a + 1.0) * 2.0
    exe = s.bind(ctx=mx.cpu(), args={"a": nd.array([3.0])}, grad_req="null")
    assert exe.forward()[0].asnumpy()[0] == 8.0
    internals = _mlp().get_internals()
    assert "fc1_output" in internals.list_outputs()


def test_grad_req_add():
    data = sym.Variable("data")
    w = sym.Variable("w")
    out = sym.sum(data * w)
    exe = out.simple_bind(ctx=mx.cpu(), grad_req={"w": "add", "data": "null"},
                          data=(2,), w=(2,))
    xv = np.array([1.0, 2.0], np.float32)
    wv = np.array([1.0, 1.0], np.float32)
    exe.forward(is_train=True, data=xv, w=wv)
    exe.backward()
    exe.forward(is_train=True, data=xv, w=wv)
    exe.backward()
    np.testing.assert_allclose(exe.grad_dict["w"].asnumpy(), 2 * xv)


def test_name_prefix_scope():
    with mx.name.Prefix("stage1_"):
        s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4)
    assert s.list_outputs()[0].startswith("stage1_fullyconnected")
    # explicit names are untouched
    with mx.name.Prefix("p_"):
        s2 = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4,
                                   name="fc9")
    assert "fc9_output" in s2.list_outputs()[0]


def test_attr_scope_on_variables():
    with mx.AttrScope(__lr_mult__="0.1", group="encoder"):
        v = mx.sym.Variable("w")
        with mx.AttrScope(group="decoder"):  # inner wins
            v2 = mx.sym.Variable("w2")
    node = v._entries[0][0]
    assert node.vattrs["lr_mult"] == 0.1
    assert node.vattrs["attr"]["group"] == "encoder"
    assert v2._entries[0][0].vattrs["attr"]["group"] == "decoder"
    # explicit attr beats the scope
    with mx.AttrScope(group="a"):
        v3 = mx.sym.Variable("w3", attr={"group": "b"})
    assert v3._entries[0][0].vattrs["attr"]["group"] == "b"
    # values must be strings, reference convention
    import pytest as _pytest

    with _pytest.raises(ValueError):
        mx.AttrScope(x=1)


def test_attr_scope_reuse_and_op_nodes():
    scope = mx.AttrScope(group="g")
    with scope:
        with scope:
            s = mx.sym.FullyConnected(mx.sym.Variable("d"), num_hidden=2)
    # scope fully restored after nested reuse of ONE instance
    v_after = mx.sym.Variable("w_after")
    assert "group" not in v_after._entries[0][0].vattrs["attr"]
    # op nodes carry the scope attrs for introspection
    node = s._entries[0][0]
    assert node.vattrs.get("attr", {}).get("group") == "g"


def test_sym_ufunc_scalar_dispatch():
    """Symbol-side ufunc family (reference symbol.py _ufunc_helper):
    array/array -> broadcast op, array/scalar -> *_scalar op node, and the
    graph serializes through tojson."""
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    vals = {"a": mx.nd.array(np.array([1., 2., 3.], np.float32)),
            "b": mx.nd.array(np.array([3., 2., 1.], np.float32))}
    for expr, expect in [(mx.sym.power(a, b), [1, 4, 3]),
                         (mx.sym.power(a, 2), [1, 4, 9]),
                         (mx.sym.equal(a, 2.0), [0, 1, 0]),
                         (mx.sym.greater_equal(2, a), [1, 1, 0]),
                         (mx.sym.logical_and(a - 1, b), [0, 1, 1]),
                         (mx.sym.mod(b, 2), [1, 0, 1])]:
        args = {k: vals[k] for k in expr.list_arguments()}
        out = expr.bind(mx.cpu(), args).forward()[0].asnumpy()
        np.testing.assert_allclose(out, expect)
    assert mx.sym.load_json(mx.sym.power(a, 2).tojson()) is not None
