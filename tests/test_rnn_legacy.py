"""Legacy mx.rnn module: symbolic cells, unroll, FusedRNNCell, bucketing
iterator + BucketingModule end-to-end, rnn checkpoints.

Reference behavioral spec: tests/python/unittest/test_rnn.py.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def _unroll_outputs(cell, T=3, B=2, I=4, merge=True, layout="NTC"):
    x = mx.sym.Variable("data")
    outputs, states = cell.unroll(T, inputs=x, layout=layout,
                                  merge_outputs=merge)
    return outputs, states


def test_rnn_cell_unroll_shapes():
    cell = mx.rnn.RNNCell(10, prefix="rnn_")
    outputs, states = _unroll_outputs(cell)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(2, 3, 4))
    out = ex.forward()[0]
    assert out.shape == (2, 3, 10)
    # param names follow the reference convention
    names = sorted(cell.params._params.keys())
    assert names == ["rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias",
                     "rnn_i2h_weight"]


def test_lstm_gru_cell_unroll():
    for cls, prefix in [(mx.rnn.LSTMCell, "lstm_"), (mx.rnn.GRUCell, "gru_")]:
        cell = cls(6, prefix=prefix)
        outputs, states = _unroll_outputs(cell)
        ex = outputs.simple_bind(ctx=mx.cpu(), data=(2, 3, 4))
        out = ex.forward()[0]
        assert out.shape == (2, 3, 6)
        assert np.isfinite(out.asnumpy()).all()


def test_sequential_and_residual_stack():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.ResidualCell(mx.rnn.LSTMCell(8, prefix="l1_")))
    outputs, states = stack.unroll(3, inputs=mx.sym.Variable("data"),
                                   merge_outputs=True)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(2, 3, 8))
    out = ex.forward()[0]
    assert out.shape == (2, 3, 8)
    assert len(states) == 4  # 2 cells x (h, c)


def test_bidirectional_cell():
    cell = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(5, prefix="l_"), mx.rnn.LSTMCell(5, prefix="r_"))
    outputs, states = cell.unroll(4, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(2, 4, 3))
    out = ex.forward()[0]
    assert out.shape == (2, 4, 10)


def test_dropout_zoneout_cells_inference():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.GRUCell(6, prefix="g0_"))
    stack.add(mx.rnn.DropoutCell(0.5, prefix="do_"))
    outputs, _ = stack.unroll(3, inputs=mx.sym.Variable("data"),
                              merge_outputs=True)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(2, 3, 4))
    out = ex.forward()[0]  # inference: dropout is identity
    assert np.isfinite(out.asnumpy()).all()
    z = mx.rnn.ZoneoutCell(mx.rnn.RNNCell(4, prefix="z_"),
                           zoneout_states=0.3)
    outputs, _ = z.unroll(2, inputs=mx.sym.Variable("data"),
                          merge_outputs=True)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(1, 2, 4))
    assert np.isfinite(ex.forward()[0].asnumpy()).all()


def test_fused_cell_matches_unfused():
    """FusedRNNCell (RNN op) must agree with its unfuse() stack when fed
    the same packed weights."""
    T, B, I, H = 4, 2, 3, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                prefix="lstm_", get_next_state=True)
    f_out, f_states = fused.unroll(T, inputs=mx.sym.Variable("data"),
                                   merge_outputs=True)
    ex = f_out.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    rng = np.random.RandomState(0)
    flat = rng.randn(*ex.arg_dict["lstm_parameters"].shape).astype(
        np.float32) * 0.2
    ex.arg_dict["lstm_parameters"][:] = flat
    fused_out = ex.forward()[0].asnumpy()

    stack = fused.unfuse()
    s_out, _ = stack.unroll(T, inputs=mx.sym.Variable("data"),
                            merge_outputs=True)
    ex2 = s_out.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    # pack_weights maps per-gate arrays -> fused; here go the other way:
    # slice the flat vector the same way the RNN op does
    G = 4
    off = 0
    wi = flat[off:off + G * H * I].reshape(G * H, I); off += G * H * I
    wh = flat[off:off + G * H * H].reshape(G * H, H); off += G * H * H
    bi = flat[off:off + G * H]; off += G * H
    bh = flat[off:off + G * H]
    ex2.arg_dict["lstm_l0_i2h_weight"][:] = wi
    ex2.arg_dict["lstm_l0_h2h_weight"][:] = wh
    ex2.arg_dict["lstm_l0_i2h_bias"][:] = bi
    ex2.arg_dict["lstm_l0_h2h_bias"][:] = bh
    unfused_out = ex2.forward()[0].asnumpy()
    np.testing.assert_allclose(fused_out, unfused_out, rtol=1e-4, atol=1e-5)

    data = ex.arg_dict["data"]
    data[:] = rng.randn(B, T, I).astype(np.float32)
    # also check input actually flows (non-zero input changes output)
    out2 = ex.forward()[0].asnumpy()
    assert not np.allclose(out2, fused_out)


def test_pack_unpack_weights_roundtrip():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    rng = np.random.RandomState(1)
    args = {
        "lstm_i2h_weight": nd.array(rng.randn(16, 3).astype(np.float32)),
        "lstm_i2h_bias": nd.array(rng.randn(16).astype(np.float32)),
        "lstm_h2h_weight": nd.array(rng.randn(16, 4).astype(np.float32)),
        "lstm_h2h_bias": nd.array(rng.randn(16).astype(np.float32)),
    }
    unpacked = cell.unpack_weights(dict(args))
    assert "lstm_i2h_i_weight" in unpacked
    assert unpacked["lstm_i2h_i_weight"].shape == (4, 3)
    packed = cell.pack_weights(unpacked)
    for k in args:
        np.testing.assert_allclose(packed[k].asnumpy(), args[k].asnumpy())


def test_fused_unroll_default_merge_returns_tensor():
    fused = mx.rnn.FusedRNNCell(4, num_layers=1, mode="gru", prefix="gru_")
    out, _ = fused.unroll(3, inputs=mx.sym.Variable("data"))
    assert isinstance(out, mx.sym.Symbol)  # merged, not a list
    ex = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 5))
    assert ex.forward()[0].shape == (2, 3, 4)


def test_sequential_stack_with_fused_cell():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.FusedRNNCell(6, num_layers=1, mode="gru",
                                  prefix="gru_", get_next_state=True))
    stack.add(mx.rnn.LSTMCell(6, prefix="lstm_"))
    out, states = stack.unroll(3, inputs=mx.sym.Variable("data"),
                               merge_outputs=True)
    ex = out.simple_bind(ctx=mx.cpu(), data=(4, 3, 5))
    res = ex.forward()[0]
    assert res.shape == (4, 3, 6)
    assert np.isfinite(res.asnumpy()).all()


def test_fused_pack_unpack_roundtrip_and_unfused_interchange():
    T, B, I, H = 3, 2, 4, 5
    fused = mx.rnn.FusedRNNCell(H, num_layers=2, mode="lstm", prefix="lstm_",
                                bidirectional=True)
    f_out, _ = fused.unroll(T, inputs=mx.sym.Variable("data"),
                            merge_outputs=True)
    ex = f_out.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    rng = np.random.RandomState(3)
    flat = rng.randn(*ex.arg_dict["lstm_parameters"].shape).astype(
        np.float32) * 0.2
    ex.arg_dict["lstm_parameters"][:] = flat
    fused_out = ex.forward()[0].asnumpy()

    args = {"lstm_parameters": nd.array(flat)}
    unpacked = fused.unpack_weights(args)
    assert "lstm_parameters" not in unpacked
    assert unpacked["lstm_l0_i2h_i_weight"].shape == (H, I)
    assert unpacked["lstm_r1_h2h_o_bias"].shape == (H,)
    packed = fused.pack_weights(unpacked)
    np.testing.assert_allclose(packed["lstm_parameters"].asnumpy(), flat)

    # the unpacked arrays drive the unfused stack to the same output
    stack = fused.unfuse()
    s_out, _ = stack.unroll(T, inputs=mx.sym.Variable("data"),
                            merge_outputs=True)
    ex2 = s_out.simple_bind(ctx=mx.cpu(), data=(B, T, I))
    per_cell = stack.pack_weights(fused.unpack_weights(
        {"lstm_parameters": nd.array(flat)}))
    for k, v in per_cell.items():
        ex2.arg_dict[k][:] = v.asnumpy()
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(), fused_out,
                               rtol=1e-4, atol=1e-5)


def test_lstm_forget_bias_initialized():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_", forget_bias=2.0)
    outputs, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                             merge_outputs=True)
    mod = mx.mod.Module(outputs, data_names=["data"], label_names=[])
    mod.bind(data_shapes=[("data", (1, 2, 3))], for_training=False)
    mod.init_params(initializer=mx.init.Zero())
    arg_params, _ = mod.get_params()
    bias = arg_params["lstm_i2h_bias"].asnumpy()
    np.testing.assert_allclose(bias[4:8], 2.0)  # forget-gate block
    np.testing.assert_allclose(bias[:4], 0.0)


def test_encode_sentences_and_bucket_iter():
    sents = [["a", "b", "c"], ["a", "c"], ["b", "c", "a", "b"],
             ["a", "b"], ["c", "b", "a"]] * 4
    coded, vocab = mx.rnn.encode_sentences(sents, start_label=1,
                                           invalid_label=0)
    assert len(vocab) == 4  # 3 tokens + invalid
    it = mx.rnn.BucketSentenceIter(coded, batch_size=2, buckets=[2, 3, 4],
                                   invalid_label=0)
    seen = 0
    for batch in it:
        seen += 1
        assert batch.data[0].shape == (2, batch.bucket_key)
        # label is data shifted left by one
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
    assert seen == len(it.idx) and seen > 0


def test_bucketing_module_with_bucket_iter_converges():
    """End-to-end: BucketSentenceIter + BucketingModule + unrolled GRU
    language model trains to decreasing perplexity on a toy corpus."""
    rng = np.random.RandomState(0)
    # deterministic next-token corpus: b follows a, c follows b, a follows c
    base = [1, 2, 3] * 5
    sents = [base[s:s + ln] for s in range(3)
             for ln in (4, 6) for _ in range(8)]
    buckets = [4, 6]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=4, buckets=buckets,
                                   invalid_label=0)
    V, H = 4, 16

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=V, output_dim=8,
                                 name="embed")
        cell = mx.rnn.GRUCell(H, prefix="gru_")
        outputs, _ = cell.unroll(seq_len, inputs=embed, layout="NTC",
                                 merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, H))
        pred = mx.sym.FullyConnected(pred, num_hidden=V, name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        return mx.sym.SoftmaxOutput(pred, label, name="softmax"), \
            ["data"], ["softmax_label"]

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=it.default_bucket_key)
    mod.fit(it, num_epoch=2,
            eval_metric=mx.metric.Perplexity(ignore_label=None),
            optimizer="adam", optimizer_params={"learning_rate": 0.05})
    score = mod.score(it, mx.metric.Perplexity(ignore_label=None))
    ppl = dict(score)["perplexity"] if isinstance(score, list) else score
    assert ppl < 2.5, f"perplexity {ppl} did not drop"


def test_rnn_checkpoint_roundtrip(tmp_path):
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    outputs, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                             merge_outputs=True)
    rng = np.random.RandomState(0)
    args = {
        "lstm_i2h_weight": nd.array(rng.randn(16, 3).astype(np.float32)),
        "lstm_i2h_bias": nd.array(rng.randn(16).astype(np.float32)),
        "lstm_h2h_weight": nd.array(rng.randn(16, 4).astype(np.float32)),
        "lstm_h2h_bias": nd.array(rng.randn(16).astype(np.float32)),
    }
    prefix = str(tmp_path / "model")
    mx.rnn.save_rnn_checkpoint(cell, prefix, 1, outputs, args, {})
    sym, arg2, aux = mx.rnn.load_rnn_checkpoint(cell, prefix, 1)
    for k in args:
        np.testing.assert_allclose(arg2[k].asnumpy(), args[k].asnumpy(),
                                   rtol=1e-6)


def test_bucket_iter_empty_bucket_ok():
    sents = [[1, 2], [3, 4], [5, 6], [7, 8]]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=2, buckets=[2, 10],
                                   invalid_label=0)
    batches = list(it)
    assert all(b.bucket_key == 2 for b in batches)
    assert len(batches) == 2


def test_init_attr_survives_json_roundtrip():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_", forget_bias=3.0)
    outputs, _ = cell.unroll(2, inputs=mx.sym.Variable("data"),
                             merge_outputs=True)
    sym2 = mx.sym.load_json(outputs.tojson())
    mod = mx.mod.Module(sym2, data_names=["data"], label_names=[])
    mod.bind(data_shapes=[("data", (1, 2, 3))], for_training=False)
    mod.init_params(initializer=mx.init.Zero())
    arg_params, _ = mod.get_params()
    bias = arg_params["lstm_i2h_bias"].asnumpy()
    np.testing.assert_allclose(bias[4:8], 3.0)
