"""Encoder-decoder Transformer (BASELINE config 4 skeleton): forward
shapes, label-smoothed loss, tiny-task convergence, beam-search decode.

Reference: GluonNLP scripts/machine_translation (transformer encoder/
decoder, LabelSmoothing, BeamSearchSampler) — re-designed here as one
hybridizable block whose train step compiles to a single XLA program.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.models.transformer import (Transformer, label_smoothed_ce,
                                          transformer_base)

PAD, BOS, EOS = 0, 1, 2


def _tiny_model(vocab=16):
    mx.random.seed(0)
    net = Transformer(vocab, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=20, dropout=0.0)
    net.initialize(mx.init.Xavier())
    return net


def _reverse_batch(rng, B, L=6, vocab=16):
    """src: random tokens; tgt = <bos> reversed(src) <eos>, padded."""
    src = np.zeros((B, L + 1), np.int32)
    tgt_in = np.zeros((B, L + 2), np.int32)
    tgt_out = np.zeros((B, L + 2), np.int32)
    for b in range(B):
        toks = rng.randint(3, vocab, L)
        src[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    return src, tgt_in, tgt_out


def test_forward_shapes_and_padding_invariance():
    net = _tiny_model()
    rng = np.random.RandomState(0)
    src, tgt_in, _ = _reverse_batch(rng, 2)
    out = net(nd.array(src, dtype="int32"), nd.array(tgt_in, dtype="int32"))
    assert out.shape == (2, tgt_in.shape[1], 16)
    # padding the source must not change the (non-pad-key) logits
    src_pad = np.concatenate([src, np.zeros((2, 3), np.int32)], axis=1)
    out_pad = net(nd.array(src_pad, dtype="int32"),
                  nd.array(tgt_in, dtype="int32"))
    np.testing.assert_allclose(out.asnumpy(), out_pad.asnumpy(),
                               rtol=1e-4, atol=1e-4)


def test_bf16_cast_stays_bf16_and_roundtrips():
    """cast('bfloat16') must keep the whole forward in bf16 (an f32
    causal-mask constant used to promote the decoder attention chain),
    and save/load round-trips the tied/positional weights."""
    import tempfile

    net = _tiny_model()
    rng = np.random.RandomState(4)
    src = nd.array(rng.randint(3, 16, (2, 7)).astype(np.int32), dtype="int32")
    tgt = nd.array(rng.randint(3, 16, (2, 8)).astype(np.int32), dtype="int32")
    out32 = net(src, tgt).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        p = f"{d}/t.params"
        net.save_parameters(p)
        net2 = _tiny_model()
        net2.load_parameters(p)
        np.testing.assert_allclose(out32, net2(src, tgt).asnumpy(), rtol=1e-6)
    net.cast("bfloat16")
    outb = net(src, tgt)
    assert "bfloat16" in str(outb.dtype), outb.dtype
    assert np.isfinite(outb.asnumpy().astype(np.float32)).all()


def test_label_smoothed_ce_reduces_to_ce():
    rng = np.random.RandomState(1)
    logits = nd.array(rng.randn(3, 5, 7).astype(np.float32))
    labels = nd.array(rng.randint(1, 7, (3, 5)).astype(np.float32))
    ls0 = float(label_smoothed_ce(logits, labels, smoothing=0.0).asscalar())
    # plain masked CE reference
    lp = np.log(np.exp(logits.asnumpy()) /
                np.exp(logits.asnumpy()).sum(-1, keepdims=True))
    lab = labels.asnumpy().astype(int)
    ref = -np.mean([lp[b, t, lab[b, t]] for b in range(3) for t in range(5)])
    np.testing.assert_allclose(ls0, ref, rtol=1e-5)
    ls1 = float(label_smoothed_ce(logits, labels, smoothing=0.1).asscalar())
    assert ls1 != ls0  # smoothing changes the value


def test_fused_step_multi_input_seq2seq():
    """DataParallelStep with a (src, tgt) input tuple: the whole seq2seq
    train step (incl. tied-embedding softmax) compiles to one XLA program
    over a dp2 mesh and the loss decreases; a dp2 x sp2 mesh runs too."""
    import jax

    from mxnet_tpu.parallel import DataParallelStep, local_mesh, make_mesh

    net = _tiny_model()
    rng = np.random.RandomState(3)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))

    step = DataParallelStep(
        net, lambda logits, labels: label_smoothed_ce(logits, labels,
                                                      smoothing=0.1),
        mesh=local_mesh(devices=jax.devices("cpu")[:2]),
        optimizer="adam", optimizer_params={"learning_rate": 3e-3})
    losses = [float(np.asarray(step.step((sb, tb), lb))) for _ in range(25)]
    assert all(np.isfinite(losses))
    assert losses[-1] < 0.5 * losses[0], f"no descent: {losses[::6]}"

    # dp2 x sp2: src len 7 is not sp-divisible -> auto-decline to batch
    # sharding; the step still runs and is finite
    net2 = _tiny_model()
    step2 = DataParallelStep(
        net2, lambda logits, labels: label_smoothed_ce(logits, labels),
        mesh=make_mesh(sp=2, devices=jax.devices("cpu")[:4]),
        optimizer="adam", optimizer_params={"learning_rate": 3e-3})
    assert np.isfinite(float(np.asarray(step2.step((sb, tb), lb))))


def test_seq2seq_learns_reverse_and_beam_decodes():
    """Memorize a tiny reversal task end-to-end, then beam-search it back.

    The memorize loop runs through the fused DataParallelStep (one XLA
    program per step) — the eager Trainer path on this model is covered by
    test_fused_step_multi_input_seq2seq's sibling assertions and the gluon
    suite; here the point is convergence + beam decode, not dispatch."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net = _tiny_model()
    rng = np.random.RandomState(2)
    src, tgt_in, tgt_out = _reverse_batch(rng, 8)

    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))
    step = DataParallelStep(
        net, lambda logits, labels: label_smoothed_ce(logits, labels,
                                                      smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    losses = [float(np.asarray(step.step((sb, tb), lb)))
              for _ in range(48)]
    assert losses[-1] < 0.15, f"no convergence: {losses[::20]}"
    step.sync_to_block()  # beam decode below reads the block's params

    # beam=3 reproduces the memorized reversal (incremental KV-cache path)
    hyp = net.translate(sb, bos_id=BOS, eos_id=EOS, max_len=tgt_in.shape[1],
                        beam_size=3)
    # hypothesis rows start at position 1 (pos 0 is BOS)
    L = 6
    got = hyp[:, 1:L + 1]
    want = src[:, :L][:, ::-1]
    match = (got == want).mean()
    assert match > 0.9, f"beam decode mismatch {match}: {got[0]} vs {want[0]}"

    # the O(L) cached scorer and the O(L^2) full-prefix scorer agree
    # (token-agreement, not exact equality: the two reduce in different
    # float orders, so near-tied beam candidates may legally swap)
    hyp_full = net.translate(sb, bos_id=BOS, eos_id=EOS,
                             max_len=tgt_in.shape[1], beam_size=3,
                             incremental=False)
    agreement = (hyp == hyp_full).mean()
    assert agreement > 0.95, f"scorer disagreement {agreement}"
