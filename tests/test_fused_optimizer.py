"""Fused optimizer apply + bucketed gradient allreduce
(docs/PERFORMANCE.md): fused-vs-per-param parity, the O(1)-dispatch
guarantee, multi-precision masters, kill switch, sparse fallback, and
bucketed push/pull semantics on the device kvstore.
"""
import math
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, telemetry
from mxnet_tpu.gluon import nn
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.optimizer import FusedUpdater, Updater


@pytest.fixture
def fused_env(monkeypatch):
    """Fused path pinned ON with default bucketing, restored afterwards."""
    monkeypatch.setenv("MX_FUSED_UPDATE", "1")
    monkeypatch.delenv("MX_ALLREDUCE_BUCKET_MB", raising=False)
    yield monkeypatch


def _toy_net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4), nn.Dense(3))
    return net


def _train(opt, opt_params, fused, monkeypatch, steps=4, ctx_list=None):
    monkeypatch.setenv("MX_FUSED_UPDATE", "1" if fused else "0")
    mx.random.seed(7)
    net = _toy_net()
    net.initialize(mx.init.Xavier(), ctx=ctx_list)
    trainer = gluon.Trainer(net.collect_params(), opt, dict(opt_params))
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(6, 5).astype(np.float32))
    y = nd.array(rng.randn(6, 3).astype(np.float32))
    loss_fn = gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(6)
    return [p.data().asnumpy() for p in net.collect_params().values()], \
        trainer


# ---------------------------------------------------------------------------
# fused vs per-param parity
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("opt,opt_params", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "clip_gradient": 0.01}),
    ("adam", {"learning_rate": 0.01, "wd": 1e-4}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
])
def test_fused_matches_per_param(opt, opt_params, fused_env):
    w_fused, tr = _train(opt, opt_params, True, fused_env)
    w_ref, _ = _train(opt, opt_params, False, fused_env)
    for a, b in zip(w_fused, w_ref):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    info = tr._updaters[0].last_info
    assert info["n_fused"] == 6 and info["n_fallback"] == 0
    assert info["n_jitted_calls"] == 1


def test_fused_updater_installed_by_default(fused_env):
    _w, tr = _train("sgd", {"learning_rate": 0.1}, True, fused_env, steps=1)
    assert all(isinstance(u, FusedUpdater) for u in tr._updaters)


def test_kill_switch_pins_per_param_updater(fused_env):
    fused_env.setenv("MX_FUSED_UPDATE", "0")
    _w, tr = _train("sgd", {"learning_rate": 0.1}, False, fused_env, steps=1)
    for u in tr._updaters:
        assert isinstance(u, Updater)
        assert not isinstance(u, FusedUpdater)


def test_lr_change_does_not_retrace(fused_env):
    """Per-step scalars are traced arguments: a scheduler sweeping lr must
    reuse the ONE cached fused executable."""
    mx.random.seed(0)
    net = _toy_net()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = nd.array(np.random.RandomState(0).randn(4, 5).astype(np.float32))
    for step in range(4):
        trainer.set_learning_rate(0.1 / (step + 1))
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(4)
    upd = trainer._updaters[0]
    assert isinstance(upd, FusedUpdater)
    assert len(upd._fn_cache) == 1, "lr change must not build a new executable"


# ---------------------------------------------------------------------------
# multi-precision (bf16 weight + fp32 master)
# ---------------------------------------------------------------------------
def _mp_updater_run(cls, w_np, g_np, steps=3):
    import jax.numpy as jnp
    import ml_dtypes

    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           multi_precision=True)
    upd = cls(opt)
    w = NDArray(jnp.asarray(w_np.astype(ml_dtypes.bfloat16)), ctx=mx.cpu())
    g = NDArray(jnp.asarray(g_np.astype(ml_dtypes.bfloat16)), ctx=mx.cpu())
    for _ in range(steps):
        if isinstance(upd, FusedUpdater):
            upd.apply([(0, g, w)])
        else:
            upd(0, g, w)
    master, _mom = upd.states[0]
    return w.asnumpy().astype(np.float32), master.asnumpy()


def test_multi_precision_fused_matches_per_param_and_oracle(fused_env):
    import jax.numpy as jnp
    import ml_dtypes

    rng = np.random.RandomState(3)
    w_np = rng.randn(6, 4).astype(np.float32)
    g_np = rng.randn(6, 4).astype(np.float32)
    w_f, m_f = _mp_updater_run(FusedUpdater, w_np, g_np)
    w_p, m_p = _mp_updater_run(Updater, w_np, g_np)
    np.testing.assert_array_equal(w_f, w_p)  # bf16 weights bitwise equal
    np.testing.assert_allclose(m_f, m_p, rtol=1e-7, atol=1e-8)

    # fp32-master oracle: same bf16-rounded start + grads, pure fp32 SGD —
    # the master trajectory IS full-precision training
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    upd = Updater(opt)
    w32 = NDArray(jnp.asarray(
        w_np.astype(ml_dtypes.bfloat16).astype(np.float32)), ctx=mx.cpu())
    g32 = NDArray(jnp.asarray(
        g_np.astype(ml_dtypes.bfloat16).astype(np.float32)), ctx=mx.cpu())
    for _ in range(3):
        upd(0, g32, w32)
    np.testing.assert_allclose(m_f, w32.asnumpy(), rtol=1e-6, atol=1e-7)
    # and the bf16 weight is exactly the rounded master
    np.testing.assert_array_equal(
        w_f, m_f.astype(ml_dtypes.bfloat16).astype(np.float32))


# ---------------------------------------------------------------------------
# O(1) dispatch + telemetry accounting
# ---------------------------------------------------------------------------
def test_step_issues_one_jitted_update_call(fused_env, tmp_path):
    """The acceptance bar: a dense-param Trainer.step() runs O(1) jitted
    update calls regardless of parameter count, and says so in the
    per-step fused_update telemetry event."""
    telemetry.reset()
    telemetry.enable(str(tmp_path))
    try:
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            for _ in range(5):
                net.add(nn.Dense(4))  # 10 params
        net.initialize(mx.init.Xavier())
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-3})
        x = nd.array(np.random.RandomState(0).randn(2, 4).astype(np.float32))
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            trainer.step(2)
        s = telemetry.summary()["fused_update"]
        assert s["count"] == 3              # one event per step
        assert s["jitted_calls"] == 3       # ONE jitted call per step
        assert s["n_params"] == 30          # 10 params x 3 steps
        events = [e for e in telemetry.flight_tail(100)
                  if e["kind"] == "fused_update"]
        assert events and events[-1]["n_jitted_calls"] == 1
        assert events[-1]["n_params"] == 10
        # and the executable cache holds exactly one program (no retrace)
        assert len(trainer._updaters[0]._fn_cache) == 1
    finally:
        telemetry.reset()


# ---------------------------------------------------------------------------
# sparse fallback
# ---------------------------------------------------------------------------
class _EmbedNet(nn.HybridBlock):
    def __init__(self):
        super().__init__()
        with self.name_scope():
            self.emb = nn.Embedding(12, 4, sparse_grad=True)
            self.fc = nn.Dense(3)

    def hybrid_forward(self, F, x):
        return self.fc(self.emb(x))


def _train_sparse(fused, monkeypatch):
    monkeypatch.setenv("MX_FUSED_UPDATE", "1" if fused else "0")
    mx.random.seed(5)
    net = _EmbedNet()
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5, "momentum": 0.9})
    x = nd.array(np.array([[1, 3], [3, 5]], np.float32))
    for _ in range(3):
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        trainer.step(2)
    return [p.data().asnumpy() for p in net.collect_params().values()], \
        trainer


def test_sparse_grads_fall_back_per_param(fused_env):
    w_fused, tr = _train_sparse(True, fused_env)
    w_ref, _ = _train_sparse(False, fused_env)
    for a, b in zip(w_fused, w_ref):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    info = tr._updaters[0].last_info
    assert info["n_fallback"] == 1     # the row_sparse embedding grad
    assert info["n_fused"] == 2        # the dense fc weight+bias


# ---------------------------------------------------------------------------
# trainer state io through the fused updater
# ---------------------------------------------------------------------------
def test_fused_trainer_states_roundtrip(fused_env, tmp_path):
    _w, tr = _train("adam", {"learning_rate": 0.01}, True, fused_env,
                    steps=2)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr.load_states(f)
    # states reload into the same per-param layout the fused path reads
    assert isinstance(tr._updaters[0], FusedUpdater)
    assert set(tr._updaters[0].states) == {0, 1, 2, 3, 4, 5}


# ---------------------------------------------------------------------------
# bucketed gradient allreduce (kvstore)
# ---------------------------------------------------------------------------
def _bucket_fixture_vals():
    rng = np.random.RandomState(0)
    keys = [0, 1, 2, 3]
    shapes = [(4, 3), (7,), (2, 2, 2), (5, 1)]
    vals = {}
    for k, s in zip(keys, shapes):
        vals[k] = [nd.array(rng.randn(*s).astype(np.float32), ctx=mx.cpu(0)),
                   nd.array(rng.randn(*s).astype(np.float32), ctx=mx.cpu(1))]
    return keys, shapes, vals


@pytest.mark.parametrize("cap_mb", ["32", None])
def test_push_bucketed_matches_per_key_push(cap_mb, fused_env):
    if cap_mb is not None:
        fused_env.setenv("MX_ALLREDUCE_BUCKET_MB", cap_mb)
    keys, shapes, vals = _bucket_fixture_vals()
    kv_b, kv_ref = mx.kv.create("device"), mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv_b.init(k, nd.zeros(s))
        kv_ref.init(k, nd.zeros(s))
    n_buckets = kv_b.push_bucketed(keys, [vals[k] for k in keys])
    assert n_buckets == 1  # everything fits one 32MB bucket
    for k in keys:
        kv_ref.push(k, vals[k])
    for k, s in zip(keys, shapes):
        got, want = nd.zeros(s), nd.zeros(s)
        kv_b.pull(k, got)
        kv_ref.pull(k, want)
        np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-6)


def test_push_bucketed_tiny_cap_splits_buckets(fused_env):
    # 20-byte cap: every key overflows into its own bucket
    fused_env.setenv("MX_ALLREDUCE_BUCKET_MB", str(20 / (1 << 20)))
    keys, shapes, vals = _bucket_fixture_vals()
    kv_b, kv_ref = mx.kv.create("device"), mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv_b.init(k, nd.zeros(s))
        kv_ref.init(k, nd.zeros(s))
    assert kv_b.push_bucketed(keys, [vals[k] for k in keys]) == len(keys)
    for k in keys:
        kv_ref.push(k, vals[k])
    for k, s in zip(keys, shapes):
        got, want = nd.zeros(s), nd.zeros(s)
        kv_b.pull(k, got)
        kv_ref.pull(k, want)
        np.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-6)


def test_push_bucketed_zero_cap_disables(fused_env):
    fused_env.setenv("MX_ALLREDUCE_BUCKET_MB", "0")
    keys, shapes, vals = _bucket_fixture_vals()
    kv = mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    assert kv.push_bucketed(keys, [vals[k] for k in keys]) == 0
    got = nd.zeros(shapes[0])
    kv.pull(0, got)
    want = vals[0][0].asnumpy() + vals[0][1].asnumpy()
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-6)


def test_push_bucketed_server_optimizer_semantics(fused_env):
    """update_on_kvstore semantics survive bucketing: the server-side
    optimizer sees exactly the per-key merged grads (and applies them in
    one fused call when the updater supports it)."""
    keys, shapes, vals = _bucket_fixture_vals()
    kv_b, kv_ref = mx.kv.create("device"), mx.kv.create("device")
    for kv in (kv_b, kv_ref):
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
        for k, s in zip(keys, shapes):
            kv.init(k, nd.ones(s))
    for _ in range(2):
        kv_b.push_bucketed(keys, [vals[k] for k in keys])
        for k in keys:
            kv_ref.push(k, vals[k])
    for k, s in zip(keys, shapes):
        got, want = nd.zeros(s), nd.zeros(s)
        kv_b.pull(k, got)
        kv_ref.pull(k, want)
        np.testing.assert_allclose(got.asnumpy(), want.asnumpy(),
                                   rtol=1e-6, atol=1e-7)
    assert isinstance(kv_b._updater, FusedUpdater)
    assert kv_b._updater.last_info["n_jitted_calls"] == 1


def test_custom_updater_with_apply_stays_per_key(fused_env):
    """A user updater installed via set_updater that happens to define an
    unrelated `apply` method must NOT be routed through the batched fused
    contract — only FusedUpdater's apply takes [(key, grad, stored)]."""
    class CustomUpdater:
        def __init__(self):
            self.calls = []

        def __call__(self, key, inp, stored):
            self.calls.append(key)
            stored += inp

        def apply(self, *a, **kw):  # different contract entirely
            raise AssertionError("batched path must not call this")

    keys, shapes, vals = _bucket_fixture_vals()
    kv = mx.kv.create("device")
    for k, s in zip(keys, shapes):
        kv.init(k, nd.zeros(s))
    upd = CustomUpdater()
    kv.set_updater(upd)
    kv.push_bucketed(keys, [vals[k] for k in keys])
    assert sorted(upd.calls) == keys
    got = nd.zeros(shapes[1])
    kv.pull(1, got)
    want = vals[1][0].asnumpy() + vals[1][1].asnumpy()
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-6)


def test_multi_device_trainer_one_allreduce_per_step(fused_env, tmp_path):
    """The wire half of the acceptance bar: a multi-device Trainer.step()
    issues <= ceil(total_grad_bytes / cap) device allreduces — here ONE
    flat-bucket collective for the whole net — and matches the
    per-param-pushpull trainer exactly."""
    def run(bucketed):
        if bucketed:
            fused_env.setenv("MX_ALLREDUCE_BUCKET_MB", "32")
        else:
            fused_env.setenv("MX_ALLREDUCE_BUCKET_MB", "0")
        mx.random.seed(11)
        ctxs = [mx.cpu(0), mx.cpu(1)]
        net = _toy_net()
        net.initialize(mx.init.Xavier(), ctx=ctxs)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                update_on_kvstore=False)
        rng = np.random.RandomState(2)
        xs = [nd.array(rng.randn(4, 5).astype(np.float32), ctx=c)
              for c in ctxs]
        ys = [nd.array(rng.randn(4, 3).astype(np.float32), ctx=c)
              for c in ctxs]
        loss_fn = gluon.loss.L2Loss()
        for _ in range(3):
            with autograd.record():
                losses = [loss_fn(net(x), y) for x, y in zip(xs, ys)]
            autograd.backward(losses)
            trainer.step(8)
        return net, trainer

    telemetry.reset()
    telemetry.enable(str(tmp_path))
    try:
        net_b, tr_b = run(bucketed=True)
        before = telemetry.summary()["collectives"]["count"]
        x = nd.array(np.random.RandomState(2).randn(4, 5).astype(np.float32),
                     ctx=mx.cpu(0))
        # grads already populated; one more step counts its collectives
        with autograd.record():
            loss = gluon.loss.L2Loss()(
                net_b(x), nd.zeros((4, 3), ctx=mx.cpu(0)))
        loss.backward()
        tr_b.step(8)
        n_collectives = telemetry.summary()["collectives"]["count"] - before
        total_bytes = sum(p.data().size * 4
                          for p in net_b.collect_params().values())
        assert n_collectives <= math.ceil(total_bytes / (32 << 20))
        assert tr_b._last_n_buckets == 1
    finally:
        telemetry.reset()
    net_ref, _ = run(bucketed=False)
    # note: run(bucketed=True) above took one extra (asymmetric) step, so
    # compare fresh symmetric runs instead
    net_b2, _ = run(bucketed=True)
    for a, b in zip(net_b2.collect_params().values(),
                    net_ref.collect_params().values()):
        np.testing.assert_allclose(a.data().asnumpy(), b.data().asnumpy(),
                                   rtol=1e-6, atol=1e-7)
