"""Profiler aggregate stats (reference: src/profiler/aggregate_stats.cc;
mx.profiler.dumps() must answer \"which op is slow\" for a model step).
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu import profiler


def _resnet_ish():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1), gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"), gluon.nn.MaxPool2D(2),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    return net


def test_dumps_ranks_ops_for_model_step(tmp_path):
    profiler.reset_stats()
    profiler.set_config(filename=str(tmp_path / "prof.json"),
                        profile_all=True)
    net = _resnet_ish()
    x = nd.array(np.random.rand(2, 3, 16, 16).astype(np.float32))
    net(x)  # resolve deferred init outside the profile window
    profiler.start()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    y = nd.array(np.random.randint(0, 10, 2).astype(np.float32))
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    profiler.stop()
    table = profiler.dumps()
    assert "Profile Statistics" in table
    for op_name in ("Convolution", "BatchNorm", "Pooling", "FullyConnected"):
        assert op_name in table, table
    assert "Calls" in table and "Total(ms)" in table
    # ranked: rows are sorted by total time descending (use the json form)
    import json

    rows = json.loads(profiler.dumps(format="json"))
    totals = [r["total_ms"] for r in rows]
    assert totals == sorted(totals, reverse=True), totals


def test_dumps_includes_cached_op(tmp_path):
    profiler.reset_stats()
    profiler.set_config(filename=str(tmp_path / "prof2.json"))
    net = _resnet_ish()
    net.hybridize()
    x = nd.array(np.random.rand(2, 3, 16, 16).astype(np.float32))
    net(x)  # compile outside the window
    profiler.start()
    net(x)
    profiler.stop()
    table = profiler.dumps(reset=True)
    assert "CachedOp:HybridSequential" in table
    # reset=True clears the aggregation
    assert "no per-op stats" in profiler.dumps()


def test_profiled_cached_op_with_nested_outputs(tmp_path):
    # regression: profiling a hybridized block whose forward returns a
    # nested (output, [states...]) pytree must not crash
    cell = gluon.rnn.LSTMCell(8)
    cell.initialize(mx.init.Xavier())
    cell.hybridize()
    x = nd.array(np.random.rand(2, 4).astype(np.float32))
    states = cell.begin_state(2)
    cell(x, states)  # compile outside the window
    profiler.set_config(filename=str(tmp_path / "prof3.json"))
    profiler.start()
    out, new_states = cell(x, states)
    profiler.stop()
    assert "CachedOp:LSTMCell" in profiler.dumps(reset=True)
    assert np.isfinite(out.asnumpy()).all()


def test_stats_not_collected_when_stopped():
    profiler.reset_stats()
    x = nd.array(np.random.rand(4, 4).astype(np.float32))
    (x + x).asnumpy()
    assert "no per-op stats" in profiler.dumps()
