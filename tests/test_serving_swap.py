"""Zero-downtime weight hot-swap (ISSUE 16 tentpole;
docs/SERVING.md §Weight hot-swap).

Covers: the mid-stream flip (a pending swap applies at a stream
boundary while requests are in flight — zero dropped requests, zero
fresh decode compiles, post-swap outputs bitwise equal a fresh engine
booted on the new weights), the verify-before-publish rejection path
(fingerprint mismatch keeps the old weights, loudly), swapping straight
from a shard-granular format-2 checkpoint, the memwatch "staging"
census draining after the flip, and the weight-generation telemetry
(summary rollup, ``weight_swap`` events, ``mx_serve_weight_generation``
prometheus gauge).
"""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import checkpoint, memwatch, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.models.transformer import Transformer, label_smoothed_ce
from mxnet_tpu.serving import Request, ServingEngine, TransformerAdapter

PAD, BOS, EOS = 0, 1, 2


@pytest.fixture
def tele(tmp_path):
    telemetry.reset()
    memwatch.reset()
    telemetry.enable(str(tmp_path))
    yield telemetry
    telemetry.reset()
    memwatch.reset()


def _warm(net):
    # materialize deferred shapes: checkpoint/swap need concrete params
    net(nd.array([[3, 4, 5, 0, 0]], dtype="int32"),
        nd.array([[BOS, 3, 4, 5, 0, 0]], dtype="int32"))
    return net


def _tiny_model(seed=0):
    mx.random.seed(seed)
    net = Transformer(16, units=32, hidden_size=64, num_heads=4,
                      num_layers=2, max_length=48, dropout=0.0)
    net.initialize(mx.init.Xavier())
    return _warm(net)


def _engine(net, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_len", 16)
    kw.setdefault("stream_every", 2)
    return ServingEngine(TransformerAdapter(net, src_max_len=8), **kw)


def _gathered_ckpt(net, d):
    ck = checkpoint.AsyncCheckpointer(d, save_every=1, keep=2)
    ck.step(net)
    ck.close()
    return d


def _reqs(rng, n, max_new=8, tag=""):
    return [Request(rng.randint(3, 16, 5), max_new_tokens=max_new,
                    bos_id=BOS, eos_id=EOS, request_id=f"{tag}{i}")
            for i in range(n)]


# ---------------------------------------------------------------------------
# the mid-stream flip
# ---------------------------------------------------------------------------
def test_hot_swap_mid_stream_zero_drop_zero_recompile(tele, tmp_path):
    """ACCEPTANCE: a swap staged while the run loop is live applies at
    the next stream boundary — wave A finishes across the flip with
    nothing dropped, wave B (arriving after) decodes bitwise-identical
    to a fresh engine booted on the new checkpoint, and the trace books
    exactly ONE decode compile (the swap never recompiles)."""
    net_a, net_b = _tiny_model(0), _tiny_model(7)
    ckdir = _gathered_ckpt(net_b, str(tmp_path / "ck"))
    eng = _engine(net_a)
    eng._ensure_compiled()
    rng = np.random.RandomState(3)
    wave_a = _reqs(rng, 2, max_new=10, tag="a")
    wave_b = _reqs(rng, 2, max_new=10, tag="b")
    # stage the swap as a LIVE run loop would see it: with the engine
    # marked running the flip must defer to a stream boundary, not
    # apply synchronously here
    eng._running = True
    try:
        assert eng.swap_weights(ckdir) == 1
    finally:
        eng._running = False
    assert eng.weight_generation == 0 and eng._swap_pending is not None
    # staging census: the transient 2x-weights window is attributed
    assert memwatch.census()["host_bytes"]["staging"] > 0
    flip_steps = []
    orig_apply = eng._apply_pending_swap
    eng._apply_pending_swap = (
        lambda: (flip_steps.append(eng.step_count), orig_apply())[-1])
    out = eng.serve(wave_a + wave_b, arrival_steps=[0, 0, 8, 8])
    # the pending swap flipped at the FIRST stream boundary inside
    # run() — wave A was mid-flight, wave B hadn't even arrived
    assert eng.weight_generation == 1
    assert flip_steps == [2], flip_steps
    assert eng._swap_pending is None and not eng._staging
    assert memwatch.census()["host_bytes"].get("staging", 0) == 0
    # zero dropped: every request (in-flight and post-swap) completed
    for r in wave_a + wave_b:
        assert len(out[r.id]) == r.max_new_tokens, r.id
        assert r.stream.finished
    # post-swap arrivals must match a FRESH engine on the new weights
    fresh = _engine(_tiny_model(7))
    ref = fresh.serve([Request(r.tokens, max_new_tokens=10, bos_id=BOS,
                               eos_id=EOS, request_id=r.id)
                       for r in wave_b])
    for r in wave_b:
        np.testing.assert_array_equal(out[r.id], ref[r.id])
    # and the swap visibly changed the model: wave B != what the OLD
    # weights would have produced for the same prompts
    old = _engine(_tiny_model(0)).serve(
        [Request(r.tokens, max_new_tokens=10, bos_id=BOS, eos_id=EOS,
                 request_id=r.id) for r in wave_b])
    assert all(not np.array_equal(out[r.id], old[r.id]) for r in wave_b)
    # zero fresh compiles: one decode + one prefill executable, total
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    compiles = [e for e in events if e["kind"] == "compile"
                and e.get("executor") == "ServingEngine"]
    assert sorted(e["site"] for e in compiles) == \
        ["serving_decode", "serving_prefill"], compiles
    # the weight_swap event rode into the JSONL with its payload facts
    swaps = [e for e in events if e["kind"] == "weight_swap"]
    assert len(swaps) == 1 and swaps[0]["generation"] == 1
    assert swaps[0]["staged_bytes"] > 0 and swaps[0]["step"] == 1
    sv = telemetry.summary()["serving"]
    assert sv["weight_generation"] == 1 and sv["weight_swaps"] == 1
    prom = telemetry.render_prometheus()
    assert 'mx_serve_weight_generation{rank="0"} 1' in prom
    assert "mx_serve_weight_swaps_total" in prom


def test_idle_swap_applies_immediately(tele, tmp_path):
    """No run loop live: swap_weights flips synchronously and the next
    serve() call decodes on the new weights — parity with a fresh
    engine, end to end."""
    net_a, net_b = _tiny_model(0), _tiny_model(7)
    ckdir = _gathered_ckpt(net_b, str(tmp_path / "ck"))
    eng = _engine(net_a)
    src = np.array([3, 4, 5, 6, 7], np.int32)
    before = eng.serve([Request(src, max_new_tokens=6, bos_id=BOS,
                                eos_id=EOS, request_id="r0")])["r0"]
    assert eng.swap_weights(ckdir) == 1
    assert eng.weight_generation == 1 and not eng._staging
    after = eng.serve([Request(src, max_new_tokens=6, bos_id=BOS,
                               eos_id=EOS, request_id="r1")])["r1"]
    ref = _engine(_tiny_model(7)).serve(
        [Request(src, max_new_tokens=6, bos_id=BOS, eos_id=EOS,
                 request_id="r2")])["r2"]
    np.testing.assert_array_equal(after, ref)
    assert not np.array_equal(before, after)


def test_swap_from_sharded_checkpoint(tele, tmp_path):
    """Tentpole synergy: the engine hot-swaps straight out of a
    shard-granular format-2 checkpoint (lazy shard composition feeds the
    staging buffer; no gathered params.nd anywhere on disk)."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net_a, net_b = _tiny_model(0), _tiny_model(7)
    step = DataParallelStep(
        net_b, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    rng = np.random.RandomState(2)
    src = np.zeros((4, 6), np.int32)
    src[:, :5] = rng.randint(3, 16, (4, 5))
    tgt_in = np.zeros((4, 7), np.int32)
    tgt_in[:, 0] = BOS
    step.step((nd.array(src, dtype="int32"),
               nd.array(tgt_in, dtype="int32")),
              nd.array(tgt_in.astype(np.float32)))
    step.sync_to_block()  # net_b now holds the trained weights
    ckdir = str(tmp_path / "shard_ck")
    ck = checkpoint.AsyncCheckpointer(ckdir, save_every=1, sharded=True)
    ck.step(step)
    ck.close()
    meta = json.load(open(os.path.join(ckdir, "step-1", "meta.json")))
    assert meta["format"] == 2
    assert not os.path.exists(os.path.join(ckdir, "step-1", "params.nd"))

    eng = _engine(net_a)
    assert eng.swap_weights(ckdir) == 1
    assert eng.weight_generation == 1
    q = np.array([3, 4, 5], np.int32)
    got = eng.serve([Request(q, max_new_tokens=6, bos_id=BOS, eos_id=EOS,
                             request_id="s0")])["s0"]
    ref = _engine(net_b).serve(
        [Request(q, max_new_tokens=6, bos_id=BOS, eos_id=EOS,
                 request_id="s1")])["s1"]
    np.testing.assert_array_equal(got, ref)


# ---------------------------------------------------------------------------
# rejection: verify-before-publish
# ---------------------------------------------------------------------------
def test_swap_rejected_on_fingerprint_mismatch(tele, tmp_path):
    """A checkpoint whose param structure doesn't match the compiled
    decode executable is rejected LOUDLY: old weights keep serving,
    generation unchanged, staging empty, a rejected weight_swap event
    books the reason."""
    big = _tiny_model(7)
    ckdir = _gathered_ckpt(big, str(tmp_path / "ck"))
    small = Transformer(16, units=16, hidden_size=32, num_heads=4,
                        num_layers=1, max_length=48, dropout=0.0)
    small.initialize(mx.init.Xavier())
    eng = _engine(_warm(small))
    src = np.array([3, 4, 5], np.int32)
    before = eng.serve([Request(src, max_new_tokens=5, bos_id=BOS,
                                eos_id=EOS, request_id="p0")])["p0"]
    with pytest.raises(MXNetError, match="fingerprint|missing parameter"):
        eng.swap_weights(ckdir)
    assert eng.weight_generation == 0 and not eng._staging
    assert eng._swap_pending is None
    # still serving, on the ORIGINAL weights
    after = eng.serve([Request(src, max_new_tokens=5, bos_id=BOS,
                               eos_id=EOS, request_id="p1")])["p1"]
    np.testing.assert_array_equal(before, after)
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    rej = [e for e in events if e["kind"] == "weight_swap"
           and e.get("rejected")]
    assert len(rej) == 1 and rej[0]["generation"] == 0
    assert telemetry.summary()["serving"]["weight_generation"] == 0


def test_swap_invalidates_prefix_cache(tele, tmp_path):
    """ISSUE 17 satellite: prefix-cache entries are generation-stamped
    and die at the weight flip — a post-swap request with the SAME
    (source, prefix) MISSES, re-ingests under the new weights, and
    decodes bitwise what a fresh engine on the new checkpoint decodes.
    It can never fork KV pages teacher-forced under the old weights."""
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    net_a, net_b = _tiny_model(0), _tiny_model(7)
    # briefly train net_b (reverse task) — untrained nets PARROT a
    # forced prefix identically regardless of weights, which would mask
    # a failed invalidation; a few adam steps make the continuation
    # weight-sensitive
    rng = np.random.RandomState(2)
    L = 6
    src_t = np.zeros((8, L + 1), np.int32)
    tgt_in = np.zeros((8, L + 2), np.int32)
    tgt_out = np.zeros((8, L + 2), np.int32)
    for b in range(8):
        toks = rng.randint(3, 16, L)
        src_t[b, :L] = toks
        rev = toks[::-1]
        tgt_in[b, 0] = BOS
        tgt_in[b, 1:L + 1] = rev
        tgt_out[b, :L] = rev
        tgt_out[b, L] = EOS
    step = DataParallelStep(
        net_b, lambda lo, la: label_smoothed_ce(lo, la, smoothing=0.0),
        mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="adam", optimizer_params={"learning_rate": 5e-3})
    for _ in range(16):
        step.step((nd.array(src_t, dtype="int32"),
                   nd.array(tgt_in, dtype="int32")),
                  nd.array(tgt_out.astype(np.float32)))
    step.sync_to_block()
    ckdir = _gathered_ckpt(net_b, str(tmp_path / "ck"))
    src = np.array([3, 4, 5], np.int32)
    prefix = np.array([6, 7, 8, 9, 10], np.int32)

    def mk(rid):
        return Request(src, max_new_tokens=5, bos_id=BOS, eos_id=-1,
                       request_id=rid, prefix=prefix)

    eng = _engine(net_a, prefix_cache=True)
    eng.serve([mk("r0")])  # registers the gen-0 pages + prefill entries
    eng.serve([mk("r1")])  # and proves they hit pre-swap
    assert eng._prefix.hits == 2 and len(eng._prefix) == 2
    held = eng._cache.num_pages - 1 - eng._cache.pages_free
    assert held > 0, "the registered entry must hold pages"

    assert eng.swap_weights(ckdir) == 1
    # the flip dropped EVERY stale-generation entry and released its
    # pages back to the pool
    assert len(eng._prefix) == 0
    assert eng._cache.pages_free == eng._cache.num_pages - 1

    out = eng.serve([mk("r2")])["r2"]
    assert eng._prefix.hits == 2, "post-swap request must MISS, not fork"
    ref = _engine(net_b, prefix_cache=True).serve(
        [mk("r3")])["r3"]
    np.testing.assert_array_equal(out, ref)
    old = _engine(_tiny_model(0), prefix_cache=True).serve(
        [mk("r4")])["r4"]
    assert not np.array_equal(out, old), \
        "old-weight KV would have produced these tokens — invalidation " \
        "did nothing"
    telemetry.flush()
    events = [json.loads(line)
              for line in open(telemetry.event_path(str(tmp_path), 0))]
    inval = [e for e in events if e["kind"] == "serve_prefix_invalidate"]
    assert len(inval) == 1 and inval[0]["dropped"] == 2


def test_swap_rejects_missing_or_torn_checkpoint(tmp_path):
    eng = _engine(_tiny_model(0))
    os.makedirs(str(tmp_path / "empty"), exist_ok=True)
    with pytest.raises(MXNetError, match="no valid checkpoint"):
        eng.swap_weights(str(tmp_path / "empty"))
    # a torn gathered checkpoint (bad digest) is invisible to the swap
    ckdir = _gathered_ckpt(_tiny_model(7), str(tmp_path / "ck"))
    pnd = os.path.join(ckdir, "step-1", "params.nd")
    with open(pnd, "r+b") as f:
        f.truncate(os.path.getsize(pnd) // 2)
    with pytest.raises(MXNetError, match="no valid checkpoint"):
        eng.swap_weights(ckdir)
    assert eng.weight_generation == 0
