"""mxlint: per-rule positive/negative fixtures, the suppression machinery,
the baseline round-trip, and the tier-1 full-tree gate.

The full-tree test at the bottom is the actual invariant: the rules that
six PRs paid for (no host sync in dispatch bodies, shard_map only via the
compat shim, perf_counter for durations, no imports in signal handlers,
registered env vars, ...) fail CI the moment a change breaks them.
"""
import importlib.util
import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MXLINT = os.path.join(_REPO, "tools", "mxlint.py")

_spec = importlib.util.spec_from_file_location("mxlint", _MXLINT)
mxlint = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(mxlint)


def lint_src(tmp_path, src, relpath="mxnet_tpu/fixture.py", rules=None,
             hot_entries=None, env_registry=frozenset(), pass_entries=None):
    """Write one fixture file under a fake repo root and lint it."""
    path = tmp_path / relpath
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    findings, stats = mxlint.run_lint(
        [str(path)], root=str(tmp_path), rules=rules,
        hot_entries=hot_entries if hot_entries is not None else {},
        env_registry=env_registry,
        pass_entries=pass_entries if pass_entries is not None else {})
    return findings, stats


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# hot-sync
# ---------------------------------------------------------------------------
HOT = {"mxnet_tpu/fixture.py": ("Step._step_impl",)}

def test_hot_sync_direct_readback_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        class Step:
            def _step_impl(self, loss):
                return float(loss)
        """, hot_entries=HOT)
    assert rules_of(findings) == ["hot-sync"]
    assert findings[0].context == "Step._step_impl"


def test_hot_sync_reaches_through_call_graph(tmp_path):
    # entry -> self method -> module function -> np.asarray
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        def _materialize(x):
            return np.asarray(x)

        class Step:
            def _step_impl(self, x):
                return self._place(x)

            def _place(self, x):
                return _materialize(x)
        """, hot_entries=HOT)
    assert rules_of(findings) == ["hot-sync"]
    assert findings[0].context == "_materialize"


def test_hot_sync_method_syncs_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        class Step:
            def _step_impl(self, loss):
                loss.block_until_ready()
                return loss.item()
        """, hot_entries=HOT)
    assert sorted(rules_of(findings)) == ["hot-sync", "hot-sync"]


def test_hot_sync_ignores_cold_functions_and_literals(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class Step:
            def _step_impl(self, x):
                scale = float(1e-3)              # constant: no readback
                arr = np.asarray([1.0, 2.0])     # host literal
                return scale, arr

            def sync_to_block(self, x):
                return float(x)                  # NOT a per-step body
        """, hot_entries=HOT)
    assert findings == []


def test_hot_sync_flags_memory_apis_in_dispatch(tmp_path):
    """PR 8: memory polling (memory_stats / live_arrays /
    memory_analysis) must never run inside a per-step dispatch body —
    sample via memwatch at step boundaries instead."""
    findings, _ = lint_src(tmp_path, """
        import jax

        class Step:
            def _step_impl(self, dev, compiled):
                stats = dev.memory_stats()
                live = jax.live_arrays()
                ma = compiled.memory_analysis()
                return stats, live, ma
        """, hot_entries=HOT)
    assert rules_of(findings) == ["hot-sync"] * 3
    assert all("memwatch" in f.message or "memory" in f.message
               for f in findings)


def test_hot_sync_flags_live_arrays_from_import(tmp_path):
    findings, _ = lint_src(tmp_path, """
        from jax import live_arrays

        class Step:
            def _step_impl(self):
                return live_arrays()
        """, hot_entries=HOT)
    assert rules_of(findings) == ["hot-sync"]


def test_hot_sync_memory_apis_allowed_off_hot_path(tmp_path):
    """The same calls at a step boundary (not reachable from a dispatch
    body) are exactly where the memwatch sampler runs — clean."""
    findings, _ = lint_src(tmp_path, """
        import jax

        class Step:
            def _step_impl(self, x):
                return x

            def on_step_boundary(self, dev):
                return dev.memory_stats(), jax.live_arrays()
        """, hot_entries=HOT)
    assert findings == []


# ---------------------------------------------------------------------------
# raw-shard-map
# ---------------------------------------------------------------------------
def test_raw_shard_map_import_and_call_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax
        from jax.experimental.shard_map import shard_map

        def f(fn, mesh, spec):
            return jax.shard_map(fn, mesh=mesh, in_specs=spec,
                                 out_specs=spec)
        """)
    assert rules_of(findings).count("raw-shard-map") >= 2


def test_raw_shard_map_allowed_in_shim_home_and_via_compat(tmp_path):
    findings, _ = lint_src(tmp_path, """
        from jax.experimental.shard_map import shard_map
        """, relpath="mxnet_tpu/parallel/sharding.py")
    assert findings == []
    findings, _ = lint_src(tmp_path, """
        from mxnet_tpu.parallel.sharding import shard_map_compat

        def f(fn, mesh, spec):
            return shard_map_compat(fn, mesh=mesh, in_specs=spec,
                                    out_specs=spec)
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# wall-clock-duration
# ---------------------------------------------------------------------------
def test_wall_clock_duration_local_and_attr_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import time

        def f():
            t0 = time.time()
            work()
            return time.time() - t0

        class H:
            def begin(self):
                self.t0 = time.time()

            def end(self):
                return time.time() - self.t0
        """)
    assert rules_of(findings) == ["wall-clock-duration",
                                  "wall-clock-duration"]


def test_wall_clock_cross_process_age_not_flagged(tmp_path):
    # age vs a wall stamp read from another process's file is the
    # legitimate use of time.time() (heartbeats) — must stay clean
    findings, _ = lint_src(tmp_path, """
        import time

        def age(rec):
            return time.time() - float(rec.get("time", 0.0))

        def ok():
            t0 = time.perf_counter()
            work()
            return time.perf_counter() - t0
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------
def test_retrace_hazard_jit_in_hot_path(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        class Step:
            def _step_impl(self, f, x):
                return jax.jit(f)(x)
        """, hot_entries=HOT)
    assert rules_of(findings) == ["retrace-hazard"]


def test_retrace_hazard_unhashable_static_arg(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        g = jax.jit(run, static_argnums=(1,))

        def call(x):
            bad = g(x, [4, 8])       # list literal in a static position
            ok = g(x, (4, 8))        # hashable tuple: fine
            return bad, ok
        """)
    assert rules_of(findings) == ["retrace-hazard"]
    assert "unhashable" in findings[0].message


def test_jit_outside_hot_path_not_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import jax

        class Step:
            def _step_impl(self, x):
                return x

        def build(f):
            return jax.jit(f)
        """, hot_entries=HOT)
    assert findings == []


def test_stale_hot_entry_is_a_finding(tmp_path):
    # a renamed dispatch body must not silently no-op the flagship rule
    findings, _ = lint_src(tmp_path, """
        class Step:
            def _step_impl_renamed(self, x):
                return x
        """, hot_entries=HOT)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "Step._step_impl" in findings[0].message


def test_superstep_entries_registered_and_rename_fails_loudly(tmp_path):
    """The superstep dispatch/scan-body qualnames are in the REAL
    HOT_PATH_ENTRIES (the new hottest path must stay under the hot-sync
    rule), and renaming the scan-body builder in a fixture carrying
    those entries flags stale-hot-entry rather than silently un-linting
    the path."""
    real = mxlint.HOT_PATH_ENTRIES["mxnet_tpu/parallel/data_parallel.py"]
    assert "DataParallelStep._superstep_impl" in real
    assert "DataParallelStep._super_fn" in real

    entries = {"mxnet_tpu/fixture.py": ("DataParallelStep._superstep_impl",
                                        "DataParallelStep._super_fn")}
    findings, _ = lint_src(tmp_path, """
        class DataParallelStep:
            def _superstep_impl(self, group):
                return group

            def _super_fn_renamed(self, k):
                return k
        """, hot_entries=entries)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "DataParallelStep._super_fn" in findings[0].message
    # a host readback reachable from the superstep dispatch body is
    # flagged like any hot path
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class DataParallelStep:
            def _superstep_impl(self, group):
                return np.asarray(group)

            def _super_fn(self, k):
                return k
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]


# ---------------------------------------------------------------------------
# precision subsystem entries (ISSUE 15): the loss-scale shim and the
# int8 decode body are hot paths; the OLD per-gradient readback pattern
# must be flagged if ever reintroduced
# ---------------------------------------------------------------------------
def test_precision_entries_registered():
    assert mxlint.HOT_PATH_ENTRIES["mxnet_tpu/precision/loss_scale.py"] \
        == ("overflow_flag",)
    # the decode body lives on the shared rewrite-adapter base since the
    # int4 path joined int8 (both delegate through it)
    assert mxlint.HOT_PATH_ENTRIES["mxnet_tpu/precision/quantize.py"] \
        == ("_RewriteAdapterBase.decode",)
    amp_entries = mxlint.HOT_PATH_ENTRIES["mxnet_tpu/contrib/amp/amp.py"]
    assert "DynamicLossScaler.has_overflow" in amp_entries


def test_old_scaler_readback_pattern_would_be_flagged(tmp_path):
    """The pre-PR-15 DynamicLossScaler.has_overflow body — one blocking
    asnumpy() PER GRADIENT inside the per-step path — fires hot-sync
    under the entry now registered for the shim.  Reintroducing the old
    pattern cannot land silently."""
    entries = {"mxnet_tpu/fixture.py": ("DynamicLossScaler.has_overflow",)}
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class DynamicLossScaler:
            def has_overflow(self, params):
                for param in params:
                    for g in param.list_grad():
                        arr = g.asnumpy()
                        if not np.isfinite(arr).all():
                            return True
                return False
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]
    assert ".asnumpy()" in findings[0].message


def test_new_scaler_shim_shape_is_clean(tmp_path):
    """The fused-delegate shim shape — collect raw grad buffers, ONE
    fused device reduce, one justified boundary readback — lints clean
    under the same entry."""
    entries = {"mxnet_tpu/fixture.py": ("DynamicLossScaler.has_overflow",)}
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        def overflow_flag(arrays):
            return arrays

        class DynamicLossScaler:
            def has_overflow(self, params):
                grads = [g._data for p in params for g in p.list_grad()]
                if not grads:
                    return False
                flag = overflow_flag(grads)
                # mxlint: disable=hot-sync — ONE readback at the eager
                # python-bool API boundary
                return bool(np.asarray(flag))
        """, hot_entries=entries)
    assert rules_of(findings) == []


def test_quantized_decode_body_guarded(tmp_path):
    """A host readback sneaking into the int8 adapter's decode body (the
    trace body of the ONE quantized executable) is flagged."""
    entries = {"mxnet_tpu/fixture.py": ("QuantizedAdapter.decode",)}
    findings, _ = lint_src(tmp_path, """
        class QuantizedAdapter:
            def decode(self, F, tok):
                return float(tok.sum())
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]
    findings, _ = lint_src(tmp_path, """
        class QuantizedAdapter:
            def decode(self, F, tok):
                return self._inner.decode(F, tok)
        """, hot_entries=entries)
    assert rules_of(findings) == []


def test_precision_entry_rename_fails_loudly(tmp_path):
    entries = {"mxnet_tpu/fixture.py": ("overflow_flag",)}
    findings, _ = lint_src(tmp_path, """
        def overflow_flag_renamed(arrays):
            return arrays
        """, hot_entries=entries)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "overflow_flag" in findings[0].message


# ---------------------------------------------------------------------------
# signal-unsafe
# ---------------------------------------------------------------------------
def test_signal_unsafe_import_open_acquire_flagged(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import signal

        def install(lock):
            def _handler(signum, frame):
                import os
                open("/tmp/x", "w")
                lock.acquire()

            signal.signal(signal.SIGTERM, _handler)
        """)
    assert sorted(rules_of(findings)) == ["signal-unsafe"] * 3


def test_signal_safe_handler_clean(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import signal
        import sys

        def install():
            def _handler(signum, frame):
                mod = sys.modules.get("mxnet_tpu.parallel.async_loss")
                if mod is not None:
                    mod.drain_all()
                print("preempted", flush=True)

            signal.signal(signal.SIGTERM, _handler)
        """)
    assert findings == []


# ---------------------------------------------------------------------------
# thread-shared-write (the race detector)
# ---------------------------------------------------------------------------
def test_race_worker_and_consumer_write_unlocked(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class Iter:
            def start(self):
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def _worker(self):
                self.cursor = self.cursor + 1

            def reset(self):
                self.cursor = 0
        """)
    assert rules_of(findings) == ["thread-shared-write"]
    assert "cursor" in findings[0].message


def test_race_clean_when_both_sides_hold_the_lock(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class Iter:
            def start(self):
                self._lock = threading.Lock()
                self._thread = threading.Thread(target=self._worker)
                self._thread.start()

            def _worker(self):
                with self._lock:
                    self.cursor = self.cursor + 1

            def reset(self):
                with self._lock:
                    self.cursor = 0
        """)
    assert findings == []


def test_race_init_writes_are_pre_thread_and_safe(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import threading

        class Iter:
            def __init__(self):
                self.cursor = 0      # before the thread exists: safe
                threading.Thread(target=self._worker).start()

            def _worker(self):
                self.cursor = self.cursor + 1
        """)
    assert findings == []


def test_race_nested_worker_fn_not_its_own_consumer(tmp_path):
    # a nested Thread target's writes are worker-side ONLY — they must not
    # also register as a "consumer method" and race with themselves
    findings, _ = lint_src(tmp_path, """
        import threading

        class Iter:
            def start(self):
                def worker():
                    self.count = self.count + 1

                threading.Thread(target=worker).start()
        """)
    assert findings == []
    # ...but a real consumer-side write still races with the nested worker
    findings, _ = lint_src(tmp_path, """
        import threading

        class Iter:
            def start(self):
                def worker():
                    self.count = self.count + 1

                threading.Thread(target=worker).start()

            def reset(self):
                self.count = 0
        """)
    assert rules_of(findings) == ["thread-shared-write"]


def test_race_threaded_iter_produce_is_worker_side(tmp_path):
    findings, _ = lint_src(tmp_path, """
        class _ThreadedIter:
            pass

        class Prefetch(_ThreadedIter):
            def _produce(self):
                self.count = self.count + 1

            def reset(self):
                self.count = 0
        """)
    assert rules_of(findings) == ["thread-shared-write"]


# ---------------------------------------------------------------------------
# silent-except
# ---------------------------------------------------------------------------
def test_silent_except_flagged_and_justification_accepted(tmp_path):
    findings, _ = lint_src(tmp_path, """
        def bad():
            try:
                work()
            except Exception:
                pass

        def justified():
            try:
                work()
            except Exception:
                # best-effort teardown while already dying
                pass

        def narrow():
            import queue
            try:
                work()
            except queue.Empty:
                pass
        """)
    assert rules_of(findings) == ["silent-except"]
    assert findings[0].line == 5  # the `except Exception:` line


def test_silent_except_bare_and_tuple_broad(tmp_path):
    findings, _ = lint_src(tmp_path, """
        def f():
            try:
                work()
            except (ValueError, Exception):
                pass
        """)
    assert rules_of(findings) == ["silent-except"]


# ---------------------------------------------------------------------------
# env-unregistered
# ---------------------------------------------------------------------------
def test_env_unregistered_ast_level(tmp_path):
    findings, _ = lint_src(tmp_path, '''
        """Docstring mentioning "MX_NOT_A_READ" is prose, not a use-site."""
        import os

        KNOWN = os.environ.get("MX_KNOWN_KNOB", "1")
        DRIFT = os.environ.get("MX_DRIFTED_KNOB")
        ''', env_registry={"MX_KNOWN_KNOB"})
    assert rules_of(findings) == ["env-unregistered"]
    assert "MX_DRIFTED_KNOB" in findings[0].message


def test_env_rule_scope_excludes_examples(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import os

        os.environ.setdefault("MX_DRIFTED_KNOB", "1")
        """, relpath="examples/fixture.py", env_registry=set())
    assert findings == []


# ---------------------------------------------------------------------------
# suppression machinery
# ---------------------------------------------------------------------------
def test_suppression_trailing_and_own_line(tmp_path):
    findings, stats = lint_src(tmp_path, """
        import time

        def f():
            t0 = time.time()
            dt = time.time() - t0  # mxlint: disable=wall-clock-duration ok

        def g():
            t0 = time.time()
            # mxlint: disable=wall-clock-duration — cross-epoch wall fact
            # (continuation of the justification)
            dt = time.time() - t0
        """)
    assert findings == []
    assert stats["suppressed"] == 2


def test_suppression_comma_in_justification_not_a_rule(tmp_path):
    # "disable=<rule>, free text" must not read the free text as rules
    findings, _ = lint_src(tmp_path, """
        import time

        def f():
            t0 = time.time()
            dt = time.time() - t0  # mxlint: disable=wall-clock-duration, staged input path
        """)
    assert findings == []
    # ...but a lone unknown word after the comma is still a typo finding
    findings, _ = lint_src(tmp_path, """
        import time

        def f():
            t0 = time.time()
            dt = time.time() - t0  # mxlint: disable=wall-clock-duration,wall-clck
        """)
    assert rules_of(findings) == ["bad-suppression"]


def test_nested_function_finding_not_duplicated(tmp_path):
    # a nested fn's body is walked via the enclosing scope AND as its own
    # entry; one defect must yield exactly one finding (and one baseline
    # fingerprint)
    findings, _ = lint_src(tmp_path, """
        import time

        def outer():
            def inner():
                t0 = time.time()
                return time.time() - t0

            return inner
        """)
    assert rules_of(findings) == ["wall-clock-duration"]


def test_suppression_wrong_rule_does_not_silence(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import time

        def f():
            t0 = time.time()
            dt = time.time() - t0  # mxlint: disable=hot-sync
        """)
    assert rules_of(findings) == ["wall-clock-duration"]


def test_unknown_rule_in_suppression_is_a_finding(tmp_path):
    findings, _ = lint_src(tmp_path, """
        x = 1  # mxlint: disable=definitely-not-a-rule
        """)
    assert rules_of(findings) == ["bad-suppression"]
    assert "definitely-not-a-rule" in findings[0].message


def test_rules_filter_and_unknown_rule_rejected(tmp_path):
    findings, _ = lint_src(tmp_path, """
        import time

        def f():
            try:
                t0 = time.time()
                return time.time() - t0
            except Exception:
                pass
        """, rules=["silent-except"])
    assert rules_of(findings) == ["silent-except"]
    with pytest.raises(ValueError, match="unknown rule"):
        mxlint.run_lint([str(tmp_path)], root=str(tmp_path),
                        rules=["no-such-rule"])


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------
def _one_finding_repo(tmp_path):
    (tmp_path / "mxnet_tpu").mkdir(parents=True, exist_ok=True)
    f = tmp_path / "mxnet_tpu" / "mod.py"
    f.write_text(textwrap.dedent("""
        import time

        def f():
            t0 = time.time()
            return time.time() - t0
        """))
    return f


def test_baseline_roundtrip_add_then_remove(tmp_path):
    src = _one_finding_repo(tmp_path)
    findings, _ = mxlint.run_lint([str(src)], root=str(tmp_path),
                                  hot_entries={}, env_registry=set())
    assert len(findings) == 1
    bl = tmp_path / "baseline.json"

    # write: the new entry is marked for review
    entries = mxlint.write_baseline(str(bl), findings, str(tmp_path), [])
    assert len(entries) == 1
    assert entries[0]["justification"].startswith("UNREVIEWED")

    # a reviewed justification survives a rewrite (carried by fingerprint)
    entries[0]["justification"] = "epoch wall is a cross-run fact"
    bl.write_text(json.dumps({"version": 1, "entries": entries}))
    entries2 = mxlint.write_baseline(str(bl), findings, str(tmp_path),
                                     mxlint.load_baseline(str(bl)))
    assert entries2[0]["justification"] == "epoch wall is a cross-run fact"

    # apply: finding is baselined away -> clean
    new, baselined, stale = mxlint.apply_baseline(
        findings, mxlint.load_baseline(str(bl)), str(tmp_path))
    assert new == [] and len(baselined) == 1 and stale == []

    # fix the code -> the entry goes stale and is reported for removal
    src.write_text(src.read_text().replace("time.time", "time.perf_counter"))
    findings, _ = mxlint.run_lint([str(src)], root=str(tmp_path),
                                  hot_entries={}, env_registry=set())
    assert findings == []
    new, baselined, stale = mxlint.apply_baseline(
        findings, mxlint.load_baseline(str(bl)), str(tmp_path))
    assert new == [] and baselined == [] and len(stale) == 1


def test_baseline_is_line_number_independent(tmp_path):
    src = _one_finding_repo(tmp_path)
    findings, _ = mxlint.run_lint([str(src)], root=str(tmp_path),
                                  hot_entries={}, env_registry=set())
    bl = tmp_path / "baseline.json"
    mxlint.write_baseline(str(bl), findings, str(tmp_path), [])
    # shift the finding down: unrelated edits above must not un-baseline it
    src.write_text("# leading comment\n\n" + src.read_text())
    findings, _ = mxlint.run_lint([str(src)], root=str(tmp_path),
                                  hot_entries={}, env_registry=set())
    new, baselined, stale = mxlint.apply_baseline(
        findings, mxlint.load_baseline(str(bl)), str(tmp_path))
    assert new == [] and len(baselined) == 1 and stale == []


def test_malformed_baseline_rejected(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": [{"nope": 1}]}')
    with pytest.raises(ValueError, match="malformed"):
        mxlint.load_baseline(str(bl))


def test_write_baseline_with_rules_subset_preserves_other_entries(tmp_path):
    # --rules silent-except --write-baseline must NOT delete (or
    # un-justify) entries owned by rules that didn't run
    src = _one_finding_repo(tmp_path)   # wall-clock-duration finding
    bl = tmp_path / "baseline.json"
    findings, _ = mxlint.run_lint([str(src)], root=str(tmp_path),
                                  hot_entries={}, env_registry=set())
    entries = mxlint.write_baseline(str(bl), findings, str(tmp_path), [])
    entries[0]["justification"] = "reviewed: epoch wall fact"
    bl.write_text(json.dumps({"version": 1, "entries": entries}))

    p = _cli(["mxnet_tpu", "--root", str(tmp_path), "--baseline", str(bl),
              "--rules", "silent-except", "--write-baseline"],
             cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr
    kept = mxlint.load_baseline(str(bl))
    assert len(kept) == 1, kept
    assert kept[0]["justification"] == "reviewed: epoch wall fact"


def test_write_baseline_rejects_malformed_existing(tmp_path):
    # the write path must not silently regenerate over a corrupt file,
    # discarding every reviewed justification
    _one_finding_repo(tmp_path)
    bl = tmp_path / "baseline.json"
    bl.write_text("{not json")
    p = _cli(["mxnet_tpu", "--root", str(tmp_path), "--baseline", str(bl),
              "--write-baseline"], cwd=str(tmp_path))
    assert p.returncode == 2
    assert "unreadable" in p.stderr
    assert bl.read_text() == "{not json"


# ---------------------------------------------------------------------------
# CLI contract (exit codes + --json schema, documented in
# docs/STATIC_ANALYSIS.md for supervisor/trace_report consumption)
# ---------------------------------------------------------------------------
def _cli(args, cwd):
    return subprocess.run([sys.executable, _MXLINT] + args, cwd=cwd,
                          capture_output=True, text=True, timeout=60)


def test_cli_exit_codes_and_json_schema(tmp_path):
    _one_finding_repo(tmp_path)
    p = _cli(["mxnet_tpu", "--root", str(tmp_path), "--no-baseline",
              "--json"], cwd=str(tmp_path))
    assert p.returncode == 3, p.stderr
    rep = json.loads(p.stdout)
    for key in ("version", "files_scanned", "elapsed_s", "counts",
                "findings", "suppressed", "baselined", "stale_baseline"):
        assert key in rep, key
    assert rep["counts"] == {"wall-clock-duration": 1}
    f = rep["findings"][0]
    for key in ("rule", "path", "line", "col", "context", "message"):
        assert key in f, key
    assert f["path"] == "mxnet_tpu/mod.py"

    # clean tree -> 0
    (tmp_path / "mxnet_tpu" / "mod.py").write_text("x = 1\n")
    p = _cli(["mxnet_tpu", "--root", str(tmp_path), "--no-baseline"],
             cwd=str(tmp_path))
    assert p.returncode == 0, p.stderr

    # usage error -> 2
    p = _cli(["--rules", "bogus", "--root", str(tmp_path)],
             cwd=str(tmp_path))
    assert p.returncode == 2
    assert "unknown rule" in p.stderr


def test_serving_dispatch_entry_registered_and_rename_fails_loudly(tmp_path):
    """The serving engine's decode-dispatch body is in the REAL
    HOT_PATH_ENTRIES (a host sync there would serialize the whole
    serving pipeline), and renaming it in a fixture carrying the entry
    flags stale-hot-entry rather than silently un-linting the path."""
    real = mxlint.HOT_PATH_ENTRIES["mxnet_tpu/serving/engine.py"]
    assert "ServingEngine._dispatch_step" in real

    entries = {"mxnet_tpu/fixture.py": ("ServingEngine._dispatch_step",)}
    findings, _ = lint_src(tmp_path, """
        class ServingEngine:
            def _dispatch_step_renamed(self):
                return None
        """, hot_entries=entries)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "ServingEngine._dispatch_step" in findings[0].message

    # positive: a per-token host readback reachable from the dispatch
    # body (the exact bug the serving refactor removed from translate)
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class ServingEngine:
            def _dispatch_step(self):
                outs = self._run()
                return self._emit(outs)

            def _emit(self, outs):
                return np.asarray(outs[0])   # per-token sync: flagged

            def _run(self):
                return (object(),)
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]
    assert findings[0].context == "ServingEngine._emit"

    # negative: the real body's shape — chain device state, admit the
    # lazy handle, stamp the compile wall — carries no syncs
    findings, _ = lint_src(tmp_path, """
        import time

        class ServingEngine:
            def _dispatch_step(self):
                self._ring.make_room(self._window)
                arrays = [a._data for a in self._state.values()]
                t0 = time.perf_counter()
                outs = self._run(self._params(), *arrays)
                handle = self._wrap(outs[0])
                self._ring.admit(handle)
                return handle

            def _params(self):
                return tuple(p.data() for _, p in self._param_items)

            def _wrap(self, toks):
                return toks
        """, hot_entries=entries)
    assert findings == []


def test_serving_front_door_entries_registered(tmp_path):
    """PR 17's jitted bodies (sampled decode, speculative verify, prefix
    ingest) and the spec dispatch are in the REAL HOT_PATH_ENTRIES, and
    the replica/router HTTP handlers are in the REAL JAX_FREE_ENTRIES."""
    real = mxlint.HOT_PATH_ENTRIES["mxnet_tpu/serving/engine.py"]
    for entry in ("ServingEngine._dispatch_spec",
                  "ServingEngine._decode_body",
                  "ServingEngine._verify_body",
                  "ServingEngine._ingest_body"):
        assert entry in real, entry
    handlers = mxlint.JAX_FREE_ENTRIES["mxnet_tpu/serving/router.py"]
    for entry in ("_ReplicaHandler.do_GET", "_ReplicaHandler.do_POST",
                  "_RouterHandler.do_GET", "_RouterHandler.do_POST"):
        assert entry in handlers, entry


def test_verify_body_sync_flagged_and_clean_shape_passes(tmp_path):
    """A host readback inside the speculative verify trace body (or
    anything it reaches) is flagged; the real body's shape — pure
    NDArray math chained through helpers — is clean."""
    entries = {"mxnet_tpu/fixture.py": ("ServingEngine._verify_body",)}
    findings, _ = lint_src(tmp_path, """
        class ServingEngine:
            def _verify_body(self, nds):
                logits = self._chain(nds)
                return self._accept(logits)

            def _accept(self, logits):
                return logits[0].asnumpy()   # sync inside the trace body

            def _chain(self, nds):
                return nds
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]
    assert findings[0].context == "ServingEngine._accept"

    findings, _ = lint_src(tmp_path, """
        class ServingEngine:
            def _verify_body(self, nds):
                state = dict(zip(self._names, nds))
                logits, extra, pools = self._chain_logits(state)
                counts = self._accept(logits)
                return (counts,) + tuple(state.values())

            def _chain_logits(self, state):
                return state, state, state

            def _accept(self, logits):
                return logits
        """, hot_entries=entries)
    assert findings == []


def test_router_handler_jax_use_flagged(tmp_path):
    """A jax import (or device readback) reachable from the replica
    /generate handler is flagged — handlers must only submit and poll
    host-side stream flags; the engine-driver thread owns the device."""
    jax_free = {"mxnet_tpu/fixture.py": ("_ReplicaHandler.do_POST",)}
    findings, _ = _lint_jaxfree(tmp_path, """
        class _ReplicaHandler:
            def do_POST(self):
                import jax
                jax.block_until_ready(self.server.replica.engine._state)
        """, jax_free=jax_free)
    assert "jax-in-handler" in rules_of(findings)

    findings, _ = _lint_jaxfree(tmp_path, """
        import json
        import time

        class _ReplicaHandler:
            def do_POST(self):
                req = self.server.replica.submit(self._body())
                while not req.stream.finished:
                    time.sleep(0.002)
                self._send(200, json.dumps(list(req.stream)))

            def _body(self):
                return {}

            def _send(self, code, payload):
                pass
        """, jax_free=jax_free)
    assert findings == []


def test_tracez_handler_jax_use_flagged(tmp_path):
    """The router's /tracez handler (docs/OBSERVABILITY.md §Request
    tracing) is reachable from ``_RouterHandler.do_GET`` — it must stay
    a host-side rollup read: a jax touch on that path would block a
    trace scrape on the device."""
    jax_free = {"mxnet_tpu/fixture.py": ("_RouterHandler.do_GET",)}
    findings, _ = _lint_jaxfree(tmp_path, """
        class _RouterHandler:
            def do_GET(self):
                return self._send(200, self.server.router.tracez())
        """, jax_free=jax_free)
    assert findings == []

    findings, _ = _lint_jaxfree(tmp_path, """
        class _RouterHandler:
            def do_GET(self):
                return self._send(200, self.server.router.tracez())

            def _send(self, code, payload):
                import jax

                jax.block_until_ready(payload)
        """, jax_free=jax_free)
    assert "jax-in-handler" in rules_of(findings)


def test_syntax_error_is_a_finding_not_a_crash(tmp_path):
    (tmp_path / "mxnet_tpu").mkdir(parents=True)
    (tmp_path / "mxnet_tpu" / "broken.py").write_text("def f(:\n")
    findings, _ = mxlint.run_lint([str(tmp_path / "mxnet_tpu")],
                                  root=str(tmp_path), hot_entries={},
                                  env_registry=set())
    assert rules_of(findings) == ["syntax-error"]


# ---------------------------------------------------------------------------
# jax-in-handler (metrics endpoint jax-free reachability)
# ---------------------------------------------------------------------------
JAXFREE = {"mxnet_tpu/fixture.py": ("Handler.do_GET",)}


def _lint_jaxfree(tmp_path, src, jax_free=None):
    path = tmp_path / "mxnet_tpu" / "fixture.py"
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    findings, stats = mxlint.run_lint(
        [str(path)], root=str(tmp_path), hot_entries={},
        env_registry=frozenset(),
        jax_free_entries=jax_free if jax_free is not None else JAXFREE)
    return findings, stats


def test_jax_in_handler_inline_import_flagged(tmp_path):
    findings, _ = _lint_jaxfree(tmp_path, """
        class Handler:
            def do_GET(self):
                import jax

                return jax.devices()
        """)
    assert "jax-in-handler" in rules_of(findings)


def test_jax_in_handler_module_alias_use_flagged(tmp_path):
    # a module-level `import jax.numpy as jnp` USED in the handler is
    # the same defect as an inline import
    findings, _ = _lint_jaxfree(tmp_path, """
        import jax.numpy as jnp

        class Handler:
            def do_GET(self):
                return self._render()

            def _render(self):
                return jnp.zeros(3)
        """)
    assert "jax-in-handler" in rules_of(findings)
    assert any(f.context == "Handler._render" for f in findings)


def test_jax_in_handler_hot_sync_also_checked(tmp_path):
    # handler entries ride the hot-sync readback checks too: a scrape
    # must never block on a device value
    findings, _ = _lint_jaxfree(tmp_path, """
        class Handler:
            def do_GET(self):
                return self.loss.item()
        """)
    assert rules_of(findings) == ["hot-sync"]


def test_jax_free_handler_clean(tmp_path):
    findings, _ = _lint_jaxfree(tmp_path, """
        import json

        class Handler:
            def do_GET(self):
                return json.dumps(self._snapshot())

            def _snapshot(self):
                return {"ok": True}
        """)
    assert findings == []


def test_stale_jax_free_entry_is_a_finding(tmp_path):
    # renaming the handler must not silently un-lint the endpoint
    findings, _ = _lint_jaxfree(tmp_path, """
        class Handler:
            def do_GET_renamed(self):
                return 1
        """)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "Handler.do_GET" in findings[0].message


def test_metrics_server_entries_registered():
    """The REAL metrics_server handler is under the jax-free rule (and
    resolves — the full-tree gate below would flag stale-hot-entry if a
    refactor moved it without updating JAX_FREE_ENTRIES)."""
    real = mxlint.JAX_FREE_ENTRIES["mxnet_tpu/metrics_server.py"]
    assert "_Handler.do_GET" in real


# ---------------------------------------------------------------------------
# the tier-1 gate: the real tree is lint-clean, fast, at head
# ---------------------------------------------------------------------------
def test_plan_dispatch_entry_registered_and_rename_fails_loudly(tmp_path):
    """The unified Plan dispatch body is in the REAL HOT_PATH_ENTRIES
    (every strategy's every step funnels through it — a host sync there
    stalls dp, tp, pp, ring and ulysses at once), and renaming it in a
    fixture carrying the entry flags stale-hot-entry rather than
    silently un-linting the path."""
    real = mxlint.HOT_PATH_ENTRIES["mxnet_tpu/parallel/data_parallel.py"]
    assert "DataParallelStep._plan_dispatch" in real

    entries = {"mxnet_tpu/fixture.py": ("DataParallelStep._plan_dispatch",)}
    findings, _ = lint_src(tmp_path, """
        class DataParallelStep:
            def _plan_dispatch_renamed(self):
                return None
        """, hot_entries=entries)
    assert rules_of(findings) == ["stale-hot-entry"]
    assert "DataParallelStep._plan_dispatch" in findings[0].message

    # positive: a readback reachable from the dispatch body through a
    # helper (e.g. forcing the loss before returning) is flagged
    findings, _ = lint_src(tmp_path, """
        import numpy as np

        class DataParallelStep:
            def _plan_dispatch(self, fn, call_args):
                out = fn(*call_args)
                return self._force(out)

            def _force(self, out):
                return np.asarray(out)   # host sync in the hot funnel
        """, hot_entries=entries)
    assert rules_of(findings) == ["hot-sync"]
    assert findings[0].context == "DataParallelStep._force"

    # negative: the real body's shape — fault hooks, scopes, AOT swap,
    # dispatch — carries no syncs
    findings, _ = lint_src(tmp_path, """
        class DataParallelStep:
            def _plan_dispatch(self, fn, call_args, step_nos,
                               resolve_aot):
                for s in step_nos:
                    self._on_dispatch(s)
                run = fn
                if resolve_aot is not None:
                    aot = resolve_aot(call_args)
                    if aot is not None:
                        run = aot
                return run(*call_args)

            def _on_dispatch(self, s):
                return s
        """, hot_entries=entries)
    assert findings == []


def test_full_tree_is_clean_and_fast():
    t0 = time.perf_counter()
    findings, stats = mxlint.run_lint()   # mxnet_tpu tools examples
    entries = mxlint.load_baseline(mxlint.DEFAULT_BASELINE)
    new, baselined, stale = mxlint.apply_baseline(findings, entries, _REPO)
    elapsed = time.perf_counter() - t0
    assert new == [], "\n".join(f.render() for f in new)
    assert stale == [], (
        f"stale baseline entries (finding fixed? remove them): {stale}")
    # the 870s tier-1 budget is tight; the full pass must stay cheap on
    # this 2-vCPU box.  Budget sized for the box's documented 2-3x drift
    # (the SAME scan measured 4.5s-8.5s across three consecutive runs
    # while PR 8 landed) on a 157-file tree — the gate exists to catch an
    # mxlint pass going algorithmically slow, not to flake on a noisy
    # neighbor
    assert elapsed < 12.0, f"mxlint full tree took {elapsed:.1f}s"
    assert stats["files"] > 100, "scanner lost most of the tree"


def test_baseline_is_small_and_justified():
    entries = mxlint.load_baseline(mxlint.DEFAULT_BASELINE)
    assert len(entries) <= 15, "baseline is for ACCEPTED legacy findings"
    for e in entries:
        j = e.get("justification", "")
        assert j and not j.startswith("UNREVIEWED"), (
            f"baseline entry needs a reviewed one-line justification: {e}")


def test_every_rule_is_documented():
    doc = open(os.path.join(_REPO, "docs", "STATIC_ANALYSIS.md")).read()
    for rule in mxlint.RULES:
        assert rule in doc, f"rule {rule} missing from docs/STATIC_ANALYSIS.md"


# ---------------------------------------------------------------------------
# pass-outside-pipeline (PR 20: the pass-pipeline dispatch contract)
# ---------------------------------------------------------------------------
_PASS_FIXTURE_ENTRIES = {
    "mxnet_tpu/fixture.py": {
        "function": "_invoke_impl",
        "hook_module": "_pass_hooks",
        "allowed": (("_pass_hooks", "_OP_HOOKS"),),
    },
}


def test_pass_outside_pipeline_flags_smuggled_global(tmp_path):
    """The pre-PR-20 pattern — dispatch reading a precision module global
    directly instead of the pass-hook tuple — fires: a rewrite the
    pipeline fingerprint cannot see must not land silently."""
    findings, _ = lint_src(tmp_path, """
        from .passes import hooks as _pass_hooks
        from .precision import runtime as _precision

        def _invoke_impl(op, inputs):
            op_hooks = _pass_hooks._OP_HOOKS
            if _precision._AMP_POLICY is not None:
                inputs = [x.astype("bfloat16") for x in inputs]
            return op.fn(*inputs)
    """, rules=["pass-outside-pipeline"],
        pass_entries=_PASS_FIXTURE_ENTRIES)
    assert rules_of(findings) == ["pass-outside-pipeline"]
    assert "_precision._AMP_POLICY" in findings[0].message
    assert "GraphPass" in findings[0].message


def test_pass_outside_pipeline_clean_dispatch(tmp_path):
    """The sanctioned shape — ONE _OP_HOOKS read, locals/op attrs free —
    is clean; `x._data`-style loads on locals are not module globals."""
    findings, _ = lint_src(tmp_path, """
        from .passes import hooks as _pass_hooks

        def _invoke_impl(op, inputs):
            op_hooks = _pass_hooks._OP_HOOKS
            if op_hooks and inputs:
                for h in op_hooks:
                    inputs = h.rewrite_inputs(op.name, inputs)
            arrays = [x._data for x in inputs]
            return op.fn(*arrays)
    """, rules=["pass-outside-pipeline"],
        pass_entries=_PASS_FIXTURE_ENTRIES)
    assert findings == []


def test_pass_rule_stale_entry_fails_loudly(tmp_path):
    """A renamed dispatch body must not silently turn the rule into a
    no-op (the stale-hot-entry contract, applied here)."""
    findings, _ = lint_src(tmp_path, """
        from .passes import hooks as _pass_hooks

        def renamed_dispatch(op, inputs):
            return _pass_hooks._OP_HOOKS
    """, rules=["pass-outside-pipeline"],
        pass_entries=_PASS_FIXTURE_ENTRIES)
    assert rules_of(findings) == ["pass-outside-pipeline"]
    assert "does not resolve" in findings[0].message


def test_pass_rule_disconnected_hook_fails_loudly(tmp_path):
    """Deleting the _OP_HOOKS consultation disconnects the whole pass
    pipeline from dispatch — itself a finding."""
    findings, _ = lint_src(tmp_path, """
        from .passes import hooks as _pass_hooks

        def _invoke_impl(op, inputs):
            return op.fn(*inputs)
    """, rules=["pass-outside-pipeline"],
        pass_entries=_PASS_FIXTURE_ENTRIES)
    assert rules_of(findings) == ["pass-outside-pipeline"]
    assert "no longer consults" in findings[0].message


def test_pass_dispatch_entry_registered():
    """The real repo's consultation point is pinned, and the live tree
    is clean under the rule (the 0-findings gate covers it)."""
    cfg = mxlint.PASS_DISPATCH_ENTRIES["mxnet_tpu/ops/registry.py"]
    assert cfg["function"] == "_invoke_impl"
    assert cfg["hook_module"] == "_pass_hooks"
    assert ("_pass_hooks", "_OP_HOOKS") in cfg["allowed"]
