"""Op-tail additions (r3): batch_take, khatri_rao, linalg extras,
cast_storage, mrcnn_mask_target, env-var map.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_batch_take():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array(np.array([0, 2, 1, 0], np.float32))
    out = nd.batch_take(a, idx).asnumpy()
    np.testing.assert_array_equal(out, [0, 5, 7, 9])


def test_khatri_rao():
    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)
    out = nd.khatri_rao(nd.array(a), nd.array(b)).asnumpy()
    expect = np.stack([np.kron(a[:, 0], b[:, 0]),
                       np.kron(a[:, 1], b[:, 1])], axis=1)
    np.testing.assert_allclose(out, expect)


def test_linalg_extras():
    rng = np.random.RandomState(0)
    a = rng.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    inv = nd.linalg_inverse(nd.array(a)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-4, atol=1e-5)
    det = float(nd.linalg_det(nd.array(a)).asnumpy())
    np.testing.assert_allclose(det, np.linalg.det(a), rtol=1e-4)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    np.testing.assert_allclose(float(sign.asnumpy())
                               * np.exp(float(logdet.asnumpy())),
                               np.linalg.det(a), rtol=1e-4)
    tri = np.tril(a)
    sld = float(nd.linalg_sumlogdiag(nd.array(tri)).asnumpy())
    np.testing.assert_allclose(sld, np.log(np.diag(tri)).sum(), rtol=1e-5)
    d = nd.linalg_extractdiag(nd.array(a)).asnumpy()
    np.testing.assert_allclose(d, np.diag(a))
    # LQ: A = L @ Q, Q Q^T = I; reference convention returns (Q, L)
    q, l_ = nd.linalg_gelqf(nd.array(a))
    np.testing.assert_allclose(l_.asnumpy() @ q.asnumpy(), a, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(3),
                               atol=1e-5)


def test_linalg_makediag_offsets():
    # regression (review): nonzero offsets must give the square np.diag
    # result, not a wrapped (n, n+|k|) matrix
    v = np.array([1.0, 2.0, 3.0], np.float32)
    for k in (-2, -1, 0, 1, 2):
        out = nd.linalg_makediag(nd.array(v), offset=k).asnumpy()
        np.testing.assert_array_equal(out, np.diag(v, k), err_msg=f"k={k}")


def test_print_summary_multi_input(capsys):
    # regression (review): auxiliary INPUTS (rois etc.) are not parameters
    from mxnet_tpu import sym

    data = sym.Variable("data")
    rois = sym.Variable("rois")
    feat = sym.Convolution(data, name="cmi", kernel=(1, 1), num_filter=2)
    pooled = sym.contrib.ROIAlign(feat, rois, pooled_size=(2, 2),
                                  spatial_scale=1.0)
    mx.viz.print_summary(pooled, shape={"data": (1, 3, 8, 8),
                                        "rois": (4, 5)})
    out = capsys.readouterr().out
    # conv: 2*3*1*1 + 2 = 8; rois' 20 elements must NOT be counted
    assert "Total params: 8" in out, out


def test_cast_storage_roundtrip():
    a = np.zeros((5, 3), np.float32)
    a[1] = [1, 2, 3]
    a[4] = [4, 5, 6]
    rs = nd.cast_storage(nd.array(a), stype="row_sparse")
    assert rs.stype == "row_sparse"
    np.testing.assert_array_equal(sorted(rs.indices.asnumpy().tolist()),
                                  [1, 4])
    back = nd.cast_storage(rs, stype="default")
    np.testing.assert_array_equal(back.asnumpy(), a)
    csr = nd.cast_storage(nd.array(a), stype="csr")
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), a)


def test_cast_storage_same_stype_copies():
    a = np.zeros((5, 3), np.float32)
    a[1] = [1, 2, 3]
    rs = nd.array(a).tostype("row_sparse")
    rs2 = nd.cast_storage(rs, stype="row_sparse")
    assert rs2 is not rs
    assert rs2.stype == "row_sparse"
    assert rs2.shape == (5, 3)
    np.testing.assert_array_equal(rs2.asnumpy(), a)


def test_cast_storage_out_sparse():
    a = np.zeros((4, 2), np.float32)
    a[2] = [7, 8]
    dst = nd.zeros((4, 2)).tostype("row_sparse")
    out = nd.cast_storage(nd.array(a), stype="row_sparse", out=dst)
    assert out is dst
    np.testing.assert_array_equal(out.asnumpy(), a)
    np.testing.assert_array_equal(out.indices.asnumpy(), [2])
    with pytest.raises(mx.base.MXNetError):
        nd.cast_storage(nd.array(a), stype="csr", out=dst)


def test_mrcnn_mask_target_shapes_and_crop():
    B, N, M, H, W = 1, 2, 2, 16, 16
    rois = np.array([[[0, 0, 8, 8], [8, 8, 16, 16]]], np.float32)
    gt = np.zeros((B, M, H, W), np.float32)
    gt[0, 0, :8, :8] = 1.0     # mask 0 fills the first roi exactly
    gt[0, 1, 12:, 12:] = 1.0   # mask 1 fills a corner of the second
    matches = np.array([[0, 1]], np.float32)
    cls = np.array([[1, 2]], np.float32)
    targets, weights = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(gt), nd.array(matches), nd.array(cls),
        num_rois=N, num_classes=3, mask_size=(8, 8))
    t = targets.asnumpy()
    wgt = weights.asnumpy()
    assert t.shape == (B, N, 3, 8, 8) and wgt.shape == t.shape
    # roi 0 / class 1: mask fully covers -> interior ~1
    assert t[0, 0, 1, 2:6, 2:6].min() > 0.9
    # weights one-hot the matched class
    assert wgt[0, 0, 1].min() == 1.0 and wgt[0, 0, 2].max() == 0.0
    assert wgt[0, 1, 2].min() == 1.0 and wgt[0, 1, 1].max() == 0.0


def test_env_vars_map():
    from mxnet_tpu import env_vars

    table = env_vars.describe()
    assert "MXNET_ENGINE_TYPE" in table
    assert "MXNET_SAFE_ACCUMULATION" in table
    # every entry has a known disposition
    for name, (disp, detail) in env_vars.ENV_VARS.items():
        assert disp in ("honored", "absorbed", "n/a"), name
        assert detail
    env_vars._warned = False
    env_vars.check({"MXNET_GPU_MEM_POOL_TYPE": "Round",
                    "MXNET_MYSTERY_FLAG": "1"})
