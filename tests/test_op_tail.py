"""Op-tail additions (r3): batch_take, khatri_rao, linalg extras,
cast_storage, mrcnn_mask_target, env-var map.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_batch_take():
    a = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    idx = nd.array(np.array([0, 2, 1, 0], np.float32))
    out = nd.batch_take(a, idx).asnumpy()
    np.testing.assert_array_equal(out, [0, 5, 7, 9])


def test_khatri_rao():
    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[5.0, 6.0], [7.0, 8.0]], np.float32)
    out = nd.khatri_rao(nd.array(a), nd.array(b)).asnumpy()
    expect = np.stack([np.kron(a[:, 0], b[:, 0]),
                       np.kron(a[:, 1], b[:, 1])], axis=1)
    np.testing.assert_allclose(out, expect)


def test_linalg_extras():
    rng = np.random.RandomState(0)
    a = rng.rand(3, 3).astype(np.float32) + 3 * np.eye(3, dtype=np.float32)
    inv = nd.linalg_inverse(nd.array(a)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(a), rtol=1e-4, atol=1e-5)
    det = float(nd.linalg_det(nd.array(a)).asnumpy())
    np.testing.assert_allclose(det, np.linalg.det(a), rtol=1e-4)
    sign, logdet = nd.linalg_slogdet(nd.array(a))
    np.testing.assert_allclose(float(sign.asnumpy())
                               * np.exp(float(logdet.asnumpy())),
                               np.linalg.det(a), rtol=1e-4)
    tri = np.tril(a)
    sld = float(nd.linalg_sumlogdiag(nd.array(tri)).asnumpy())
    np.testing.assert_allclose(sld, np.log(np.diag(tri)).sum(), rtol=1e-5)
    d = nd.linalg_extractdiag(nd.array(a)).asnumpy()
    np.testing.assert_allclose(d, np.diag(a))
    # LQ: A = L @ Q, Q Q^T = I; reference convention returns (Q, L)
    q, l_ = nd.linalg_gelqf(nd.array(a))
    np.testing.assert_allclose(l_.asnumpy() @ q.asnumpy(), a, rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(3),
                               atol=1e-5)


def test_linalg_makediag_offsets():
    # regression (review): nonzero offsets must give the square np.diag
    # result, not a wrapped (n, n+|k|) matrix
    v = np.array([1.0, 2.0, 3.0], np.float32)
    for k in (-2, -1, 0, 1, 2):
        out = nd.linalg_makediag(nd.array(v), offset=k).asnumpy()
        np.testing.assert_array_equal(out, np.diag(v, k), err_msg=f"k={k}")


def test_print_summary_multi_input(capsys):
    # regression (review): auxiliary INPUTS (rois etc.) are not parameters
    from mxnet_tpu import sym

    data = sym.Variable("data")
    rois = sym.Variable("rois")
    feat = sym.Convolution(data, name="cmi", kernel=(1, 1), num_filter=2)
    pooled = sym.contrib.ROIAlign(feat, rois, pooled_size=(2, 2),
                                  spatial_scale=1.0)
    mx.viz.print_summary(pooled, shape={"data": (1, 3, 8, 8),
                                        "rois": (4, 5)})
    out = capsys.readouterr().out
    # conv: 2*3*1*1 + 2 = 8; rois' 20 elements must NOT be counted
    assert "Total params: 8" in out, out


def test_cast_storage_roundtrip():
    a = np.zeros((5, 3), np.float32)
    a[1] = [1, 2, 3]
    a[4] = [4, 5, 6]
    rs = nd.cast_storage(nd.array(a), stype="row_sparse")
    assert rs.stype == "row_sparse"
    np.testing.assert_array_equal(sorted(rs.indices.asnumpy().tolist()),
                                  [1, 4])
    back = nd.cast_storage(rs, stype="default")
    np.testing.assert_array_equal(back.asnumpy(), a)
    csr = nd.cast_storage(nd.array(a), stype="csr")
    assert csr.stype == "csr"
    np.testing.assert_array_equal(csr.asnumpy(), a)


def test_cast_storage_same_stype_copies():
    a = np.zeros((5, 3), np.float32)
    a[1] = [1, 2, 3]
    rs = nd.array(a).tostype("row_sparse")
    rs2 = nd.cast_storage(rs, stype="row_sparse")
    assert rs2 is not rs
    assert rs2.stype == "row_sparse"
    assert rs2.shape == (5, 3)
    np.testing.assert_array_equal(rs2.asnumpy(), a)


def test_cast_storage_out_sparse():
    a = np.zeros((4, 2), np.float32)
    a[2] = [7, 8]
    dst = nd.zeros((4, 2)).tostype("row_sparse")
    out = nd.cast_storage(nd.array(a), stype="row_sparse", out=dst)
    assert out is dst
    np.testing.assert_array_equal(out.asnumpy(), a)
    np.testing.assert_array_equal(out.indices.asnumpy(), [2])
    with pytest.raises(mx.base.MXNetError):
        nd.cast_storage(nd.array(a), stype="csr", out=dst)


def test_mrcnn_mask_target_shapes_and_crop():
    B, N, M, H, W = 1, 2, 2, 16, 16
    rois = np.array([[[0, 0, 8, 8], [8, 8, 16, 16]]], np.float32)
    gt = np.zeros((B, M, H, W), np.float32)
    gt[0, 0, :8, :8] = 1.0     # mask 0 fills the first roi exactly
    gt[0, 1, 12:, 12:] = 1.0   # mask 1 fills a corner of the second
    matches = np.array([[0, 1]], np.float32)
    cls = np.array([[1, 2]], np.float32)
    targets, weights = nd.contrib.mrcnn_mask_target(
        nd.array(rois), nd.array(gt), nd.array(matches), nd.array(cls),
        num_rois=N, num_classes=3, mask_size=(8, 8))
    t = targets.asnumpy()
    wgt = weights.asnumpy()
    assert t.shape == (B, N, 3, 8, 8) and wgt.shape == t.shape
    # roi 0 / class 1: mask fully covers -> interior ~1
    assert t[0, 0, 1, 2:6, 2:6].min() > 0.9
    # weights one-hot the matched class
    assert wgt[0, 0, 1].min() == 1.0 and wgt[0, 0, 2].max() == 0.0
    assert wgt[0, 1, 2].min() == 1.0 and wgt[0, 1, 1].max() == 0.0


def test_env_vars_map():
    from mxnet_tpu import env_vars

    table = env_vars.describe()
    assert "MXNET_ENGINE_TYPE" in table
    assert "MXNET_SAFE_ACCUMULATION" in table
    # every entry has a known disposition
    for name, (disp, detail) in env_vars.ENV_VARS.items():
        assert disp in ("honored", "absorbed", "n/a"), name
        assert detail
    env_vars._warned = False
    env_vars.check({"MXNET_GPU_MEM_POOL_TYPE": "Round",
                    "MXNET_MYSTERY_FLAG": "1"})


def test_split_v2_and_reshape_like():
    x = nd.array(np.arange(12, dtype=np.float32).reshape(6, 2))
    parts = nd.split_v2(x, 3, axis=0)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    parts = nd.split_v2(x, (1, 4), axis=0)
    assert [p.shape[0] for p in parts] == [1, 3, 2]
    y = nd.zeros((3, 4))
    out = nd.reshape_like(x, y)
    assert out.shape == (3, 4)


def test_cumsum_logsumexp():
    x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    np.testing.assert_allclose(nd.cumsum(nd.array(x), axis=1).asnumpy(),
                               np.cumsum(x, axis=1), rtol=1e-6)
    from scipy.special import logsumexp as ref_lse
    np.testing.assert_allclose(
        nd.logsumexp(nd.array(x), axis=1).asnumpy(),
        ref_lse(x, axis=1), rtol=1e-5)


def test_legacy_index_ops():
    lhs = nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    rhs = nd.array(np.array([0, 2, 1, 0], np.float32))
    out = nd.choose_element_0index(lhs, rhs).asnumpy()
    np.testing.assert_array_equal(out, [0, 5, 7, 9])
    mhs = nd.array(np.array([-1, -2, -3, -4], np.float32))
    filled = nd.fill_element_0index(lhs, mhs, rhs).asnumpy()
    assert filled[0, 0] == -1 and filled[1, 2] == -2
    # public ufunc wrappers dispatch array/array, array/scalar,
    # scalar/array, scalar/scalar (reference _ufunc_helper)
    a = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    b = nd.array(np.array([3.0, 2.0, 1.0], np.float32))
    np.testing.assert_allclose(nd.power(a, b).asnumpy(), [1, 4, 3])
    np.testing.assert_allclose(nd.power(a, 2).asnumpy(), [1, 4, 9])
    np.testing.assert_allclose(nd.power(2, a).asnumpy(), [2, 4, 8])
    assert nd.add(1, 1) == 2.0
    np.testing.assert_allclose(nd.equal(a, b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose(nd.greater_equal(a, 2).asnumpy(), [0, 1, 1])
    np.testing.assert_allclose(nd.lesser_equal(a, b).asnumpy(), [1, 1, 0])
    np.testing.assert_allclose(nd.hypot(a, b).asnumpy(),
                               np.hypot([1, 2, 3], [3, 2, 1]), rtol=1e-6)
    np.testing.assert_allclose(nd.mod(b, 2).asnumpy(), [1, 0, 1])
    np.testing.assert_allclose(nd.logical_xor(a - 1, b).asnumpy(), [1, 0, 0])
    np.testing.assert_allclose(nd.true_divide(a, b).asnumpy(),
                               [1 / 3, 1.0, 3.0], rtol=1e-6)
    # scalars are STATIC attrs, not inputs cast to the array dtype:
    # float-vs-int comparisons stay exact, fractional exponents promote
    ia = nd.array(np.array([1, 2], np.int32), dtype="int32")
    np.testing.assert_allclose(nd.equal(ia, 1.5).asnumpy(), [0, 0])
    np.testing.assert_allclose(nd.power(ia, 2.5).asnumpy(),
                               [1.0, 2 ** 2.5], rtol=1e-6)
    assert nd.add(1, 1) == 2 and not isinstance(nd.add(1, 1), float)

    # pick accepts the axis dim removed OR kept as size 1 (reference
    # PickOpShape) — gluon SoftmaxCE feeds (B,1) ImageRecordIter labels
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    flat = nd.pick(x, nd.array(np.array([1, 2], np.float32)), axis=1)
    kept = nd.pick(x, nd.array(np.array([[1], [2]], np.float32)), axis=1)
    np.testing.assert_array_equal(flat.asnumpy(), [1, 5])
    np.testing.assert_array_equal(kept.asnumpy(), [1, 5])
    tgt = nd.zeros((2, 3))
    ret = nd.onehot_encode(nd.array(np.array([1, 0], np.float32)), tgt)
    np.testing.assert_array_equal(ret.asnumpy(), [[0, 1, 0], [1, 0, 0]])
    # legacy in-place semantics: the second positional arg IS the output
    # (reference ndarray_function.cc OnehotEncode; r3 advisor finding)
    assert ret is tgt
    np.testing.assert_array_equal(tgt.asnumpy(), [[0, 1, 0], [1, 0, 0]])


def test_linalg_gemm_trmm_potri():
    rng = np.random.RandomState(0)
    a = rng.rand(3, 3).astype(np.float32)
    b = rng.rand(3, 3).astype(np.float32)
    c = rng.rand(3, 3).astype(np.float32)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         alpha=2.0, beta=0.5).asnumpy()
    np.testing.assert_allclose(out, 2 * a @ b + 0.5 * c, rtol=1e-5)
    tri = np.tril(a)
    out = nd.linalg_trmm(nd.array(a), nd.array(b)).asnumpy()
    np.testing.assert_allclose(out, tri @ b, rtol=1e-5)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = np.linalg.cholesky(spd)
    inv = nd.linalg_potri(nd.array(L)).asnumpy()
    np.testing.assert_allclose(inv, np.linalg.inv(spd), rtol=1e-3, atol=1e-4)


def test_multi_sgd_and_preloaded():
    rng = np.random.RandomState(0)
    ws = [rng.rand(4).astype(np.float32) for _ in range(2)]
    gs = [rng.rand(4).astype(np.float32) for _ in range(2)]
    outs = nd.multi_sgd_update(nd.array(ws[0]), nd.array(gs[0]),
                               nd.array(ws[1]), nd.array(gs[1]),
                               lrs=(0.1, 0.2), wds=(0.0, 0.0),
                               num_weights=2)
    np.testing.assert_allclose(outs[0].asnumpy(), ws[0] - 0.1 * gs[0],
                               rtol=1e-6)
    np.testing.assert_allclose(outs[1].asnumpy(), ws[1] - 0.2 * gs[1],
                               rtol=1e-6)
    lrs = nd.array(np.array([0.1, 0.2], np.float32))
    wds = nd.array(np.zeros(2, np.float32))
    outs2 = nd.preloaded_multi_sgd_update(
        nd.array(ws[0]), nd.array(gs[0]), nd.array(ws[1]), nd.array(gs[1]),
        lrs, wds, num_weights=2)
    np.testing.assert_allclose(outs2[0].asnumpy(), outs[0].asnumpy(),
                               rtol=1e-6)
    # momentum variant keeps state
    m = nd.zeros((4,))
    w2, m2 = nd.multi_sgd_mom_update(nd.array(ws[0]), nd.array(gs[0]), m,
                                     lrs=(0.1,), wds=(0.0,), momentum=0.9,
                                     num_weights=1)
    np.testing.assert_allclose(m2.asnumpy(), -0.1 * gs[0], rtol=1e-6)


def test_reshape_like_negative_indices():
    lhs = nd.zeros((30, 12))
    rhs = nd.zeros((4, 2, 2, 3))
    # lhs dims [1:) replaced by rhs dims [1:3): (30, 2, 2) -> wrong size;
    # use the documented MXNet example: lhs (30,12), rhs (4,2,2,3),
    # lhs_begin=-1 means dim 1: (30,) + rhs[1:] would not fit, so take
    # rhs dims (2,2,3) -> (30, 2, 2, 3)? sizes must match: 12 == 2*2*3
    out = nd.reshape_like(lhs, rhs, lhs_begin=-1, lhs_end=None,
                          rhs_begin=1, rhs_end=None)
    assert out.shape == (30, 2, 2, 3)


def test_linalg_gemm_axis():
    rng = np.random.RandomState(0)
    # batched with matrix axes (0,1), batch axis 2
    a = rng.rand(3, 4, 5).astype(np.float32)
    b = rng.rand(4, 2, 5).astype(np.float32)
    c = rng.rand(3, 2, 5).astype(np.float32)
    out = nd.linalg_gemm(nd.array(a), nd.array(b), nd.array(c),
                         axis=0).asnumpy()
    expect = np.einsum("ikb,kjb->ijb", a, b) + c
    np.testing.assert_allclose(out, expect, rtol=1e-5)


def test_param_struct_describe_and_validate():
    from mxnet_tpu.ops import params

    table = params.describe("Pooling")
    assert "pool_type" in table and "max" in table
    # validate coerces and range-checks
    out = params.validate("Dropout", {"p": "0.3"})
    assert out["p"] == 0.3
    with pytest.raises(mx.base.MXNetError):
        params.validate("Dropout", {"p": 1.5})  # above upper bound
    with pytest.raises(mx.base.MXNetError):
        params.validate("Pooling", {"pool_type": "mean"})  # not in enum
    with pytest.raises(mx.base.MXNetError):
        params.validate("Pooling", {"bogus": 1})  # unknown key
    # every registered op can render its table (signature-derived)
    from mxnet_tpu.ops.registry import list_ops

    for name in list_ops():
        params.describe(name)


def test_param_validation_on_dispatch():
    # bad enum value rejected at first dispatch (jit-cache miss)
    with pytest.raises(mx.base.MXNetError):
        nd.Pooling(nd.zeros((1, 1, 4, 4)), kernel=(2, 2),
                   pool_type="mean")
    from mxnet_tpu import autograd

    with pytest.raises(mx.base.MXNetError):
        with autograd.record(train_mode=True):  # inference skips the op
            nd.Dropout(nd.zeros((4,)), p=2.0)


def test_param_check_string_coercions():
    from mxnet_tpu.ops.params import ParamField

    assert ParamField("b", "bool").check("false") is False
    assert ParamField("b", "bool").check("true") is True
    assert ParamField("t", "tuple").check("(2, 2)") == (2, 2)
    with pytest.raises(mx.base.MXNetError):
        ParamField("b", "bool").check("maybe")
    # describe() prints the name once per line
    from mxnet_tpu.ops import params

    line = [l for l in params.describe("Pooling").splitlines()
            if "pool_type" in l][0]
    assert line.count("pool_type") == 1


def test_param_validation_inside_hybridized_block():
    from mxnet_tpu import gluon

    class Bad(gluon.nn.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Pooling(x, kernel=(2, 2), pool_type="mean")

    net = Bad()
    net.initialize()
    net.hybridize()
    with pytest.raises(mx.base.MXNetError):
        net(nd.zeros((1, 1, 4, 4)))


def test_linalg_namespaces():
    """nd.linalg.* / sym.linalg.* spellings (reference:
    python/mxnet/{ndarray,symbol}/linalg.py) match the flat linalg_* ops."""
    import mxnet_tpu as mx

    rng = np.random.RandomState(0)
    A = nd.array(rng.rand(3, 3).astype(np.float32))
    B = nd.array(rng.rand(3, 3).astype(np.float32))
    np.testing.assert_allclose(nd.linalg.gemm2(A, B).asnumpy(),
                               A.asnumpy() @ B.asnumpy(), rtol=1e-5)
    spd = nd.array((np.eye(3) * 4).astype(np.float32))
    np.testing.assert_allclose(nd.linalg.potrf(spd).asnumpy(),
                               np.eye(3, dtype=np.float32) * 2, atol=1e-6)
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.linalg.gemm2(a, b).bind(
        mx.cpu(), {"a": A, "b": B}).forward()[0]
    np.testing.assert_allclose(out.asnumpy(), A.asnumpy() @ B.asnumpy(),
                               rtol=1e-5)
