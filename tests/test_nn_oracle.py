"""Core NN ops vs the torch oracle (CPU build baked into the image).

The r5 Deconvolution finding (missing kernel flip — numerically wrong
for years of rounds, invisible to loss-decrease tests AND to the
cpu-vs-tpu consistency suite, which compares the same formula against
itself) motivates pinning every convention-sensitive op to an external
implementation: conv (grouping/dilation/stride/padding conventions),
pooling (ceil_mode, count_include_pad), the norm family, and the exact
activation formulas."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

from mxnet_tpu import nd  # noqa: E402

RS = np.random.RandomState


@pytest.mark.parametrize(
    "cin,cout,groups,kernel,stride,pad,dilate",
    [
        (3, 8, 1, (3, 3), (1, 1), (1, 1), (1, 1)),
        (4, 8, 2, (3, 3), (2, 2), (1, 1), (1, 1)),
        (4, 4, 4, (3, 3), (1, 1), (1, 1), (1, 1)),   # depthwise
        (3, 6, 1, (2, 3), (2, 1), (0, 2), (1, 1)),   # asym everything
        (3, 6, 1, (3, 3), (1, 1), (2, 2), (2, 2)),   # dilated
    ])
def test_convolution_matches_torch(cin, cout, groups, kernel, stride, pad,
                                   dilate):
    rng = RS(0)
    x = rng.randn(2, cin, 9, 9).astype(np.float32)
    w = rng.randn(cout, cin // groups, *kernel).astype(np.float32)
    b = rng.randn(cout).astype(np.float32)
    ref = TF.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                    stride=stride, padding=pad, dilation=dilate,
                    groups=groups).numpy()
    got = nd.Convolution(nd.array(x), nd.array(w), nd.array(b),
                         kernel=kernel, stride=stride, pad=pad,
                         dilate=dilate, num_filter=cout,
                         num_group=groups, no_bias=False).asnumpy()
    np.testing.assert_allclose(ref, got, atol=5e-5, rtol=1e-4)


@pytest.mark.parametrize("convention", ["valid", "full"])
@pytest.mark.parametrize("pool", ["max", "avg"])
def test_pooling_matches_torch(pool, convention):
    rng = RS(1)
    x = rng.randn(2, 3, 9, 9).astype(np.float32)
    kw = dict(kernel_size=3, stride=2, padding=1,
              ceil_mode=convention == "full")
    if pool == "max":
        ref = TF.max_pool2d(torch.tensor(x), **kw).numpy()
    else:
        ref = TF.avg_pool2d(torch.tensor(x), count_include_pad=True,
                            **kw).numpy()
    got = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                     pad=(1, 1), pool_type=pool,
                     pooling_convention=convention).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-6, rtol=1e-6)


def test_avg_pool_exclude_pad_matches_torch():
    rng = RS(2)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    ref = TF.avg_pool2d(torch.tensor(x), kernel_size=3, stride=2,
                        padding=1, count_include_pad=False).numpy()
    got = nd.Pooling(nd.array(x), kernel=(3, 3), stride=(2, 2),
                     pad=(1, 1), pool_type="avg",
                     count_include_pad=False).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-6, rtol=1e-6)


def test_batchnorm_inference_matches_torch():
    rng = RS(3)
    x = rng.randn(2, 5, 4, 4).astype(np.float32)
    gamma = rng.rand(5).astype(np.float32) + 0.5
    beta = rng.randn(5).astype(np.float32)
    mean = rng.randn(5).astype(np.float32)
    var = rng.rand(5).astype(np.float32) + 0.5
    ref = TF.batch_norm(torch.tensor(x), torch.tensor(mean),
                        torch.tensor(var), torch.tensor(gamma),
                        torch.tensor(beta), training=False,
                        eps=1e-3).numpy()
    got = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                       nd.array(mean), nd.array(var), eps=1e-3,
                       fix_gamma=False, use_global_stats=True).asnumpy()
    np.testing.assert_allclose(ref, got, atol=1e-5, rtol=1e-5)


def test_layernorm_matches_torch():
    rng = RS(4)
    x = rng.randn(3, 7, 16).astype(np.float32)
    gamma = rng.rand(16).astype(np.float32) + 0.5
    beta = rng.randn(16).astype(np.float32)
    ref = TF.layer_norm(torch.tensor(x), (16,), torch.tensor(gamma),
                        torch.tensor(beta), eps=1e-5).numpy()
    got = nd.LayerNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                       axis=-1, eps=1e-5).asnumpy()
    np.testing.assert_allclose(ref, got, atol=2e-5, rtol=1e-5)


def test_instance_group_norm_match_torch():
    rng = RS(5)
    x = rng.randn(2, 6, 5, 5).astype(np.float32)
    gamma = rng.rand(6).astype(np.float32) + 0.5
    beta = rng.randn(6).astype(np.float32)
    ref = TF.instance_norm(torch.tensor(x), weight=torch.tensor(gamma),
                           bias=torch.tensor(beta), eps=1e-3).numpy()
    got = nd.InstanceNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                          eps=1e-3).asnumpy()
    np.testing.assert_allclose(ref, got, atol=2e-5, rtol=1e-5)

    ref_g = TF.group_norm(torch.tensor(x), 3, torch.tensor(gamma),
                          torch.tensor(beta), eps=1e-3).numpy()
    got_g = nd.GroupNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                         num_groups=3, eps=1e-3).asnumpy()
    np.testing.assert_allclose(ref_g, got_g, atol=2e-5, rtol=1e-5)


def test_activation_formulas_match_torch():
    rng = RS(6)
    x = rng.randn(4, 33).astype(np.float32) * 3
    tx = torch.tensor(x)
    cases = [
        (nd.LeakyReLU(nd.array(x), act_type="gelu"),
         TF.gelu(tx)),                                   # exact erf form
        (nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0),
         TF.elu(tx, alpha=1.0)),
        (nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1),
         TF.leaky_relu(tx, 0.1)),
        (nd.Activation(nd.array(x), act_type="softrelu"),
         TF.softplus(tx)),
        (nd.Activation(nd.array(x), act_type="softsign"),
         TF.softsign(tx)),
        (nd.log_softmax(nd.array(x), axis=-1),
         TF.log_softmax(tx, dim=-1)),
    ]
    for got, ref in cases:
        np.testing.assert_allclose(ref.numpy(), got.asnumpy(),
                                   atol=2e-5, rtol=1e-5)


@pytest.mark.parametrize("bidirectional", [False, True])
def test_lstm_matches_torch(bidirectional):
    """Fused RNN op (mode='lstm') vs torch.nn.LSTM — both use the
    (i, f, g, o) cuDNN gate order, so torch weights pack directly into
    the MXNet flat vector (i2h w, h2h w per layer/dir, then biases)."""
    rng = RS(8)
    T, B, I, H = 5, 3, 4, 6
    dirs = 2 if bidirectional else 1
    x = rng.randn(T, B, I).astype(np.float32)
    ref_rnn = torch.nn.LSTM(I, H, num_layers=1,
                            bidirectional=bidirectional)
    with torch.no_grad():
        ref_out, (ref_h, ref_c) = ref_rnn(torch.tensor(x))
    sd = ref_rnn.state_dict()
    weights, biases = [], []
    for d in range(dirs):
        sfx = "_reverse" if d else ""
        weights += [sd[f"weight_ih_l0{sfx}"].numpy().ravel(),
                    sd[f"weight_hh_l0{sfx}"].numpy().ravel()]
        biases += [sd[f"bias_ih_l0{sfx}"].numpy().ravel(),
                   sd[f"bias_hh_l0{sfx}"].numpy().ravel()]
    flat = np.concatenate(weights + biases).astype(np.float32)
    h0 = np.zeros((dirs, B, H), np.float32)
    c0 = np.zeros((dirs, B, H), np.float32)
    out, hN, cN = nd.RNN(nd.array(x), nd.array(flat), nd.array(h0),
                         nd.array(c0), state_size=H, num_layers=1,
                         mode="lstm", bidirectional=bidirectional,
                         state_outputs=True)
    np.testing.assert_allclose(ref_out.numpy(), out.asnumpy(),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(ref_h.numpy(), hN.asnumpy(),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(ref_c.numpy(), cN.asnumpy(),
                               atol=2e-5, rtol=1e-4)


def test_gru_matches_torch():
    """mode='gru' vs torch.nn.GRU: both (r, z, n) gate order.  NOTE the
    n-gate bias convention matters: cuDNN/MXNet apply r AFTER adding the
    h2h bias (n = tanh(i_n + b_in + r*(h W_hn^T + b_hn))), and torch.GRU
    matches that cuDNN form on CPU too."""
    rng = RS(9)
    T, B, I, H = 5, 3, 4, 6
    x = rng.randn(T, B, I).astype(np.float32)
    ref_rnn = torch.nn.GRU(I, H, num_layers=1)
    with torch.no_grad():
        ref_out, ref_h = ref_rnn(torch.tensor(x))
    sd = ref_rnn.state_dict()
    flat = np.concatenate([
        sd["weight_ih_l0"].numpy().ravel(),
        sd["weight_hh_l0"].numpy().ravel(),
        sd["bias_ih_l0"].numpy().ravel(),
        sd["bias_hh_l0"].numpy().ravel()]).astype(np.float32)
    h0 = np.zeros((1, B, H), np.float32)
    out, hN = nd.RNN(nd.array(x), nd.array(flat), nd.array(h0),
                     state_size=H, num_layers=1, mode="gru",
                     state_outputs=True)
    np.testing.assert_allclose(ref_out.numpy(), out.asnumpy(),
                               atol=2e-5, rtol=1e-4)
    np.testing.assert_allclose(ref_h.numpy(), hN.asnumpy(),
                               atol=2e-5, rtol=1e-4)


def test_selu_matches_torch():
    rng = RS(7)
    x = rng.randn(3, 9).astype(np.float32)
    ref = TF.selu(torch.tensor(x)).numpy()
    got = nd.LeakyReLU(nd.array(x), act_type="selu").asnumpy()
    np.testing.assert_allclose(ref, got, atol=2e-5, rtol=1e-5)
