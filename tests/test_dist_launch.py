"""Multi-process-on-one-host distributed tests (SURVEY §4.4 item 4 —
reference: CI runs tools/launch.py -n 3 --launcher local
tests/nightly/dist_sync_kvstore.py).

These spawn REAL worker processes via tools/launch.py local mode; inside,
gradients cross process boundaries through the compiled Gloo/DCN allreduce
in parallel/dist.py.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(n, script, timeout=240):
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), "--force-cpu", "--",
           sys.executable, os.path.join(_REPO, script)]
    return subprocess.run(cmd, cwd=_REPO, timeout=timeout,
                          capture_output=True, text=True)


def test_dist_sync_kvstore_two_workers():
    res = _launch(2, "tests/dist/dist_sync_kvstore_worker.py")
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist_sync kvstore OK") == 2, res.stdout


def test_dist_sync_training_two_workers():
    res = _launch(2, "tests/dist/dist_train_worker.py")
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist train OK") == 2, res.stdout


def test_dist_sync_kvstore_three_workers():
    """n=3 exercises non-power-of-two reduction and rank indexing that n=2
    cannot (reference CI: tools/launch.py -n 3 -s 3 --launcher local
    tests/nightly/dist_sync_kvstore.py)."""
    res = _launch(3, "tests/dist/dist_sync_kvstore_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist_sync kvstore OK") == 3, res.stdout


def test_dist_sync_training_three_workers():
    res = _launch(3, "tests/dist/dist_train_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist train OK") == 3, res.stdout


def test_launch_detects_nonrank0_crash(tmp_path):
    """A crash in ANY rank must terminate the job promptly — rank 0 may be
    blocked in a collective waiting for the dead peer."""
    worker = tmp_path / "crashy.py"
    worker.write_text(
        "import os, sys, time\n"
        "if os.environ['MX_PROC_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(120)\n")
    import time as _time

    t0 = _time.time()
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", "2", "--force-cpu", "--", sys.executable, str(worker)],
        timeout=60, capture_output=True, text=True)
    assert res.returncode == 3
    assert _time.time() - t0 < 30, "launcher failed to fan out the crash"


def test_launch_cli_rejects_missing_command():
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"), "-n", "2"],
        capture_output=True, text=True)
    assert res.returncode != 0
