"""Multi-process-on-one-host distributed tests (SURVEY §4.4 item 4 —
reference: CI runs tools/launch.py -n 3 --launcher local
tests/nightly/dist_sync_kvstore.py).

These spawn REAL worker processes via tools/launch.py local mode; inside,
gradients cross process boundaries through the compiled Gloo/DCN allreduce
in parallel/dist.py.
"""
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launch(n, script, timeout=240, env=None, launcher_args=()):
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), "--force-cpu", *launcher_args, "--",
           sys.executable, os.path.join(_REPO, script)]
    return subprocess.run(cmd, cwd=_REPO, timeout=timeout,
                          capture_output=True, text=True, env=env)


def test_dist_sync_kvstore_two_workers():
    res = _launch(2, "tests/dist/dist_sync_kvstore_worker.py")
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist_sync kvstore OK") == 2, res.stdout


def test_dist_sync_training_two_workers():
    res = _launch(2, "tests/dist/dist_train_worker.py")
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist train OK") == 2, res.stdout


@pytest.mark.slow
def test_dist_bucketed_allreduce_two_workers():
    """Bucketed-allreduce parity across a real 2-rank gang: a tiny bucket
    cap forces multi-bucket coalescing, pulls must equal the analytic
    global sums, and a fused+bucketed Trainer must keep replicas
    bit-identical (docs/PERFORMANCE.md)."""
    res = _launch(2, "tests/dist/dist_bucketed_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("bucketed allreduce OK") == 2, res.stdout


def test_dist_sync_kvstore_three_workers():
    """n=3 exercises non-power-of-two reduction and rank indexing that n=2
    cannot (reference CI: tools/launch.py -n 3 -s 3 --launcher local
    tests/nightly/dist_sync_kvstore.py)."""
    res = _launch(3, "tests/dist/dist_sync_kvstore_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist_sync kvstore OK") == 3, res.stdout


def test_dist_sync_training_three_workers():
    res = _launch(3, "tests/dist/dist_train_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist train OK") == 3, res.stdout


def test_dist_preemption_checkpoint_resume(tmp_path):
    """Kill a 2-worker sync job mid-run ("preemption"), relaunch fresh
    processes, resume from the step-granular checkpoint (params + trainer
    momentum + RNG), and finish with the SAME final weights as an
    uninterrupted run — preemption must be trajectory-invisible (SURVEY
    §5.3's TPU-native recovery posture; the reference stalls forever)."""
    worker = "tests/dist/dist_resume_worker.py"
    env = dict(os.environ, MX_RESUME_DIR=str(tmp_path))

    # uninterrupted baseline (its own checkpoint dir)
    env["MX_RESUME_PHASE"] = "0"
    res0 = _launch(2, worker, env=dict(env))
    assert res0.returncode == 0, (res0.stdout[-1500:], res0.stderr[-800:])

    env["MX_RESUME_PHASE"] = "1"
    res1 = _launch(2, worker, env=dict(env))
    assert res1.returncode == 43, (res1.stdout[-1500:], res1.stderr[-800:])
    assert res1.stdout.count("preempting at step 30") >= 1, res1.stdout

    env["MX_RESUME_PHASE"] = "2"
    res2 = _launch(2, worker, env=dict(env))
    assert res2.returncode == 0, (res2.stdout[-1500:], res2.stderr[-800:])
    assert res2.stdout.count("resume train OK") == 2, res2.stdout
    assert "matches uninterrupted baseline" in res2.stdout, res2.stdout


def test_launch_detects_nonrank0_crash(tmp_path):
    """A crash in ANY rank must terminate the job promptly — rank 0 may be
    blocked in a collective waiting for the dead peer."""
    worker = tmp_path / "crashy.py"
    worker.write_text(
        "import os, sys, time\n"
        "if os.environ['MX_PROC_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(120)\n")
    import time as _time

    t0 = _time.time()
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
         "-n", "2", "--force-cpu", "--", sys.executable, str(worker)],
        timeout=60, capture_output=True, text=True)
    assert res.returncode == 3
    assert _time.time() - t0 < 30, "launcher failed to fan out the crash"


def test_launch_cli_rejects_missing_command():
    res = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "launch.py"), "-n", "2"],
        capture_output=True, text=True)
    assert res.returncode != 0


# ---------------------------------------------------------------------------
# gang supervision (--max-restarts) — chaos tier.  The unit tests use
# trivial no-jax worker scripts so the supervisor machinery itself gets
# fast default-tier coverage; the full kill-and-recover training run is
# the slow e2e at the bottom.
# ---------------------------------------------------------------------------
def _run_supervised(tmp_path, script_body, n=2, extra_args=(), timeout=90):
    worker = tmp_path / "worker.py"
    worker.write_text(script_body)
    cmd = [sys.executable, os.path.join(_REPO, "tools", "launch.py"),
           "-n", str(n), "--restart-backoff", "0.05", *extra_args,
           "--", sys.executable, str(worker)]
    return subprocess.run(cmd, timeout=timeout, capture_output=True,
                          text=True)


@pytest.mark.chaos
def test_supervisor_restarts_crashed_gang(tmp_path):
    """Incarnation 0 crashes rank 1; the supervisor re-spawns the whole
    gang (fresh MX_RESTART_COUNT) and the retry exits clean."""
    res = _run_supervised(tmp_path, (
        "import os, sys\n"
        "restart = int(os.environ['MX_RESTART_COUNT'])\n"
        "port = os.environ['MX_COORDINATOR']\n"
        "print(f\"rank {os.environ['MX_PROC_ID']} incarnation {restart} "
        "coord {port}\", flush=True)\n"
        "if restart == 0 and os.environ['MX_PROC_ID'] == '1':\n"
        "    sys.exit(7)\n"
    ), extra_args=("--max-restarts", "2"))
    assert res.returncode == 0, (res.stdout, res.stderr)
    assert "restarting gang (1/2)" in res.stderr, res.stderr
    assert res.stdout.count("incarnation 1") == 2, res.stdout
    # the restarted gang rendezvouses on a FRESH coordinator port
    import re

    coords = {m.group(1) for m in re.finditer(r"coord (\S+)", res.stdout)}
    assert len(coords) == 2, coords


@pytest.mark.chaos
def test_supervisor_exhausts_restarts_with_history(tmp_path):
    res = _run_supervised(tmp_path, (
        "import os, sys\n"
        "sys.exit(7 if os.environ['MX_PROC_ID'] == '1' else 0)\n"
    ), extra_args=("--max-restarts", "1"))
    assert res.returncode == 7
    assert "giving up after 2 attempts" in res.stderr, res.stderr
    assert "per-rank exit history" in res.stderr
    assert res.stderr.count("rank1=7") == 2, res.stderr


@pytest.mark.chaos
def test_teardown_escalates_to_sigkill(tmp_path):
    """A rank that ignores SIGTERM (blocked in a native collective) must
    be SIGKILLed within --term-timeout and REAPED — the launcher may not
    hang on it (the seed's KeyboardInterrupt path leaked these)."""
    import time as _time

    t0 = _time.time()
    res = _run_supervised(tmp_path, (
        "import os, signal, sys, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "if os.environ['MX_PROC_ID'] == '0':\n"
        "    sys.exit(5)\n"
        "time.sleep(120)\n"
    ), extra_args=("--term-timeout", "1"), timeout=60)
    assert res.returncode == 5
    assert _time.time() - t0 < 30, "SIGKILL escalation failed to reap"


@pytest.mark.chaos
@pytest.mark.slow
def test_supervised_restart_end_to_end(tmp_path):
    """The acceptance-criteria scenario, hands-off: rank 1 is killed at
    step 30 by MX_FAULT_SPEC on the first incarnation, tools/launch.py
    --max-restarts 1 tears down and re-spawns the gang, the restarted
    ranks agree on the latest mutually-valid checkpoint (step 20) and the
    final weights MATCH the uninterrupted baseline."""
    worker = "tests/dist/dist_resume_worker.py"
    env = dict(os.environ, MX_RESUME_DIR=str(tmp_path))

    env["MX_RESUME_PHASE"] = "0"  # uninterrupted baseline
    res0 = _launch(2, worker, env=dict(env))
    assert res0.returncode == 0, (res0.stdout[-1500:], res0.stderr[-800:])

    env["MX_RESUME_PHASE"] = "3"
    env["MX_FAULT_SPEC"] = "crash:step=30:rank=1:if-restart=0"
    res = _launch(2, worker, env=dict(env), timeout=420,
                  launcher_args=("--max-restarts", "1",
                                 "--term-timeout", "5",
                                 "--restart-backoff", "0.2"))
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert "injected crash at step 30" in res.stdout
    assert "restarting gang (1/1)" in res.stderr
    assert res.stdout.count("incarnation 1 resuming at step 20") == 2, \
        res.stdout
    assert res.stdout.count("resume train OK") == 2, res.stdout
    assert res.stdout.count("matches uninterrupted baseline") == 2, res.stdout


@pytest.mark.chaos
def test_sharded_ckpt_chaos_resume_tp_gang(tmp_path):
    """ISSUE 16 acceptance: a tp=4 mesh spanning both processes makes
    every param cross-process-sharded; scheduled saves and a lockstep
    off-cycle save_now land as rank-local shard files with ZERO
    collectives (per-rank checkpoint_save events carry per-rank bytes);
    the chaos harness kills rank 1 mid-run, the supervisor restarts the
    gang, it agrees on the newest COMPLETE scheduled step (10) and the
    resumed run matches the uninterrupted baseline bitwise."""
    import json

    worker = "tests/dist/shard_ckpt_worker.py"
    tele_dir = str(tmp_path / "tele")
    env = dict(os.environ, MX_SHARD_DIR=str(tmp_path))

    env["MX_SHARD_PHASE"] = "0"  # uninterrupted baseline
    res0 = _launch(2, worker, env=dict(env))
    assert res0.returncode == 0, (res0.stdout[-2000:], res0.stderr[-1000:])
    assert res0.stdout.count("shard baseline OK") == 2, res0.stdout

    env["MX_SHARD_PHASE"] = "1"
    env["MX_FAULT_SPEC"] = "crash:step=12:rank=1:if-restart=0"
    env["MX_TELEMETRY_DIR"] = tele_dir
    res = _launch(2, worker, env=dict(env), timeout=420,
                  launcher_args=("--max-restarts", "1",
                                 "--term-timeout", "5",
                                 "--restart-backoff", "0.2"))
    assert res.returncode == 0, (res.stdout[-2500:], res.stderr[-1500:])
    assert "injected crash at step 12" in res.stdout
    assert "restarting gang (1/1)" in res.stderr
    assert res.stdout.count("incarnation 1 resuming at step 10") == 2, \
        res.stdout
    assert res.stdout.count("sharded resume OK") == 2, res.stdout
    # the zero-collective audit trail: BOTH ranks booked sharded
    # checkpoint_save events with their OWN (local-shard) byte counts
    saves = {}
    for rank_id in (0, 1):
        path = os.path.join(tele_dir, f"rank-{rank_id}.jsonl")
        for line in open(path):
            e = json.loads(line)
            if e.get("kind") == "checkpoint_save" and e.get("sharded"):
                saves.setdefault(e["rank"], []).append(e["nbytes"])
    assert set(saves) == {0, 1}, saves
    assert all(nb > 0 for v in saves.values() for nb in v), saves
    # the shared dir holds per-rank shard files for the resumed steps
    step_dir = os.path.join(str(tmp_path), "ckpt", "step-15")
    names = set(os.listdir(step_dir))
    assert {"params-shard-0.nd", "params-shard-1.nd", "shard-0.json",
            "shard-1.json", "meta.json"} <= names, names


def test_dist_tp_combo_two_workers_parity():
    """2 processes x 2 devices each, global mesh dp2(hosts)xtp2(local) —
    the v5p pod shape in miniature (r4 verdict #6).  The multi-process
    run's loss trajectory must match the SAME config on a single-process
    dp2xtp2 mesh."""
    res = _launch(2, "tests/dist/dist_tp_worker.py", timeout=300)
    assert res.returncode == 0, (res.stdout[-2000:], res.stderr[-2000:])
    assert res.stdout.count("dist tp OK") == 2, res.stdout
    import re

    worker_losses = {
        tuple(float(x) for x in m.group(1).split(","))
        for m in re.finditer(r"dist tp OK losses=([\d.,-]+)", res.stdout)
    }
    assert len(worker_losses) == 1, f"workers diverged: {worker_losses}"

    # single-process reference on this process's virtual devices
    import jax
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.models import bert_small
    from mxnet_tpu.models.bert import bert_sharding_rules
    from mxnet_tpu.parallel import DataParallelStep, make_mesh

    mesh = make_mesh(tp=2, devices=jax.devices("cpu")[:4])
    mx.random.seed(0)
    net = bert_small()
    net.initialize(mx.init.Normal(0.02))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    step = DataParallelStep(net, mlm_loss, mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-3},
                            rules=bert_sharding_rules())
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, 512, (8, 16)).astype(np.int32)
    labels = tokens.astype(np.float32)
    ref = [float(np.asarray(step.step(nd.array(tokens, dtype="int32"),
                                      nd.array(labels))))
           for _ in range(3)]
    np.testing.assert_allclose(list(worker_losses)[0], ref, rtol=1e-4,
                               err_msg="multi-process vs single-process")
