"""Test configuration: run everything on a virtual 8-device CPU mesh.

The driver environment boots python with the axon TPU backend registered
(sitecustomize imports jax before we run).  jax leaves backend *initialization*
lazy, so re-pointing the platform here — before any test touches a device —
reliably gives us an 8-way CPU mesh for sharding tests, per SURVEY §4.4
(xla_force_host_platform_device_count).
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")
# persistent compile cache: repeat suite runs on this VM skip XLA
# compilation for the model-sized programs (the suite is compile-heavy)
_JAX_CACHE = os.environ.get("MXNET_TEST_JAX_CACHE",
                            "/tmp/mxnet_tpu_test_jax_cache")
jax.config.update("jax_compilation_cache_dir", _JAX_CACHE)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
# subprocess children (dist workers, examples-e2e, launcher tests) must
# inherit the persistent cache too — they dominate suite wall time and
# otherwise recompile their BERT/ResNet programs cold on every run
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _JAX_CACHE)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(42)
    yield


# ---------------------------------------------------------------------------
# smoke tier (r3 verdict #7): `pytest -m smoke` gives <2 min signal across
# every subsystem; the full ~750-test suite stays the default.  The tier
# list is central here so it's one place to curate.
# ---------------------------------------------------------------------------
_SMOKE = {
    "test_ndarray.py::test_arithmetic",
    "test_autograd.py::test_chain_rule",
    "test_gluon.py::test_sequential_forward",
    "test_symbol.py::test_infer_shape_conv_batchnorm",
    "test_module.py::test_module_fit_converges",
    "test_op_tail.py::test_batch_take",
    "test_pallas.py::test_flash_attention_forward",
    "test_amp.py::test_amp_bf16_workflow_trains",
    "test_checkpoint_viz.py::test_async_checkpoint_write_rotate",
    "test_io_image.py::test_recordio_roundtrip",
    "test_native_io.py::test_native_iter_shapes_and_labels",
    "test_control_flow.py::test_foreach_cumsum",
    "test_quantization_subgraph.py::test_quantized_fc_matches_f32",
    "test_sparse_namespace.py::test_sparse_dot_csr",
    "test_model_zoo.py::test_model_forward",
    "test_profiler.py::test_dumps_ranks_ops_for_model_step",
    "test_rnn_legacy.py::test_lstm_gru_cell_unroll",
    "test_cv_ops.py::test_box_nms_suppresses_overlaps",
    "test_compat_tail.py::test_legacy_save_load_roundtrip",
    "test_parallel.py::test_make_mesh_axes",
    "test_parallel.py::test_kvstore_semantics",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        # nodeid like "tests/test_x.py::test_y[param]" -> "test_x.py::test_y"
        base = item.nodeid.split("/")[-1].split("[")[0]
        if base in _SMOKE:
            item.add_marker(pytest.mark.smoke)
        name = item.nodeid.split("/")[-1]
        if name.startswith("test_dist_launch.py::"):
            item.add_marker(pytest.mark.dist)
        # slow-tier by rationale: the n=3 dist variants re-cover the n=2
        # path with non-power-of-two ranks (redundant for the default
        # tier, r4 verdict #9); the 3D bert example is a ~1 min
        # subprocess whose parity is already covered by
        # test_bert_pp.py::test_pp_tp_dp_3d_parity in the default tier
        if base in ("test_dist_launch.py::test_dist_sync_kvstore_three_workers",
                    "test_dist_launch.py::test_dist_sync_training_three_workers",
                    "test_examples_e2e.py::test_bert_pretrain_3d_e2e"):
            item.add_marker(pytest.mark.slow)
        # compile-heavy composition tests whose constituent paths keep
        # default-tier coverage (the tier-1 wall-clock budget is tight on
        # this box — cold XLA:CPU compiles run ~20s each): ring-parity
        # re-covers the ring kernel units + sp sharding tests; the
        # telemetry gang e2e re-covers the telemetry units + the no-jax
        # supervisor tests; the 2D pp parity is subsumed by
        # test_pp_tp_dp_3d_parity, which deliberately STAYS default-tier —
        # it is the 3D coverage the e2e exclusion above leans on and it
        # exercises the same GPipe schedule plus tp.
        if base in ("test_parallel.py::test_ring_attention_training_step_parity",
                    "test_bert_pp.py::test_pp_bert_matches_dp_only",
                    "test_telemetry.py::"
                    "test_two_rank_gang_emits_jsonl_and_advancing_heartbeats"):
            item.add_marker(pytest.mark.slow)
        if (name.startswith("test_op_sweep.py::test_gradient")
                or name.startswith("test_op_sweep.py::test_bf16_backward")):
            item.add_marker(pytest.mark.slow)
        # the int4 AOT restart story spawns three subprocesses that each
        # cold-compile a Transformer engine (~33s total); its constituent
        # paths keep default-tier coverage (in-process engine-fingerprint
        # splits + restart-stable digests in test_passes.py, the
        # cross-process AOT hit/miss machinery in the int8 and cold-start
        # tests)
        if base == "test_passes.py::test_int4_aot_cache_roundtrip":
            item.add_marker(pytest.mark.slow)
