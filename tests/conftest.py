"""Test configuration: run everything on a virtual 8-device CPU mesh.

The driver environment boots python with the axon TPU backend registered
(sitecustomize imports jax before we run).  jax leaves backend *initialization*
lazy, so re-pointing the platform here — before any test touches a device —
reliably gives us an 8-way CPU mesh for sharding tests, per SURVEY §4.4
(xla_force_host_platform_device_count).
"""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest


@pytest.fixture(autouse=True)
def _seed():
    import mxnet_tpu as mx

    mx.random.seed(42)
    yield
