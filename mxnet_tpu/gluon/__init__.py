"""Gluon: the imperative high-level API (reference: python/mxnet/gluon/)."""
from .parameter import Parameter, Constant, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from . import utils
from .utils import split_and_load
from .trainer import Trainer
from . import data
from . import rnn
from . import model_zoo
from . import contrib
