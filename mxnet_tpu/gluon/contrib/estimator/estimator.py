"""Gluon Estimator (reference:
python/mxnet/gluon/contrib/estimator/estimator.py ~L1-500): a compact
fit/evaluate driver over net + loss + Trainer with an event-handler bus.
"""
from __future__ import annotations

from ....base import MXNetError
from ....context import current_context
from ....metric import Accuracy, EvalMetric, Loss
from ... import Trainer
from ...loss import Loss as GluonLoss
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, StoppingHandler,
                            TrainBegin, TrainEnd, ValidationHandler)

__all__ = ["Estimator"]


class Estimator:
    """Train/evaluate a Gluon net with pluggable event handlers."""

    def __init__(self, net, loss, metrics=None, initializer=None,
                 trainer=None, context=None):
        self.net = net
        if not isinstance(loss, GluonLoss):
            raise MXNetError("loss must be a gluon Loss instance")
        self.loss = loss
        if metrics is None:
            metrics = [Accuracy()]
        elif isinstance(metrics, EvalMetric):
            metrics = [metrics]
        self.train_metrics = list(metrics)
        self.train_loss_metric = Loss(f"train {type(loss).__name__.lower()}")
        # independent deep copies (preserving name/axis/every config) so
        # val updates don't mix into train state
        import copy

        self.val_metrics = [copy.deepcopy(m) for m in self.train_metrics]
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric = Loss(f"val {type(loss).__name__.lower()}")

        self.context = context or current_context()
        params = self.net.collect_params()
        # no-op on already-initialized parameters (initialize only touches
        # uninitialized params unless force_reinit)
        self.net.initialize(init=initializer, ctx=self.context)
        self.trainer = trainer or Trainer(params, "adam",
                                          {"learning_rate": 1e-3})

    # ------------------------------------------------------------------
    def _batch_arrays(self, batch):
        from .... import ndarray as nd

        if hasattr(batch, "data"):  # DataBatch
            return batch.data[0], batch.label[0]
        data, label = batch[0], batch[1]
        if not hasattr(data, "context"):
            data = nd.array(data, ctx=self.context)
        if not hasattr(label, "context"):
            label = nd.array(label, ctx=self.context)
        return data, label

    def evaluate(self, val_data, batch_axis=0):
        """Run validation, updating val metrics (reference evaluate)."""
        for metric in self.val_metrics:
            metric.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            data, label = self._batch_arrays(batch)
            pred = self.net(data)
            loss = self.loss(pred, label)
            for metric in self.val_metrics:
                metric.update(label, pred)
            self.val_loss_metric.update(0, loss)
        if hasattr(val_data, "reset"):
            val_data.reset()
        return {m.get()[0]: m.get()[1]
                for m in self.val_metrics + [self.val_loss_metric]}

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_axis=0):
        """Train for `epochs` (or `batches`) with event handlers
        (reference fit ~L300)."""
        from .... import autograd

        if epochs is None and batches is None:
            epochs = 1
        handlers = self._prepare_handlers(val_data, event_handlers,
                                          epochs, batches)
        train_begin, epoch_begin, batch_begin, batch_end, epoch_end, \
            train_end = self._categorize(handlers)
        stop_handlers = [h for h in handlers
                         if hasattr(h, "stop_training")]

        for h in train_begin:
            h.train_begin(self)
        stop = any(h.stop_training for h in stop_handlers)
        while not stop:
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in train_data:
                for h in batch_begin:
                    h.batch_begin(self, batch=batch)
                data, label = self._batch_arrays(batch)
                with autograd.record():
                    pred = self.net(data)
                    loss = self.loss(pred, label)
                loss.backward()
                batch_size = data.shape[batch_axis]
                self.trainer.step(batch_size)
                self.train_loss_metric.update(0, loss)
                for h in batch_end:
                    h.batch_end(self, batch=batch, pred=pred, label=label,
                                loss=loss)
                if any(h.stop_training for h in stop_handlers):
                    stop = True
                    break
            if hasattr(train_data, "reset"):
                train_data.reset()
            for h in epoch_end:
                h.epoch_end(self)
            if any(h.stop_training for h in stop_handlers):
                stop = True
        for h in train_end:
            h.train_end(self)

    # ------------------------------------------------------------------
    def _prepare_handlers(self, val_data, event_handlers, epochs, batches):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(
                self.train_metrics + [self.train_loss_metric]))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=self.train_metrics + [self.train_loss_metric]))
        handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return handlers

    def _categorize(self, handlers):
        return ([h for h in handlers if isinstance(h, TrainBegin)],
                [h for h in handlers if isinstance(h, EpochBegin)],
                [h for h in handlers if isinstance(h, BatchBegin)],
                [h for h in handlers if isinstance(h, BatchEnd)],
                [h for h in handlers if isinstance(h, EpochEnd)],
                [h for h in handlers if isinstance(h, TrainEnd)])
