"""Estimator event handlers (reference:
python/mxnet/gluon/contrib/estimator/event_handler.py ~L1-700)."""
from __future__ import annotations

import logging
import os
import time

__all__ = ["TrainBegin", "TrainEnd", "EpochBegin", "EpochEnd", "BatchBegin",
           "BatchEnd", "StoppingHandler", "MetricHandler",
           "ValidationHandler", "LoggingHandler", "CheckpointHandler",
           "EarlyStoppingHandler"]


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop training at max_epoch or max_batch (reference ~L60)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0
        # a zero budget means "don't train" (e.g. resume-and-evaluate)
        self.stop_training = self.max_epoch == 0 or self.max_batch == 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch is not None and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch is not None and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics at epoch start, update per batch (reference ~L100)."""

    def __init__(self, metrics):
        self.metrics = metrics or []
        self.priority = -1000

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs["pred"]
        label = kwargs["label"]
        loss = kwargs["loss"]
        from ....metric import Loss

        for metric in self.metrics:
            if isinstance(metric, Loss):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation on an interval (reference ~L150)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        self.priority = priority

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchEnd):
    """Periodic train logging (reference ~L240)."""

    def __init__(self, log_interval="epoch", metrics=None):
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        self.priority = 1000

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.perf_counter()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.perf_counter() - self.train_start
        self.logger.info("Training finished in %.3fs", t)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.perf_counter()
        self.batch_index = 0
        self.processed_samples = 0

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.perf_counter() - self.epoch_start
        msg = f"Epoch[{self.current_epoch}] finished in {t:.3f}s: "
        for m in self.metrics:
            name, value = m.get()
            msg += f"{name}: {value:.4f} "
        self.logger.info(msg)
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        if isinstance(self.log_interval, int):
            batch = kwargs.get("batch")
            if batch is not None:
                self.processed_samples += batch.data[0].shape[0] \
                    if hasattr(batch, "data") else len(batch[0])
            self.batch_index += 1
            if self.batch_index % self.log_interval == 0:
                msg = f"Epoch[{self.current_epoch}] Batch[{self.batch_index}] "
                for m in self.metrics:
                    name, value = m.get()
                    msg += f"{name}: {value:.4f} "
                self.logger.info(msg)


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save model (and trainer states) periodically; optionally keep only
    the best by a monitored metric (reference ~L380)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        self.model_dir = model_dir
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.verbose = verbose
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.resume_from_checkpoint = resume_from_checkpoint
        self.current_epoch = 0
        self.current_batch = 0
        self.best = None
        self._saved = []  # rolling (non-best) checkpoint prefixes
        self.logger = logging.getLogger("mxnet_tpu.estimator")
        if mode == "min" or (mode == "auto" and monitor is not None
                             and "loss" in monitor.get()[0]):
            self._improved = lambda new, best: new < best
        else:
            self._improved = lambda new, best: new > best

    def train_begin(self, estimator, *args, **kwargs):
        import glob
        import re

        os.makedirs(self.model_dir, exist_ok=True)
        self.current_epoch = 0
        self.current_batch = 0
        self._saved = []
        if self.resume_from_checkpoint:
            # adopt pre-existing rolling checkpoints so pruning and epoch
            # numbering continue instead of restarting (a fresh run in the
            # same dir must NOT adopt: pruning would delete its own saves)
            existing = sorted(
                (c for c in glob.glob(os.path.join(
                    self.model_dir, f"{self.model_prefix}-*.params"))
                 if not c.endswith("-best.params")), key=os.path.getmtime)
            self._saved = [c[:-len(".params")] for c in existing]
            latest = self._latest_checkpoint()
            if latest is not None:
                estimator.net.load_parameters(latest + ".params")
                if (estimator.trainer is not None
                        and os.path.exists(latest + ".states")):
                    estimator.trainer.load_states(latest + ".states")
                # continue epoch numbering from the LOADED checkpoint's
                # tag; if the newest file is a batch-period checkpoint,
                # use the MOST RECENTLY WRITTEN epoch tag (mtime order, not
                # max number: stale higher-epoch files from an older run
                # must not win)
                m = re.search(r"epoch(\d+)$", latest)
                if m:
                    self.current_epoch = int(m.group(1))
                else:
                    stamped = [
                        (os.path.getmtime(c + ".params"), int(em.group(1)))
                        for c in self._saved
                        for em in [re.search(r"epoch(\d+)$", c)] if em]
                    if stamped:
                        self.current_epoch = max(stamped)[1]
                if self.verbose:
                    self.logger.info("resumed from %s", latest)

    def _latest_checkpoint(self):
        import glob

        cands = glob.glob(os.path.join(
            self.model_dir, f"{self.model_prefix}-*.params"))
        cands = [c for c in cands if not c.endswith("-best.params")]
        if not cands:
            return None
        return max(cands, key=os.path.getmtime)[:-len(".params")]

    def _save(self, estimator, tag):
        prefix = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}")
        estimator.net.save_parameters(prefix + ".params")
        if estimator.trainer is not None:
            estimator.trainer.save_states(prefix + ".states")
        if self.verbose:
            self.logger.info("saved checkpoint %s", prefix)
        if tag != "best":
            self._saved.append(prefix)
            while (self.max_checkpoints
                   and len(self._saved) > self.max_checkpoints):
                old = self._saved.pop(0)
                for suffix in (".params", ".states"):
                    try:
                        os.remove(old + suffix)
                    except OSError:
                        pass

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")
        if self.save_best and self.monitor is not None:
            _, value = self.monitor.get()
            if self.best is None or self._improved(value, self.best):
                self.best = value
                self._save(estimator, "best")


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when a monitored metric stops improving (reference ~L550)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.baseline = baseline
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        name = monitor.get()[0] if monitor is not None else ""
        if mode == "min" or (mode == "auto" and "loss" in name):
            self._improved = lambda new, best: new < best - min_delta
        else:
            self._improved = lambda new, best: new > best + min_delta
        self.best = baseline

    def train_begin(self, estimator, *args, **kwargs):
        self.wait = 0
        self.stop_training = False
        self.current_epoch = 0
        self.stopped_epoch = 0
        self.best = self.baseline  # a second fit() starts fresh

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        _, value = self.monitor.get()
        if self.best is None or self._improved(value, self.best):
            self.best = value
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stopping at epoch %d", self.stopped_epoch)
