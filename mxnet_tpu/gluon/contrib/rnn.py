"""Gluon contrib RNN cells (reference: python/mxnet/gluon/contrib/rnn/
conv_rnn_cell.py + rnn_cell.py): convolutional recurrences
(Conv{1,2,3}D{RNN,LSTM,GRU}Cell), VariationalDropoutCell, LSTMPCell.

TPU-native: each step's gate math is Convolution/FullyConnected registered
ops, so an unrolled sequence compiles into one XLA program and the conv
gates land on the MXU like any other conv.
"""
from __future__ import annotations

from ...base import MXNetError
from ..rnn.rnn_cell import HybridRecurrentCell, _ModifierCell as ModifierCell

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell",
           "VariationalDropoutCell", "LSTMPCell"]


def _tuplify(v, n):
    return (v,) * n if isinstance(v, int) else tuple(v)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Convolutional recurrence base (reference conv_rnn_cell.py
    _BaseConvRNNCell ~L40).  input_shape is (C, *spatial), required up
    front: the recurrent state's spatial extent must be known to allocate
    h2h weights and begin_state."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, activation, prefix=None, params=None, conv_dims=2,
                 num_gates=1):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)
        self._hidden_channels = hidden_channels
        self._conv_dims = conv_dims
        self._num_gates = num_gates
        self._activation = activation
        self._i2h_kernel = _tuplify(i2h_kernel, conv_dims)
        self._h2h_kernel = _tuplify(h2h_kernel, conv_dims)
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise MXNetError("h2h_kernel dims must be odd to preserve "
                                 f"the state shape, got {self._h2h_kernel}")
        self._i2h_pad = _tuplify(i2h_pad, conv_dims)
        self._h2h_pad = tuple(k // 2 for k in self._h2h_kernel)

        in_c = self._input_shape[0]
        spatial = self._input_shape[1:]
        self._state_shape = (hidden_channels,) + tuple(
            s + 2 * p - k + 1
            for s, p, k in zip(spatial, self._i2h_pad, self._i2h_kernel))
        G = num_gates
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight",
                shape=(G * hidden_channels, in_c) + self._i2h_kernel)
            self.h2h_weight = self.params.get(
                "h2h_weight",
                shape=(G * hidden_channels, hidden_channels)
                + self._h2h_kernel)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(G * hidden_channels,),
                init="zeros")
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(G * hidden_channels,),
                init="zeros")

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NC" + "DHW"[-self._conv_dims:]}]

    def _conv_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        G = self._num_gates
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=G * self._hidden_channels)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=G * self._hidden_channels)
        return i2h, h2h


class _ConvRNNCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, activation="tanh", prefix=None, params=None,
                 conv_dims=2):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix=prefix,
                         params=params, conv_dims=conv_dims, num_gates=1)

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        output = self._get_activation(F, i2h + h2h, self._activation)
        return output, [output]


class _ConvLSTMCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, activation="tanh", prefix=None, params=None,
                 conv_dims=2):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix=prefix,
                         params=params, conv_dims=conv_dims, num_gates=4)

    def _alias(self):
        return "conv_lstm"

    def state_info(self, batch_size=0):
        info = super().state_info(batch_size)
        return info + [dict(info[0])]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sliced = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.Activation(sliced[0], act_type="sigmoid")
        forget_gate = F.Activation(sliced[1], act_type="sigmoid")
        in_transform = self._get_activation(F, sliced[2], self._activation)
        out_gate = F.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c, self._activation)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad=0, activation="tanh", prefix=None, params=None,
                 conv_dims=2):
        super().__init__(input_shape, hidden_channels, i2h_kernel,
                         h2h_kernel, i2h_pad, activation, prefix=prefix,
                         params=params, conv_dims=conv_dims, num_gates=3)

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_n = F.split(i2h, num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = F.split(h2h, num_outputs=3, axis=1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = self._get_activation(F, i2h_n + reset * h2h_n,
                                          self._activation)
        next_h = (1.0 - update) * next_h_tmp + update * states[0]
        return next_h, [next_h]


def _make_conv_cell(base, dims, doc_kind):
    class Cell(base):
        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, activation="tanh",
                     prefix=None, params=None):
            super().__init__(input_shape, hidden_channels, i2h_kernel,
                             h2h_kernel, i2h_pad=i2h_pad,
                             activation=activation, prefix=prefix,
                             params=params, conv_dims=dims)

    Cell.__doc__ = (f"{dims}D convolutional {doc_kind} cell "
                    f"(reference conv_rnn_cell.py)")
    return Cell


Conv1DRNNCell = _make_conv_cell(_ConvRNNCell, 1, "RNN")
Conv2DRNNCell = _make_conv_cell(_ConvRNNCell, 2, "RNN")
Conv3DRNNCell = _make_conv_cell(_ConvRNNCell, 3, "RNN")
Conv1DLSTMCell = _make_conv_cell(_ConvLSTMCell, 1, "LSTM")
Conv2DLSTMCell = _make_conv_cell(_ConvLSTMCell, 2, "LSTM")
Conv3DLSTMCell = _make_conv_cell(_ConvLSTMCell, 3, "LSTM")
Conv1DGRUCell = _make_conv_cell(_ConvGRUCell, 1, "GRU")
Conv2DGRUCell = _make_conv_cell(_ConvGRUCell, 2, "GRU")
Conv3DGRUCell = _make_conv_cell(_ConvGRUCell, 3, "GRU")
Conv1DRNNCell.__name__ = "Conv1DRNNCell"
Conv2DRNNCell.__name__ = "Conv2DRNNCell"
Conv3DRNNCell.__name__ = "Conv3DRNNCell"
Conv1DLSTMCell.__name__ = "Conv1DLSTMCell"
Conv2DLSTMCell.__name__ = "Conv2DLSTMCell"
Conv3DLSTMCell.__name__ = "Conv3DLSTMCell"
Conv1DGRUCell.__name__ = "Conv1DGRUCell"
Conv2DGRUCell.__name__ = "Conv2DGRUCell"
Conv3DGRUCell.__name__ = "Conv3DGRUCell"


class VariationalDropoutCell(ModifierCell):
    """Applies the SAME dropout mask at every time step to inputs, states
    and outputs (Gal & Ghahramani; reference contrib VariationalDropoutCell
    ~L40).  Masks are sampled once per unroll and cleared by reset()."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_mask = None
        self._output_mask = None

    def _mask(self, F, p, like, cache_name):
        cached = getattr(self, cache_name)
        if cached is None:
            cached = F.Dropout(F.ones_like(like), p=p)
            setattr(self, cache_name, cached)
        return cached

    def __call__(self, inputs, states):
        from ... import ndarray as F
        from ... import autograd

        cell = self.base_cell
        if self.drop_inputs and autograd.is_training():
            inputs = inputs * self._mask(F, self.drop_inputs, inputs,
                                         "_input_mask")
        if self.drop_states and autograd.is_training():
            mask = self._mask(F, self.drop_states, states[0], "_state_mask")
            states = [states[0] * mask] + list(states[1:])
        output, states = cell(inputs, states)
        if self.drop_outputs and autograd.is_training():
            output = output * self._mask(F, self.drop_outputs, output,
                                         "_output_mask")
        return output, states


class LSTMPCell(HybridRecurrentCell):
    """LSTM with a learned projection of the hidden state
    (reference contrib LSTMPCell ~L200: h = W_r (o * tanh(c)))."""

    def __init__(self, hidden_size, projection_size,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 h2r_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._input_size = input_size
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(4 * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(4 * hidden_size, projection_size),
                init=h2h_weight_initializer, allow_deferred_init=True)
            self.h2r_weight = self.params.get(
                "h2r_weight", shape=(projection_size, hidden_size),
                init=h2r_weight_initializer, allow_deferred_init=True)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(4 * hidden_size,),
                init=i2h_bias_initializer, allow_deferred_init=True)
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(4 * hidden_size,),
                init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._projection_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstmp"

    def infer_shape(self, x, *args):
        self.i2h_weight._set_shape_if_deferred(
            (4 * self._hidden_size, int(x.shape[-1])))

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       h2r_weight, i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        sliced = F.split(gates, num_outputs=4, axis=1)
        in_gate = F.Activation(sliced[0], act_type="sigmoid")
        forget_gate = F.Activation(sliced[1], act_type="sigmoid")
        in_transform = F.Activation(sliced[2], act_type="tanh")
        out_gate = F.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        hidden = out_gate * F.Activation(next_c, act_type="tanh")
        next_r = F.FullyConnected(hidden, h2r_weight, no_bias=True,
                                  num_hidden=self._projection_size)
        return next_r, [next_r, next_c]
