"""Contrib layers (reference: gluon/contrib/nn/basic_layers.py).

SyncBatchNorm: in the reference this is cross-GPU BN with a hand-written
NCCL reduce (contrib/nn SyncBatchNorm ~L100).  In the eager per-device path
we fall back to per-device stats (documented divergence); under the fused
pjit step the batch axis is global, so ordinary BatchNorm IS sync-BN —
XLA computes batch statistics over the sharded batch with an ICI all-reduce,
which is the TPU-native realization of SyncBatchNorm.
"""
from ...nn.basic_layers import BatchNorm as _BatchNorm
from ...block import HybridBlock

__all__ = ["SyncBatchNorm", "HybridConcurrent", "Concurrent", "Identity"]


class SyncBatchNorm(_BatchNorm):
    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True, use_global_stats=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", prefix=None, params=None):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=running_variance_initializer,
                         in_channels=in_channels, prefix=prefix, params=params)


from ...nn.basic_layers import HybridSequential as _HS
from ...nn.basic_layers import Sequential as _S


class HybridConcurrent(HybridBlock):
    """Parallel application + concat (reference: contrib/nn HybridConcurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Concurrent(HybridConcurrent):
    pass


class Identity(HybridBlock):
    def hybrid_forward(self, F, x):
        return x
