"""Model zoo: vision (reference: gluon/model_zoo/vision/__init__.py —
get_model name table ~L1-150)."""
from ....base import MXNetError
from .resnet import *
from .resnet import __all__ as _resnet_all
from .alexnet import *
from .vgg import *
from .squeezenet import *
from .densenet import *
from .mobilenet import *
from .inception import *

_models = {name: globals()[name] for name in _resnet_all
           if name.startswith("resnet")}
_models.update({
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
    "vgg19_bn": vgg19_bn,
    "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
    "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
    "mobilenetv2_1.0": mobilenet_v2_1_0, "mobilenetv2_0.75": mobilenet_v2_0_75,
    "mobilenetv2_0.5": mobilenet_v2_0_5, "mobilenetv2_0.25": mobilenet_v2_0_25,
    "inceptionv3": inception_v3,
})


def get_model(name, **kwargs):
    name = name.lower()
    if name not in _models:
        raise MXNetError(
            f"Model {name} is not supported yet. Available: "
            f"{sorted(_models)}")
    if kwargs.pop("pretrained", False):
        raise MXNetError(
            "pretrained weights are unavailable in a zero-egress "
            "environment; initialize() and train, or load_parameters() "
            "from a local file")
    return _models[name](**kwargs)


def register_model(name, fn):
    _models[name.lower()] = fn
