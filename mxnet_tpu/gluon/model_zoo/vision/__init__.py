"""Model zoo: vision (reference: gluon/model_zoo/vision/__init__.py).

get_model resolves by name; families land incrementally (resnet first —
the BASELINE flagship; alexnet/vgg/mobilenet/squeezenet/densenet follow).
"""
from ....base import MXNetError
from .resnet import *
from .resnet import __all__ as _resnet_all

_models = {name: globals()[name] for name in _resnet_all
           if name.startswith("resnet")}


def get_model(name, **kwargs):
    name = name.lower()
    try:
        return _models[name](**kwargs)
    except KeyError:
        raise MXNetError(
            f"Model {name} is not supported yet. Available: "
            f"{sorted(_models)}") from None


def register_model(name, fn):
    _models[name.lower()] = fn
