"""Gluon Parameter / ParameterDict.

Reference parity: python/mxnet/gluon/parameter.py (Parameter deferred init
~L300, per-context replication, grad_req handling; ParameterDict ~L500).

TPU-native notes: a Parameter holds one NDArray per context (data-parallel
replication, as the reference does for multi-GPU); each NDArray is an
immutable jax buffer mutated by swap, so optimizer updates never invalidate
in-flight readers.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import List, Optional

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, cpu, current_context
from .. import initializer as init_mod

__all__ = ["Parameter", "Constant", "ParameterDict", "DeferredInitializationError"]


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is known (reference ~L40)."""


# ---------------------------------------------------------------------------
# CachedOp trace substitution: while a HybridBlock trace is active, Parameter
# .data() returns the traced value instead of the concrete buffer, and aux
# mutations (BatchNorm running stats) are collected instead of applied.
# This replaces the reference's symbol-proxy tracing (gluon/block.py
# _build_cache ~L750) with jaxpr tracing.
# ---------------------------------------------------------------------------
import threading as _threading


class _TraceState(_threading.local):
    def __init__(self):
        self.active = None  # None or dict with 'params', 'aux', 'ctx'


_trace = _TraceState()


def trace_active() -> bool:
    return _trace.active is not None


def begin_trace(param_map, ctx):
    prev = _trace.active
    _trace.active = {"params": param_map, "aux": [], "ctx": ctx}
    return prev


def end_trace(prev):
    state = _trace.active
    _trace.active = prev
    return state


def record_aux_update(param: "Parameter", value) -> None:
    """Aux-state write: collected during trace, applied by buffer swap in
    eager mode (on the value's context)."""
    if _trace.active is not None:
        _trace.active["aux"].append((param, value))
    else:
        ctx = value.context
        target = param._data.get(ctx) if param._data else None
        if target is None:
            param._check_initialized(ctx)
        target._set_data(value._data)


def _shape_known(shape) -> bool:
    return shape is not None and all(s > 0 for s in shape)


class Parameter:
    def __init__(self, name: str, grad_req: str = "write", shape=None,
                 dtype="float32", lr_mult: float = 1.0, wd_mult: float = 1.0,
                 init=None, allow_deferred_init: bool = False,
                 differentiable: bool = True, stype: str = "default",
                 grad_stype: str = "default"):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self.stype = stype
        self.grad_stype = grad_stype
        self._grad_req = grad_req if differentiable else "null"
        self._data: Optional[OrderedDict] = None  # ctx -> NDArray
        self._grad: Optional[OrderedDict] = None
        self._deferred = None  # (init, ctx_list) awaiting shape
        self._trainer = None

    # ------------------------------------------------------------------
    @property
    def grad_req(self) -> str:
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req: str):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
        elif self._data is not None:
            self._init_grad()

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # ------------------------------------------------------------------
    # initialization
    # ------------------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit: bool = False) -> None:
        """Allocate + fill per-context arrays (reference: _init_impl ~L300)."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        eff_init = init or self.init or default_init
        if not _shape_known(self.shape):
            if self.allow_deferred_init:
                self._deferred = (eff_init, list(ctx))
                return
            raise MXNetError(
                f"cannot initialize {self.name}: shape {self.shape} unknown; "
                "set allow_deferred_init=True or specify the full shape")
        self._init_impl(eff_init, ctx)

    def _init_impl(self, eff_init, ctx_list) -> None:
        import jax

        from ..ndarray import NDArray

        initializer = (eff_init if isinstance(eff_init, (init_mod.Initializer,
                                                         init_mod.Mixed))
                       else init_mod.create(eff_init))
        host = initializer.init_array(self.name, self.shape, self.dtype)
        self._data = OrderedDict()
        for ctx in ctx_list:
            self._data[ctx] = NDArray(jax.device_put(host, ctx.jax_device),
                                      ctx=ctx)
        self._deferred = None
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self) -> None:
        import jax.numpy as jnp

        from ..ndarray import NDArray
        from ..ndarray.sparse import RowSparseNDArray
        from .. import autograd

        self._grad = OrderedDict()
        for ctx, data in self._data.items():
            if self.grad_stype == "row_sparse":
                # sparse grad buffer (reference: grad_stype='row_sparse'
                # on sparse-grad Embedding weights); autograd writes
                # (indices, values) into it without densifying
                g = RowSparseNDArray(
                    jnp.zeros((0,) + tuple(data.shape[1:]), data._data.dtype),
                    {"indices": jnp.zeros((0,), jnp.int32)},
                    tuple(data.shape), ctx=ctx)
            else:
                g = NDArray(jnp.zeros_like(data._data), ctx=ctx)
            self._grad[ctx] = g
            data._grad = g
            data._grad_req = self._grad_req
            autograd.register_leaf(data)

    def _finish_deferred_init(self) -> None:
        if self._deferred is None:
            return
        if not _shape_known(self.shape):
            raise DeferredInitializationError(
                f"parameter {self.name} shape still unknown")
        eff_init, ctx_list = self._deferred
        self._init_impl(eff_init, ctx_list)

    def _set_shape_if_deferred(self, shape) -> None:
        """Adopt an inferred shape, honoring any user-fixed dims."""
        if self.shape is None:
            self.shape = tuple(shape)
            return
        merged = []
        for have, got in zip(self.shape, shape):
            if have > 0 and got > 0 and have != got:
                raise MXNetError(
                    f"inferred shape {shape} incompatible with declared "
                    f"{self.shape} for parameter {self.name}")
            merged.append(have if have > 0 else got)
        self.shape = tuple(merged)

    # ------------------------------------------------------------------
    # access
    # ------------------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred is not None:
                raise DeferredInitializationError(
                    f"parameter {self.name} deferred (shape unknown yet)")
            raise MXNetError(
                f"parameter {self.name} has not been initialized; call "
                ".initialize() first")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"parameter {self.name} not initialized on {ctx}; it lives on "
                f"{list(self._data)}")

    def data(self, ctx: Optional[Context] = None):
        if _trace.active is not None:
            sub = _trace.active["params"].get(self)
            if sub is not None:
                return sub
        if ctx is None:
            self._check_initialized()
            ctx = next(iter(self._data))
        else:
            self._check_initialized(ctx)
        return self._data[ctx]

    def list_data(self) -> List:
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx: Optional[Context] = None):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"parameter {self.name} has grad_req='null'")
        if ctx is None:
            ctx = next(iter(self._grad))
        return self._grad[ctx]

    def list_grad(self) -> List:
        self._check_initialized()
        if self._grad is None:
            raise MXNetError(f"parameter {self.name} has grad_req='null'")
        return list(self._grad.values())

    def list_ctx(self) -> List[Context]:
        self._check_initialized()
        return list(self._data.keys())

    def set_data(self, data) -> None:
        """Overwrite the parameter value on every context."""
        import jax

        from ..ndarray import NDArray

        self.shape = tuple(data.shape)
        if self._data is None:
            # loading into a not-yet-initialized parameter acts as its
            # initialization (reference: Parameter._load_init)
            if self._deferred is not None:
                _, ctx_list = self._deferred
            else:
                ctx_list = [current_context()]
            host = data.asnumpy() if isinstance(data, NDArray) else np.asarray(data)
            self._data = OrderedDict()
            for ctx in ctx_list:
                self._data[ctx] = NDArray(
                    jax.device_put(host.astype(dtype_np(self.dtype)),
                                   ctx.jax_device), ctx=ctx)
            self._deferred = None
            if self._grad_req != "null":
                self._init_grad()
            return
        src = data.asnumpy() if isinstance(data, NDArray) else np.asarray(data)
        for ctx, nd in self._data.items():
            nd._set_data(jax.device_put(src.astype(np.dtype(nd._data.dtype)),
                                        ctx.jax_device))

    def _reduce(self):
        """One host-complete copy of the value (reference Parameter._reduce:
        device-0 copy for dense params)."""
        self._check_initialized()
        return next(iter(self._data.values()))

    def _load_init(self, value, ctx=None, cast_dtype=False) -> None:
        shape = getattr(value, "shape", None)
        if _shape_known(self.shape) and tuple(self.shape) != tuple(shape):
            raise MXNetError(
                f"parameter {self.name} shape {self.shape} != loaded "
                f"{tuple(shape)}")
        if ctx is not None and self._data is None:
            # loading initializes on the requested ctx, not current_context()
            ctx_list = list(ctx) if isinstance(ctx, (list, tuple)) else [ctx]
            init = self._deferred[0] if self._deferred else None
            self._deferred = (init, ctx_list)
        self.set_data(value)
        if ctx is not None and self._data is not None:
            ctx_list = list(ctx) if isinstance(ctx, (list, tuple)) else [ctx]
            if list(self._data.keys()) != ctx_list:
                self.reset_ctx(ctx_list)

    def zero_grad(self) -> None:
        if self._grad is None:
            return
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        for g in self._grad.values():
            if isinstance(g, RowSparseNDArray):
                g.zero()
            else:
                g._set_data(jnp.zeros_like(g._data))

    def reset_ctx(self, ctx) -> None:
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._check_initialized()
        host = next(iter(self._data.values())).asnumpy()
        import jax

        from ..ndarray import NDArray

        self._data = OrderedDict(
            (c, NDArray(jax.device_put(host, c.jax_device), ctx=c)) for c in ctx
        )
        if self._grad_req != "null":
            self._init_grad()

    def cast(self, dtype) -> None:
        self.dtype = dtype
        if self._data is None:
            return
        import jax

        for nd in self._data.values():
            nd._set_data(nd._data.astype(dtype_np(dtype)))
        if self._grad:
            for g in self._grad.values():
                g._set_data(g._data.astype(dtype_np(dtype)))

    def var(self):
        """A symbol variable carrying this parameter's name (used when a
        HybridBlock is traced into a Symbol graph for export).  Cached so
        repeated calls (weight sharing within one trace) return the SAME
        graph node — otherwise list_arguments would show duplicates."""
        from .. import symbol as _sym

        cached = getattr(self, "_var_sym", None)
        if cached is None:
            cached = _sym.var(self.name, shape=self.shape, dtype=self.dtype)
            self._var_sym = cached
        return cached


class Constant(Parameter):
    """Non-learnable constant parameter (reference: gluon/parameter.py Constant)."""

    def __init__(self, name, value):
        from ..ndarray import NDArray

        if isinstance(value, NDArray):
            value = value.asnumpy()
        value = np.asarray(value)
        self.value = value

        class _CInit(init_mod.Initializer):
            def _init_weight(self, _name, arr):
                arr[:] = value

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_CInit())


class ParameterDict:
    """Prefix-scoped parameter collection (reference ~L500)."""

    def __init__(self, prefix: str = "", shared: Optional["ParameterDict"] = None):
        self._prefix = prefix
        self._params: "OrderedDict[str, Parameter]" = OrderedDict()
        self._shared = shared

    @property
    def prefix(self) -> str:
        return self._prefix

    def __repr__(self):
        items = "\n".join(f"  {v}" for v in self._params.values())
        return f"ParameterDict '{self._prefix}' (\n{items}\n)"

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def __getitem__(self, key) -> Parameter:
        return self._params[key]

    def __contains__(self, key) -> bool:
        return key in self._params

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    def get(self, name: str, **kwargs) -> Parameter:
        """Get or create `prefix+name` (reference: ParameterDict.get)."""
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Parameter(full, **kwargs)
            self._params[full] = param
        else:
            for k, v in kwargs.items():
                if k == "shape" and v is not None:
                    if param.shape is None:
                        param.shape = tuple(v)
                elif k == "init" and v is not None and param.init is None:
                    param.init = v
        return param

    def get_constant(self, name: str, value=None) -> Constant:
        full = self._prefix + name
        param = self._get_impl(full)
        if param is None:
            param = Constant(full, value)
            self._params[full] = param
        return param

    def _get_impl(self, full_name):
        if full_name in self._params:
            return self._params[full_name]
        if self._shared is not None and full_name in self._shared:
            self._params[full_name] = self._shared[full_name]
            return self._params[full_name]
        return None

    def update(self, other: "ParameterDict") -> None:
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError(f"duplicate parameter name {k}")
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        default = init if init is not None else init_mod.Uniform(0.07)
        for param in self.values():
            param.initialize(None, ctx, default_init=default,
                             force_reinit=force_reinit)

    def zero_grad(self) -> None:
        for param in self.values():
            param.zero_grad()

    def reset_ctx(self, ctx) -> None:
        for param in self.values():
            param.reset_ctx(ctx)

    def setattr(self, name, value) -> None:
        for param in self.values():
            setattr(param, name, value)

    def save(self, filename: str, strip_prefix: str = "") -> None:
        from .. import ndarray as nd

        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = block[0]
            name = param.name
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg_dict[name] = weight
        nd.save(filename, arg_dict)

    def load(self, filename: str, ctx=None, allow_missing: bool = False,
             ignore_extra: bool = False, restore_prefix: str = "",
             loaded=None) -> None:
        from .. import ndarray as nd

        if loaded is None:
            loaded = nd.load(filename)
        loaded = {restore_prefix + k: v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in loaded:
                    raise MXNetError(f"parameter {name} missing in {filename}")
        for name, value in loaded.items():
            if name not in self._params:
                if ignore_extra:
                    continue
                raise MXNetError(f"parameter {name} in file not in model")
            self._params[name].set_data(value)
