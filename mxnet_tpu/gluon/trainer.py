"""Gluon Trainer.

Reference parity: python/mxnet/gluon/trainer.py (_init_kvstore decision table
~L150, allreduce_grads ~L250, step/update ~L300, save/load_states ~L400).

On a single device the Trainer applies fused optimizer ops directly; on
multiple devices it preserves KVStore semantics (push grads / server update /
pull weights).  The throughput path for a full pod is the fused pjit step in
mxnet_tpu.parallel — this class is the semantic-parity imperative path.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Union

from ..base import MXNetError
from .. import optimizer as opt_mod
from .. import kvstore as kvs_mod
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = [params[k] for k in sorted(params.keys())] \
                if isinstance(params, dict) else list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError(
                "First argument must be a list or dict of Parameters")
        self._params: List[Parameter] = []
        self._param2idx: Dict[str, int] = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise MXNetError(
                    f"First argument must contain Parameters, got {type(param)}")
            self._param2idx[param.name] = i
            self._params.append(param)
            param._trainer = self
        self._compression_params = compression_params
        self._contains_sparse_grad = False
        optimizer_params = optimizer_params or {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._updaters = None
        self._params_to_init: List[Parameter] = []
        self._step_count = 0
        self._last_n_buckets = 0
        self._inflight = None  # lazy InflightRing (MX_ASYNC_INFLIGHT > 0)

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: p for i, p in enumerate(self._params)}
        if isinstance(optimizer, opt_mod.Optimizer):
            if optimizer_params and set(optimizer_params) != {"rescale_grad"}:
                raise MXNetError(
                    "optimizer_params must be None if optimizer is an "
                    "optimizer instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt_mod.create(
                optimizer, param_dict=param_dict, **optimizer_params)

    # ------------------------------------------------------------------
    @property
    def optimizer(self) -> opt_mod.Optimizer:
        return self._optimizer

    @property
    def learning_rate(self) -> float:
        return self._optimizer.learning_rate

    def set_learning_rate(self, lr: float) -> None:
        self._optimizer.set_learning_rate(lr)

    # ------------------------------------------------------------------
    def _init_kvstore(self) -> None:
        config = self._kvstore_params
        ctx_list = self._check_contexts()
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        kv = None
        if kvstore:
            if isinstance(kvstore, kvs_mod.KVStore):
                kv = kvstore
            elif len(ctx_list) > 1 or "dist" in str(kvstore):
                kv = kvs_mod.create(kvstore)
        if kv is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            self._kvstore = kv
            if self._compression_params:
                kv.set_gradient_compression(self._compression_params)
            if update_on_kvstore is None:
                update_on_kvstore = True
            self._update_on_kvstore = update_on_kvstore
            if update_on_kvstore:
                kv.set_updater(opt_mod.get_updater(self._optimizer))
            for i, param in enumerate(self._params):
                if param._data is not None:
                    kv.init(i, param.data(param.list_ctx()[0]))
        if not self._update_on_kvstore:
            n_dev = len(ctx_list)
            self._updaters = [opt_mod.get_updater(self._optimizer)
                              for _ in range(n_dev)]
        self._kv_initialized = True

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data is not None else None
            if contexts is None:
                contexts = ctx
        return contexts or []

    def _row_sparse_pull(self, parameter, out, row_id, full_idx=False):
        # dense emulation: plain pull
        if self._kvstore is not None:
            i = self._param2idx[parameter.name]
            self._kvstore.pull(i, out)

    # ------------------------------------------------------------------
    def step(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        """Rescale grads by 1/batch_size, aggregate across devices, update.

        Dispatch is non-blocking (jax queues the reduce/update programs);
        a bounded in-flight window (``MX_ASYNC_INFLIGHT``, the same knob
        as the fused ``DataParallelStep``) keeps the host from racing more
        than N un-synced steps ahead of the device: past the cap the step
        blocks on the OLDEST pending update's buffers first.  ``=0`` adds
        no fences (the pre-window behavior)."""
        import time as _time

        from .. import telemetry
        from ..parallel.async_loss import (InflightRing, StepFence,
                                           inflight_limit)

        t0 = _time.perf_counter()
        limit = inflight_limit()
        block_wait_s = 0.0
        if limit > 0:
            if self._inflight is None:
                self._inflight = InflightRing("Trainer")
            block_wait_s = self._inflight.make_room(limit)
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        for upd in self._fused_updaters():
            upd.last_info = None
        self._allreduce_grads()
        self._update(ignore_stale_grad)
        self._step_count += 1
        depth = 0
        if limit > 0:
            fence = StepFence(
                [arr._data for p in self._params if p.grad_req != "null"
                 and p._data is not None for arr in p.list_data()],
                step=self._step_count, executor="Trainer",
                ring=self._inflight)
            depth = self._inflight.admit(fence)
        if telemetry.enabled():
            # first step pays kvstore init + jit compiles of the
            # reduce/update programs — keep it out of the exec aggregates
            # (make_room's internal wait() already recorded the blocked
            # time in the rollup; the per-event field below is metadata)
            telemetry.record_step("Trainer", step=self._step_count,
                                  wall_s=_time.perf_counter() - t0,
                                  samples=int(batch_size),
                                  traced=self._step_count == 1,
                                  inflight_depth=depth,
                                  block_wait_ms=round(block_wait_s * 1e3, 3))
            info = {"n_params": 0, "n_fused": 0, "nbytes": 0,
                    "n_jitted_calls": 0}
            for upd in self._fused_updaters():
                li = upd.last_info
                if li:
                    # per-device updaters each saw the same param replicas:
                    # count params/bytes once, but dispatches per device
                    info["n_params"] = max(info["n_params"],
                                           li.get("n_params", 0))
                    info["nbytes"] = max(info["nbytes"], li.get("nbytes", 0))
                    info["n_fused"] += li.get("n_fused", 0)
                    info["n_jitted_calls"] += li.get("n_jitted_calls", 0)
            if info["n_fused"]:
                telemetry.record_fused_update(
                    n_params=info["n_params"],
                    n_buckets=self._last_n_buckets,
                    nbytes=info["nbytes"],
                    n_jitted_calls=info["n_jitted_calls"],
                    step=self._step_count)
            telemetry.heartbeat(self._step_count)
        # memory watchdog step boundary (after the dispatches, outside
        # any hot dispatch body; samples every MX_MEMWATCH_EVERY calls)
        from .. import memwatch

        memwatch.on_step(self._step_count)

    def drain(self) -> None:
        """Block until every in-flight update has landed in the parameter
        buffers (epoch end / pre-checkpoint sync)."""
        if self._inflight is not None:
            self._inflight.drain()

    def _fused_updaters(self):
        """Every FusedUpdater this trainer's step can route through — its
        own per-device updaters, or the kvstore's server-side one."""
        from ..optimizer.fused import FusedUpdater

        upds = list(self._updaters or [])
        if self._kvstore is not None and self._kvstore._updater is not None:
            upds.append(self._kvstore._updater)
        return [u for u in upds if isinstance(u, FusedUpdater)]

    def allreduce_grads(self) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported (reference behavior)")
        self._allreduce_grads()

    def _allreduce_grads(self) -> None:
        self._last_n_buckets = 0
        if self._kvstore is None:
            return
        live = [(i, param) for i, param in enumerate(self._params)
                if param.grad_req != "null"]
        if not live:
            return
        # size-capped flat buckets move many grads per collective;
        # push_bucketed itself falls back to per-key pushes when bucketing
        # is disabled, and unflattens before the store so pull is unchanged
        self._last_n_buckets = self._kvstore.push_bucketed(
            [i for i, _p in live], [p.list_grad() for _i, p in live])
        if not self._update_on_kvstore:
            for i, param in live:
                self._kvstore.pull(i, param.list_grad())

    def update(self, batch_size: int, ignore_stale_grad: bool = False) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "update() when parameters are updated on kvstore is not "
                "supported (call step() instead)")
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad: bool = False) -> None:
        from ..optimizer.fused import FusedUpdater

        entries_per_dev = [[] for _ in (self._updaters or [])]
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            # raises a clear error for never-initialized / still-deferred
            # parameters (reference behavior: step before init is an error)
            param._check_initialized()
            if self._update_on_kvstore:
                # server updated the stored weight during push; fetch it
                self._kvstore.pull(i, param.list_data())
                continue
            for entries, w, g in zip(entries_per_dev, param.list_data(),
                                     param.list_grad()):
                entries.append((i, g, w))
        if self._update_on_kvstore:
            return
        for upd, entries in zip(self._updaters, entries_per_dev):
            if isinstance(upd, FusedUpdater):
                # the trainer owns its parameter buffers — donate them so
                # XLA updates in place (no-op on the CPU backend)
                upd.apply(entries, donate=True)
            else:
                for i, g, w in entries:
                    upd(i, g, w)

    # ------------------------------------------------------------------
    def save_states(self, fname: str) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=False)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters[0].get_states(dump_optimizer=False))

    def load_states(self, fname: str) -> None:
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                states = f.read()
            for updater in self._updaters:
                updater.set_states(states)
                updater.optimizer = self._optimizer
