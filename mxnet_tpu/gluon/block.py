"""Gluon Block / HybridBlock and the CachedOp graph executor.

Reference parity: python/mxnet/gluon/block.py (Block.__call__ ~L500,
HybridBlock.hybridize ~L700, _build_cache ~L750) over src/imperative/
cached_op.cc (CachedOp::Forward ~L700, GetForwardGraph ~L200).

TPU-native design: hybridize() does not build an nnvm graph — calling a
hybridized block traces its eager forward (all NDArray ops hit the traced
branch of ops.registry) into a jaxpr, which jax.jit compiles into ONE XLA
executable.  XLA performs the memory planning, fusion and bulking that
PlanMemory / FusedOp / engine bulk-exec do in the reference.  The
per-input-signature executable cache that CachedOp keeps (GetForwardGraph
re-planning on new shapes) is exactly jax.jit's signature cache.

Mutable-state parity: parameter reads inside the trace are substituted with
traced values (see parameter.begin_trace); BatchNorm-style aux mutations are
collected during the trace, returned as extra outputs, and applied by buffer
swap after each call; dropout RNG becomes an explicit key argument threaded
through the traced function (random.set_trace_key_provider).
"""
from __future__ import annotations

import re
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..base import MXNetError
from ..context import Context, cpu, current_context
from .. import autograd
from .. import random as _random
from .parameter import (DeferredInitializationError, Parameter, ParameterDict,
                        begin_trace, end_trace, trace_active)

__all__ = ["Block", "HybridBlock", "SymbolBlock", "CachedOp"]


class _BlockScope(threading.local):
    """Name-scope manager (reference: block.py _BlockScope)."""

    def __init__(self):
        self._current: Optional["Block"] = None
        self._counters: Dict[str, int] = {}

    def create(self, prefix, params, hint):
        current = self._current
        if current is None:
            if prefix is None:
                count = self._counters.get(hint, 0)
                self._counters[hint] = count + 1
                prefix = f"{hint}{count}_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._scope_counters.get(hint, 0)
            current._scope_counters[hint] = count + 1
            prefix = f"{hint}{count}_"
        if params is None:
            parent = current._params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current.prefix + prefix, params


_scope = _BlockScope()


class _NameScopeCtx:
    def __init__(self, block):
        self._block = block
        self._prev = None

    def __enter__(self):
        self._prev = _scope._current
        _scope._current = self._block
        return self

    def __exit__(self, *exc):
        _scope._current = self._prev
        return False


class Block:
    """Base building block (reference: gluon/block.py Block)."""

    def __init__(self, prefix: Optional[str] = None,
                 params: Optional[ParameterDict] = None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _scope.create(prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") else self._prefix
        self._scope_counters: Dict[str, int] = {}
        self._children: "OrderedDict[str, Block]" = OrderedDict()
        self._reg_params: Dict[str, Parameter] = {}
        self._forward_hooks: List = []
        self._forward_pre_hooks: List = []

    def _alias(self) -> str:
        return self.__class__.__name__.lower()

    # ------------------------------------------------------------------
    @property
    def prefix(self) -> str:
        return self._prefix

    @property
    def name(self) -> str:
        return self._name

    @property
    def params(self) -> ParameterDict:
        return self._params

    def name_scope(self):
        return _NameScopeCtx(self)

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
        super().__setattr__(name, value)

    def register_child(self, block: "Block", name: Optional[str] = None) -> None:
        self._children[name or str(len(self._children))] = block

    def register_forward_hook(self, hook):
        self._forward_hooks.append(hook)

    def register_forward_pre_hook(self, hook):
        self._forward_pre_hooks.append(hook)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------------
    def collect_params(self, select: Optional[str] = None) -> ParameterDict:
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self._params)
        else:
            pattern = re.compile(select)
            ret._params.update(
                {k: v for k, v in self._params.items() if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select))
        return ret

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False) -> None:
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def _collect_params_with_prefix(self, prefix: str = "") -> Dict[str, Parameter]:
        """Structural dot-names ('0.weight', 'body.1.bias', ...) — the
        scope-independent naming save_parameters uses (reference block.py
        _collect_params_with_prefix ~L380)."""
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename: str, deduplicate: bool = False) -> None:
        """Save with structural names (reference gluon/block.py
        save_parameters ~L400: format is independent of name scopes)."""
        from .. import ndarray as nd

        params = self._collect_params_with_prefix()
        arg_dict = {}
        seen = {}
        for name, param in params.items():
            if deduplicate and id(param) in seen:
                continue
            seen[id(param)] = name
            arg_dict[name] = param._reduce()
        nd.save(filename, arg_dict)

    def load_parameters(self, filename: str, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current") -> None:
        from .. import ndarray as nd

        loaded = nd.load(filename)
        params = self._collect_params_with_prefix()
        if loaded and params and not any(k in params for k in loaded):
            # legacy full-name format (save_params): go through ParameterDict
            self.collect_params().load(filename, ctx, allow_missing,
                                       ignore_extra,
                                       restore_prefix=self.prefix,
                                       loaded=loaded)
            return
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise MXNetError(
                        f"parameter {name} missing in {filename}")
        for name, value in loaded.items():
            if name not in params:
                if ignore_extra:
                    continue
                raise MXNetError(f"parameter {name} in file not in model")
            params[name]._load_init(value, ctx, cast_dtype=cast_dtype)

    # legacy names
    def save_params(self, filename: str) -> None:
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.load_parameters(filename, ctx, allow_missing, ignore_extra)

    def cast(self, dtype) -> None:
        for child in self._children.values():
            child.cast(dtype)
        for param in self._params.values():
            param.cast(dtype)

    def hybridize(self, active: bool = True, **kwargs) -> None:
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    # ------------------------------------------------------------------
    def __call__(self, *args):
        for hook in self._forward_pre_hooks:
            hook(self, args)
        out = self.forward(*args)
        for hook in self._forward_hooks:
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        raise NotImplementedError(
            "summary() lands with the visualization module")


def _indent(s, n):
    pad = " " * n
    return ("\n" + pad).join(s.split("\n"))


class CachedOp:
    """The hybridization executor: block forward as ONE jitted function.

    Reference: src/imperative/cached_op.cc.  Signature cache and memory
    planning are delegated to jax.jit / XLA; we keep one traced+jitted
    callable per train-mode flag (dropout/BN change the traced program).
    """

    _instance_counter = 0

    def __init__(self, block: "HybridBlock", flags: Dict[str, Any]):
        self.block = block
        self.flags = flags
        # retrace tracking is per-instance: a model holding many
        # same-class blocks of different widths must not pool their (one
        # each, perfectly stable) signatures into a false retrace storm
        CachedOp._instance_counter += 1
        self._tele_name = (f"CachedOp:{type(block).__name__}"
                           f"#{CachedOp._instance_counter}")
        # keyed by (train, input treedef): inputs may be arbitrary pytrees of
        # NDArrays (e.g. RNN layers take (x, [h, c]))
        self._jitted: Dict[Any, Any] = {}
        self._param_items: Optional[List] = None  # [(name, Parameter)]
        self._aux_params: Dict[Any, List[Parameter]] = {}
        self._out_treedef: Dict[Any, Any] = {}
        self._n_out: Dict[Any, int] = {}
        # persistent AOT executables per (cache_key, input signature)
        # (MX_EXECUTABLE_CACHE_DIR): a restarted process deserializes the
        # compiled forward instead of re-tracing + re-compiling; False =
        # resolution failed, stay on the plain jit path.  The entry meta
        # carries the trace-time structural facts (n_out, output treedef,
        # aux param names) a no-trace warm load cannot otherwise know.
        self._aot_execs: Dict[Any, Any] = {}
        self._aot_info: Dict[str, Any] = {}

    def _ensure_params(self, ctx):
        if self._param_items is None:
            params = self.block.collect_params()
            self._param_items = list(params.items())
        # triggers deferred-init errors before tracing
        return [p.data(ctx) for _, p in self._param_items]

    @staticmethod
    def _flatten(args):
        import jax.tree_util as jtu

        from ..ndarray import NDArray

        leaves, treedef = jtu.tree_flatten(
            list(args), is_leaf=lambda x: isinstance(x, NDArray))
        return leaves, treedef

    def _build(self, cache_key, train: bool, ctx, in_treedef):
        import jax
        import jax.tree_util as jtu

        block = self.block
        param_list = [p for _, p in self._param_items]
        cached = self

        def fn(param_arrays, key, *input_arrays):
            from ..ndarray import NDArray

            param_map = {
                p: NDArray(arr, ctx=ctx)
                for p, arr in zip(param_list, param_arrays)
            }
            nd_leaves = [NDArray(a, ctx=ctx) for a in input_arrays]
            nd_inputs = jtu.tree_unflatten(in_treedef, nd_leaves)
            prev_trace = begin_trace(param_map, ctx)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(train)
            prev_key = _random.set_trace_key_provider(
                _random._TraceKeyProvider(key))
            try:
                out = block.forward(*nd_inputs)
            finally:
                state = end_trace(prev_trace)
                autograd.set_recording(prev_rec)
                autograd.set_training(prev_train)
                _random.set_trace_key_provider(prev_key)
            out_nds, out_treedef = jtu.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            cached._out_treedef[cache_key] = out_treedef
            cached._n_out[cache_key] = len(out_nds)
            cached._aux_params[cache_key] = [p for p, _ in state["aux"]]
            aux_vals = [v._data for _, v in state["aux"]]
            return tuple(o._data for o in out_nds) + tuple(aux_vals)

        return jax.jit(fn)

    def _resolve_aot(self, cache_key, shape_sig, jfn, call_args, ctx):
        """Persistent AOT executable for (cache_key, input signature),
        or None (plain jit dispatch).  On a MISS ``get_or_compile``
        lowers ``jfn`` — the trace populates the structural output
        dicts as a side effect — and persists them as entry meta via
        ``meta_fn``; on a warm HIT in a fresh process those facts are
        restored from the meta, so the python forward is NEVER traced
        (the restart win).  Failed resolutions are negative-cached."""
        akey = (cache_key, shape_sig)
        entry = self._aot_execs.get(akey)
        if entry is not None:
            return entry if entry is not False else None
        from .. import aot_cache, memwatch

        train, in_treedef = cache_key
        parts = ("cachedop", type(self.block).__name__, bool(train),
                 str(in_treedef), shape_sig,
                 tuple((tuple(a.shape), str(a.dtype))
                       for a in call_args[0]))

        def meta_fn():
            # runs after the fresh lower+compile: jfn traced, so the
            # output structure is known — persist it for warm restarts
            name_of = {id(p): n
                       for n, p in self.block.collect_params().items()}
            return {
                "n_out": self._n_out[cache_key],
                "out_treedef": self._out_treedef[cache_key],
                "aux_names": [name_of[id(p)]
                              for p in self._aux_params[cache_key]],
            }

        dev = ctx.jax_device
        exec_, info = aot_cache.get_or_compile(
            jfn, call_args, fingerprint=memwatch.fingerprint(parts),
            platform=dev.platform, mesh_shape=(),
            device_ids=(int(dev.id),), meta_fn=meta_fn)
        self._aot_info = info
        if exec_ is not None and cache_key not in self._n_out:
            # warm hit, fresh process: restore the structural facts from
            # the entry meta — without them the outputs can't be
            # unflattened and the executable is unusable
            meta = info.get("meta") or {}
            try:
                params = self.block.collect_params()
                self._out_treedef[cache_key] = meta["out_treedef"]
                self._aux_params[cache_key] = [params[n]
                                               for n in meta["aux_names"]]
                self._n_out[cache_key] = int(meta["n_out"])
            except (KeyError, TypeError):
                exec_ = None
        self._aot_execs[akey] = exec_ if exec_ is not None else False
        return exec_

    def __call__(self, *inputs):
        import jax.tree_util as jtu

        from ..ndarray import NDArray

        in_nds, in_treedef = self._flatten(inputs)
        ctx = in_nds[0].context
        param_nds = self._ensure_params(ctx)
        train = autograd.is_training()
        cache_key = (train, in_treedef)
        jfn = self._jitted.get(cache_key)
        was_cold = jfn is None
        if jfn is None:
            jfn = self._build(cache_key, train, ctx, in_treedef)
            self._jitted[cache_key] = jfn

        # telemetry retrace detection: jax.jit re-traces (and XLA
        # recompiles) this block for every new input shape/dtype/treedef —
        # shape-churning data pipelines silently spend their time compiling
        from .. import telemetry

        shape_sig = None
        if telemetry.retrace_enabled():
            # note_signature returns True for a NEW signature = this call
            # traces + XLA-compiles; OR with was_cold so a second
            # executor over a seen signature still books its compile.
            # With detection OFF, traced falls back to the first build
            # per cache key only — per-shape respecializations then go
            # unbooked, by design: the kill switch exists to remove the
            # per-call signature probe that would detect them
            shape_sig = tuple((tuple(x.shape), str(x._data.dtype))
                              for x in in_nds)
            traced = telemetry.note_signature(
                self._tele_name, (train, str(in_treedef), shape_sig)) \
                or was_cold
        else:
            traced = was_cold

        key = _random.next_key()
        arrays = tuple(p._data for p in param_nds)
        in_arrays = [x._data for x in in_nds]
        import time as _time

        # timed only when a compile event can fire: the warm steady-state
        # path (cached jit, detection off) must pay nothing here
        t0 = _time.perf_counter() if traced else 0.0

        recording = autograd.is_recording()
        if recording:
            import jax

            outs, vjp_fn = jax.vjp(jfn, arrays, key, *in_arrays)
            flat_inputs = list(arrays) + [key] + in_arrays

            def adapter(cots):
                pc, kc, *ic = vjp_fn(cots if isinstance(cots, tuple) else (cots,))
                return list(pc) + [kc] + list(ic)

            n_params = len(arrays)

            def flat_fwd(*flat, _jfn=jfn, _np_=n_params):
                # flat-args twin of jfn for create_graph re-linearization
                return _jfn(tuple(flat[:_np_]), flat[_np_],
                            *flat[_np_ + 1:])

            autograd.record_node(adapter, flat_inputs, list(outs),
                                 input_nds=param_nds + in_nds,
                                 fwd_fn=flat_fwd)
        else:
            run = jfn
            from .. import aot_cache

            if aot_cache.enabled():
                import jax

                # inference dispatch only: the vjp/recording path above
                # needs the traceable fn, and in-trace calls (tracer
                # inputs — e.g. the serving decode trace) must inline
                if not any(isinstance(a, jax.core.Tracer)
                           for a in (key,) + tuple(arrays)
                           + tuple(in_arrays)):
                    if shape_sig is None:
                        shape_sig = tuple((tuple(x.shape),
                                           str(x._data.dtype))
                                          for x in in_nds)
                    aot = self._resolve_aot(cache_key, shape_sig, jfn,
                                            (arrays, key, *in_arrays),
                                            ctx)
                    if aot is not None:
                        run = aot
            outs = run(arrays, key, *in_arrays)

        if traced:
            # one compile event per specialized executable of this block
            # (per train flag + treedef + input signature) — never
            # re-emitted on the cached steady-state path
            from .. import memwatch

            if shape_sig is None:  # detection off: built only on compile
                shape_sig = tuple((tuple(x.shape), str(x._data.dtype))
                                  for x in in_nds)
            aot_extra = {k: v for k, v in self._aot_info.items()
                         if k != "meta"}
            self._aot_info = {}
            memwatch.note_compile(
                self._tele_name,
                ("CachedOp", type(self.block).__name__, train,
                 str(in_treedef), shape_sig,
                 tuple((tuple(a.shape), str(a.dtype)) for a in arrays)),
                wall_s=_time.perf_counter() - t0, site="cached_op",
                # a deserialized executable never traced the forward —
                # don't pay that trace just for cost analysis
                jitted=(None if aot_extra.get("cache_hit") else jfn),
                args=(memwatch.shape_structs(arrays),
                      memwatch.shape_structs(key),
                      *memwatch.shape_structs(tuple(in_arrays))),
                **aot_extra)
        else:
            # an AOT resolution on a NON-traced call (retrace detection
            # off, new shape under a warm cache_key) must not leak its
            # cache facts into the next unrelated compile event
            self._aot_info = {}

        n_out = self._n_out[cache_key]
        out_nds = [NDArray(o, ctx=ctx) for o in outs[:n_out]]
        # apply collected aux-state updates by buffer swap
        for p, new in zip(self._aux_params[cache_key], outs[n_out:]):
            target = p._data.get(ctx)
            if target is not None:
                target._set_data(new)
        return jtu.tree_unflatten(self._out_treedef[cache_key], out_nds)


class HybridBlock(Block):
    """A Block compilable into one XLA executable via hybridize()."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags: Dict[str, Any] = {}
        self._cached_op: Optional[CachedOp] = None

    def hybridize(self, active: bool = True, static_alloc: bool = False,
                  static_shape: bool = False, inline_limit: int = 2,
                  forward_bulk_size: Optional[int] = None,
                  backward_bulk_size: Optional[int] = None) -> None:
        self._active = active
        self._flags = {"static_alloc": static_alloc,
                       "static_shape": static_shape}
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape)

    def _clear_cached_op(self) -> None:
        self._cached_op = None

    def infer_shape(self, *args) -> None:
        """Shape-inference hook for deferred parameter init.  Built-in layers
        override this; composite blocks rely on their children."""
        raise MXNetError(
            f"{type(self).__name__} has deferred-initialized parameters but "
            "no infer_shape(); initialize with explicit shapes or override "
            "infer_shape")

    def _deferred_infer_shape(self, *args) -> None:
        self.infer_shape(*args)
        for param in self._reg_params.values():
            if param._deferred is not None:
                param._finish_deferred_init()

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def __call__(self, *args):
        from .. import symbol as _sym

        if args and isinstance(args[0], _sym.Symbol):
            # symbol trace: bypass hooks/cached-op, compose the graph
            return self.forward(*args)
        # inside an active trace, always run the eager path (ops see tracers)
        if self._active and not trace_active():
            try:
                return self._call_cached_op(*args)
            except DeferredInitializationError:
                self._infer_and_retry_params(*args)
                return self._call_cached_op(*args)
        return super().__call__(*args)

    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._cached_op = CachedOp(self, self._flags)
        from .. import profiler

        if profiler.is_recording():
            return profiler.timed_call(f"CachedOp:{type(self).__name__}",
                                       self._cached_op, *args)
        return self._cached_op(*args)

    def _infer_and_retry_params(self, *args) -> None:
        # Run one eager forward: each leaf layer resolves its own deferred
        # params via its infer_shape on the way through.
        with autograd.pause(train_mode=autograd.is_training()):
            super().__call__(*args)

    def forward(self, x, *args):
        """Dispatch to hybrid_forward with params bound (reference ~L750)."""
        from .. import symbol as _sym

        if isinstance(x, _sym.Symbol):
            # symbol trace (export path): params become named variables
            params = {name: p.var()
                      for name, p in self._reg_params.items()}
            return self.hybrid_forward(_sym, x, *args, **params)
        ctx = x.context
        try:
            params = {name: p.data(ctx) for name, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            params = {name: p.data(ctx) for name, p in self._reg_params.items()}
        from .. import ndarray as F

        return self.hybrid_forward(F, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    def export(self, path: str, epoch: int = 0, input_names=("data",)):
        """Emit {path}-symbol.json + {path}-{epoch:04d}.params (reference:
        gluon/block.py export ~L900): trace hybrid_forward with Symbol
        proxies, then save parameters keyed arg:/aux: by graph role, so
        SymbolBlock.imports / Module.load round-trip.  Multi-input blocks
        (seq2seq src/tgt, ...) pass their input names via `input_names`."""
        from .. import symbol as _sym
        from ..ndarray import save as nd_save

        out = self(*[_sym.var(n) for n in input_names])
        if isinstance(out, (list, tuple)):
            out = _sym.Group(out)
        out.save(f"{path}-symbol.json")

        aux_names = set(out.list_auxiliary_states())
        save_dict = {}
        for param in self.collect_params().values():
            if param._data is None:
                raise MXNetError(
                    f"export: parameter {param.name!r} is not initialized "
                    "(run one forward to resolve deferred shapes first)")
            arr = param._reduce()
            key = (f"aux:{param.name}" if param.name in aux_names
                   else f"arg:{param.name}")
            save_dict[key] = arr
        nd_save(f"{path}-{epoch:04d}.params", save_dict)
        return out


class SymbolBlock(HybridBlock):
    """Construct a block from a symbol graph (reference: gluon/block.py
    SymbolBlock.imports ~L900).

    The symbol's whole graph runs as one pure jax function through the
    imperative dispatch layer, so autograd recording, tracing inside an
    outer HybridBlock, and jit all work unchanged."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from .. import symbol as _sym

        if isinstance(outputs, (list, tuple)):
            outputs = _sym.Group(outputs)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._sym = outputs
        self._sym_input_names = [s.name for s in inputs]
        arg_names = outputs.list_arguments()
        self._sym_aux_names = list(outputs.list_auxiliary_states())
        self._sym_param_names = [n for n in arg_names
                                 if n not in self._sym_input_names]
        for n in self._sym_param_names:
            p = self.params.get(n, grad_req="write", allow_deferred_init=True)
            self._reg_params[n] = p
        for n in self._sym_aux_names:
            p = self.params.get(n, grad_req="null", allow_deferred_init=True)
            self._reg_params[n] = p

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as _sym
        from ..context import current_context

        sym = _sym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym.var(n) for n in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            from .. import ndarray as nd

            raw = nd.load(param_file)
            arg, aux = {}, {}
            for k, v in raw.items():
                tp, _, name = k.partition(":")
                (aux if tp == "aux" else arg)[name if tp in ("arg", "aux")
                                              else k] = v
            ctx = ctx or current_context()
            for name, val in {**arg, **aux}.items():
                if name in ret._reg_params:
                    ret._reg_params[name]._load_init(val, ctx=ctx)
        return ret

    def _infer_sym_param_shapes(self, *args):
        shapes = {n: a.shape
                  for n, a in zip(self._sym_input_names, args)}
        arg_shapes, _, aux_shapes = self._sym.infer_shape(**shapes)
        arg_names = self._sym.list_arguments()
        for name, shp in zip(arg_names, arg_shapes):
            if name in self._reg_params:
                self._reg_params[name]._set_shape_if_deferred(shp)
                self._reg_params[name]._finish_deferred_init()
        for name, shp in zip(self._sym_aux_names, aux_shapes):
            self._reg_params[name]._set_shape_if_deferred(shp)
            self._reg_params[name]._finish_deferred_init()

    def forward(self, x, *args):
        from .. import autograd
        from .. import random as _rng
        from .. import symbol as _sym
        from ..ops import registry as _reg
        from ..symbol.symbol import build_graph_eval

        if isinstance(x, _sym.Symbol):
            # symbol trace (re-export path): splice the stored graph onto
            # the incoming symbols by input-variable name
            mapping = dict(zip(self._sym_input_names, [x, *args]))
            return self._sym(**mapping)

        ctx = x.context
        try:
            params = {n: p.data(ctx) for n, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._infer_sym_param_shapes(x, *args)
            params = {n: p.data(ctx) for n, p in self._reg_params.items()}

        training = autograd.is_training()
        eval_fn = build_graph_eval(self._sym._entries, training)
        key = _rng.next_key()
        data_nds = [x, *args]
        names = (self._sym_input_names
                 + [n for n in params])
        input_nds = data_nds + [params[n] for n in params]
        aux_upd = list(self._sym_aux_names) if training else []
        n_out = len(self._sym.list_outputs())

        def fn(*arrays):
            vals = dict(zip(names, arrays))
            outs, aux_updates = eval_fn(vals, key)
            flat = tuple(outs) + tuple(aux_updates.get(n, vals[n])
                                       for n in aux_upd)
            # single output unwraps: the tape passes a bare cotangent for
            # one-output nodes, so the vjp structure must match
            return flat[0] if len(flat) == 1 else flat

        results = _reg.invoke_fn(fn, input_nds)
        if not isinstance(results, (list, tuple)):
            results = [results]
        outs, aux_vals = results[:n_out], results[n_out:]
        for n, v in zip(aux_upd, aux_vals):
            self._reg_params[n].set_data(v.detach())
        return outs[0] if n_out == 1 else list(outs)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
