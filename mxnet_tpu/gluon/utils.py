"""Gluon utilities.

Reference parity: python/mxnet/gluon/utils.py — split_data/split_and_load
(~L40, the data-parallel batch sharder), clip_global_norm, check_sha1,
download (stubbed: zero-egress environments).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..base import MXNetError
from ..context import Context

__all__ = ["split_data", "split_and_load", "clip_global_norm"]


def split_data(data, num_slice: int, batch_axis: int = 0,
               even_split: bool = True) -> List:
    size = data.shape[batch_axis]
    if size < num_slice:
        raise MXNetError(
            f"Too many slices: data with shape {data.shape} only has {size} "
            f"entries on axis {batch_axis} but {num_slice} slices requested")
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}")
    step = size // num_slice
    if not even_split and size % num_slice != 0:
        slices = [
            _slice_axis(data, batch_axis, i * step, (i + 1) * step)
            for i in range(num_slice - 1)
        ]
        slices.append(_slice_axis(data, batch_axis, (num_slice - 1) * step, size))
        return slices
    return [
        _slice_axis(data, batch_axis, i * step, (i + 1) * step)
        for i in range(num_slice)
    ]


def _slice_axis(data, axis, begin, end):
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(begin, end)
    return data[tuple(idx)]


def split_and_load(data, ctx_list: List[Context], batch_axis: int = 0,
                   even_split: bool = True) -> List:
    """Shard a batch across contexts (the Gluon data-parallel entry point).

    On TPU the per-context shards feed either per-device eager forward or the
    sharded pjit path in mxnet_tpu.parallel."""
    from ..ndarray import NDArray, array

    if not isinstance(data, NDArray):
        data = array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [piece.as_in_context(ctx) for piece, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm: float, check_isfinite: bool = True):
    """Rescale arrays so their joint L2 norm is at most max_norm."""
    import jax.numpy as jnp

    if not arrays:
        raise MXNetError("clip_global_norm requires at least one array")
    total = None
    for arr in arrays:
        sq = jnp.sum(jnp.square(arr._data.astype(jnp.float32)))
        total = sq if total is None else total + sq
    norm = float(jnp.sqrt(total))
    if check_isfinite and not np.isfinite(norm):
        import warnings

        warnings.warn("nan or inf is detected. Clipping results will be "
                      "undefined.", stacklevel=2)
    scale = max_norm / (norm + 1e-8)
    if scale < 1.0:
        for arr in arrays:
            arr._set_data(arr._data * scale)
    return norm
