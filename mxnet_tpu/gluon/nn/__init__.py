"""Gluon neural-network layers (reference: python/mxnet/gluon/nn/)."""
from ..block import Block, HybridBlock, SymbolBlock
from .basic_layers import *
from .conv_layers import *
