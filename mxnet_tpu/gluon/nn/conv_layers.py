"""Gluon convolution / pooling layers.

Reference parity: python/mxnet/gluon/nn/conv_layers.py (~L1-1200): Conv1D/2D/3D,
Conv2DTranspose/Conv3DTranspose, Max/Avg pooling 1D/2D/3D, global pooling.
Supports NC[DHW] (MXNet default) and channel-last N[DHW]C layouts; on TPU
channel-last is the MXU-native tiling (the reference's NHWC tensor-core
analog, python/mxnet/gluon/nn/conv_layers.py layout= param).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...base import MXNetError
from ...ops.nn import _channels_last
from ...precision.runtime import quant_entry
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _tup(val, n):
    return (val,) * n if isinstance(val, int) else tuple(val)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="Convolution", adj=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._channels = channels
        self._in_channels = in_channels
        ndim = len(kernel_size)
        self._layout = layout
        self._channel_axis = -1 if _channels_last(layout) else 1
        self._kwargs = {
            "kernel": kernel_size, "stride": strides, "dilate": dilation,
            "pad": padding, "num_filter": channels, "num_group": groups,
            "no_bias": not use_bias, "layout": layout}
        if adj is not None:
            self._kwargs["adj"] = adj
        self._op_name = op_name
        self._act_type = activation
        with self.name_scope():
            ig = in_channels // groups if in_channels else 0
            og = channels // groups if channels else 0
            if self._channel_axis == -1:  # weight layout follows data layout
                wshape = ((channels,) + kernel_size + (ig,)
                          if op_name == "Convolution"
                          else (in_channels,) + kernel_size + (og,))
            elif op_name == "Convolution":
                wshape = (channels, ig) + kernel_size
            else:  # Deconvolution weight layout (in, out/group, *k)
                wshape = (in_channels, og) + kernel_size
            self.weight = self.params.get(
                "weight", shape=wshape, init=weight_initializer,
                allow_deferred_init=True)
            self.bias = (self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None)

    def infer_shape(self, x, *args):
        in_c = int(x.shape[self._channel_axis])
        groups = self._kwargs["num_group"]
        k = tuple(self._kwargs["kernel"])
        if self._channel_axis == -1:
            wshape = ((self._channels,) + k + (in_c // groups,)
                      if self._op_name == "Convolution"
                      else (in_c,) + k + (self._channels // groups,))
        elif self._op_name == "Convolution":
            wshape = (self._channels, in_c // groups) + k
        else:
            wshape = (in_c, self._channels // groups) + k
        self.weight._set_shape_if_deferred(wshape)
        if self.bias is not None:
            self.bias._set_shape_if_deferred((self._channels,))

    def hybrid_forward(self, F, x, weight, bias=None):
        twin = quant_entry(self)
        if twin is not None:
            # active precision.quant_scope (int8 serving): the calibrated
            # int8 twin replaces the f32 conv inside the traced graph
            return twin(F, x, bias)
        op = getattr(F, self._op_name)
        if bias is None:
            out = op(x, weight, **self._kwargs)
        else:
            out = op(x, weight, bias, **self._kwargs)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, "
                f"kernel_size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, prefix=prefix, params=params)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW",
                 activation=None, use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 1), _tup(strides, 1),
                         _tup(padding, 1), _tup(dilation, 1), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 1), prefix=prefix,
                         params=params)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 in_channels=0, prefix=None, params=None):
        super().__init__(channels, _tup(kernel_size, 2), _tup(strides, 2),
                         _tup(padding, 2), _tup(dilation, 2), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 2), prefix=prefix,
                         params=params)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", activation=None,
                 use_bias=True, weight_initializer=None,
                 bias_initializer="zeros", in_channels=0, prefix=None,
                 params=None):
        super().__init__(channels, _tup(kernel_size, 3), _tup(strides, 3),
                         _tup(padding, 3), _tup(dilation, 3), groups, layout,
                         in_channels, activation, use_bias, weight_initializer,
                         bias_initializer, op_name="Deconvolution",
                         adj=_tup(output_padding, 3), prefix=prefix,
                         params=params)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout, count_include_pad=None, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        if strides is None:
            strides = pool_size
        self._kwargs = {
            "kernel": pool_size, "stride": strides, "pad": padding,
            "pool_type": pool_type, "global_pool": global_pool,
            "pooling_convention": "full" if ceil_mode else "valid",
            "layout": layout}
        if count_include_pad is not None:
            self._kwargs["count_include_pad"] = count_include_pad

    def hybrid_forward(self, F, x):
        return F.Pooling(x, **self._kwargs)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kwargs['kernel']}, "
                f"stride={self._kwargs['stride']}, "
                f"padding={self._kwargs['pad']})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 1),
                         None if strides is None else _tup(strides, 1),
                         _tup(padding, 1), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 2),
                         None if strides is None else _tup(strides, 2),
                         _tup(padding, 2), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, prefix=None, params=None):
        super().__init__(_tup(pool_size, 3),
                         None if strides is None else _tup(strides, 3),
                         _tup(padding, 3), ceil_mode, False, "max", layout,
                         prefix=prefix, params=params)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, prefix=None,
                 params=None):
        super().__init__(_tup(pool_size, 1),
                         None if strides is None else _tup(strides, 1),
                         _tup(padding, 1), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_tup(pool_size, 2),
                         None if strides is None else _tup(strides, 2),
                         _tup(padding, 2), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True,
                 prefix=None, params=None):
        super().__init__(_tup(pool_size, 3),
                         None if strides is None else _tup(strides, 3),
                         _tup(padding, 3), ceil_mode, False, "avg", layout,
                         count_include_pad, prefix=prefix, params=params)


class _GlobalPool(_Pooling):
    def __init__(self, pool_type, ndim, layout, prefix=None, params=None):
        super().__init__((1,) * ndim, (1,) * ndim, (0,) * ndim, False, True,
                         pool_type, layout, prefix=prefix, params=params)


class GlobalMaxPool1D(_GlobalPool):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__("max", 1, layout, prefix=prefix, params=params)


class GlobalMaxPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__("max", 2, layout, prefix=prefix, params=params)


class GlobalMaxPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__("max", 3, layout, prefix=prefix, params=params)


class GlobalAvgPool1D(_GlobalPool):
    def __init__(self, layout="NCW", prefix=None, params=None):
        super().__init__("avg", 1, layout, prefix=prefix, params=params)


class GlobalAvgPool2D(_GlobalPool):
    def __init__(self, layout="NCHW", prefix=None, params=None):
        super().__init__("avg", 2, layout, prefix=prefix, params=params)


class GlobalAvgPool3D(_GlobalPool):
    def __init__(self, layout="NCDHW", prefix=None, params=None):
        super().__init__("avg", 3, layout, prefix=prefix, params=params)
