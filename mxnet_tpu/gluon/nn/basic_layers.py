"""Gluon basic layers.

Reference parity: python/mxnet/gluon/nn/basic_layers.py (~L1-800): Dense,
Dropout, BatchNorm, Embedding, LayerNorm, InstanceNorm, Flatten, Lambda,
HybridLambda, Sequential, HybridSequential, activation layers.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ...base import MXNetError
from ... import initializer as init_mod
from ...precision.runtime import quant_entry
from ..block import Block, HybridBlock
from ..parameter import record_aux_update

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "Embedding", "LayerNorm", "GroupNorm", "InstanceNorm", "Flatten",
           "Lambda", "HybridLambda", "Activation", "LeakyReLU", "PReLU",
           "ELU", "SELU", "Swish", "GELU"]


class Sequential(Block):
    """Stack of Blocks run sequentially."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net._children = type(self._children)(
                (str(i), l) for i, l in enumerate(layers))
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(
                isinstance(c, HybridBlock) for c in self._children.values()):
            import warnings

            warnings.warn(
                "All children of this Sequential layer are HybridBlocks. "
                "Consider using HybridSequential for the best performance.",
                stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stack of HybridBlocks, compilable into one XLA executable."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            net._children = type(self._children)(
                (str(i), l) for i, l in enumerate(layers))
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer y = act(xW^T + b) (reference ~L50)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = (self.params.get(
                "bias", shape=(units,), dtype=dtype, init=bias_initializer,
                allow_deferred_init=True) if use_bias else None)

    def infer_shape(self, x, *args):
        in_units = (int(np.prod(x.shape[1:])) if self._flatten
                    else int(x.shape[-1]))
        self.weight._set_shape_if_deferred((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        twin = quant_entry(self)
        if twin is not None:
            # active precision.quant_scope (int8 serving): route through
            # the calibrated int8 twin — the scope is only ever set
            # around a QuantizedAdapter's traced prefill/decode bodies
            return twin(F, x, bias)
        if bias is None:
            out = F.FullyConnected(x, weight, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        if self._act_type:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        shape = self.weight.shape if self.weight.shape else ("?", "?")
        return (f"Dense({shape[1] if len(shape) > 1 else '?'} -> {self._units}, "
                f"{self._act_type or 'linear'})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = tuple(axes)

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving-stat aux state (reference ~L300).

    The aux update is pure-functional under the hood: the new moving stats
    are computed in-graph and written back by buffer swap (or collected and
    returned as extra outputs when traced inside a CachedOp)."""

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True,
                grad_req="write" if scale else "null")
            self.beta = self.params.get(
                "beta", shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True,
                grad_req="write" if center else "null")
            self.running_mean = self.params.get(
                "running_mean", shape=(in_channels,),
                init=running_mean_initializer, grad_req="null",
                allow_deferred_init=True)
            self.running_var = self.params.get(
                "running_var", shape=(in_channels,),
                init=running_variance_initializer, grad_req="null",
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = int(x.shape[self._axis])
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._set_shape_if_deferred((c,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        from ... import autograd

        training = autograd.is_training() and not self._use_global_stats
        if training:
            out, mean, var = F.BatchNorm(
                x, gamma, beta, running_mean, running_var, eps=self._epsilon,
                momentum=self._momentum, fix_gamma=not self._scale,
                use_global_stats=False, output_mean_var=True, axis=self._axis,
                training=True)
            m = self._momentum
            record_aux_update(self.running_mean,
                              running_mean * m + mean.astype(running_mean.dtype) * (1 - m))
            record_aux_update(self.running_var,
                              running_var * m + var.astype(running_var.dtype) * (1 - m))
            return out
        return F.BatchNorm(
            x, gamma, beta, running_mean, running_var, eps=self._epsilon,
            momentum=self._momentum, fix_gamma=not self._scale,
            use_global_stats=True, output_mean_var=False, axis=self._axis,
            training=False)

    def __repr__(self):
        return (f"BatchNorm(axis={self._axis}, eps={self._epsilon}, "
                f"momentum={self._momentum}, in_channels="
                f"{self.gamma.shape[0] if self.gamma.shape else '?'})")


class Embedding(HybridBlock):
    """Embedding lookup.  With sparse_grad=True the weight's gradient is a
    RowSparseNDArray holding only the looked-up rows, and lazy-update
    optimizers touch only those rows (reference: gluon/nn/basic_layers.py
    Embedding(sparse_grad) + grad_stype='row_sparse' weights)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim,
                           sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = int(x.shape[self._axis])
        self.gamma._set_shape_if_deferred((c,))
        self.beta._set_shape_if_deferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = int(x.shape[1])
        self.gamma._set_shape_if_deferred((c,))
        self.beta._set_shape_if_deferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         init=gamma_initializer,
                                         allow_deferred_init=True)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        init=beta_initializer,
                                        allow_deferred_init=True)

    def infer_shape(self, x, *args):
        c = int(x.shape[1])
        self.gamma._set_shape_if_deferred((c,))
        self.beta._set_shape_if_deferred((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class Flatten(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return "Flatten"


class Lambda(Block):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            function = getattr(nd, function)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        self._func_name = function if isinstance(function, str) else None
        self._func = function

    def hybrid_forward(self, F, *args):
        if self._func_name is not None:
            return getattr(F, self._func_name)(*args)
        return self._func(F, *args)


class Activation(HybridBlock):
    def __init__(self, activation, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._act_type = activation

    def _alias(self):
        return self._act_type if hasattr(self, "_act_type") else "activation"

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=init_mod.Constant(0.25),
                 in_channels=1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(in_channels,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)


class GELU(HybridBlock):
    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="gelu")
